"""Frozen TF graph → JAX inference interpreter (the TFNet role).

Rebuild of the reference's TFNet (``pipeline/api/net/TFNet.scala:56``,
``TFNetForInference.scala``): a frozen TF graph (or SavedModel signature)
embedded as an inference-only module. The reference runs the graph through
libtensorflow JNI inside executor JVMs; here the graph is lowered ONCE —
``convert_variables_to_constants_v2`` folds variables and inlines function
calls — and the flat GraphDef is interpreted op-by-op in JAX, so inference
jits/shards/AOT-compiles like everything else (SURVEY §2.9(2)).

Inference-only by design, exactly like TFNet ("no training"); for
trainable ingestion use :mod:`zoo_tpu.bridges.keras_bridge`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_TF_OPS: Dict[str, Callable] = {}


def _tf_op(*names):
    def deco(fn):
        for n in names:
            _TF_OPS[n] = fn
        return fn
    return deco


def _dtype_from_attr(node, ctx, key="T"):
    import tensorflow as tf
    if key in node.attr:
        return jnp.dtype(tf.dtypes.as_dtype(node.attr[key].type)
                         .as_numpy_dtype)
    return None


# elementwise / math
_tf_op("Identity", "StopGradient", "CheckNumerics", "PreventGradient",
       "Snapshot")(lambda ctx, n, x, *rest: x)
_tf_op("Add", "AddV2")(lambda ctx, n, a, b: a + b)
_tf_op("Sub")(lambda ctx, n, a, b: a - b)
_tf_op("Mul")(lambda ctx, n, a, b: a * b)
_tf_op("RealDiv", "Div")(lambda ctx, n, a, b: a / b)
_tf_op("FloorDiv")(lambda ctx, n, a, b: jnp.floor_divide(a, b))
_tf_op("Pow")(lambda ctx, n, a, b: jnp.power(a, b))
_tf_op("Square")(lambda ctx, n, x: x * x)
_tf_op("SquaredDifference")(lambda ctx, n, a, b: (a - b) ** 2)
_tf_op("Sqrt")(lambda ctx, n, x: jnp.sqrt(x))
_tf_op("Rsqrt")(lambda ctx, n, x: lax.rsqrt(x))
_tf_op("Exp")(lambda ctx, n, x: jnp.exp(x))
_tf_op("Log")(lambda ctx, n, x: jnp.log(x))
_tf_op("Neg")(lambda ctx, n, x: -x)
_tf_op("Abs")(lambda ctx, n, x: jnp.abs(x))
_tf_op("Erf")(lambda ctx, n, x: lax.erf(x))
_tf_op("Tanh")(lambda ctx, n, x: jnp.tanh(x))
_tf_op("Sigmoid")(lambda ctx, n, x: jax.nn.sigmoid(x))
_tf_op("Relu")(lambda ctx, n, x: jax.nn.relu(x))
_tf_op("Relu6")(lambda ctx, n, x: jnp.clip(x, 0, 6))
_tf_op("LeakyRelu")(lambda ctx, n, x: jax.nn.leaky_relu(
    x, n.attr["alpha"].f if "alpha" in n.attr else 0.2))
_tf_op("Elu")(lambda ctx, n, x: jax.nn.elu(x))
_tf_op("Selu")(lambda ctx, n, x: jax.nn.selu(x))
_tf_op("Softplus")(lambda ctx, n, x: jax.nn.softplus(x))
_tf_op("Softmax")(lambda ctx, n, x: jax.nn.softmax(x, axis=-1))
_tf_op("LogSoftmax")(lambda ctx, n, x: jax.nn.log_softmax(x, axis=-1))
_tf_op("Maximum")(lambda ctx, n, a, b: jnp.maximum(a, b))
_tf_op("Minimum")(lambda ctx, n, a, b: jnp.minimum(a, b))
_tf_op("Greater")(lambda ctx, n, a, b: a > b)
_tf_op("GreaterEqual")(lambda ctx, n, a, b: a >= b)
_tf_op("Less")(lambda ctx, n, a, b: a < b)
_tf_op("LessEqual")(lambda ctx, n, a, b: a <= b)
_tf_op("Equal")(lambda ctx, n, a, b: a == b)
_tf_op("NotEqual")(lambda ctx, n, a, b: a != b)
_tf_op("LogicalNot")(lambda ctx, n, x: jnp.logical_not(x))
_tf_op("LogicalAnd")(lambda ctx, n, a, b: jnp.logical_and(a, b))
_tf_op("Select", "SelectV2")(lambda ctx, n, c, a, b: jnp.where(c, a, b))
_tf_op("Sin")(lambda ctx, n, x: jnp.sin(x))
_tf_op("Cos")(lambda ctx, n, x: jnp.cos(x))
_tf_op("Floor")(lambda ctx, n, x: jnp.floor(x))
_tf_op("Round")(lambda ctx, n, x: jnp.round(x))
_tf_op("Sign")(lambda ctx, n, x: jnp.sign(x))


@_tf_op("Cast")
def _cast(ctx, n, x):
    import tensorflow as tf
    dt = jnp.dtype(tf.dtypes.as_dtype(n.attr["DstT"].type).as_numpy_dtype)
    if dt == jnp.int64:
        dt = jnp.int32
    elif dt == jnp.float64:
        dt = jnp.float32
    return jnp.asarray(x).astype(dt)


@_tf_op("MatMul")
def _matmul(ctx, n, a, b):
    if n.attr["transpose_a"].b:
        a = a.T
    if n.attr["transpose_b"].b:
        b = b.T
    return a @ b


@_tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(ctx, n, a, b):
    if n.attr["adj_x"].b:
        a = jnp.swapaxes(a, -1, -2)
    if n.attr["adj_y"].b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@_tf_op("BiasAdd")
def _bias_add(ctx, n, x, b):
    fmt = n.attr["data_format"].s.decode() if "data_format" in n.attr \
        else "NHWC"
    if fmt == "NCHW" and x.ndim > 2:
        return x + b.reshape((1, -1) + (1,) * (x.ndim - 2))
    return x + b


@_tf_op("Conv2D")
def _conv2d(ctx, n, x, w):
    strides = list(n.attr["strides"].list.i)
    pad = n.attr["padding"].s.decode()
    fmt = n.attr["data_format"].s.decode() if "data_format" in n.attr \
        else "NHWC"
    dil = list(n.attr["dilations"].list.i) if "dilations" in n.attr \
        else [1, 1, 1, 1]
    if fmt != "NHWC":
        raise NotImplementedError("Conv2D NCHW in frozen graphs")
    return lax.conv_general_dilated(
        x, w, window_strides=strides[1:3], padding=pad,
        rhs_dilation=dil[1:3],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@_tf_op("DepthwiseConv2dNative")
def _depthwise_conv(ctx, n, x, w):
    strides = list(n.attr["strides"].list.i)
    pad = n.attr["padding"].s.decode()
    c = x.shape[-1]
    # HWIM -> HWI(M) grouped conv with feature_group_count=C
    kh, kw, cin, mult = w.shape
    w2 = w.reshape(kh, kw, 1, cin * mult)
    return lax.conv_general_dilated(
        x, w2, window_strides=strides[1:3], padding=pad,
        feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@_tf_op("MaxPool")
def _max_pool(ctx, n, x):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    pad = n.attr["padding"].s.decode()
    return lax.reduce_window(x, -jnp.inf, lax.max, tuple(k), tuple(s), pad)


@_tf_op("AvgPool")
def _avg_pool(ctx, n, x):
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    pad = n.attr["padding"].s.decode()
    summed = lax.reduce_window(x, 0.0, lax.add, tuple(k), tuple(s), pad)
    if pad == "VALID":
        return summed / (k[1] * k[2])
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, tuple(k),
                               tuple(s), pad)
    return summed / counts


@_tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(ctx, n, x, gamma, beta, mean, var):
    eps = n.attr["epsilon"].f if "epsilon" in n.attr else 1e-3
    out = (x - mean) * lax.rsqrt(var + eps) * gamma + beta
    return (out, mean, var, mean, var, mean)


@_tf_op("Mean", "Sum", "Max", "Min", "Prod", "Any", "All")
def _reduce(ctx, n, x, axes):
    keep = n.attr["keep_dims"].b if "keep_dims" in n.attr else False
    ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
    fn = {"Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max,
          "Min": jnp.min, "Prod": jnp.prod, "Any": jnp.any,
          "All": jnp.all}[n.op]
    return fn(x, axis=ax, keepdims=keep)


@_tf_op("ArgMax")
def _arg_max(ctx, n, x, axis):
    return jnp.argmax(x, axis=int(np.asarray(axis))).astype(jnp.int32)


@_tf_op("Reshape")
def _reshape(ctx, n, x, shape):
    tgt = [int(s) for s in np.asarray(shape).reshape(-1)]
    return jnp.reshape(x, tgt)


@_tf_op("Squeeze")
def _squeeze(ctx, n, x):
    dims = tuple(n.attr["squeeze_dims"].list.i) if "squeeze_dims" in n.attr \
        else None
    return jnp.squeeze(x, axis=dims if dims else None)


@_tf_op("ExpandDims")
def _expand_dims(ctx, n, x, axis):
    return jnp.expand_dims(x, int(np.asarray(axis)))


@_tf_op("Transpose")
def _transpose(ctx, n, x, perm):
    return jnp.transpose(x, [int(p) for p in np.asarray(perm).reshape(-1)])


@_tf_op("ConcatV2")
def _concat(ctx, n, *args):
    axis = int(np.asarray(args[-1]))
    return jnp.concatenate(args[:-1], axis=axis)


@_tf_op("Pack")
def _pack(ctx, n, *args):
    axis = n.attr["axis"].i if "axis" in n.attr else 0
    # shape-arithmetic subgraphs (Shape→…→Pack→Reshape) must stay host-side
    # numpy: a traced scalar here would poison the Reshape target
    if all(isinstance(a, (int, np.integer, np.ndarray)) for a in args):
        return np.stack([np.asarray(a) for a in args], axis=axis)
    return jnp.stack(args, axis=axis)


@_tf_op("Unpack")
def _unpack(ctx, n, x):
    axis = n.attr["axis"].i if "axis" in n.attr else 0
    num = n.attr["num"].i
    parts = jnp.split(x, num, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


@_tf_op("Pad", "PadV2")
def _pad(ctx, n, x, paddings, *rest):
    val = float(np.asarray(rest[0])) if rest else 0.0
    p = np.asarray(paddings)
    return jnp.pad(x, [(int(a), int(b)) for a, b in p],
                   constant_values=val)


@_tf_op("GatherV2")
def _gather(ctx, n, params, indices, axis):
    return jnp.take(params, jnp.asarray(indices).astype(jnp.int32),
                    axis=int(np.asarray(axis)))


@_tf_op("Shape")
def _shape(ctx, n, x):
    # static under jit (shapes are trace-time constants); keep as numpy so
    # downstream shape arithmetic stays host-side
    return np.asarray(x.shape, np.int32)


@_tf_op("StridedSlice")
def _strided_slice(ctx, n, x, begin, end, strides):
    begin = np.asarray(begin).reshape(-1)
    end = np.asarray(end).reshape(-1)
    strides = np.asarray(strides).reshape(-1)
    bm = n.attr["begin_mask"].i
    em = n.attr["end_mask"].i
    sm = n.attr["shrink_axis_mask"].i
    nm = n.attr["new_axis_mask"].i
    if nm:
        raise NotImplementedError("StridedSlice new_axis_mask")
    ix = []
    for i in range(len(begin)):
        if sm & (1 << i):
            ix.append(int(begin[i]))
            continue
        b = None if bm & (1 << i) else int(begin[i])
        e = None if em & (1 << i) else int(end[i])
        ix.append(slice(b, e, int(strides[i])))
    return x[tuple(ix)]


@_tf_op("Fill")
def _fill(ctx, n, dims, value):
    return jnp.full([int(d) for d in np.asarray(dims).reshape(-1)],
                    np.asarray(value))


@_tf_op("Range")
def _range(ctx, n, start, limit, delta):
    return jnp.arange(int(np.asarray(start)), int(np.asarray(limit)),
                      int(np.asarray(delta)))


# -- ops that appear in TF1 *training* graphs (loss heads etc.) -----------

@_tf_op("SparseSoftmaxCrossEntropyWithLogits")
def _sparse_xent(ctx, n, logits, labels):
    lp = jax.nn.log_softmax(logits, axis=-1)
    idx = jnp.asarray(labels).astype(jnp.int32)[..., None]
    loss = -jnp.take_along_axis(lp, idx, axis=-1)[..., 0]
    # output 1 is TF's precomputed backprop; forward graphs only read
    # output 0 and jax.grad differentiates the log_softmax form directly
    return (loss, jnp.zeros_like(logits))


@_tf_op("SoftmaxCrossEntropyWithLogits")
def _xent(ctx, n, logits, labels):
    lp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.sum(jnp.asarray(labels) * lp, axis=-1)
    return (loss, jnp.zeros_like(logits))


_tf_op("L2Loss")(lambda ctx, n, x: jnp.sum(jnp.square(x)) * 0.5)
_tf_op("AddN", "AccumulateNV2")(
    lambda ctx, n, *xs: sum(xs[1:], start=xs[0]))
_tf_op("ZerosLike")(lambda ctx, n, x: jnp.zeros_like(x))
_tf_op("OnesLike")(lambda ctx, n, x: jnp.ones_like(x))
_tf_op("Log1p")(lambda ctx, n, x: jnp.log1p(x))
_tf_op("Rank")(lambda ctx, n, x: np.int32(jnp.asarray(x).ndim))
_tf_op("Size")(lambda ctx, n, x: np.int32(jnp.asarray(x).size))


@_tf_op("OneHot")
def _one_hot(ctx, n, indices, depth, on_value, off_value):
    axis = n.attr["axis"].i if "axis" in n.attr else -1
    oh = jax.nn.one_hot(jnp.asarray(indices).astype(jnp.int32),
                        int(np.asarray(depth)), axis=axis)
    on = np.asarray(on_value)
    off = np.asarray(off_value)
    return oh * (on - off) + off


@_tf_op("Tile")
def _tile(ctx, n, x, multiples):
    return jnp.tile(x, [int(m) for m in np.asarray(multiples).reshape(-1)])


@_tf_op("BroadcastTo")
def _broadcast_to(ctx, n, x, shape):
    return jnp.broadcast_to(x, [int(s) for s in
                                np.asarray(shape).reshape(-1)])


# ops with >1 output beyond the FusedBatchNorm/Unpack special cases
_MULTI_OUT = {"SparseSoftmaxCrossEntropyWithLogits": 2,
              "SoftmaxCrossEntropyWithLogits": 2}

# stateful mutation ops: never on a forward/loss value path; reaching one
# means the caller asked for a target behind an assignment
_STATE_OPS = ("Assign", "AssignVariableOp", "AssignAdd", "AssignSub",
              "AssignAddVariableOp", "AssignSubVariableOp")


def _interpret(nodes: Dict[str, object], env: Dict[str, object],
               targets: Sequence[str]):
    """Walk a GraphDef from ``targets`` back to seeds in ``env``
    (placeholders AND captured variable nodes), computing each node once.
    The shared core of frozen-graph inference and TF1-graph training."""
    from tensorflow.python.framework import tensor_util

    def value_of(ref: str):
        if ref.startswith("^"):
            return None  # control edge
        name, _, idx = ref.partition(":")
        out = compute(name)
        if idx and int(idx) > 0:
            return out[int(idx)]
        return out[0] if isinstance(out, tuple) and n_outputs(name) > 1 \
            else (out if not isinstance(out, tuple) else out[0])

    def n_outputs(name):
        node = nodes[name]
        return 6 if node.op.startswith("FusedBatchNorm") else (
            node.attr["num"].i if node.op == "Unpack"
            else _MULTI_OUT.get(node.op, 1))

    def compute(name):
        if name in env:
            return env[name]
        node = nodes[name]
        if node.op == "Const":
            val = tensor_util.MakeNdarray(node.attr["value"].tensor)
            if val.dtype == np.float64:
                val = val.astype(np.float32)
            elif val.dtype == np.int64:
                val = val.astype(np.int32)
            env[name] = val
            return val
        if node.op in ("Placeholder", "PlaceholderWithDefault"):
            raise ValueError(f"unbound graph input: {name}")
        if node.op in ("VariableV2", "Variable", "VarHandleOp"):
            raise ValueError(
                f"uncaptured variable node {name!r}: pass it through the "
                "params/frozen dicts (capture_trainable_graph) or freeze "
                "the graph first")
        if node.op == "ReadVariableOp":
            out = value_of(node.input[0])
            env[name] = out
            return out
        if node.op in _STATE_OPS:
            raise NotImplementedError(
                f"TF op {node.op} (node {name}) mutates graph state; the "
                "JAX interpreter is pure — evaluate value tensors, not "
                "assignment ops (moving-stat updates are captured frozen "
                "at conversion time)")
        if node.op == "NoOp":
            env[name] = None
            return None
        fn = _TF_OPS.get(node.op)
        if fn is None:
            raise NotImplementedError(
                f"TF op {node.op} (node {name}) has no JAX mapping in "
                "zoo_tpu.bridges.tf_graph._TF_OPS")
        args = [value_of(i) for i in node.input if not i.startswith("^")]
        out = fn(None, node, *args)
        env[name] = out
        return out

    return [value_of(ref) for ref in targets]


class TFGraphFunction:
    """A frozen GraphDef interpreted as a pure JAX function."""

    def __init__(self, graph_def, input_names: List[str],
                 output_names: List[str]):
        self.graph_def = graph_def
        self.input_names = input_names
        self.output_names = output_names
        self._nodes = {n.name: n for n in graph_def.node}

    def __call__(self, *inputs):
        env: Dict[str, object] = dict(zip(self.input_names, inputs))
        results = _interpret(self._nodes, env, self.output_names)
        return results[0] if len(results) == 1 else tuple(results)


def convert_tf_callable(fn, example_args: Sequence) -> TFGraphFunction:
    """Freeze a tf.function / keras model / callable and return the JAX
    interpreter over its graph."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    if not isinstance(fn, tf.types.experimental.GenericFunction):
        wrapped = tf.function(fn)
    else:
        wrapped = fn
    specs = [tf.TensorSpec((None,) + tuple(np.asarray(a).shape[1:]),
                           tf.dtypes.as_dtype(np.asarray(a).dtype))
             for a in example_args]
    cf = wrapped.get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name for t in frozen.outputs]
    return TFGraphFunction(gd, in_names, out_names)


def load_saved_model(path: str, signature: str = "serving_default",
                     example_args: Optional[Sequence] = None
                     ) -> TFGraphFunction:
    """SavedModel → JAX function (reference: ``TFNet.fromSavedModel``)."""
    import tensorflow as tf

    sm = tf.saved_model.load(path)
    fn = sm.signatures[signature]
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )
    frozen = convert_variables_to_constants_v2(fn)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name for t in frozen.outputs]
    out = TFGraphFunction(gd, in_names, out_names)
    out._keepalive = sm  # the loaded object owns the variables
    return out


class TrainableTFGraph:
    """A TF1 graph whose trainable variables are a JAX params pytree.

    The training-side counterpart of :class:`TFGraphFunction` — the
    mechanism the reference's TFOptimizer/TFTrainingHelper provided by
    exporting the session graph to the JVM fabric
    (``pyzoo/zoo/tfpark/tf_optimizer.py:464,514``). Here the graph is
    interpreted in JAX with variable nodes seeded from a params dict, so
    ``jax.grad`` of the interpreted loss IS the backward pass — exactly
    the treatment the ONNX loader gives initializers
    (``pipeline/api/onnx/onnx_loader.py``).

    ``params``: {variable node name: ndarray} — trainable.
    ``frozen``: non-trainable globals (BN moving stats, global_step…)
    captured as constants at conversion time.
    """

    def __init__(self, graph_def, input_names: List[str],
                 label_names: List[str], loss_ref: Optional[str],
                 output_refs: List[str], params: Dict[str, np.ndarray],
                 frozen: Optional[Dict[str, np.ndarray]] = None,
                 metric_refs: Optional[Dict[str, str]] = None):
        self.graph_def = graph_def
        self.input_names = list(input_names)
        self.label_names = list(label_names)
        self.loss_ref = loss_ref
        self.output_refs = list(output_refs)
        self.params = {k: np.asarray(v) for k, v in params.items()}
        self.frozen = {k: np.asarray(v) for k, v in (frozen or {}).items()}
        self.metric_refs = dict(metric_refs or {})
        self._nodes = {n.name: n for n in graph_def.node}

    def _env(self, params, inputs, labels=()):
        env: Dict[str, object] = dict(self.frozen)
        env.update(params)
        env.update(zip(self.input_names, inputs))
        env.update(zip(self.label_names, labels))
        return env

    def loss_fn(self, params, inputs: Sequence, labels: Sequence = ()):
        """Scalar loss as a pure function of (params, data) — jittable
        and differentiable."""
        if self.loss_ref is None:
            raise ValueError("graph captured without a loss tensor")
        out = _interpret(self._nodes, self._env(params, inputs, labels),
                         [self.loss_ref])[0]
        return jnp.asarray(out).reshape(())

    def forward(self, params, inputs: Sequence):
        outs = _interpret(self._nodes, self._env(params, inputs),
                          self.output_refs)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def metrics_fn(self, params, inputs: Sequence, labels: Sequence = ()):
        if not self.metric_refs:
            return {}
        names = list(self.metric_refs)
        vals = _interpret(self._nodes, self._env(params, inputs, labels),
                          [self.metric_refs[k] for k in names])
        return {k: jnp.asarray(v) for k, v in zip(names, vals)}


def capture_trainable_graph(*, inputs: Sequence, labels: Sequence = (),
                            loss=None, outputs: Sequence = (),
                            metrics: Optional[Dict[str, object]] = None,
                            sess=None) -> "tuple":
    """Capture a live TF1 graph (placeholders + variables + loss tensor)
    into a :class:`TrainableTFGraph`.

    Trainable variables become the params pytree with their CURRENT
    session values (uninitialized ones are initialized first — the
    ``sess`` contract of the reference's ``from_loss``:
    ``tf_optimizer.py:514`` "if you want to use a pre-trained model,
    pass the Session that loaded it"). Non-trainable globals are frozen.

    Returns ``(TrainableTFGraph, sess, trainable_tf_vars)`` so the
    caller can write trained values back into the session.
    """
    import tensorflow as tf
    tf1 = tf.compat.v1

    anchor = loss if loss is not None else \
        (list(outputs) + list(inputs))[0]
    graph = anchor.graph
    if sess is None:
        sess = tf1.Session(graph=graph)
    with graph.as_default():
        gvars = tf1.global_variables()
        # a finalized graph (MonitoredTrainingSession etc.) can't grow
        # init-check ops — its variables are initialized by contract
        if gvars and not graph.finalized:
            uninit = {n.decode() if isinstance(n, bytes) else str(n)
                      for n in sess.run(
                          tf1.report_uninitialized_variables(gvars))}
            to_init = [v for v in gvars if v.op.name in uninit]
            if to_init:
                sess.run(tf1.variables_initializer(to_init))
        tvars = tf1.trainable_variables()

    def _np(v):
        a = np.asarray(v)
        if a.dtype == np.float64:
            a = a.astype(np.float32)
        elif a.dtype == np.int64:
            a = a.astype(np.int32)
        return a

    tset = {id(v) for v in tvars}
    params = {v.op.name: _np(val)
              for v, val in zip(tvars, sess.run(list(tvars)))} \
        if tvars else {}
    nt = [v for v in gvars if id(v) not in tset]
    frozen = {v.op.name: _np(val)
              for v, val in zip(nt, sess.run(list(nt)))} if nt else {}

    trainable = TrainableTFGraph(
        graph.as_graph_def(),
        input_names=[t.op.name for t in inputs],
        label_names=[t.op.name for t in labels],
        loss_ref=(loss.name if loss is not None else None),
        output_refs=[t.name for t in outputs],
        params=params, frozen=frozen,
        metric_refs={k: t.name for k, t in (metrics or {}).items()})
    return trainable, sess, list(tvars)


def write_back_variables(sess, tf_vars, params: Dict[str, np.ndarray]):
    """Push trained JAX params back into the TF session's variables, so
    the user's saver/export flow sees the trained weights — the round
    trip the reference closes after ``TFOptimizer.optimize()``."""
    for v in tf_vars:
        if v.op.name not in params:
            continue
        val = np.asarray(params[v.op.name])
        # feed the initializer's value input and re-run it: writes the
        # variable without adding ops to an already-run graph (the
        # classic pre-trained-weight-load trick; tf1.assign here would
        # mutate the graph post-session and TF warns/errors)
        init = v.initializer
        sess.run(init, feed_dict={init.inputs[1]: val})


_APPLY_OPTIM = {
    "ApplyGradientDescent": ("sgd", {"lr": 1}),
    "ResourceApplyGradientDescent": ("sgd", {"lr": 1}),
    "ApplyMomentum": ("sgd_momentum", {"lr": 2, "momentum": 4}),
    "ResourceApplyMomentum": ("sgd_momentum", {"lr": 2, "momentum": 4}),
    "ResourceApplyKerasMomentum": ("sgd_momentum",
                                   {"lr": 2, "momentum": 4}),
    "ApplyAdam": ("adam", {"lr": 5, "beta_1": 6, "beta_2": 7,
                           "epsilon": 8}),
    "ResourceApplyAdam": ("adam", {"lr": 5, "beta_1": 6, "beta_2": 7,
                                   "epsilon": 8}),
    "ApplyAdagrad": ("adagrad", {"lr": 2}),
    "ResourceApplyAdagrad": ("adagrad", {"lr": 2}),
    "ResourceApplyAdagradV2": ("adagrad", {"lr": 2}),
    "ApplyRMSProp": ("rmsprop", {"lr": 3, "rho": 4}),
    "ResourceApplyRMSProp": ("rmsprop", {"lr": 3, "rho": 4}),
}


def optimizer_from_train_op(graph_def, train_op_name: str):
    """Recover the optimizer family + hyperparameters from a TF1
    ``train_op`` (the role of the reference's
    ``_get_vars_grads_from_train_op``, ``tf_optimizer.py:464``): the
    train_op groups ``Apply*`` ops whose const inputs carry lr/betas.

    Returns a zoo optimizer instance. Raises ``NotImplementedError``
    when the optimizer family is unknown or the learning rate is not a
    graph constant (e.g. a schedule subgraph) — the graceful-error
    contract for unconvertible train_ops."""
    from zoo_tpu.pipeline.api.keras import optimizers as zopt

    nodes = {n.name: n for n in graph_def.node}
    name = train_op_name.split(":")[0].lstrip("^")
    if name not in nodes:
        raise ValueError(f"train_op node {name!r} not in graph")

    # collect Apply* ops reachable via control/data deps of the train_op
    seen, stack, applies = set(), [name], []
    while stack:
        cur = stack.pop()
        if cur in seen or cur not in nodes:
            continue
        seen.add(cur)
        node = nodes[cur]
        if node.op in _APPLY_OPTIM:
            applies.append(node)
            continue
        for ref in node.input:
            stack.append(ref.lstrip("^").split(":")[0])
    if not applies:
        raise NotImplementedError(
            f"train_op {name!r} leads to no recognized Apply* optimizer "
            f"op (supported: {sorted(set(_APPLY_OPTIM))}); use "
            "TFOptimizer.from_loss with an explicit optim_method")

    def const_of(ref):
        nd = nodes.get(ref.split(":")[0].lstrip("^"))
        while nd is not None and nd.op in ("Identity", "ReadVariableOp"):
            nd = nodes.get(nd.input[0].split(":")[0])
        if nd is None or nd.op != "Const":
            raise NotImplementedError(
                f"hyperparameter input {ref!r} of the train_op is not a "
                "graph constant (a schedule subgraph?); pass the "
                "optimizer explicitly via TFOptimizer.from_loss")
        from tensorflow.python.framework import tensor_util
        return float(tensor_util.MakeNdarray(nd.attr["value"].tensor))

    node = applies[0]
    kind, slots = _APPLY_OPTIM[node.op]
    hp = {k: const_of(node.input[i]) for k, i in slots.items()}
    if kind == "sgd":
        return zopt.SGD(lr=hp["lr"])
    if kind == "sgd_momentum":
        return zopt.SGD(lr=hp["lr"], momentum=hp["momentum"])
    if kind == "adam":
        return zopt.Adam(lr=hp["lr"], beta_1=hp["beta_1"],
                         beta_2=hp["beta_2"], epsilon=hp["epsilon"])
    if kind == "adagrad":
        return zopt.Adagrad(lr=hp["lr"])
    if kind == "rmsprop":
        return zopt.RMSprop(lr=hp["lr"], rho=hp["rho"])
    raise NotImplementedError(kind)


class TFGraphWrapper:
    """Predict-surface adapter so InferenceModel can hold a frozen TF
    graph like any other model (inference-only, as TFNet was)."""

    def __init__(self, graph_fn: TFGraphFunction):
        self.graph_fn = graph_fn
        self._jit = jax.jit(graph_fn)

    def predict(self, x, batch_size: int = 256,
                feature_cols=None) -> np.ndarray:
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        xs = [np.asarray(a) for a in xs]
        n = xs[0].shape[0]
        outs = []
        for lo in range(0, n, batch_size):
            chunk = [a[lo:lo + batch_size] for a in xs]
            real = chunk[0].shape[0]
            if real < batch_size and lo > 0:
                # pad to the steady batch shape to avoid a recompile
                chunk = [np.concatenate(
                    [a, np.repeat(a[:1], batch_size - real, axis=0)])
                    for a in chunk]
            out = self._jit(*[jnp.asarray(a) for a in chunk])
            if isinstance(out, tuple):
                out = out[0]
            outs.append(out[:real])
        return np.asarray(jnp.concatenate(outs, axis=0))
