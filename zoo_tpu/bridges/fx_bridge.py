"""torch → JAX bridge via ``torch.export`` graph tracing.

Rebuild of the reference's "any torch module" ingestion contract
(``pipeline/api/net/TorchModel.scala:34`` ships the live module to
executors and runs it under jep per step). Here the module is traced ONCE
with ``torch.export`` into a core-ATen graph, and that graph is
*interpreted in JAX*: every ATen op maps to a jax/lax equivalent, weights
come across as a pytree keyed by the torch parameter FQNs, and the whole
thing jits/differentiates/shards like any native model — torch never runs
on the hot path.

Compared to the round-1 structural bridge (isinstance-walk over
``nn.Sequential``), tracing supports arbitrary ``forward`` code,
multi-input models, attention blocks, and HuggingFace-style transformers.

Notes / contract:
  * The module is exported in ``eval()`` mode: dropout layers drop out of
    the graph and BatchNorm uses (frozen) running statistics. Gradients
    still flow to all parameters, so fine-tuning works; stochastic-depth
    style regularization does not.
  * int64 tensors are computed as int32 (JAX default; indices and masks at
    model scale fit comfortably).
  * Unsupported ATen ops raise ``NotImplementedError`` naming the op.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_INT64_MAX = 2 ** 63 - 1


def _torch_dtype_to_jnp(tdtype):
    import torch
    table = {
        torch.float32: jnp.float32, torch.float64: jnp.float32,
        torch.float16: jnp.float16, torch.bfloat16: jnp.bfloat16,
        torch.int64: jnp.int32, torch.int32: jnp.int32,
        torch.int16: jnp.int16, torch.int8: jnp.int8,
        torch.uint8: jnp.uint8, torch.bool: jnp.bool_,
    }
    return table[tdtype]


def _t2j(t) -> jnp.ndarray:
    """torch tensor -> jnp array (f64->f32, i64->i32, bf16 preserved)."""
    import torch
    if t.dtype == torch.bfloat16:  # .numpy() rejects bf16
        return jnp.asarray(t.detach().cpu().float().numpy(),
                           dtype=jnp.bfloat16)
    a = t.detach().cpu().numpy()
    if a.dtype == np.float64:
        a = a.astype(np.float32)
    elif a.dtype == np.int64:
        a = a.astype(np.int32)
    return jnp.asarray(a)


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


# --------------------------------------------------------------------- ops

_OPS: Dict[str, Callable] = {}


def _op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _alpha_add(x, y, alpha=1):
    return x + (y * alpha if alpha != 1 else y)


_op("aten.add.Tensor", "aten.add.Scalar")(
    lambda x, y, alpha=1: _alpha_add(x, y, alpha))
_op("aten.sub.Tensor", "aten.sub.Scalar")(
    lambda x, y, alpha=1: x - (y * alpha if alpha != 1 else y))
_op("aten.rsub.Scalar", "aten.rsub.Tensor")(
    lambda x, y, alpha=1: y - (x * alpha if alpha != 1 else x))
_op("aten.mul.Tensor", "aten.mul.Scalar")(lambda x, y: x * y)
_op("aten.div.Tensor", "aten.div.Scalar")(lambda x, y: x / y)
_op("aten.pow.Tensor_Scalar", "aten.pow.Tensor_Tensor")(jnp.power)
_op("aten.neg.default")(jnp.negative)
_op("aten.abs.default")(jnp.abs)
_op("aten.exp.default")(jnp.exp)
_op("aten.log.default")(jnp.log)
_op("aten.sqrt.default")(jnp.sqrt)
_op("aten.rsqrt.default")(lambda x: lax.rsqrt(x))
_op("aten.erf.default")(lax.erf)
_op("aten.tanh.default")(jnp.tanh)
_op("aten.sin.default")(jnp.sin)
_op("aten.cos.default")(jnp.cos)
_op("aten.reciprocal.default")(lambda x: 1.0 / x)
_op("aten.relu.default", "aten.relu_.default")(jax.nn.relu)
_op("aten.sigmoid.default")(jax.nn.sigmoid)
_op("aten.silu.default", "aten.silu_.default")(jax.nn.silu)
_op("aten.maximum.default")(jnp.maximum)
_op("aten.minimum.default")(jnp.minimum)
_op("aten.floor.default")(jnp.floor)
_op("aten.round.default")(jnp.round)
_op("aten.logical_not.default")(jnp.logical_not)
_op("aten.logical_and.default")(jnp.logical_and)
_op("aten.logical_or.default")(jnp.logical_or)
_op("aten.bitwise_not.default")(
    lambda x: jnp.logical_not(x) if x.dtype == jnp.bool_
    else jnp.bitwise_not(x))
_op("aten.eq.Scalar", "aten.eq.Tensor")(lambda x, y: x == y)
_op("aten.ne.Scalar", "aten.ne.Tensor")(lambda x, y: x != y)
_op("aten.lt.Scalar", "aten.lt.Tensor")(lambda x, y: x < y)
_op("aten.le.Scalar", "aten.le.Tensor")(lambda x, y: x <= y)
_op("aten.gt.Scalar", "aten.gt.Tensor")(lambda x, y: x > y)
_op("aten.ge.Scalar", "aten.ge.Tensor")(lambda x, y: x >= y)
_op("aten.where.self")(jnp.where)
_op("aten.clone.default")(lambda x, memory_format=None: x)
_op("aten.alias.default", "aten.detach.default", "aten.lift_fresh.default",
    "aten.contiguous.default")(lambda x, *a, **k: x)
_op("aten._assert_tensor_metadata.default")(lambda *a, **k: None)
_op("aten.sym_size.int")(lambda x, d: x.shape[d])


@_op("aten.clamp.default")
def _clamp(x, mn=None, mx=None):
    return jnp.clip(x, mn, mx)


@_op("aten.hardtanh.default", "aten.hardtanh_.default")
def _hardtanh(x, mn=-1.0, mx=1.0):
    return jnp.clip(x, mn, mx)


@_op("aten.leaky_relu.default")
def _leaky_relu(x, slope=0.01):
    return jax.nn.leaky_relu(x, slope)


@_op("aten.elu.default")
def _elu(x, alpha=1.0, scale=1.0, input_scale=1.0):
    return scale * jax.nn.elu(x * input_scale, alpha)


@_op("aten.gelu.default", "aten.gelu_.default")
def _gelu(x, approximate="none"):
    return jax.nn.gelu(x, approximate=(approximate == "tanh"))


@_op("aten.mm.default")
def _mm(a, b):
    return a @ b


@_op("aten.bmm.default")
def _bmm(a, b):
    return jnp.einsum("bij,bjk->bik", a, b)


@_op("aten.matmul.default")
def _matmul(a, b):
    return a @ b


@_op("aten.addmm.default")
def _addmm(bias, a, b, beta=1, alpha=1):
    out = a @ b
    if alpha != 1:
        out = out * alpha
    return out + (bias * beta if beta != 1 else bias)


@_op("aten.baddbmm.default")
def _baddbmm(bias, a, b, beta=1, alpha=1):
    out = jnp.einsum("bij,bjk->bik", a, b)
    if alpha != 1:
        out = out * alpha
    return out + (bias * beta if beta != 1 else bias)


@_op("aten.t.default")
def _t(x):
    return x.T


@_op("aten.view.default", "aten.reshape.default", "aten._unsafe_view.default")
def _view(x, shape):
    return jnp.reshape(x, [int(s) for s in shape])


@_op("aten.permute.default")
def _permute(x, dims):
    return jnp.transpose(x, dims)


@_op("aten.transpose.int")
def _transpose(x, d0, d1):
    return jnp.swapaxes(x, d0, d1)


@_op("aten.unsqueeze.default")
def _unsqueeze(x, dim):
    return jnp.expand_dims(x, dim)


@_op("aten.squeeze.dim", "aten.squeeze.dims")
def _squeeze(x, dim):
    dims = (dim,) if isinstance(dim, int) else tuple(dim)
    dims = tuple(d for d in dims if x.shape[d] == 1)
    return jnp.squeeze(x, dims) if dims else x


@_op("aten.expand.default")
def _expand(x, sizes, implicit=False):
    # -1 keeps the existing dim; leading new axes broadcast
    nd_new = len(sizes) - x.ndim
    shape = []
    for i, s in enumerate(sizes):
        if int(s) == -1:
            shape.append(x.shape[i - nd_new] if i >= nd_new else 1)
        else:
            shape.append(int(s))
    return jnp.broadcast_to(x, shape)


@_op("aten.cat.default")
def _cat(tensors, dim=0):
    return jnp.concatenate(tensors, axis=dim)


@_op("aten.stack.default")
def _stack(tensors, dim=0):
    return jnp.stack(tensors, axis=dim)


@_op("aten.split.Tensor", "aten.split_with_sizes.default")
def _split(x, sizes, dim=0):
    if isinstance(sizes, int):
        n = x.shape[dim]
        sizes = [sizes] * (n // sizes) + ([n % sizes] if n % sizes else [])
    idx = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=dim))


@_op("aten.slice.Tensor")
def _slice(x, dim=0, start=None, end=None, step=1):
    start = 0 if start is None else start
    end = x.shape[dim] if end is None or end >= _INT64_MAX else end
    ix = [slice(None)] * x.ndim
    ix[dim] = slice(start, end, step)
    return x[tuple(ix)]


@_op("aten.select.int")
def _select(x, dim, index):
    return lax.index_in_dim(x, index, axis=dim, keepdims=False)


@_op("aten.index_select.default")
def _index_select(x, dim, index):
    return jnp.take(x, index, axis=dim)


@_op("aten.gather.default")
def _gather(x, dim, index, sparse_grad=False):
    return jnp.take_along_axis(x, index, axis=dim)


@_op("aten.embedding.default")
def _embedding(weight, indices, padding_idx=-1, scale_grad_by_freq=False,
               sparse=False):
    return jnp.take(weight, indices, axis=0)


@_op("aten.masked_fill.Scalar", "aten.masked_fill.Tensor")
def _masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@_op("aten.cumsum.default")
def _cumsum(x, dim, dtype=None):
    out = jnp.cumsum(x, axis=dim)
    return out.astype(_torch_dtype_to_jnp(dtype)) if dtype else out


@_op("aten.tril.default")
def _tril(x, diagonal=0):
    return jnp.tril(x, diagonal)


@_op("aten.triu.default")
def _triu(x, diagonal=0):
    return jnp.triu(x, diagonal)


@_op("aten.sum.dim_IntList", "aten.sum.default")
def _sum(x, dim=None, keepdim=False, dtype=None):
    out = jnp.sum(x, axis=tuple(dim) if isinstance(dim, (list, tuple))
                  else dim, keepdims=keepdim)
    return out.astype(_torch_dtype_to_jnp(dtype)) if dtype else out


@_op("aten.mean.dim", "aten.mean.default")
def _mean(x, dim=None, keepdim=False, dtype=None):
    out = jnp.mean(x, axis=tuple(dim) if isinstance(dim, (list, tuple))
                   else dim, keepdims=keepdim)
    return out.astype(_torch_dtype_to_jnp(dtype)) if dtype else out


@_op("aten.var.correction")
def _var(x, dim=None, correction=1, keepdim=False):
    return jnp.var(x, axis=tuple(dim) if isinstance(dim, (list, tuple))
                   else dim, ddof=int(correction), keepdims=keepdim)


@_op("aten.amax.default")
def _amax(x, dim=None, keepdim=False):
    return jnp.max(x, axis=tuple(dim) if isinstance(dim, (list, tuple))
                   else dim, keepdims=keepdim)


@_op("aten.amin.default")
def _amin(x, dim=None, keepdim=False):
    return jnp.min(x, axis=tuple(dim) if isinstance(dim, (list, tuple))
                   else dim, keepdims=keepdim)


@_op("aten.argmax.default")
def _argmax(x, dim=None, keepdim=False):
    return jnp.argmax(x, axis=dim, keepdims=keepdim).astype(jnp.int32)


@_op("aten.any.dim", "aten.any.default")
def _any(x, dim=None, keepdim=False):
    return jnp.any(x, axis=dim, keepdims=keepdim)


@_op("aten.all.dim", "aten.all.default")
def _all(x, dim=None, keepdim=False):
    return jnp.all(x, axis=dim, keepdims=keepdim)


@_op("aten._softmax.default", "aten.softmax.int")
def _softmax(x, dim, half_to_float=False):
    return jax.nn.softmax(x, axis=dim)


@_op("aten._log_softmax.default", "aten.log_softmax.int")
def _log_softmax(x, dim, half_to_float=False):
    return jax.nn.log_softmax(x, axis=dim)


@_op("aten.native_layer_norm.default")
def _native_layer_norm(x, normalized_shape, weight, bias, eps):
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    out = (xf - mean) * rstd
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype), mean, rstd


@_op("aten._native_batch_norm_legit_no_training.default")
def _bn_eval(x, weight, bias, running_mean, running_var, momentum, eps):
    shape = (1, -1) + (1,) * (x.ndim - 2)
    mean = running_mean.reshape(shape)
    var = running_var.reshape(shape)
    out = (x - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, jnp.zeros((0,), x.dtype), jnp.zeros((0,), x.dtype)


@_op("aten.native_group_norm.default")
def _group_norm(x, weight, bias, n, c, hw, group, eps):
    b = x.shape[0]
    xg = x.reshape((b, group, -1))
    mean = jnp.mean(xg, axis=-1, keepdims=True)
    var = jnp.var(xg, axis=-1, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, mean, var


@_op("aten.convolution.default")
def _convolution(x, weight, bias, stride, padding, dilation, transposed,
                 output_padding, groups):
    stride, padding = tuple(stride), tuple(padding)
    dilation = tuple(dilation)
    nd = len(stride)
    if transposed:
        if groups != 1:
            raise NotImplementedError("grouped ConvTranspose in the bridge")
        # torch semantics: out = (i-1)*s - 2p + d*(k-1) + output_padding + 1
        # implemented as a fractionally-strided conv: lhs_dilation=s, the
        # kernel spatially flipped and (in,out) transposed, with pads
        # d*(k-1)-p (low) / d*(k-1)-p+output_padding (high)
        op = tuple(output_padding)
        k = weight.shape[2:]
        spatial = tuple(range(2, 2 + nd))
        w = jnp.swapaxes(jnp.flip(weight, spatial), 0, 1)
        pads = tuple(
            (dilation[i] * (k[i] - 1) - padding[i],
             dilation[i] * (k[i] - 1) - padding[i] + op[i])
            for i in range(nd))
        out = lax.conv_general_dilated(
            x, w, window_strides=(1,) * nd, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=_conv_dims(nd))
    else:
        out = lax.conv_general_dilated(
            x, weight, window_strides=stride,
            padding=tuple((p, p) for p in padding),
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=_conv_dims(nd))
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def _conv_dims(nd: int):
    sp = "DHW"[-nd:]
    return (f"NC{sp}", f"OI{sp}", f"NC{sp}")


@_op("aten.max_pool2d.default")
def _max_pool2d_single(x, kernel, stride=None, padding=0, dilation=1,
                       ceil_mode=False):
    return _max_pool2d(x, kernel, stride, padding, dilation, ceil_mode)[0]


@_op("aten.max_pool2d_with_indices.default")
def _max_pool2d(x, kernel, stride=None, padding=0, dilation=1,
                ceil_mode=False):
    k = _pair(kernel)
    s = _pair(stride) if stride not in (None, []) else k
    p = _pair(padding)
    if _pair(dilation) != (1, 1):
        raise NotImplementedError("dilated max_pool2d")
    hi = [p[0], p[1]]
    if ceil_mode:
        # extra high-side -inf padding so the last partial window counts
        # (torch ceil_mode); identity element keeps values exact. Torch
        # drops a ceil window whose START lies entirely in the padding:
        # out = ceil((in+2p-k)/s)+1, minus 1 if (out-1)*s >= in+p.
        for i in (0, 1):
            size = x.shape[2 + i]
            out = -(-(size + 2 * p[i] - k[i]) // s[i]) + 1
            if (out - 1) * s[i] >= size + p[i]:
                out -= 1
            extra = max(0, (out - 1) * s[i] + k[i] - (size + 2 * p[i]))
            hi[i] = p[i] + extra
    out = lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min,
        lax.max, (1, 1) + k, (1, 1) + s,
        ((0, 0), (0, 0), (p[0], hi[0]), (p[1], hi[1])))
    return out, None  # indices not materialized; loud failure if consumed


@_op("aten.avg_pool2d.default")
def _avg_pool2d(x, kernel, stride=None, padding=0, ceil_mode=False,
                count_include_pad=True, divisor_override=None):
    if ceil_mode:
        raise NotImplementedError("avg_pool2d with ceil_mode=True")
    k = _pair(kernel)
    s = _pair(stride) if stride not in (None, []) else k
    p = _pair(padding)
    summed = lax.reduce_window(
        x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
        ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    if divisor_override:
        return summed / divisor_override
    if count_include_pad or p == (0, 0):
        return summed / (k[0] * k[1])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
        ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    return summed / counts


@_op("aten._adaptive_avg_pool2d.default", "aten.adaptive_avg_pool2d.default")
def _adaptive_avg_pool2d(x, output_size):
    oh, ow = _pair(output_size)
    h, w = x.shape[-2], x.shape[-1]
    if h % oh or w % ow:
        raise NotImplementedError("adaptive_avg_pool2d with non-divisible "
                                  f"output {output_size} from {(h, w)}")
    kh, kw = h // oh, w // ow
    return _avg_pool2d(x, (kh, kw), (kh, kw))


@_op("aten.full.default")
def _full(size, fill_value, dtype=None, layout=None, device=None,
          pin_memory=None):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else None
    return jnp.full([int(s) for s in size], fill_value, dtype=dt)


@_op("aten.full_like.default")
def _full_like(x, fill_value, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else x.dtype
    return jnp.full(x.shape, fill_value, dtype=dt)


@_op("aten.zeros.default")
def _zeros(size, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else jnp.float32
    return jnp.zeros([int(s) for s in size], dtype=dt)


@_op("aten.ones.default")
def _ones(size, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else jnp.float32
    return jnp.ones([int(s) for s in size], dtype=dt)


@_op("aten.zeros_like.default")
def _zeros_like(x, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else x.dtype
    return jnp.zeros(x.shape, dtype=dt)


@_op("aten.ones_like.default")
def _ones_like(x, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else x.dtype
    return jnp.ones(x.shape, dtype=dt)


@_op("aten.scalar_tensor.default")
def _scalar_tensor(value, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else None
    return jnp.asarray(value, dtype=dt)


@_op("aten.arange.default", "aten.arange.start", "aten.arange.start_step")
def _arange(*args, dtype=None, **kw):
    dt = _torch_dtype_to_jnp(dtype) if dtype is not None else None
    if dt is None and all(isinstance(a, int) for a in args):
        dt = jnp.int32
    return jnp.arange(*args, dtype=dt)


@_op("aten._to_copy.default", "aten.to.dtype")
def _to_copy(x, dtype=None, layout=None, device=None, pin_memory=None,
             non_blocking=False, memory_format=None):
    if dtype is None:
        return x
    return x.astype(_torch_dtype_to_jnp(dtype))


@_op("aten.type_as.default")
def _type_as(x, other):
    return x.astype(other.dtype)


@_op("aten.dropout.default", "aten.native_dropout.default")
def _dropout(x, p, train=None):
    # exported in eval mode; if a train-mode graph slips through, dropout
    # is identity (documented contract)
    return x


@_op("aten.repeat.default")
def _repeat(x, repeats):
    return jnp.tile(x, [int(r) for r in repeats])


@_op("aten.flatten.using_ints")
def _flatten(x, start_dim=0, end_dim=-1):
    end = end_dim if end_dim >= 0 else x.ndim + end_dim
    shape = x.shape[:start_dim] + (-1,) + x.shape[end + 1:]
    return jnp.reshape(x, shape)


@_op("aten.constant_pad_nd.default")
def _constant_pad_nd(x, pad, value=0.0):
    # torch pad: last dim first, (l, r) pairs
    cfg = [(0, 0, 0)] * x.ndim
    for i in range(len(pad) // 2):
        cfg[x.ndim - 1 - i] = (pad[2 * i], pad[2 * i + 1], 0)
    return lax.pad(x, jnp.asarray(value, x.dtype), cfg)


def _getitem(obj, idx):
    return operator.getitem(obj, idx)


# ----------------------------------------------------------- converter

class ConvertedModule:
    """A torch module lowered to a pure JAX callable.

    ``fn(params, buffers, *inputs)`` where params/buffers are dicts keyed
    by torch FQN. Outputs follow the module's flattened output order
    (single tensor unwrapped)."""

    def __init__(self, graph_module, input_specs, output_specs,
                 params: Dict[str, jnp.ndarray],
                 buffers: Dict[str, jnp.ndarray],
                 constants: Dict[str, jnp.ndarray],
                 n_user_inputs: int,
                 input_shapes: List[Tuple]):
        self.gm = graph_module
        self.input_specs = input_specs
        self.output_specs = output_specs
        self.params = params
        self.buffers = buffers
        self.constants = constants
        self.n_user_inputs = n_user_inputs
        self.input_shapes = input_shapes

    def __call__(self, params: Dict[str, Any], buffers: Dict[str, Any],
                 *user_inputs):
        from torch.export.graph_signature import InputKind, OutputKind

        env: Dict[str, Any] = {}
        it_user = iter(user_inputs)
        placeholders = [n for n in self.gm.graph.nodes
                        if n.op == "placeholder"]
        for node, spec in zip(placeholders, self.input_specs):
            if spec.kind == InputKind.PARAMETER:
                env[node.name] = params[spec.target]
            elif spec.kind == InputKind.BUFFER:
                env[node.name] = buffers[spec.target]
            elif spec.kind == InputKind.CONSTANT_TENSOR:
                env[node.name] = self.constants[spec.target]
            elif spec.kind == InputKind.USER_INPUT:
                env[node.name] = next(it_user)
            else:
                raise NotImplementedError(f"input kind {spec.kind}")

        def resolve(a):
            import torch.fx
            if isinstance(a, torch.fx.Node):
                return env[a.name]
            if isinstance(a, (list, tuple)):
                return type(a)(resolve(x) for x in a) \
                    if not isinstance(a, tuple) else tuple(resolve(x)
                                                           for x in a)
            return a

        import torch.fx  # noqa: F401 — resolve() uses it

        result = None
        for node in self.gm.graph.nodes:
            if node.op == "placeholder":
                continue
            if node.op == "output":
                result = resolve(node.args[0])
                break
            if node.op != "call_function":
                raise NotImplementedError(f"fx node op {node.op}")
            args = [resolve(a) for a in node.args]
            kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            target = node.target
            if target is operator.getitem:
                env[node.name] = _getitem(*args, **kwargs)
                continue
            # symbolic-shape arithmetic (dynamic batch dim) lowers to plain
            # operator/math calls on python ints
            if getattr(target, "__module__", None) in ("operator",
                                                       "_operator", "math"):
                env[node.name] = target(*args, **kwargs)
                continue
            key = str(target)
            fn = _OPS.get(key)
            if fn is None:
                # try without the overload suffix
                fn = _OPS.get(key.rsplit(".", 1)[0] + ".default")
            if fn is None:
                raise NotImplementedError(
                    f"ATen op {key} has no JAX mapping in the bridge; "
                    "add a handler to zoo_tpu.bridges.fx_bridge._OPS")
            env[node.name] = fn(*args, **kwargs)

        outs = []
        for spec, val in zip(self.output_specs, result):
            if spec.kind == OutputKind.USER_OUTPUT:
                outs.append(val)
        if len(outs) == 1:
            return outs[0]
        return tuple(outs)


def convert_torch_export(module, example_args: Sequence,
                         example_kwargs: Optional[dict] = None
                         ) -> ConvertedModule:
    """Trace ``module`` with torch.export (eval mode) and return a
    :class:`ConvertedModule`."""
    import torch

    was_training = getattr(module, "training", False)
    module = module.eval()
    args = tuple(
        torch.as_tensor(np.asarray(a)) if not isinstance(a, torch.Tensor)
        else a for a in example_args)
    # a shared symbolic batch dim keeps the traced graph batch-size
    # polymorphic (otherwise view/expand bake in the example batch);
    # fall back to a static trace for modules whose forward constrains it
    try:
        batch = torch.export.Dim("batch", min=1)
        dyn = tuple({0: batch} if a.ndim > 0 else None for a in args)
        ep = torch.export.export(module, args,
                                 kwargs=example_kwargs or None,
                                 dynamic_shapes=dyn)
    except Exception:
        ep = torch.export.export(module, args,
                                 kwargs=example_kwargs or None)
    ep = ep.run_decompositions()
    sig = ep.graph_signature
    params = {k: _t2j(v) for k, v in ep.state_dict.items()
              if k in set(sig.parameters)}
    buffers = {k: _t2j(v) for k, v in ep.state_dict.items()
               if k in set(sig.buffers)}
    constants = {k: _t2j(v) for k, v in ep.constants.items()
                 if hasattr(v, "detach")}
    # non-persistent buffers (e.g. HF position_ids) are excluded from
    # state_dict and carried in ep.constants instead
    for k in sig.buffers:
        if k not in buffers and k in constants:
            buffers[k] = constants[k]
    n_user = sum(1 for s in sig.input_specs
                 if s.kind.name == "USER_INPUT")
    if was_training:
        module.train()
    return ConvertedModule(ep.graph_module, sig.input_specs,
                           sig.output_specs, params, buffers, constants,
                           n_user, [tuple(a.shape) for a in args])


# ------------------------------------------------------ KerasNet adapter

from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet  # noqa: E402


class TorchGraphNet(KerasNet):
    """A :class:`ConvertedModule` presented through the KerasNet surface so
    the whole estimator machinery (jitted sharded train step, superbatch
    staging, checkpoints, summaries, triggers) drives a traced torch model
    unchanged. Buffers ride in the ``stats`` subtree, which the train step
    already treats as non-trainable state."""

    def __init__(self, converted: ConvertedModule, output_index: int = 0,
                 name: Optional[str] = None):
        super().__init__(name=name or "torch_graph")
        self.converted = converted
        self.output_index = output_index
        self.params = {"torch_graph": {"w": dict(converted.params),
                                       "stats": dict(converted.buffers)}}
        self._built_shapes = [(None,) + tuple(s[1:])
                              for s in converted.input_shapes]

    @property
    def layers(self):
        return []

    def _input_shapes(self):
        return self._built_shapes

    def _init_params(self, rng, input_shapes):
        return self.params

    def _forward(self, params, inputs, *, training, rng, collect):
        g = params["torch_graph"]
        out = self.converted(g["w"], g.get("stats", {}), *inputs)
        if isinstance(out, tuple):
            out = out[self.output_index]
        return out


def torch_to_graph_net(module, example_inputs: Sequence,
                       output_index: int = 0) -> TorchGraphNet:
    """One-call torch module → trainable KerasNet (traced, weights
    imported)."""
    cm = convert_torch_export(module, example_inputs)
    return TorchGraphNet(cm, output_index=output_index)
