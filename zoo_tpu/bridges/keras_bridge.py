"""tf.keras → zoo_tpu layer bridge (the TF2 ingestion path).

Rebuild of the reference's TF2/Keras training fabric entry point: there a
user ``model_creator`` returns a compiled tf.keras model and the estimator
trains it per-worker under ``MultiWorkerMirroredStrategy``
(``pyzoo/zoo/orca/learn/tf2/estimator.py:86``, ``tf_runner.py:226,316``).
Here the keras model is converted ONCE — layer configs map onto the
zoo_tpu layer zoo, weights are imported — and training runs as the jitted
sharded XLA step; TF never executes in the loop.

Supports keras 2 (tf_keras) and keras 3 Sequential models and
single-chain Functional models built from the common layer set. For
arbitrary TF graphs use the frozen-graph inference path
(:mod:`zoo_tpu.bridges.tf_graph`, the reference's TFNet role).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def _cfg(layer) -> dict:
    return layer.get_config()


def convert_keras_model(kmodel):
    """Return a compiled-weight zoo_tpu Sequential mirroring ``kmodel``."""
    from zoo_tpu.pipeline.api.keras import Sequential

    layers = _layer_list(kmodel)
    model = Sequential(name="keras_bridge")
    zoo_layers: List[Tuple[object, object]] = []  # (zoo_layer, keras_layer)
    for kl in layers:
        z = _convert_layer(kl)
        if z is None:  # structural no-op (InputLayer, Dropout at inference)
            continue
        zoo_layers.append((z, kl))
        model.add(z)

    in_shape = _input_shape(kmodel)
    if model.layers and model.layers[0].batch_input_shape is None:
        model.layers[0].batch_input_shape = (None,) + tuple(in_shape)

    import jax

    model.build(jax.random.PRNGKey(0),
                [(None,) + tuple(in_shape)])
    for z, kl in zoo_layers:
        p = _convert_weights(z, kl)
        if p:
            model.params[model._key_of(z)] = p
    return model


def _layer_list(kmodel):
    layers = list(kmodel.layers)
    # Functional models must be single-chain: every layer feeds the next
    for i, l in enumerate(layers[:-1]):
        out_nodes = getattr(l, "_outbound_nodes", None)
        if out_nodes is not None and len(out_nodes) > 1:
            raise ValueError(
                f"keras layer {l.name} fans out; only Sequential / "
                "single-chain Functional models convert structurally — "
                "use tf_graph frozen-graph ingestion for general graphs")
    return layers


def _input_shape(kmodel):
    shape = None
    try:
        shape = kmodel.input_shape
    except Exception:
        pass
    if shape is None:
        first = kmodel.layers[0]
        shape = getattr(first, "batch_input_shape", None) or \
            getattr(first, "input_shape", None)
    if shape is None:
        raise ValueError("cannot infer keras model input shape; build the "
                         "model (call it once) before conversion")
    if isinstance(shape, list):
        shape = shape[0]
    return tuple(int(s) for s in shape[1:])


def _convert_layer(kl):
    """keras layer → fresh zoo layer (weights imported separately)."""
    from zoo_tpu.pipeline.api.keras import layers as L
    from zoo_tpu.pipeline.api.keras.layers.self_attention import LayerNorm

    t = type(kl).__name__
    c = _cfg(kl)
    if t == "InputLayer":
        return None
    if t == "Dense":
        return L.Dense(c["units"], activation=_act(c.get("activation")),
                       bias=c.get("use_bias", True))
    if t == "Activation":
        return L.Activation(c["activation"])
    if t in ("ReLU",):
        return L.Activation("relu")
    if t == "LeakyReLU":
        return L.LeakyReLU(c.get("negative_slope",
                                 c.get("alpha", 0.3)))
    if t == "Softmax":
        return L.Activation("softmax")
    if t == "ELU":
        return L.ELU(c.get("alpha", 1.0))
    if t == "Dropout":
        return L.Dropout(c["rate"])
    if t == "Flatten":
        return L.Flatten()
    if t == "Reshape":
        return L.Reshape(tuple(c["target_shape"]))
    if t == "Embedding":
        return L.Embedding(c["input_dim"], c["output_dim"])
    if t == "BatchNormalization":
        return L.BatchNormalization(epsilon=c.get("epsilon", 1e-3),
                                    momentum=c.get("momentum", 0.99))
    if t == "LayerNormalization":
        return LayerNorm(epsilon=c.get("epsilon", 1e-3))
    if t in ("Conv1D", "Conv2D"):
        dil = c.get("dilation_rate", 1)
        dil = tuple(dil) if isinstance(dil, (list, tuple)) else (dil,)
        if any(d != 1 for d in dil) or c.get("groups", 1) != 1:
            raise ValueError(
                f"keras {t} with dilation_rate={dil}/groups="
                f"{c.get('groups', 1)} has no exact structural mapping; "
                "use tf_graph frozen-graph ingestion")
    if t == "Conv1D":
        return L.Convolution1D(
            c["filters"], c["kernel_size"][0],
            border_mode=c.get("padding", "valid"),
            subsample_length=c["strides"][0],
            activation=_act(c.get("activation")),
            bias=c.get("use_bias", True))
    if t == "Conv2D":
        return L.Convolution2D(
            c["filters"], c["kernel_size"][0], c["kernel_size"][1],
            border_mode=c.get("padding", "valid"),
            subsample=tuple(c["strides"]),
            dim_ordering="tf",
            activation=_act(c.get("activation")),
            bias=c.get("use_bias", True))
    if t == "MaxPooling2D":
        return L.MaxPooling2D(tuple(c["pool_size"]),
                              tuple(c["strides"] or c["pool_size"]),
                              border_mode=c.get("padding", "valid"),
                              dim_ordering="tf")
    if t == "AveragePooling2D":
        return L.AveragePooling2D(tuple(c["pool_size"]),
                                  strides=tuple(c["strides"]
                                                or c["pool_size"]),
                                  border_mode=c.get("padding", "valid"),
                                  dim_ordering="tf")
    if t == "GlobalAveragePooling2D":
        return L.GlobalAveragePooling2D(dim_ordering="tf")
    if t == "GlobalMaxPooling2D":
        return L.GlobalMaxPooling2D(dim_ordering="tf")
    if t == "MaxPooling1D":
        return L.MaxPooling1D(c["pool_size"], c.get("strides"))
    if t == "GlobalAveragePooling1D":
        return L.GlobalAveragePooling1D()
    if t == "GlobalMaxPooling1D":
        return L.GlobalMaxPooling1D()
    if t == "LSTM":
        return L.LSTM(c["units"],
                      activation=_act(c.get("activation")) or "tanh",
                      inner_activation=_act(
                          c.get("recurrent_activation")) or "sigmoid",
                      return_sequences=c.get("return_sequences", False))
    if t == "GRU":
        if c.get("reset_after", True):
            raise ValueError(
                "keras GRU(reset_after=True) applies the reset gate after "
                "the recurrent matmul, which zoo_tpu's classic GRU cannot "
                "reproduce exactly; rebuild with reset_after=False")
        return L.GRU(c["units"],
                     activation=_act(c.get("activation")) or "tanh",
                     inner_activation=_act(
                         c.get("recurrent_activation")) or "sigmoid",
                     return_sequences=c.get("return_sequences", False))
    raise ValueError(
        f"keras layer {t} has no structural mapping; use "
        "zoo_tpu.bridges.tf_graph for frozen-graph ingestion")


def _act(a) -> Optional[str]:
    if a is None or a == "linear":
        return None
    if isinstance(a, str):
        return a
    return getattr(a, "__name__", None)


def _convert_weights(z, kl) -> dict:
    """keras layer weights → zoo param dict (layouts already agree: Dense
    (in,out), Conv HWIO, LSTM gates i,f,c,o / GRU z,r,h)."""
    import jax.numpy as jnp

    t = type(kl).__name__
    w = [np.asarray(v) for v in kl.get_weights()]
    if not w:
        return {}
    if t == "Dense" or t.startswith("Conv"):
        p = {"W": jnp.asarray(w[0])}
        if len(w) > 1:
            p["b"] = jnp.asarray(w[1])
        return p
    if t == "Embedding":
        return {"E": jnp.asarray(w[0])}
    if t == "BatchNormalization":
        gamma, beta, mean, var = w
        return {"gamma": jnp.asarray(gamma), "beta": jnp.asarray(beta),
                "stats": {"mean": jnp.asarray(mean),
                          "var": jnp.asarray(var)}}
    if t == "LayerNormalization":
        return {"gamma": jnp.asarray(w[0]), "beta": jnp.asarray(w[1])}
    if t in ("LSTM", "GRU"):
        kernel, recurrent, bias = (w + [None])[:3]
        p = {"W": jnp.asarray(kernel), "U": jnp.asarray(recurrent)}
        if bias is not None:
            b = np.asarray(bias)
            if b.ndim == 2:  # keras GRU reset_after bias (2, 3h) — rejected
                b = b.sum(axis=0)
            p["b"] = jnp.asarray(b)
        return p
    return {}
