"""Loader for the C++ native runtime (``native/zoo_native.cc``).

Compiles the shared library on first use with the in-image g++ (no
pybind11 — plain C ABI + ctypes, as the environment prescribes), caching
the .so under ``build/`` keyed by a source hash. Everything that uses it
(``zoo_tpu.orca.data.tfrecord``, ``zoo_tpu.orca.data.cache``) carries a
pure-Python fallback, so :func:`load` returning ``None`` degrades
gracefully rather than failing.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger("zoo_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "zoo_native.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")

_lib = None
_lib_tried = False


def _annotate(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.zoo_crc32c.restype = ctypes.c_uint32
    lib.zoo_crc32c.argtypes = [u8p, ctypes.c_uint64]
    lib.zoo_tfr_reader_open.restype = ctypes.c_void_p
    lib.zoo_tfr_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.zoo_tfr_reader_next.restype = ctypes.c_int64
    lib.zoo_tfr_reader_next.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(u8p)]
    lib.zoo_tfr_reader_close.restype = None
    lib.zoo_tfr_reader_close.argtypes = [ctypes.c_void_p]
    lib.zoo_tfr_writer_open.restype = ctypes.c_void_p
    lib.zoo_tfr_writer_open.argtypes = [ctypes.c_char_p]
    lib.zoo_tfr_writer_write.restype = ctypes.c_int
    lib.zoo_tfr_writer_write.argtypes = [ctypes.c_void_p, u8p,
                                         ctypes.c_uint64]
    lib.zoo_tfr_writer_close.restype = ctypes.c_int
    lib.zoo_tfr_writer_close.argtypes = [ctypes.c_void_p]
    lib.zoo_cache_create.restype = ctypes.c_void_p
    lib.zoo_cache_create.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.zoo_cache_put.restype = ctypes.c_int64
    lib.zoo_cache_put.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    lib.zoo_cache_len.restype = ctypes.c_int64
    lib.zoo_cache_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.zoo_cache_get.restype = ctypes.c_int64
    lib.zoo_cache_get.argtypes = [ctypes.c_void_p, ctypes.c_int64, u8p,
                                  ctypes.c_uint64]
    lib.zoo_cache_count.restype = ctypes.c_int64
    lib.zoo_cache_count.argtypes = [ctypes.c_void_p]
    lib.zoo_cache_dram_used.restype = ctypes.c_int64
    lib.zoo_cache_dram_used.argtypes = [ctypes.c_void_p]
    lib.zoo_cache_destroy.restype = None
    lib.zoo_cache_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _compile(src: str, out: str) -> bool:
    os.makedirs(os.path.dirname(out), exist_ok=True)
    # Exclusive-create a temp .so then rename: concurrent test workers
    # race to build (same idea as the reference's per-node filelock around
    # `ray start`, raycontext.py:289-303).
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out))
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError) as e:
        logger.warning("native build failed (%s); using Python fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:  # zoo-lint: config-parse
    """Return the native library, building it if needed; None on failure."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("ZOO_TPU_DISABLE_NATIVE"):
        return None
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    so = os.path.join(_BUILD_DIR, f"zoo_native_{digest}.so")
    if not os.path.exists(so) and not _compile(_SRC, so):
        return None
    try:
        _lib = _annotate(ctypes.CDLL(so))
    except OSError as e:
        logger.warning("native load failed (%s); using Python fallbacks", e)
        _lib = None
    return _lib


def available() -> bool:
    return load() is not None
