"""XPlane (.xplane.pb) reader: per-op device-time breakdown without a
TensorFlow/TensorBoard dependency.

Completes the profiling story (SURVEY §5.1: the reference exposes coarse
per-phase timers + codahale ``/metrics``; the rebuild adds ``jax.profiler``
traces via ``KerasNet.set_profile(trace_dir)``): the traces land as XPlane
protobufs, and on a minimal image there is nothing to open them with. This
module parses the protobuf wire format directly (schema:
tensorflow/tsl/profiler/protobuf/xplane.proto) and aggregates device event
durations by HLO op, so `op_breakdown()` answers "where did the step time
go" in-process.

Wire layout (verified against captures from this image's libtpu):
``XSpace.planes=1``; ``XPlane{name=2, lines=3, event_metadata=4,
stat_metadata=5}``; ``XEventMetadata{id=1, name=2, metadata=3,
display_name=4}``; ``XLine{events=4}``; ``XEvent{metadata_id=1,
duration_ps=3, stats=4}``; ``XStat{metadata_id=1, uint64_value=3}``; event
durations may live either inline (field 3) or in a ``device_duration_ps``
stat.
"""

from __future__ import annotations

import re
import struct
from collections import defaultdict
from typing import Dict, Iterator, List, Tuple


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, object]]:
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        elif wt == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, v


def _metadata_map(msg: bytes, name_fields=(4, 2)) -> Dict[int, str]:
    """Decode one {id -> name} metadata map entry; prefers display_name."""
    key, names = None, {}
    for f, v in _fields(msg):
        if f == 1:
            key = v
        elif f == 2 and isinstance(v, bytes):
            for ef, ev in _fields(v):
                if ef in name_fields and isinstance(ev, bytes):
                    names[ef] = ev
    name = b""
    for f in name_fields:
        if names.get(f):
            name = names[f]
            break
    return {key: name.decode(errors="replace")} if key is not None else {}


def device_op_times(path: str, include_async: bool = False
                    ) -> Dict[str, Tuple[float, int]]:
    """Aggregate device event durations by full HLO op text.

    Returns {op_name: (total_ms, count)} for the ``/device:TPU:*`` planes,
    counting ONLY the per-op trace lines (``XLA Ops``; plus ``Async XLA
    Ops`` when ``include_async``). The other lines a real device plane
    carries — ``Steps`` and ``XLA Modules`` span whole training steps,
    host planes carry python-function spans in different units — must not
    be mixed into an op breakdown (they made earlier breakdowns report
    step-length "ops" named by their step number).
    """
    data = open(path, "rb").read()
    out: Dict[str, List] = defaultdict(lambda: [0, 0])
    op_lines = {b"XLA Ops"} | ({b"Async XLA Ops"} if include_async
                               else set())
    for fn, plane in _fields(data):
        if fn != 1 or not isinstance(plane, bytes):
            continue
        name = b""
        event_meta: Dict[int, str] = {}
        stat_meta: Dict[int, str] = {}
        lines = []
        for pf, pv in _fields(plane):
            if pf == 2:
                name = pv
            elif pf == 4 and isinstance(pv, bytes):
                # XEventMetadata: display_name=4, name=2 (3 is the binary
                # `metadata` payload — never a display string)
                event_meta.update(_metadata_map(pv, name_fields=(4, 2)))
            elif pf == 5 and isinstance(pv, bytes):
                stat_meta.update(_metadata_map(pv, name_fields=(2,)))
            elif pf == 3:
                lines.append(pv)
        if b"TPU" not in name and b"GPU" not in name:
            continue
        dur_stat_ids = {k for k, v in stat_meta.items()
                        if v == "device_duration_ps"}
        for line in lines:
            line_name = b""
            display_name = b""
            events = []
            for lf, lv in _fields(line):
                if lf == 2 and isinstance(lv, bytes):
                    line_name = lv
                elif lf == 11 and isinstance(lv, bytes):
                    display_name = lv  # some producers name lines here
                elif lf == 4 and isinstance(lv, bytes):
                    events.append(lv)
            line_name = line_name or display_name
            # GPU planes name per-kernel lines by stream, not "XLA Ops"
            is_stream = (b"GPU" in name
                         and line_name.startswith(b"Stream"))
            if line_name not in op_lines and not is_stream:
                continue
            for lv in events:
                mid, dur = 0, 0
                for ef, ev in _fields(lv):
                    if ef == 1:
                        mid = ev
                    elif ef == 3 and not isinstance(ev, bytes):
                        dur = dur or ev
                    elif ef == 4 and isinstance(ev, bytes):
                        smid, sval = 0, 0
                        for sf, sv in _fields(ev):
                            if sf == 1:
                                smid = sv
                            elif sf == 3 and not isinstance(sv, bytes):
                                sval = sv
                        if smid in dur_stat_ids:
                            dur = sval
                a = out[event_meta.get(mid, str(mid))]
                a[0] += dur
                a[1] += 1
    return {k: (v[0] / 1e9, v[1]) for k, v in out.items()}


_OP_RE = re.compile(r"= \S+? (\w[\w.-]*?)\(")
_KIND_RE = re.compile(r"kind=(k\w+)")


def op_breakdown(path: str, top: int = 20, include_async: bool = False
                 ) -> List[Tuple[str, float, int]]:
    """Group :func:`device_op_times` by op category (fusion kind /
    primitive name); returns [(category, total_ms, count)] sorted by time.

    The practical companion to ``set_profile``: run one profiled fit with
    ``trace_dir=...``, then feed the ``*.xplane.pb`` under
    ``<trace_dir>/plugins/profile/<ts>/`` here to see where device time
    went. ``include_async`` adds the ``Async XLA Ops`` line (async
    collectives / DMA).
    """
    byop: Dict[str, List] = defaultdict(lambda: [0.0, 0])
    for nm, (ms, cnt) in device_op_times(
            path, include_async=include_async).items():
        m = _OP_RE.search(nm)
        key = m.group(1) if m else nm.split(" ")[0][:40]
        root = key.lstrip("%").split(".")[0].split("(")[0]
        if root in ("while", "call", "conditional"):
            # control-flow wrappers span their whole body; their children
            # are traced individually, so counting both double-reports
            continue
        if "fusion" in nm[:80] or "fusion" in key:
            km = _KIND_RE.search(nm)
            if km:
                key = f"fusion/{km.group(1)}"
            else:
                # device planes name fused computations by content
                # ("pad_add_fusion", "convolution_fusion.12"): strip the
                # instance suffix so repeats bucket together
                stem = re.sub(r"[.]\d+$", "",
                              nm.split(" ")[0].lstrip("%"))
                key = f"fusion/{stem[:48]}"
        byop[key][0] += ms
        byop[key][1] += cnt
    rows = sorted(((k, v[0], v[1]) for k, v in byop.items()),
                  key=lambda r: -r[1])
    return rows[:top]
