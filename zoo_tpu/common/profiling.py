"""Training-loop tracing and per-phase step timers.

Rebuild of the reference's tracing/profiling story (SURVEY §5.1): the
serving stack's per-stage ``Timer`` (``serving/engine/Timer.scala:22-60``)
and the BigDL DistriOptimizer's per-iteration wall-clock logging. On TPU
the deep half of the story is XLA's own profiler: :func:`trace` wraps
``jax.profiler.trace`` so a fit/predict window produces a
TensorBoard-viewable XPlane trace (op-level HLO timing, HBM usage), which
the reference has no equivalent of.

``StepProfiler`` is the host-side half: named-phase wall-clock stats
(data-wait vs device-step vs eval) with per-epoch reset, pushed as
scalars into the model's ``TrainSummary`` so profiles land next to Loss/
Throughput in TensorBoard. Enabling it makes the train loop synchronize
on every step (``block_until_ready``) — that is the point (accurate step
times), but it costs dispatch overlap, so it is opt-in via
``model.set_profile(...)``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

from zoo_tpu.obs.metrics import StatTimer, histogram

# PhaseTimer and serving's StageTimer were copy-pasted twins of the
# reference's Timer.scala; the one implementation now lives in
# zoo_tpu.obs. The old name stays importable from here.
PhaseTimer = StatTimer

_phase_seconds = histogram(
    "zoo_step_phase_seconds",
    "Training-loop per-phase wall time (data wait / reshard / step / eval)",
    labels=("phase",))


class StepProfiler:
    """Named-phase wall-clock profiler for the training loop.

    Phases used by ``KerasNet.fit``: ``data`` (host wait on the staged
    input pipeline), ``reshard`` (device-side sub-batch re-placement on
    the superbatch path), ``step`` (jitted train step; synced when
    ``sync=True``), ``eval`` (validation pass, when validation_data is
    given). Arbitrary extra phases are fine. ``sync=False`` skips the
    per-step ``block_until_ready`` — cheaper, but ``step`` then measures
    dispatch, not device time.
    """

    def __init__(self, trace_dir: Optional[str] = None,
                 trace_epochs: int = 1, sync: bool = True):
        self.timers: Dict[str, PhaseTimer] = {}       # current epoch
        self.cumulative: Dict[str, PhaseTimer] = {}   # whole run
        self.trace_dir = trace_dir
        self.trace_epochs = int(trace_epochs)
        self.sync = sync
        self._epoch = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record(self, name: str, dt: float):
        self.timers.setdefault(name, PhaseTimer()).record(dt)
        cum = self.cumulative.get(name)
        if cum is None:
            # the cumulative timer mirrors into the shared registry so
            # phase times show up on /metrics next to serving/checkpoint/
            # retry stats, not only in this profiler's TensorBoard scalars
            cum = self.cumulative[name] = PhaseTimer(
                histogram=_phase_seconds.labels(phase=name))
        cum.record(dt)

    def timed_iter(self, it: Iterator, name: str = "data") -> Iterator:
        """Yield from ``it`` recording the host wait per item."""
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self.record(name, time.perf_counter() - t0)
            yield item

    @contextlib.contextmanager
    def epoch_trace(self):
        """XLA profiler capture for the first ``trace_epochs`` epochs when
        ``trace_dir`` is set; no-op afterwards (traces are large). Also
        resets the per-epoch timers so an aborted previous epoch cannot
        leak partial timings into this one."""
        self.timers = {}
        self._epoch += 1
        if self.trace_dir and self._epoch <= self.trace_epochs:
            import jax

            with jax.profiler.trace(self.trace_dir):
                yield
        else:
            yield

    def stats(self) -> Dict[str, Dict[str, float]]:
        """Whole-run per-phase stats (survives epoch resets)."""
        return {name: t.stats() for name, t in self.cumulative.items()}

    def epoch_scalars(self) -> Dict[str, float]:
        """avg-ms per phase for the epoch, then reset the epoch counters
        (cumulative stats keep accruing for :meth:`stats`)."""
        out = {f"{name.capitalize()}TimeMs": t.stats()["avg_ms"]
               for name, t in self.timers.items()}
        self.timers = {}
        return out


@contextlib.contextmanager
def trace(log_dir: str):
    """Standalone XLA profiler window (``jax.profiler.trace``): wrap any
    region — a predict burst, a serving soak — and open the resulting
    ``plugins/profile`` in TensorBoard."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
