"""Global configuration flags and the per-process runtime context.

Rebuild of the reference's ``ZooContext`` / ``OrcaContextMeta`` class-property
config registry (reference: ``pyzoo/zoo/common/nncontext.py:269-313`` and
``pyzoo/zoo/orca/common.py:21-134``): a handful of ergonomic process-global
knobs, plus a ``RuntimeContext`` that owns what the reference's SparkContext +
BigDL Engine owned — here, the JAX platform, the device list, and the
``jax.sharding.Mesh`` used by every Estimator.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import contextlib
import threading
from typing import Optional

logger = logging.getLogger("zoo_tpu")


class _ClassPropertyMeta(type):
    """Metaclass providing validated class-level properties (the reference
    uses the same trick in ``OrcaContextMeta``, ``orca/common.py:21``)."""

    _log_output = False
    _pandas_read_backend = "pandas"
    _serialize_data_creator = False
    _shard_size = None
    _train_data_store = "DRAM"
    _eager_mode = True
    _debug_nans = False

    @property
    def log_output(cls) -> bool:
        """Whether worker subprocess logs are echoed to the driver process
        (reference semantics: ``OrcaContextMeta.log_output``)."""
        return cls._log_output

    @log_output.setter
    def log_output(cls, value: bool):
        _ClassPropertyMeta._log_output = bool(value)

    @property
    def pandas_read_backend(cls) -> str:
        """"pandas" or "arrow" — backend for ``zoo_tpu.orca.data.pandas.read_csv``
        (reference: ``OrcaContextMeta.pandas_read_backend``)."""
        return cls._pandas_read_backend

    @pandas_read_backend.setter
    def pandas_read_backend(cls, value: str):
        value = value.lower()
        if value not in ("pandas", "arrow"):
            raise ValueError(
                "pandas_read_backend must be 'pandas' or 'arrow', got " + value)
        _ClassPropertyMeta._pandas_read_backend = value

    @property
    def serialize_data_creator(cls) -> bool:
        """Serialize dataset creation across workers with a file lock
        (reference: ``OrcaContextMeta.serialize_data_creator``)."""
        return cls._serialize_data_creator

    @serialize_data_creator.setter
    def serialize_data_creator(cls, value: bool):
        _ClassPropertyMeta._serialize_data_creator = bool(value)

    @property
    def shard_size(cls) -> Optional[int]:
        """Target rows per XShards partition when converting tabular data
        (reference: ``OrcaContextMeta._shard_size``)."""
        return cls._shard_size

    @shard_size.setter
    def shard_size(cls, value: Optional[int]):
        if value is not None and int(value) <= 0:
            raise ValueError("shard_size must be positive or None")
        _ClassPropertyMeta._shard_size = None if value is None else int(value)

    @property
    def train_data_store(cls) -> str:
        """Memory tier for cached training data: DRAM | DISK_n
        (reference tiers DRAM/PMEM/DIRECT/DISK_n, ``orca/common.py:86-103``;
        PMEM maps to host-RAM+SSD tiering on TPU VMs — see
        ``zoo_tpu.data.cache``)."""
        return cls._train_data_store

    @train_data_store.setter
    def train_data_store(cls, value: str):
        v = value.upper()
        if v != "DRAM" and not v.startswith("DISK"):
            raise ValueError("train_data_store must be 'DRAM' or 'DISK_n'")
        _ClassPropertyMeta._train_data_store = v

    @property
    def eager_mode(cls) -> bool:
        """Whether XShards transforms execute eagerly (reference:
        ``SparkXShards`` eager-mode caching, ``orca/data/shard.py:129``)."""
        return cls._eager_mode

    @eager_mode.setter
    def eager_mode(cls, value: bool):
        _ClassPropertyMeta._eager_mode = bool(value)

    @property
    def debug_nans(cls) -> bool:
        """NaN-check/debug mode (SURVEY §5.2 rebuild commitment — the
        reference has no sanitizers; JAX purity plus this flag carry the
        role). When True: ``jax.config.jax_debug_nans`` is enabled (XLA
        re-runs the op that produced a NaN un-jitted and raises at the
        exact primitive) and every ``fit`` asserts per-epoch losses are
        finite, so divergence fails loudly at the step that caused it."""
        return cls._debug_nans

    @debug_nans.setter
    def debug_nans(cls, value: bool):
        import jax

        _ClassPropertyMeta._debug_nans = bool(value)
        jax.config.update("jax_debug_nans", bool(value))


class ZooContext(metaclass=_ClassPropertyMeta):
    """Process-global configuration knobs (set as class attributes)."""


@dataclasses.dataclass
class RuntimeContext:
    """What ``init_orca_context`` returns: the live JAX runtime handle.

    Replaces the reference's SparkContext + BigDL Engine + RayContext trio
    (``orca/common.py:161``): everything an Estimator needs to place and run
    a jitted step — the device list, the global mesh, and host-side worker
    parallelism for input pipelines.
    """

    cluster_mode: str
    platform: str
    devices: tuple
    mesh: "object"           # jax.sharding.Mesh
    num_processes: int       # jax process count (multi-host)
    process_index: int
    cores: int               # host-side data-worker parallelism
    extra: dict = dataclasses.field(default_factory=dict)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def axis_size(self, name: str) -> int:
        return self.mesh.shape.get(name, 1)


_lock = threading.Lock()
_runtime_context: Optional[RuntimeContext] = None
_thread_ctx = threading.local()


def _set_runtime_context(ctx: Optional[RuntimeContext]):
    global _runtime_context
    with _lock:
        _runtime_context = ctx


@contextlib.contextmanager
def runtime_context_scope(ctx: RuntimeContext):
    """Thread-local RuntimeContext override: code in this thread sees
    ``ctx`` from :func:`get_runtime_context` while the scope is active.

    The concurrent-AutoML mechanism (SURVEY §7.4 #6): each trial thread
    runs under its own sub-mesh context, so k trials train on k disjoint
    device groups at once — the TPU-native form of Ray Tune's
    resources_per_trial packing
    (reference ``automl/search/ray_tune_search_engine.py:64-103``)."""
    prev = getattr(_thread_ctx, "override", None)
    _thread_ctx.override = ctx
    try:
        yield ctx
    finally:
        _thread_ctx.override = prev


def get_runtime_context(required: bool = True) -> Optional[RuntimeContext]:
    """Current :class:`RuntimeContext`, or raise if ``init_orca_context`` has
    not been called (mirrors the reference's implicit ``getOrCreate`` use of
    SparkContext). A thread-local override (``runtime_context_scope``)
    wins over the process-global context."""
    override = getattr(_thread_ctx, "override", None)
    if override is not None:
        return override
    if _runtime_context is None and required:
        raise RuntimeError(
            "No runtime context. Call zoo_tpu.orca.init_orca_context() first.")
    return _runtime_context


def default_cores() -> int:  # zoo-lint: config-parse
    env = os.environ.get("ZOO_NUM_CORES")
    if env:
        return int(env)
    return max(1, os.cpu_count() or 1)
