"""nncontext compatibility layer (reference:
``pyzoo/zoo/common/nncontext.py:31,56,335`` — ``init_spark_on_local`` /
``init_spark_on_yarn`` / ``init_nncontext`` returned a SparkContext with
the BigDL engine initialized).

There is no Spark here; each entry point boots the TPU runtime context
instead (the object whose lifecycle matches the SparkContext's role:
created once, carries the cluster/mesh handles, torn down at exit).
Reference scripts that do ``sc = init_nncontext()`` and only thread
``sc`` through to zoo APIs run unmodified — every zoo_tpu API reads the
process-global context and ignores a passed ``sc``.
"""

from __future__ import annotations

import warnings
from typing import Optional

from zoo_tpu.orca.common import init_orca_context


def init_nncontext(conf=None, spark_log_level: str = "WARN",
                   redirect_spark_log: bool = True, **kwargs):
    """reference ``init_nncontext:335``; returns the runtime context."""
    return init_orca_context(cluster_mode="local")


def init_spark_on_local(cores=2, conf=None, python_location=None,
                        spark_log_level: str = "WARN", **kwargs):
    """reference ``init_spark_on_local:31``; ``cores`` sizes the host
    input-pipeline pool."""
    return init_orca_context(cluster_mode="local",
                             cores=None if cores in ("*", None)
                             else int(cores))


def init_spark_on_yarn(hadoop_conf=None, conda_name: Optional[str] = None,
                       num_executors: int = 1, executor_cores: int = 2,
                       executor_memory: str = "2g", **kwargs):
    """reference ``init_spark_on_yarn:56``. There is no YARN on a TPU
    pod; the nearest launch story is one process per TPU host (see
    ``scripts/run_tpu_pod.sh`` / ``zoo_tpu.orca.bootstrap``)."""
    warnings.warn(
        "init_spark_on_yarn: no YARN on TPU — starting the multi-host "
        "JAX runtime instead (num_executors maps to num_nodes); launch "
        "one process per host via scripts/run_tpu_pod.sh or "
        "python -m zoo_tpu.orca.bootstrap", stacklevel=2)
    return init_orca_context(cluster_mode="tpu",
                             num_nodes=int(num_executors),
                             cores=int(executor_cores))
