"""Environment doctor (the reference SparkRunner's env-check role).

The reference's ``SparkRunner``/``init_spark_on_yarn`` path validated
the launch environment (JVM presence, conda archive, env vars) before
booting executors (``pyzoo/zoo/util/spark.py``). The TPU-native
launch has its own preflight surface: JAX platform + device visibility,
mesh-axis math, multi-process coordination variables, the native IO
library, and the optional frontend stacks.

``python -m zoo_tpu.common.envcheck`` prints the report and exits
non-zero when a REQUIRED item fails (the supervisor can gate worker
launch on it).
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple


def collect() -> List[Tuple[str, bool, str]]:  # zoo-lint: config-parse
    """(name, ok, detail) triples; ok=False on required-item failure."""
    out: List[Tuple[str, bool, str]] = []
    out.append(("python", True, sys.version.split()[0]))

    try:
        import jax
        devs = jax.devices()
        kinds = {getattr(d, "device_kind", "?") for d in devs}
        out.append(("jax", True,
                    f"{jax.__version__} backend={jax.default_backend()} "
                    f"devices={len(devs)} ({', '.join(sorted(kinds))})"))
        out.append(("multiprocess", True,
                    f"process {jax.process_index()}/{jax.process_count()}"))
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        out.append(("jax", False, f"devices unavailable: {e!r}"))

    coord = os.environ.get("ZOO_COORDINATOR_ADDRESS")
    if coord:
        world = os.environ.get("ZOO_NUM_PROCESSES")
        rank = os.environ.get("ZOO_PROCESS_ID")
        # init_orca_context reads all three unconditionally — a partial
        # launcher config must FAIL the preflight, not pass as healthy
        ok = world is not None and rank is not None
        out.append(("coordinator", ok,
                    f"{coord} (world {world}, rank {rank})"
                    + ("" if ok else
                       " — ZOO_NUM_PROCESSES/ZOO_PROCESS_ID missing")))

    try:
        from zoo_tpu.common.context import get_runtime_context
        ctx = get_runtime_context(required=False)
        if ctx is not None:
            out.append(("orca context", True,
                        f"mode={ctx.cluster_mode} mesh="
                        f"{dict(ctx.mesh.shape)}"))
        else:
            out.append(("orca context", True,
                        "not initialized (init_orca_context())"))
    except Exception as e:  # noqa: BLE001
        out.append(("orca context", False, repr(e)))

    # native lib is OPTIONAL by design (documented python fallbacks);
    # None and an exception are the same condition — report, never fail
    try:
        from zoo_tpu import native as loader
        lib = loader.load()
        out.append(("native IO (zoo_native)", True,
                    "loaded" if lib is not None else
                    "python fallback (TFRecord CRC + tiered cache run "
                    "in python)"))
    except Exception as e:  # noqa: BLE001
        out.append(("native IO (zoo_native)", True,
                    f"python fallback ({e.__class__.__name__})"))

    for mod, required in (("flax", False), ("optax", True),
                          ("orbax.checkpoint", False),
                          ("tensorflow", False), ("torch", False),
                          ("pandas", True), ("pyarrow", False)):
        try:
            import importlib
            m = importlib.import_module(mod)  # leaf module, not package
            out.append((mod, True, getattr(m, "__version__", "ok")))
        except ImportError:
            out.append((mod, not required, "not installed"
                        + (" (REQUIRED)" if required else " (optional)")))
    return out


def main(argv=None) -> int:
    rows = collect()
    width = max(len(n) for n, _, _ in rows)
    ok_all = True
    for name, ok, detail in rows:
        mark = "ok " if ok else "FAIL"
        ok_all &= ok
        print(f"[{mark}] {name:<{width}}  {detail}")
    return 0 if ok_all else 1


if __name__ == "__main__":
    sys.exit(main())
