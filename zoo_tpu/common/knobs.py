# zoo-lint: jax-free
# zoo-lint: config-parse
"""The central ``ZOO_*`` knob registry.

Fourteen PRs of growth accreted ~100 environment knobs, each parsed at
its read site and documented (or not) by hand in whichever doc page the
PR touched. This module is the single declarative source of truth the
``zoo-lint`` knob-contract pass (:mod:`zoo_tpu.analysis.knob_pass`)
checks the tree against:

* every ``ZOO_*`` name read anywhere in ``zoo_tpu/`` / ``scripts/`` /
  ``bench.py`` must be registered here (rule ``KNOB-UNDECLARED``);
* every registered knob must still be read somewhere (``KNOB-DEAD``);
* every non-``internal`` knob must appear in its owning doc page
  (``KNOB-UNDOCUMENTED``), and the marked knob tables in
  docs/data_plane.md, docs/serving_ha.md, docs/llm_serving.md and
  docs/fault_tolerance.md are *generated* from this registry
  (``KNOB-DOC-DRIFT``; ``scripts/zoo_lint.py --fix-docs`` rewrites
  them).

Registration is metadata-first: most read sites keep their existing
parse helpers (``env_int``/``env_float`` from
:mod:`zoo_tpu.util.resilience`, or a ``# zoo-lint: config-parse``
annotated constructor). For knobs whose *default* must be defined in
exactly one place across modules (the PR 7 "env < spec < kwargs"
promise — ``ZOO_LLM_SPEC_K`` and ``ZOO_LLM_SAMPLING`` used to default
independently in the model and the engine), call :func:`value`, which
parses the environment with the registered type and default.

stdlib-only and jax-free: the lint runner imports this module, and the
lint runner itself is asserted to never pull in jax.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["Knob", "KNOBS", "get", "value", "all_knobs",
           "knobs_for_table", "render_table", "TABLE_DOCS"]

logger = logging.getLogger(__name__)

_TYPES = ("int", "float", "bool", "str")

#: docs whose ZOO_* knob tables are generated from this registry (the
#: marked regions ``<!-- zoo-knob-table:<group> begin/end -->``)
TABLE_DOCS = ("docs/data_plane.md", "docs/serving_ha.md",
              "docs/llm_serving.md", "docs/fault_tolerance.md",
              "docs/disaggregated_serving.md", "docs/multitenancy.md")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered ``ZOO_*`` environment knob.

    ``doc`` is the owning documentation page (repo-relative); the knob's
    name must appear there. ``table`` places the knob in that page's
    generated knob table (only pages in :data:`TABLE_DOCS` carry one);
    ``also`` cross-lists it in other pages' generated tables — an
    entry is ``(doc, table)`` or ``(doc, table, help)`` when the
    cross-listing needs page-specific semantics (e.g. the shard-plane
    vs serving-plane reading of ``ZOO_WIRE_CRC``). ``internal`` knobs are set by the platform itself (worker env
    wiring, test rigs) and are exempt from the doc requirement — the
    justification lives in ``help``. ``show`` overrides how the default
    renders in doc tables (e.g. ``unset (greedy)``).
    """

    name: str
    type: str
    default: object
    help: str
    doc: Optional[str] = None
    table: Optional[str] = None
    also: Tuple[Tuple[str, str], ...] = ()
    internal: bool = False
    show: Optional[str] = None

    def read(self, env=None):
        """Parse this knob from ``env`` (default ``os.environ``) with
        the registered type and default — the one shared parse path for
        knobs whose default must not be duplicated across modules.

        Semantics match the tree's conventions: unset/empty → default;
        malformed numerics warn and fall back (the
        ``resilience.env_float`` contract); bools treat
        ``0/false/off/no`` as False and anything else as True.
        """
        if env is None:
            env = os.environ
        raw = env.get(self.name)
        if raw is None or raw == "":
            return self.default
        if self.type == "str":
            return raw
        if self.type == "bool":
            return raw.strip().lower() not in ("0", "false", "off", "no")
        try:
            return int(float(raw)) if self.type == "int" else float(raw)
        except ValueError:
            logger.warning("bad %s=%r; using %s", self.name, raw,
                           self.default)
            return self.default

    @property
    def default_str(self) -> str:
        if self.show is not None:
            return self.show
        if self.default is None:
            return "unset"
        if self.type == "bool":
            return "1" if self.default else "0"
        return str(self.default)


KNOBS: Dict[str, Knob] = {}


def _k(name: str, type: str, default, help: str, doc=None, table=None,
       also=(), internal=False, show=None):
    if name in KNOBS:
        raise ValueError(f"duplicate knob registration {name!r}")
    if type not in _TYPES:
        raise ValueError(f"{name}: unknown knob type {type!r}")
    if not internal and doc is None:
        raise ValueError(f"{name}: non-internal knobs need an owning doc")
    KNOBS[name] = Knob(name, type, default, help, doc, table,
                       tuple(also), internal, show)


def get(name: str) -> Knob:
    """The registered :class:`Knob`; raises ``KeyError`` with a fix
    hint for unregistered names."""
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"{name} is not in the knob registry "
            "(zoo_tpu/common/knobs.py) — register it with its type, "
            "default and owning doc") from None


def value(name: str, env=None):
    """Parse knob ``name`` from the environment (see
    :meth:`Knob.read`). The registry entry is the single owner of the
    knob's default."""
    return get(name).read(env)


def all_knobs() -> Tuple[Knob, ...]:
    return tuple(KNOBS.values())


def knobs_for_table(doc: str, table: str,
                    registry: Optional[Dict[str, Knob]] = None
                    ) -> Tuple[Tuple[Knob, str], ...]:
    """``(knob, help text)`` rows for the
    ``<!-- zoo-knob-table:<table> -->`` region of ``doc`` — owned
    entries first, then cross-listed ones (which may carry a
    page-specific help override), both in registration order."""
    knobs = (registry if registry is not None else KNOBS).values()
    rows = [(k, k.help) for k in knobs
            if k.doc == doc and k.table == table]
    for k in knobs:
        for entry in k.also:
            if tuple(entry[:2]) == (doc, table):
                rows.append((k, entry[2] if len(entry) > 2
                             else k.help))
    return tuple(rows)


def render_table(doc: str, table: str,
                 registry: Optional[Dict[str, Knob]] = None) -> str:
    """The generated markdown rows (no header) for one knob table."""
    return "\n".join(
        f"| `{k.name}` | {k.default_str} | {help} |"
        for k, help in knobs_for_table(doc, table, registry))


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------
# Ordering inside each block is the order rows render in the generated
# doc tables.

_DP = "docs/data_plane.md"
_HA = "docs/serving_ha.md"
_LLM = "docs/llm_serving.md"
_FT = "docs/fault_tolerance.md"
_OBS = "docs/observability.md"
_LC = "docs/model_lifecycle.md"
_MC = "docs/multichip.md"
_DISAGG = "docs/disaggregated_serving.md"
_TEN = "docs/multitenancy.md"

# -- data plane (docs/data_plane.md, generated table "data-plane") ----------
_k("ZOO_SHARD_FETCH_CONCURRENCY", "int", 4,
   "initial threads fanning multi-get chunks across peers", _DP,
   "data-plane")
_k("ZOO_SHARD_POOL_SIZE", "int", 4,
   "idle pooled connections kept per peer", _DP, "data-plane")
_k("ZOO_SHARD_MULTIGET", "int", 32,
   "initial gids per multi-get chunk (retry granularity)", _DP,
   "data-plane")
_k("ZOO_SHARD_LANE", "str", "auto",
   "`auto` probe-and-prefer shm on same host; `tcp` never negotiate "
   "the lane; `shm` force (loud failure otherwise)", _DP, "data-plane")
_k("ZOO_SHARD_WIRE_DTYPE", "str", "off",
   "`bf16`/`int8` narrowing of f32 payloads — LOSSY, opt-in", _DP,
   "data-plane")
_k("ZOO_SHARD_WIRE_COMPRESS", "str", "off",
   "`zlib`/`lz4` per-array compression (kept only when smaller)", _DP,
   "data-plane")
_k("ZOO_SHARD_READAHEAD", "str", "adaptive",
   "`static` pins concurrency/chunk to their initial values", _DP,
   "data-plane")
_k("ZOO_SHARD_SHM_DIR", "str", None,
   "segment directory (falls back to the tempdir)", _DP, "data-plane",
   show="/dev/shm")
_k("ZOO_FEED_STAGING", "str", "auto",
   "rotating staging buffers in the fit feed (`off` to disable; "
   "buffers are allocated off XLA's zero-copy alignment and each is "
   "probed — auto-disabled unless `device_put` provably copies every "
   "one)", _DP, "data-plane")

# -- serving HA (docs/serving_ha.md, generated table "serve") ---------------
_k("ZOO_SERVE_REQUEST_TIMEOUT", "float", 120.0,
   "server reply bound (seconds) for requests with **no** propagated "
   "deadline", _HA, "serve")
_k("ZOO_SERVE_HANDSHAKE_TIMEOUT", "float", 10.0,
   "TLS handshake bound (seconds)", _HA, "serve")
_k("ZOO_SERVE_MAX_QUEUE", "int", 1024,
   "batcher queue bound; `0` = unbounded (no early shed)", _HA, "serve")
_k("ZOO_SERVE_DEDUP_CACHE", "int", 1024,
   "request-id LRU entries; `0` disables dedup", _HA, "serve")
_k("ZOO_SERVE_DEADLINE_MS", "float", 30000.0,
   "HA client default end-to-end budget; `<= 0` = none", _HA, "serve")
_k("ZOO_SERVE_HEDGE", "bool", True,
   "HA client hedging on/off", _HA, "serve")
_k("ZOO_SERVE_HEDGE_DELAY_MS", "float", 0.0,
   "hedge delay; `0` = track p95 (50 ms until warmed)", _HA, "serve")
_k("ZOO_SERVE_BREAKER_RECOVERY", "float", 1.0,
   "client-side per-replica breaker recovery (seconds)", _HA, "serve")
_k("ZOO_SERVE_DRAIN_TIMEOUT_S", "float", 30.0,
   "graceful-drain budget; also the per-replica in-flight budget in "
   "`rolling_update`", _HA, "serve")
_k("ZOO_SERVE_AB_SPLIT", "str", "",
   "client A/B split, e.g. `v2=0.1,v3=0.05` (rest unpinned)", _LC,
   show="—")

# -- LLM serving (docs/llm_serving.md, generated table "llm") ---------------
_k("ZOO_LLM_SLOTS", "int", 8,
   "decode slots (the fixed decode batch shape)", _LLM, "llm")
_k("ZOO_LLM_BLOCK_SIZE", "int", 16, "tokens per KV block", _LLM, "llm")
_k("ZOO_LLM_KV_BLOCKS", "int", 128,
   "pool size (block 0 is reserved)", _LLM, "llm")
_k("ZOO_LLM_MAX_BLOCKS_PER_SEQ", "int", 32,
   "block-table width = context ceiling / block_size", _LLM, "llm")
_k("ZOO_LLM_PREFILL_BUCKETS", "str", "32/128/512",
   "prompt-length buckets (one prefill executable each)", _LLM, "llm",
   show="`32/128/512`")
_k("ZOO_LLM_PREFILL_CHUNK", "int", 0,
   "chunked prefill: feed prompts in N-token slices interleaved with "
   "decode; collapses the bucket census to ONE chunk executable",
   _LLM, "llm", show="0 (off)")
_k("ZOO_LLM_PREFILL_BUDGET", "int", 0,
   "prompt tokens fed per tick when chunking", _LLM, "llm",
   show="chunk size")
_k("ZOO_LLM_OVERLAP", "bool", True,
   "the double-buffered async tick pipeline (0 = the synchronous "
   "pre-PR-10 loop)", _LLM, "llm")
_k("ZOO_LLM_PREFIX_CACHE", "bool", False,
   "content-hash block reuse with copy-on-write (spec: "
   "`prefix_cache=1`): a shared prompt prefix costs its KV blocks "
   "once across streams, prefill starts at the first uncached token",
   _LLM, "llm", show="0 (off)")
_k("ZOO_LLM_KV_DTYPE", "str", "f32",
   "KV cache storage dtype (spec: `kv=`): `bf16` halves cache bytes, "
   "`int8` halves again with per-block-row absmax scales, `auto` "
   "picks int8 on TPU and records the choice", _LLM, "llm",
   show="`f32`")
_k("ZOO_LLM_SPEC_K", "int", 0,
   "speculative decoding (spec: `spec_k=N`): the verify executable "
   "scores up to N drafted tokens per slot per pass; per-request "
   "`spec_k` on the wire caps (never raises) it", _LLM, "llm",
   show="0 (off)")
_k("ZOO_LLM_SPEC_NGRAM", "int", 3,
   "longest suffix n-gram the prompt-lookup drafter matches (spec: "
   "`spec_ngram=N`)", _LLM, "llm")
_k("ZOO_LLM_SAMPLING", "str", "",
   "deployment-default sampling, e.g. "
   "`temperature=0.8,top_k=40,top_p=0.95`; per-request params "
   "override", _LLM, "llm", show="unset (greedy)")
_k("ZOO_LLM_DECODE_IMPL", "str", "auto",
   "decode attention kernel: `flash` (paged Pallas) / `dense` (gather "
   "reference)", _LLM, "llm", show="`auto`")
_k("ZOO_LLM_PREFILL_IMPL", "str", "auto",
   "chunk/verify attention kernel (spec: `prefill_impl=`): `flash` "
   "(paged flash-prefill Pallas) / `dense` (gather anchor)", _LLM,
   "llm", show="`auto`")
_k("ZOO_LLM_DECODE_SPLITS", "int", 4,
   "split-KV parallelism width of the flash-decode kernel", _LLM,
   "llm")
_k("ZOO_LLM_SEED", "int", 0,
   "weight seed for spec-built params", _LLM, "llm")
_k("ZOO_LLM_EOS", "int", None,
   "eos token id (stops a stream early)", _LLM, "llm", show="unset")
_k("ZOO_LLM_MODE", "str", "continuous",
   "`oneshot` = request-level baseline", _LLM, "llm",
   show="`continuous`")
_k("ZOO_LLM_MAX_WAITING", "int", 256,
   "waiting-queue bound (overflow sheds retryable)", _LLM, "llm")
_k("ZOO_LLM_FINISHED_CACHE", "int", 256,
   "finished-stream dedup LRU", _LLM, "llm")
_k("ZOO_LLAMA_FLASH_MIN_SEQ", "int", 512,
   "seq length where `attention_impl=\"auto\"` switches to the Pallas "
   "flash kernel", _LLM, "llm")
_k("ZOO_LLAMA_ATTN_IMPL", "str", "",
   "force `dense`/`flash`/`ring` for A/B runs", _LLM, "llm",
   show="unset")

# -- disaggregated serving (docs/disaggregated_serving.md, table "disagg") --
_k("ZOO_LLM_ROLE", "str", "mixed",
   "replica role (spec: `role=`): `prefill` parks finished prompts "
   "for `kv_migrate` handoff instead of decoding, `decode` adopts "
   "migrated KV, `mixed` does both", _DISAGG, "disagg",
   show="`mixed`")
_k("ZOO_KV_MIGRATE_TTL_MS", "float", 2000.0,
   "how long a parked handoff (prefill side) or a staged adoption "
   "payload (decode side) survives before the sweep frees its blocks",
   _DISAGG, "disagg")
_k("ZOO_KV_MIGRATE_MIN_TOKENS", "int", 16,
   "prompts shorter than this skip the handoff path and run "
   "mixed/decode-local prefill (migration overhead isn't worth it)",
   _DISAGG, "disagg")
_k("ZOO_KV_MIGRATE_CHUNK_BLOCKS", "int", 4,
   "KV blocks packed per `kv_migrate` block frame on the wire",
   _DISAGG, "disagg")
_k("ZOO_ROUTE_PREFIX_WEIGHT", "float", 1.0,
   "routing weight of the prefix-affinity signal (estimated cached "
   "prefix fraction at the seat) in the HA client's plan order",
   _DISAGG, "disagg")
_k("ZOO_ROUTE_OCC_WEIGHT", "float", 0.5,
   "routing weight of decode occupancy (busy slots / total slots "
   "from `llm_stats`) — penalizes loaded seats", _DISAGG, "disagg")

# -- multi-tenant QoS (docs/multitenancy.md, table "tenancy") ---------------
_k("ZOO_QOS", "bool", True,
   "`0` disables the whole tenancy layer even with a tenant config — "
   "admission, fairness, preemption, and cache partitioning all fall "
   "back to the anonymous single-pool behavior", _TEN, "tenancy")
_k("ZOO_TENANT_CONFIG", "str", "",
   "tenant spec: `name:field=..,..;name2:..` with fields `weight` "
   "(fair-share), `class` (priority, lower preempts higher), `rate` "
   "(req/s token bucket, 0 = unlimited), `burst` (bucket depth), `kv` "
   "(live KV-block quota), `slots` (decode-slot quota); empty = "
   "tenancy off", _TEN, "tenancy", show="— (tenancy off)")
_k("ZOO_TENANT_DEFAULT_WEIGHT", "float", 1.0,
   "fair-share weight for unlisted/unlabeled tenants", _TEN, "tenancy")
_k("ZOO_TENANT_DEFAULT_CLASS", "int", 1,
   "priority class for unlisted/unlabeled tenants (lower = more "
   "important)", _TEN, "tenancy")
_k("ZOO_TENANT_DEFAULT_RATE", "float", 0.0,
   "admission rate (req/s) for unlisted/unlabeled tenants (0 = "
   "unlimited)", _TEN, "tenancy")
_k("ZOO_TENANT", "str", None,
   "the tenant id `HAServingClient` stamps on every request it sends "
   "(per-call `tenant=` overrides)", _TEN, "tenancy", show="unset")
_k("ZOO_TENANT_AB_PINS", "str", "",
   "per-tenant version pins for the HA client, `gold=v2,free=v1` — a "
   "pinned tenant's traffic bypasses the fractional "
   "`ZOO_SERVE_AB_SPLIT`", _TEN, "tenancy", show="—")
_k("ZOO_TENANT_BACKOFF_CAP_MS", "float", 2000.0,
   "ceiling on how long the HA client honors a rate-shed "
   "`retry_after_ms` hint before retrying", _TEN, "tenancy")
_k("ZOO_SLO_TENANT_SHED_RATE", "float", None,
   "per-tenant shed-rate ceiling (0..1) the SLO watchdog evaluates "
   "each window, published as `zoo_tenant_burn_rate`", _TEN, "tenancy",
   show="off")

# -- training guard (docs/fault_tolerance.md, generated table "guard") ------
_k("ZOO_GUARD", "bool", True,
   "`0` disables the guard estimators attach", _FT, "guard")
_k("ZOO_GUARD_MAX_SKIPS", "int", 8,
   "consecutive skipped steps before rollback", _FT, "guard")
_k("ZOO_GUARD_SPIKE_FACTOR", "float", 10.0,
   "window-loss multiple over the rolling median that triggers "
   "rollback", _FT, "guard")
_k("ZOO_GUARD_WINDOW", "int", 32,
   "rolling-loss window (boundaries)", _FT, "guard")
_k("ZOO_GUARD_MIN_WINDOW", "int", 5,
   "boundaries before spike detection arms", _FT, "guard")
_k("ZOO_GUARD_ROLLBACK_BUDGET", "int", 3,
   "rollbacks before `TrainingDiverged`", _FT, "guard")
_k("ZOO_GUARD_LR_BACKOFF", "float", 0.5,
   "LR multiplier on rollback resume", _FT, "guard")
_k("ZOO_GUARD_CHECK_EVERY", "int", 1,
   "read the device counter every N boundaries", _FT, "guard")
_k("ZOO_GUARD_MAX_GNORM", "float", None,
   "optional hard gradient-norm ceiling", _FT, "guard", show="off")
_k("ZOO_GUARD_QUARANTINE", "str", None,
   "journal path", _FT, "guard",
   show="`<model_dir>/guard/quarantine.jsonl`")
_k("ZOO_PREEMPT", "str", "SIGTERM",
   "preemption signal name; `none` disables", _FT, "guard",
   show="`SIGTERM`")

# -- gray failure / chaos (docs/fault_tolerance.md, table "gray") -----------
_k("ZOO_WIRE_CRC", "bool", True,
   "CRC trailers on both wire planes (negotiated; `0` disables)", _FT,
   "gray",
   also=((_DP, "data-plane",
          "per-array CRC trailer over the transported bytes (shm "
          "segments included), negotiated in the hello; a mismatch "
          "refetches the chunk instead of decoding garbage "
          "([fault_tolerance.md §6](fault_tolerance.md))"),
         (_HA, "serve",
          "CRC trailer on every serving frame (negotiated per "
          "connection; [fault_tolerance.md §6](fault_tolerance.md))")))
_k("ZOO_EJECT", "bool", True,
   "gray-failure ejection in the HA client", _FT, "gray")
_k("ZOO_EJECT_FACTOR", "float", 3.0,
   "outlier bar: multiple of the healthy-peer median EWMA", _FT,
   "gray")
_k("ZOO_EJECT_MIN_MS", "float", 25.0,
   "absolute floor — nothing under it is an outlier", _FT, "gray")
_k("ZOO_EJECT_MIN_SAMPLES", "int", 5,
   "samples before a seat can be classified", _FT, "gray")
_k("ZOO_EJECT_EWMA_ALPHA", "float", 0.35,
   "latency/error EWMA smoothing", _FT, "gray")
_k("ZOO_EJECT_PROBATION_S", "float", 1.5,
   "sustained degradation before probation → ejected", _FT, "gray")
_k("ZOO_EJECT_PROBE_S", "float", 0.5,
   "canary cadence on probation seats", _FT, "gray")
_k("ZOO_EJECT_READMIT_S", "float", 1.0,
   "ejected → probation backoff base (doubles per consecutive "
   "ejection)", _FT, "gray")
_k("ZOO_EJECT_READMIT_MAX_S", "float", 30.0, "backoff cap", _FT, "gray")
_k("ZOO_EJECT_ERROR_RATE", "float", 0.6,
   "EWMA error rate that triggers probation on its own", _FT, "gray")
_k("ZOO_QUARANTINE_PROBE_S", "float", 5.0,
   "quarantine probe-respawn backoff base", _FT, "gray")
_k("ZOO_QUARANTINE_PROBE_MAX_S", "float", 60.0,
   "probe backoff cap", _FT, "gray")
_k("ZOO_QUARANTINE_HEAL_S", "float", 30.0,
   "probe uptime that re-admits the seat", _FT, "gray")
_k("ZOO_CHAOS_SPEC", "str", "",
   "the storm's fault schedule (grammar above)", _FT, "gray", show="—")
_k("ZOO_CHAOS_SEED", "int", 0,
   "seed resolving every draw in the schedule", _FT, "gray")
_k("ZOO_CHAOS_ALLOW", "bool", False,
   "`1` lets a replica honor wire `chaos` ops", _FT, "gray",
   show="unset")
_k("ZOO_FAULT_SEED", "int", None,
   "deterministic seed for the fault-injection registry's p-draws "
   "(replay-exact chaos schedules)", _FT, "gray", show="unset")
_k("ZOO_HEARTBEAT_FILE", "str", None,
   "per-process heartbeat stamp file (set by the supervisor for every "
   "worker; hung-worker detection reads its age)", _FT, "gray",
   show="unset")
_k("ZOO_HEARTBEAT_INTERVAL", "float", 1.0,
   "heartbeat stamp cadence (seconds)", _FT, "gray")

# -- observability (docs/observability.md, hand-maintained table) -----------
_k("ZOO_TRACE_DIR", "str", None,
   "trace-span JSONL sink directory", _OBS, show="unset (off)")
_k("ZOO_OBS_FLIGHT_CAP", "int", 512,
   "flight ring capacity (0 = recorder off)", _OBS)
_k("ZOO_OBS_POSTMORTEM_DIR", "str", None,
   "bundle dir + arms the continuous spill", _OBS, show="unset")
_k("ZOO_OBS_SNAPSHOT", "str", None,
   "metrics JSONL flushed on drain/exit", _OBS, show="unset")
_k("ZOO_SLO_TTFT_P99_S", "float", None,
   "p99 time-to-first-token ceiling (s)", _OBS, show="off")
_k("ZOO_SLO_INTER_TOKEN_P99_S", "float", None,
   "p99 inter-token gap ceiling (s)", _OBS, show="off")
_k("ZOO_SLO_ERROR_RATE", "float", None,
   "served error-rate ceiling (0..1)", _OBS, show="off")
_k("ZOO_SLO_SHED_RATE", "float", None,
   "admission shed-rate ceiling (0..1)", _OBS, show="off")
_k("ZOO_SLO_KV_UTIL", "float", None,
   "KV-block pool utilization ceiling (0..1)", _OBS, show="off")
_k("ZOO_SLO_SPEC_ACCEPT_FLOOR", "float", None,
   "speculative accept-rate FLOOR (0..1)", _OBS, show="off")
_k("ZOO_SLO_WINDOW_S", "float", 60.0,
   "rolling evaluation window (s)", _OBS)
_k("ZOO_SLO_INTERVAL_S", "float", 5.0, "evaluation period (s)", _OBS)
_k("ZOO_SLO_FAIL_HEALTHZ", "bool", False,
   "1 = an active breach turns `/healthz` 503", _OBS)

# -- lifecycle (docs/model_lifecycle.md, hand-maintained table) -------------
_k("ZOO_REGISTRY_KEEP", "int", 8,
   "registry retention bound (never evicts aliased/pinned versions)",
   _LC)
_k("ZOO_CKPT_KEEP", "int", 5,
   "checkpoint retention bound (steps + `.corrupt` dirs; newest "
   "verified step protected)", _LC)
_k("ZOO_GATE_SAMPLE", "float", 0.25,
   "fraction of live traffic mirrored to the canary", _LC)
_k("ZOO_GATE_WINDOW", "int", 32,
   "mirrored samples needed for a promotion decision", _LC)
_k("ZOO_GATE_MAX_ERROR_RATE", "float", 0.02,
   "canary error-rate bound", _LC)
_k("ZOO_GATE_MAX_LATENCY_RATIO", "float", 3.0,
   "canary p50 / incumbent p50 bound", _LC)
_k("ZOO_GATE_MAX_LOSS_RATIO", "float", 1.2,
   "canary loss / incumbent loss bound", _LC)

# -- multichip (docs/multichip.md, hand-maintained table) -------------------
_k("ZOO_MESH_DATA", "int", None, "mesh `data` axis size", _MC,
   show="unset")
_k("ZOO_MESH_FSDP", "int", None, "mesh `fsdp` axis size", _MC,
   show="unset")
_k("ZOO_MESH_MODEL", "int", None, "mesh `model` axis size", _MC,
   show="unset")
_k("ZOO_MESH_SEQ", "int", None, "mesh `seq` axis size", _MC,
   show="unset")
_k("ZOO_MESH_EXPERT", "int", None, "mesh `expert` axis size", _MC,
   show="unset")
_k("ZOO_MESH_PIPE", "int", None, "mesh `pipe` axis size", _MC,
   show="unset")
_k("ZOO_FUSED_OPTIM", "bool", False,
   "AdamW takes the fused direct-apply path", _MC)
_k("ZOO_LLM_TP", "int", 1,
   "tensor-parallel ways for `llama:*` serving specs", _MC)
_k("ZOO_PLAN", "str", "auto",
   "default sharding plan for `compile()` when no `plan=` is passed "
   "(`auto`, or a registered plan: `transformer`, `pipeline`, `moe`, "
   "...)", _MC)
_k("ZOO_PIPE_MICROBATCHES", "int", 0,
   "GPipe microbatch count for the `pipeline` plan (`0` = one per "
   "pipeline stage)", _MC, show="0 (pipe size)")
_k("ZOO_MOE_CAPACITY", "float", 1.25,
   "default expert capacity factor for MoE dispatch "
   "(`ops/moe.py`; capacity = factor * tokens / experts)", _MC)

# -- serving misc (docs/serving.md / docs/orca.md prose) --------------------
_k("ZOO_MODEL_SECRET", "str", None,
   "model decryption secret for encrypted artifacts",
   "docs/serving.md", show="unset")
_k("ZOO_MODEL_SALT", "str", None,
   "salt paired with `ZOO_MODEL_SECRET`", "docs/serving.md",
   show="unset")
_k("ZOO_MODEL_ENC_MODE", "str", "cbc",
   "cipher mode for encrypted model artifacts (`cbc`/`gcm`)",
   "docs/serving.md")
_k("ZOO_INT8_MODE", "str", "auto",
   "int8 quantization policy for `InferenceModel` loads: `auto` "
   "microbenches int8 vs float and keeps the winner, `force`, `off`",
   "docs/serving.md")
_k("ZOO_SPARK_STAGING", "str", None,
   "staging directory for Spark-bridge ingestion", "docs/orca.md",
   show="unset")
_k("ZOO_NUM_CORES", "int", None,
   "local-mode core count used when no explicit `cores=` is passed",
   "docs/orca.md", show="unset")

# -- kernels ---------------------------------------------------------------
_k("ZOO_PALLAS_FORCE_INTERPRET", "bool", False,
   "run every Pallas kernel under the interpreter (CPU correctness "
   "tests of TPU kernels)", "docs/parallelism.md")
_KN = "docs/kernels.md"
_k("ZOO_CONV_IMPL", "str", "auto",
   "conv2d backend: `auto` (implicit-GEMM Pallas kernel on TPU for "
   "supported shapes, XLA reference elsewhere), `pallas`, `reference`",
   _KN)
_k("ZOO_INT8_MATMUL", "str", "auto",
   "int8 GEMM backend: `auto`/`fused` (one-kernel quantize+dot+"
   "dequant), `unfused` (XLA quantize pass + dequant matmul)", _KN)

# -- internal coordination (set by the platform itself, not operators) ------
_k("ZOO_PROCESS_ID", "int", None, internal=True,
   help="worker rank; set by launch_local_cluster for each worker",
   doc="docs/orca.md")
_k("ZOO_NUM_PROCESSES", "int", None, internal=True,
   help="world size; set by launch_local_cluster for each worker",
   doc="docs/orca.md")
_k("ZOO_COORDINATOR_ADDRESS", "str", None, internal=True,
   help="jax coordination-service address; set by "
        "launch_local_cluster", doc="docs/orca.md")
_k("ZOO_ELASTIC_ATTEMPT", "int", 0, internal=True,
   help="relaunch attempt counter run_elastic stamps into worker env")
_k("ZOO_TPU_DISABLE_NATIVE", "bool", False, internal=True,
   help="kill switch for the optional native acceleration module "
        "(debug/bisect aid)")
