from zoo_tpu.common.context import ZooContext, RuntimeContext, get_runtime_context

__all__ = ["ZooContext", "RuntimeContext", "get_runtime_context"]
