from zoo_tpu.common.context import ZooContext, RuntimeContext, get_runtime_context
from zoo_tpu.common.nncontext import (  # noqa: F401 — reference re-export
    init_nncontext,
    init_spark_on_local,
    init_spark_on_yarn,
)
from zoo_tpu.util.utils import convert_to_safe_path  # noqa: F401


class Sample:
    """reference ``zoo.common.Sample`` (the BigDL sample record): a
    (features, labels) pair of ndarrays. The rebuild's estimators take
    arrays/XShards directly; this record type keeps reference user code
    constructing Samples importable."""

    def __init__(self, features, labels=None):
        import numpy as np
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)

    @classmethod
    def from_ndarray(cls, features, labels=None):
        return cls(features, labels)


__all__ = ["ZooContext", "RuntimeContext", "get_runtime_context",
           "init_nncontext", "init_spark_on_local", "init_spark_on_yarn",
           "Sample", "convert_to_safe_path"]
