from zoo_tpu.common.context import ZooContext, RuntimeContext, get_runtime_context
from zoo_tpu.common.nncontext import (  # noqa: F401 — reference re-export
    init_nncontext,
    init_spark_on_local,
    init_spark_on_yarn,
)

__all__ = ["ZooContext", "RuntimeContext", "get_runtime_context",
           "init_nncontext", "init_spark_on_local", "init_spark_on_yarn"]
