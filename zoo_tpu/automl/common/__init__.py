"""Reference ``zoo.automl.common`` compat (``pyzoo/zoo/automl/common``)."""
