"""Reference ``zoo.automl.common.metrics`` (``automl/common/metrics.py``):
the ``Evaluate``/``Evaluator`` metric dispatch used by legacy AutoML
user code. Shares the forecaster metric table."""

from __future__ import annotations

import numpy as np

from zoo_tpu.chronos.forecaster.base import _EVAL_FNS as _METRICS


class Evaluator:
    """reference ``metrics.py`` ``Evaluator.evaluate(metric, y, yhat)``."""

    @staticmethod
    def evaluate(metric: str, y_true, y_pred, multioutput="raw_values"):
        if multioutput not in (None, "uniform_average", "raw_values"):
            raise ValueError(
                f"multioutput={multioutput!r}: expected None, "
                "'uniform_average' or 'raw_values'")
        metric = metric.lower()
        if metric not in _METRICS:
            raise ValueError(
                f"unknown metric {metric!r}; choose from "
                f"{sorted(_METRICS)}")
        y_true = np.asarray(y_true, np.float64)
        y_pred = np.asarray(y_pred, np.float64)
        if multioutput == "raw_values":
            # sklearn shape semantics (the reference delegates there):
            # one entry per output column, a 1-element array for 1-D.
            if y_true.ndim > 1:
                flat_t = y_true.reshape(-1, y_true.shape[-1])
                flat_p = y_pred.reshape(-1, y_pred.shape[-1])
                return np.asarray(
                    [_METRICS[metric](flat_t[:, i], flat_p[:, i])
                     for i in range(flat_t.shape[-1])])
            return np.asarray(
                [_METRICS[metric](y_true.ravel(), y_pred.ravel())])
        return _METRICS[metric](y_true.ravel(), y_pred.ravel())
