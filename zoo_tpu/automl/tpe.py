"""Tree-structured Parzen Estimator sampler (pure numpy).

Model-based search for the local engine — the role ray.tune's
``search_alg`` plays in the reference
(``pyzoo/zoo/automl/search/ray_tune_search_engine.py:29,151`` passes
bayesopt/skopt/hyperopt searchers into ``tune.run``). Standard TPE
(Bergstra et al., NeurIPS 2011, public algorithm): split observed trials
into good/bad by metric quantile ``gamma``, model each hyperparameter's
density in both groups (Gaussian Parzen windows for numeric dims, count
smoothing for categorical), draw candidates from the good-group model and
keep the candidate maximizing l(x)/g(x).

Grid dimensions are treated as categorical under TPE (a model-based
sampler replaces exhaustive crossing — same semantics as ray.tune, which
rejects grid_search specs under a search_alg).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from zoo_tpu.automl.hp import (
    Choice,
    LogUniform,
    QUniform,
    RandInt,
    Sampler,
    Uniform,
)

__all__ = ["TPESampler"]


class _NumericDim:
    """Parzen model over a bounded numeric dim (log-space for
    LogUniform; rounded/clamped for QUniform/RandInt)."""

    def __init__(self, sampler: Sampler):
        self.sampler = sampler
        self.log = isinstance(sampler, LogUniform)
        self.lo, self.hi = float(sampler.lower), float(sampler.upper)
        if self.log:
            self.lo, self.hi = np.log(self.lo), np.log(self.hi)

    def _transform(self, v: float) -> float:
        return float(np.log(v)) if self.log else float(v)

    def _untransform(self, t: float) -> Any:
        t = float(np.clip(t, self.lo, self.hi))
        v = float(np.exp(t)) if self.log else t
        s = self.sampler
        if isinstance(s, RandInt):
            return int(np.clip(round(v), s.lower, s.upper - 1))
        if isinstance(s, QUniform):
            return type(s.q)(np.clip(np.round(v / s.q) * s.q,
                                     s.lower, s.upper))
        return v

    def _density(self, t: float, obs: np.ndarray) -> float:
        """Parzen mixture of the observations plus one uniform-prior
        kernel over the whole range (keeps densities non-zero)."""
        width = self.hi - self.lo or 1.0
        prior = 1.0 / width
        if len(obs) == 0:
            return prior
        sigma = max(width / np.sqrt(len(obs) + 1), 1e-3 * width)
        z = (t - obs) / sigma
        kernels = np.exp(-0.5 * z * z) / (sigma * np.sqrt(2 * np.pi))
        return float((kernels.sum() + prior) / (len(obs) + 1))

    def propose(self, rng: np.random.RandomState, good: List[Any],
                bad: List[Any], n_candidates: int) -> Any:
        g = np.asarray([self._transform(v) for v in good], float)
        b = np.asarray([self._transform(v) for v in bad], float)
        width = self.hi - self.lo or 1.0
        sigma = max(width / np.sqrt(len(g) + 1), 1e-3 * width)
        cands = []
        for _ in range(n_candidates):
            if len(g) and rng.rand() > 1.0 / (len(g) + 1):
                t = rng.normal(g[rng.randint(len(g))], sigma)
            else:  # the prior kernel
                t = rng.uniform(self.lo, self.hi)
            cands.append(float(np.clip(t, self.lo, self.hi)))
        scores = [self._density(t, g) / self._density(t, b)
                  for t in cands]
        return self._untransform(cands[int(np.argmax(scores))])


class _CategoricalDim:
    def __init__(self, options: List[Any]):
        self.options = list(options)

    def _probs(self, obs: List[Any]) -> np.ndarray:
        counts = np.array([sum(1 for v in obs if v == o)
                           for o in self.options], float)
        return (counts + 1.0) / (counts.sum() + len(self.options))

    def propose(self, rng, good, bad, n_candidates) -> Any:
        pg, pb = self._probs(good), self._probs(bad)
        ratio = pg / pb
        # sample from the good model, keep the best-ratio draw
        draws = rng.choice(len(self.options), size=n_candidates, p=pg)
        best = draws[int(np.argmax(ratio[draws]))]
        return self.options[int(best)]


class TPESampler:
    """``suggest(rng, history)`` → next config.

    ``history`` is a list of ``(config, metric)``; the first
    ``n_startup`` suggestions are random (seeded via ``rng``)."""

    def __init__(self, search_space: Dict[str, Any], mode: str = "min",
                 n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 64):
        # defaults swept on the seeded quadratic+categorical toy: 64
        # candidates/8 startup/gamma .25 beat random 17/20 seeds at a
        # 40-trial budget (n_candidates 24 only won 13/20)
        self.space = dict(search_space)
        self.mode = mode
        self.n_startup = int(n_startup)
        self.gamma = float(gamma)
        self.n_candidates = int(n_candidates)
        self.dims: Dict[str, Any] = {}
        for k, v in self.space.items():
            if isinstance(v, Choice):  # incl. GridSearch
                self.dims[k] = _CategoricalDim(v.options)
            elif isinstance(v, (Uniform, LogUniform, QUniform, RandInt)):
                self.dims[k] = _NumericDim(v)
            # constants fall through (copied verbatim into configs)

    def _random(self, rng) -> Dict[str, Any]:
        return {k: (v.sample(rng) if isinstance(v, Sampler) else v)
                for k, v in self.space.items()}

    def suggest(self, rng: np.random.RandomState,
                history: List[Tuple[Dict[str, Any], float]]
                ) -> Dict[str, Any]:
        done = [(c, m) for c, m in history if np.isfinite(m)]
        if len(done) < self.n_startup or not self.dims:
            return self._random(rng)
        done.sort(key=lambda cm: cm[1], reverse=(self.mode == "max"))
        n_good = max(1, int(np.ceil(self.gamma * len(done))))
        good = [c for c, _ in done[:n_good]]
        bad = [c for c, _ in done[n_good:]] or good
        cfg = {}
        for k, v in self.space.items():
            dim = self.dims.get(k)
            if dim is None:
                cfg[k] = v.sample(rng) if isinstance(v, Sampler) else v
            else:
                cfg[k] = dim.propose(rng, [c[k] for c in good],
                                     [c[k] for c in bad],
                                     self.n_candidates)
        return cfg
