"""Hyperparameter search engines.

Rebuild of the reference's ``SearchEngine`` base
(``pyzoo/zoo/automl/search/base.py``) and ``RayTuneSearchEngine``
(``automl/search/ray_tune_search_engine.py:29``). On a TPU pod trials share
chips, so the default engine runs trials sequentially in-process (each trial
is itself data-parallel over the mesh); a Ray Tune engine is used
automatically when ray is importable — same trial function.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import zlib
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from zoo_tpu.automl.hp import Sampler

logger = logging.getLogger("zoo_tpu.automl")


@dataclasses.dataclass
class Trial:
    trial_id: int
    config: Dict[str, Any]
    metric: float = float("nan")
    artifacts: Dict[str, Any] = dataclasses.field(default_factory=dict)


class SearchEngine:
    """compile() then run(); get_best_trial() (reference base API)."""

    def compile(self, trial_fn: Callable[[Dict], Dict],
                search_space: Dict[str, Any], n_sampling: int = 1,
                metric: str = "mse", mode: str = "min", seed: int = 0):
        raise NotImplementedError

    def run(self) -> List[Trial]:
        raise NotImplementedError

    def get_best_trial(self) -> Trial:
        raise NotImplementedError


def _expand_configs(search_space: Dict[str, Any], n_sampling: int,
                    rng: np.random.RandomState) -> List[Dict[str, Any]]:
    """Grid dimensions are fully crossed; sampled dimensions drawn
    ``n_sampling`` times per grid point (ray.tune semantics)."""
    grid_keys = [k for k, v in search_space.items()
                 if isinstance(v, Sampler) and v.is_grid()]
    grid_values = [search_space[k].grid() for k in grid_keys]
    points = list(itertools.product(*grid_values)) if grid_keys else [()]
    configs = []
    for point in points:
        for _ in range(max(1, n_sampling)):
            cfg = {}
            for k, v in search_space.items():
                if k in grid_keys:
                    cfg[k] = point[grid_keys.index(k)]
                elif isinstance(v, Sampler):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            configs.append(cfg)
    # dedupe pure-grid duplicates when n_sampling > 1 but nothing is sampled
    if grid_keys and not any(isinstance(v, Sampler) and not v.is_grid()
                             for v in search_space.values()):
        seen, uniq = set(), []
        for c in configs:
            key = tuple(sorted((k, repr(v)) for k, v in c.items()))
            if key not in seen:
                seen.add(key)
                uniq.append(c)
        configs = uniq
    return configs


class TrialStopper:
    """Early stop rule for a single trial (reference: ``TrialStopper`` in
    ``ray_tune_search_engine.py`` — metric threshold and/or epoch cap)."""

    def __init__(self, metric_threshold: Optional[float] = None,
                 mode: str = "min", max_steps: Optional[int] = None):
        self.metric_threshold = metric_threshold
        self.mode = mode
        self.max_steps = max_steps

    def __call__(self, step: int, metric: float) -> bool:
        if self.max_steps is not None and step >= self.max_steps:
            return True
        if self.metric_threshold is not None:
            if self.mode == "min" and metric <= self.metric_threshold:
                return True
            if self.mode == "max" and metric >= self.metric_threshold:
                return True
        return False


class ASHAScheduler:
    """Asynchronous Successive Halving (stopping form).

    The trial-scheduler role of the reference's ray.tune ``scheduler=``
    knob (``ray_tune_search_engine.py:151``): trials report per-epoch
    metrics; at each rung (``grace_period * reduction_factor**k``) a
    trial continues only if its metric is in the top ``1/reduction_
    factor`` quantile of results recorded at that rung so far. Thread-
    safe — the local engine runs trials concurrently."""

    def __init__(self, max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3, mode: str = "min"):
        import threading

        self.mode = mode
        self.rf = int(reduction_factor)
        self.rungs: List[int] = []
        r = int(grace_period)
        while r <= int(max_t):
            self.rungs.append(r)
            r *= self.rf
        self._recorded: Dict[int, Dict[int, float]] = \
            {r: {} for r in self.rungs}
        self._lock = threading.Lock()

    def on_result(self, trial_id: int, step: int, metric: float) -> bool:
        """True = stop this trial now."""
        stop = False
        with self._lock:
            for rung in self.rungs:
                if step < rung or trial_id in self._recorded[rung]:
                    continue
                self._recorded[rung][trial_id] = metric
                vals = list(self._recorded[rung].values())
                if len(vals) < self.rf:
                    continue  # too few results to cut anyone
                q = (np.quantile(vals, 1.0 / self.rf)
                     if self.mode == "min"
                     else np.quantile(vals, 1.0 - 1.0 / self.rf))
                survives = metric <= q if self.mode == "min" \
                    else metric >= q
                if not survives:
                    stop = True
        return stop


def _make_search_alg(search_alg, search_space, mode):
    if search_alg in (None, "random", "grid"):
        return None
    if search_alg == "tpe":
        from zoo_tpu.automl.tpe import TPESampler

        return TPESampler(search_space, mode=mode)
    if hasattr(search_alg, "suggest"):
        return search_alg
    raise ValueError(
        f"unknown search_alg {search_alg!r}: use None/'random', 'tpe', "
        "or an object with suggest(rng, history)")


def _make_scheduler(scheduler, mode):
    if scheduler is None:
        return None
    if scheduler == "asha":
        return ASHAScheduler(mode=mode)
    if hasattr(scheduler, "on_result"):
        return scheduler
    raise ValueError(f"unknown scheduler {scheduler!r}: use None, "
                     "'asha', or an object with on_result(id, step, m)")


class LocalSearchEngine(SearchEngine):
    """In-process trials over a thread pool (reference value proposition:
    concurrent Ray Tune trials, ``ray_tune_search_engine.py:29``; XLA
    dispatch releases the GIL so ``n_parallel`` trials genuinely overlap
    on the host while sharing the device).

    ``search_alg``: None/'random' (grid-cross + random draws), 'tpe'
    (model-based, ``automl/tpe.py``), or any object with
    ``suggest(rng, history)``. ``scheduler``: None, 'asha', or any
    object with ``on_result(trial_id, step, metric) -> bool`` — consulted
    through the trial's ``reporter`` callback, so trials whose
    ``trial_fn`` accepts ``reporter`` get early-stopped at rungs."""

    def __init__(self, n_parallel: int = 1,
                 stopper: Optional[TrialStopper] = None,
                 search_alg=None, scheduler=None,
                 partition_devices: bool = False):
        self._trials: List[Trial] = []
        self._mode = "min"
        self._metric = "mse"
        self.n_parallel = max(1, int(n_parallel))
        self.stopper = stopper
        self.search_alg = search_alg
        self.scheduler = scheduler
        # partition the ambient mesh's devices into n_parallel disjoint
        # sub-meshes, one per concurrent trial (SURVEY §7.4 #6 — the
        # TPU-native form of Ray Tune's resources_per_trial packing)
        self.partition_devices = bool(partition_devices)

    def _sub_contexts(self):
        """Split the ambient RuntimeContext's devices into n_parallel
        disjoint data-parallel sub-meshes. Returns [] when there is no
        context or not enough devices to give each trial one."""
        if not self.partition_devices or self.n_parallel < 2:
            return []
        from zoo_tpu.common.context import get_runtime_context
        from zoo_tpu.parallel.mesh import build_mesh

        ctx = get_runtime_context(required=False)
        if ctx is None or len(ctx.devices) < self.n_parallel:
            return []
        # preserve every non-"data" axis size (model/seq/… AND fsdp —
        # a trial sized for ZeRO param sharding must not silently lose
        # it and replicate params per device); only "data" shrinks
        fixed = {name: size for name, size in ctx.mesh.shape.items()
                 if name != "data" and size > 1}
        non_data = int(np.prod(list(fixed.values()))) if fixed else 1
        devs = list(ctx.devices)
        per, rem = divmod(len(devs), self.n_parallel)
        if per % non_data:
            logger.warning(
                "cannot partition %d devices into %d sub-meshes that "
                "keep the ambient non-data axes %s; trials share the "
                "full mesh", len(devs), self.n_parallel, fixed)
            return []
        subs, lo = [], 0
        for g in range(self.n_parallel):
            size = per + (1 if g < rem else 0)
            size -= size % max(non_data, 1)  # keep non-data axes whole
            group = devs[lo:lo + size]
            lo += size
            axis_sizes = dict(fixed)
            axis_sizes["data"] = -1
            subs.append(dataclasses.replace(
                ctx, devices=tuple(group),
                mesh=build_mesh(devices=group, axis_sizes=axis_sizes,
                                axis_names=ctx.mesh.axis_names)))
        if lo < len(devs):
            logger.warning(
                "sub-mesh partition leaves %d of %d devices idle "
                "(group sizes rounded to keep non-data axes %s whole)",
                len(devs) - lo, len(devs), fixed)
        return subs

    def compile(self, trial_fn, search_space, n_sampling=1, metric="mse",
                mode="min", seed=0, search_alg=None, scheduler=None):
        self._rng = np.random.RandomState(seed)
        self._metric, self._mode = metric, mode
        self._trial_fn = trial_fn
        self._alg = _make_search_alg(search_alg or self.search_alg,
                                     search_space, mode)
        self._sched = _make_scheduler(scheduler or self.scheduler, mode)
        if self._alg is None:
            self._configs = _expand_configs(search_space, n_sampling,
                                            self._rng)
        else:
            # model-based: ask/tell loop; budget = n_sampling trials
            self._configs = None
            self._n_trials = max(1, int(n_sampling))
        return self

    def _run_one(self, i: int, cfg: Dict, total: int) -> Trial:
        import inspect

        kwargs = {}
        sig = None
        try:
            sig = inspect.signature(self._trial_fn)
        except (TypeError, ValueError):
            pass
        # only inject a reporter when something actually consumes the
        # per-epoch reports — trial_fns switch to epoch-at-a-time
        # training when given one, which costs an evaluate() per epoch
        if sig is not None and "reporter" in sig.parameters \
                and (self.stopper is not None or self._sched is not None):
            stopper, sched = self.stopper, self._sched

            def reporter(step: int, metric: float) -> bool:
                """Trial calls this per epoch; True means stop early."""
                stop = stopper(step, metric) if stopper is not None \
                    else False
                if sched is not None:
                    stop = sched.on_result(i, step, metric) or stop
                return stop

            kwargs["reporter"] = reporter
        result = self._trial_fn(dict(cfg), **kwargs)
        metric = float(result[self._metric])
        logger.info("trial %d/%d %s=%.5f cfg=%s", i + 1, total,
                    self._metric, metric, cfg)
        return Trial(i, cfg, metric, artifacts=result)

    def run(self) -> List[Trial]:
        if self._alg is not None:
            # sequential ask/tell: each suggestion conditions on every
            # completed trial (the model-based point)
            if self.n_parallel > 1:
                logger.warning(
                    "n_parallel=%d is ignored with a model-based "
                    "search_alg: ask/tell suggestions condition on every "
                    "completed trial, so trials run sequentially",
                    self.n_parallel)
            history: List = []
            self._trials = []
            for i in range(self._n_trials):
                cfg = self._alg.suggest(self._rng, history)
                t = self._run_one(i, cfg, self._n_trials)
                history.append((dict(cfg), t.metric))
                self._trials.append(t)
            return self._trials
        if self.n_parallel == 1:
            self._trials = [self._run_one(i, cfg, len(self._configs))
                            for i, cfg in enumerate(self._configs)]
            return self._trials
        from concurrent.futures import ThreadPoolExecutor

        subs = self._sub_contexts()
        if subs:
            from zoo_tpu.common.context import runtime_context_scope

            import queue as _q
            pool_q: "_q.Queue" = _q.Queue()
            for s in subs:
                pool_q.put(s)

            def submit_one(i, cfg, total):
                sub = pool_q.get()  # lease a sub-mesh for this trial
                try:
                    with runtime_context_scope(sub):
                        return self._run_one(i, cfg, total)
                finally:
                    pool_q.put(sub)
        else:
            submit_one = self._run_one
        with ThreadPoolExecutor(max_workers=self.n_parallel) as pool:
            futures = [pool.submit(submit_one, i, cfg,
                                   len(self._configs))
                       for i, cfg in enumerate(self._configs)]
            self._trials = [f.result() for f in futures]
        return self._trials

    def get_best_trial(self) -> Trial:
        if not self._trials:
            raise RuntimeError("run() first")
        key = (min if self._mode == "min" else max)
        return key(self._trials, key=lambda t: t.metric)


class RayTuneSearchEngine(SearchEngine):  # pragma: no cover - needs ray
    """ray.tune-backed engine (reference:
    ``ray_tune_search_engine.py:29``); selected automatically when ray is
    installed.

    **Untested integration**: ray is not bundled in the dev image, so
    this class has never executed here (docs/chronos.md carries the same
    caveat). The thread-pool ``LocalSearchEngine`` is the tested path."""

    def __init__(self):
        import ray  # noqa: F401  (raises if absent)
        self._engine = LocalSearchEngine()  # trial bookkeeping reuse

    def compile(self, trial_fn, search_space, n_sampling=1, metric="mse",
                mode="min", seed=0):
        import ray  # noqa: F401
        from ray import tune

        space = {}
        for k, v in search_space.items():
            if isinstance(v, Sampler):
                if v.is_grid():
                    space[k] = tune.grid_search(v.grid())
                else:
                    # distinct stream per key — one shared seed would make
                    # every sampled dim draw identical values per trial
                    kseed = (seed + zlib.crc32(k.encode())) % (2 ** 31)
                    space[k] = tune.sample_from(
                        lambda spec, s=v, r=np.random.RandomState(kseed):
                        s.sample(r))
            else:
                space[k] = v
        self._tune_kwargs = dict(config=space, num_samples=n_sampling,
                                 metric=metric, mode=mode)
        self._trial_fn = trial_fn
        self._metric, self._mode = metric, mode
        return self

    def run(self):
        from ray import tune

        def runnable(config):
            out = self._trial_fn(dict(config))
            # only scalars travel through ray metrics; artifacts (live
            # models) are re-materialized in get_best_trial
            tune.report(**{k: v for k, v in out.items()
                           if isinstance(v, (int, float))})

        self._analysis = tune.run(runnable, **self._tune_kwargs)
        return self._analysis

    def get_best_trial(self) -> Trial:
        best = self._analysis.get_best_trial(self._metric, self._mode)
        # re-run the winning config in-process to materialize artifacts
        # (the trained model object cannot ride ray's metric channel)
        result = self._trial_fn(dict(best.config))
        return Trial(0, best.config, float(result[self._metric]),
                     artifacts=result)


def make_search_engine(search_alg=None, scheduler=None,
                       n_parallel: int = 1,
                       partition_devices: Optional[bool] = None
                       ) -> SearchEngine:
    """``n_parallel > 1`` runs that many trials concurrently; by default
    each concurrent trial gets its own disjoint sub-mesh of the ambient
    devices (``partition_devices=False`` to share the full mesh
    instead)."""
    if partition_devices is None:
        partition_devices = n_parallel > 1
    if search_alg is None and scheduler is None and n_parallel == 1:
        try:
            return RayTuneSearchEngine()
        except Exception:
            return LocalSearchEngine()
    # model-based search / ASHA / sub-mesh concurrency are local-engine
    # features; the ray engine would accept tune-native searchers instead
    return LocalSearchEngine(n_parallel=n_parallel,
                             search_alg=search_alg, scheduler=scheduler,
                             partition_devices=partition_devices)
