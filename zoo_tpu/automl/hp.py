"""Search-space DSL (reference: ``pyzoo/zoo/orca/automl/hp.py`` — thin
wrappers over ray.tune sample spaces). Works standalone (local search
engine) and converts to ray.tune spaces when ray is installed.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import numpy as np


class Sampler:
    def sample(self, rng: np.random.RandomState) -> Any:
        raise NotImplementedError

    def grid(self) -> List[Any]:
        raise NotImplementedError("not a grid dimension")

    def is_grid(self) -> bool:
        return False


class Choice(Sampler):
    def __init__(self, options: Sequence):
        self.options = list(options)

    def sample(self, rng):
        return self.options[rng.randint(len(self.options))]


class GridSearch(Choice):
    def is_grid(self):
        return True

    def grid(self):
        return list(self.options)


class Uniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return float(rng.uniform(self.lower, self.upper))


class QUniform(Uniform):
    def __init__(self, lower, upper, q=1):
        super().__init__(lower, upper)
        self.q = q

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return type(self.q)(np.round(v / self.q) * self.q)


class LogUniform(Sampler):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return float(np.exp(rng.uniform(np.log(self.lower),
                                        np.log(self.upper))))


class RandInt(Sampler):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return int(rng.randint(self.lower, self.upper))


def choice(options):
    """reference: ``hp.choice``."""
    return Choice(options)


def grid_search(options):
    """reference: ``hp.grid_search`` — every value is tried."""
    return GridSearch(options)


def uniform(lower, upper):
    return Uniform(lower, upper)


def quniform(lower, upper, q=1):
    return QUniform(lower, upper, q)


def loguniform(lower, upper):
    return LogUniform(lower, upper)


def randint(lower, upper):
    return RandInt(lower, upper)
