"""Reference ``zoo.automl.recipe`` compat."""
