"""Reference ``zoo.automl.recipe.base`` — the Recipe base class (the
chronos recipes subclass it; ``chronos/config/recipe.py`` imports it
from here in the reference layout)."""

from zoo_tpu.chronos.legacy.recipe import Recipe  # noqa: F401
