"""TensorBoard event files: own writer + scalar read-back.

Rebuild of the reference's TensorBoard subsystem (SURVEY §2 #47, §5.5): a
self-contained event-file writer with CRC32C-framed records
(``tensorboard/RecordWriter.scala:30,58``), a ``FileWriter`` with a
background flush thread (``FileWriter.scala``), and scalar read-back
powering ``get_train_summary(tag)`` / ``get_validation_summary(tag)``
(``orca/learn/tf/estimator.py:167-221``). No tensorboard/tensorboardX
dependency: Event/Summary protos are hand-encoded (``proto.py``), record
framing is the TFRecord layout (shared with ``orca/data/tfrecord``), and
the files open in stock TensorBoard.
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from zoo_tpu.orca.data.tfrecord import _masked_crc  # crc32c framing
from zoo_tpu.tensorboard import proto

_FILE_VERSION = "brain.Event:2"

# Event proto fields (tensorflow/core/util/event.proto)
_EV_WALL_TIME = 1   # double
_EV_STEP = 2        # int64
_EV_FILE_VERSION = 3  # string
_EV_SUMMARY = 5     # Summary
# Summary / Summary.Value fields (tensorflow/core/framework/summary.proto)
_SUM_VALUE = 1
_VAL_TAG = 1
_VAL_SIMPLE = 2
_VAL_TENSOR = 8     # TF2 tf.summary.scalar writes a TensorProto instead
# TensorProto fields (tensorflow/core/framework/tensor.proto)
_TP_DTYPE = 1
_TP_CONTENT = 4
_TP_FLOAT_VAL = 5
_TP_DOUBLE_VAL = 6
_DT_FLOAT, _DT_DOUBLE = 1, 2


def scalar_event(tag: str, value: float, step: int,
                 wall_time: Optional[float] = None) -> bytes:
    sval = (proto.field_bytes(_VAL_TAG, tag.encode()) +
            proto.field_float(_VAL_SIMPLE, float(value)))
    summary = proto.field_message(_SUM_VALUE, sval)
    return (proto.field_double(_EV_WALL_TIME, wall_time or time.time()) +
            proto.field_varint(_EV_STEP, int(step)) +
            proto.field_message(_EV_SUMMARY, summary))


def version_event(wall_time: Optional[float] = None) -> bytes:
    return (proto.field_double(_EV_WALL_TIME, wall_time or time.time()) +
            proto.field_bytes(_EV_FILE_VERSION, _FILE_VERSION.encode()))


def frame_record(payload: bytes) -> bytes:
    """TFRecord framing: len u64le, masked-crc(len), payload,
    masked-crc(payload) — identical to ``RecordWriter.scala:30-58``."""
    hdr = struct.pack("<Q", len(payload))
    return (hdr + struct.pack("<I", _masked_crc(hdr)) + payload +
            struct.pack("<I", _masked_crc(payload)))


class EventWriter:
    """Buffered event-file writer with a background flush thread (the
    reference's ``EventWriter``+``FileWriter`` pair collapsed into one)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{time.time():.6f}."
                 f"{socket.gethostname()}.{os.getpid()}")
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._flush_secs = flush_secs
        self._closed = False
        self._q.put(frame_record(version_event()))
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        last_flush = time.time()
        while True:
            try:
                item = self._q.get(timeout=self._flush_secs)
            except queue.Empty:
                item = b""
            if item is None:
                break
            if isinstance(item, threading.Event):
                # flush barrier: everything enqueued before it is written
                self._f.flush()
                item.set()
                continue
            if item:
                self._f.write(item)
            if time.time() - last_flush >= self._flush_secs:
                self._f.flush()
                last_flush = time.time()
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        if not self._closed:
            self._q.put(frame_record(scalar_event(tag, value, step)))

    def add_event(self, event_bytes: bytes):
        if not self._closed:
            self._q.put(frame_record(event_bytes))

    def flush(self):
        """Block until everything queued so far is on disk. A sentinel
        barrier rides the queue behind the pending records, so there is no
        drained-but-unwritten race (queue.empty() can be true while the
        worker still holds the last record)."""
        if self._closed or not self._t.is_alive():
            if not self._f.closed:
                self._f.flush()
            return
        barrier = threading.Event()
        self._q.put(barrier)
        barrier.wait(timeout=30)

    def close(self):
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._t.join(timeout=10)
            self._f.close()


# ------------------------------------------------------------- read-back

def iter_event_records(path: str):
    """Yield raw Event payloads from one event file, stopping (not
    raising) at the first corrupt or truncated record.

    Crash-safety parity with the checkpoint reader: a writer killed
    mid-record, a torn tail, or flipped bytes must cost only the records
    at and after the damage — everything before it still parses. The
    length header's CRC is verified (a corrupt length would otherwise
    send the reader seeking megabytes into garbage); a record whose
    *payload* CRC fails is skipped while the scan continues, since the
    framing itself is still intact."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if len(hdr) < 12:
                return  # clean EOF or truncated header
            (length,) = struct.unpack("<Q", hdr[:8])
            (len_crc,) = struct.unpack("<I", hdr[8:])
            if _masked_crc(hdr[:8]) != len_crc:
                return  # corrupt length: cannot resync past it
            payload = f.read(length + 4)
            if len(payload) < length + 4:
                return  # truncated record (writer died mid-write)
            data, (data_crc,) = payload[:-4], struct.unpack(
                "<I", payload[-4:])
            if _masked_crc(data) != data_crc:
                continue  # bit-rotted payload: skip, framing still holds
            yield data


def read_scalars(log_dir: str, tag: Optional[str] = None
                 ) -> Dict[str, List[Tuple[int, float, float]]]:
    """Parse every event file under ``log_dir``; returns
    ``{tag: [(step, wall_time, value), ...]}`` sorted by step."""
    out: Dict[str, List[Tuple[int, float, float]]] = {}
    if not os.path.isdir(log_dir):
        return out
    files = sorted(f for f in os.listdir(log_dir)
                   if f.startswith("events.out.tfevents"))
    for fname in files:
        for rec in iter_event_records(os.path.join(log_dir, fname)):
            try:
                _scan_record(rec, tag, out)
            except (ValueError, struct.error, IndexError, TypeError,
                    UnicodeDecodeError):
                continue  # CRC-valid but unparseable: skip, keep reading
    for v in out.values():
        v.sort(key=lambda r: r[0])
    return out


def _scan_record(rec: bytes, tag: Optional[str],
                 out: Dict[str, List[Tuple[int, float, float]]]):
    """Collect the scalars of one Event record into ``out`` (raises on a
    malformed record; ``read_scalars`` skips those)."""
    fields = proto.parse_fields(rec)
    if _EV_SUMMARY not in fields:
        return
    wall = float(fields.get(_EV_WALL_TIME, [0.0])[0])
    step = proto.zigzag_to_int64(int(fields.get(_EV_STEP, [0])[0]))
    for summary in fields[_EV_SUMMARY]:
        for fld, wire, sval in proto.iter_fields(summary):
            # only Summary.value (field 1, length-delimited); a
            # varint/fixed field from another producer would be an
            # int here and must not reach parse_fields
            if fld != _SUM_VALUE or wire != 2 or not isinstance(sval, bytes):
                continue
            vf = proto.parse_fields(sval)
            if _VAL_TAG not in vf:
                continue
            t = vf[_VAL_TAG][0].decode("utf-8")
            if tag is not None and t != tag:
                continue
            val = _extract_value(vf)
            if val is not None:
                out.setdefault(t, []).append((step, wall, val))


def _extract_value(vf) -> Optional[float]:
    """simple_value, or a scalar TensorProto (how TF2's
    tf.summary.scalar encodes it)."""
    if _VAL_SIMPLE in vf:
        return float(vf[_VAL_SIMPLE][0])
    if _VAL_TENSOR not in vf:
        return None
    tp = proto.parse_fields(vf[_VAL_TENSOR][0])
    dtype = int(tp.get(_TP_DTYPE, [_DT_FLOAT])[0])
    if _TP_CONTENT in tp and tp[_TP_CONTENT][0]:
        raw = tp[_TP_CONTENT][0]
        fmt = "<f" if dtype == _DT_FLOAT else "<d"
        return float(struct.unpack_from(fmt, raw, 0)[0])
    for fld in (_TP_FLOAT_VAL, _TP_DOUBLE_VAL):
        if fld in tp:
            v = tp[fld][0]
            if isinstance(v, bytes):  # packed repeated
                fmt = "<f" if fld == _TP_FLOAT_VAL else "<d"
                return float(struct.unpack_from(fmt, v, 0)[0])
            return float(v)
    return None


class Summary:
    """File-backed scalar summary with in-memory mirror and disk
    read-back (the ``TrainSummary``/``ValidationSummary`` API,
    ``Estimator.scala:111-122``)."""

    def __init__(self, log_dir: Optional[str] = None, app_name: str = "zoo"):
        self.log_dir = (os.path.join(log_dir, app_name)
                        if log_dir is not None else None)
        self._scalars: Dict[str, List[Tuple[int, float]]] = {}
        self._writer = EventWriter(self.log_dir) if self.log_dir else None

    def add_scalar(self, tag: str, value: float, step: int):
        self._scalars.setdefault(tag, []).append((step, float(value)))
        if self._writer is not None:
            self._writer.add_scalar(tag, value, step)

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """In-memory when available; otherwise parse back from disk (so a
        fresh process can read another run's summaries, like the
        reference's ``get_train_summary`` on a loaded estimator)."""
        if tag in self._scalars:
            return list(self._scalars[tag])
        if self.log_dir:
            if self._writer is not None:
                self._writer.flush()
            recs = read_scalars(self.log_dir, tag).get(tag, [])
            return [(step, val) for step, _, val in recs]
        return []

    def close(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


TrainSummary = Summary
ValidationSummary = Summary
