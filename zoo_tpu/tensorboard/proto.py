"""Minimal protobuf wire-format codec (no protobuf dependency).

Just enough of proto3 encoding to emit and parse TensorFlow ``Event`` /
``Summary`` messages — the same role as the reference's hand-rolled
event-record layer (``zoo/.../tensorboard/RecordWriter.scala:30`` writes
raw framed bytes rather than depending on TF). Wire types: 0=varint,
1=64-bit, 2=length-delimited, 5=32-bit.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

Value = Union[int, float, bytes, "Message"]


def encode_varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1  # two's-complement for negative int64
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _key(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + encode_varint(value)


def field_double(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def field_float(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def field_bytes(field: int, value: bytes) -> bytes:
    if isinstance(value, str):
        value = value.encode("utf-8")
    return _key(field, 2) + encode_varint(len(value)) + value


field_message = field_bytes  # submessages are length-delimited too


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Value]]:
    """Yield (field_number, wire_type, raw_value) over a message body.
    Length-delimited values come back as bytes; callers recurse."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = decode_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            val, pos = decode_varint(buf, pos)
        elif wire == 1:
            (val,) = struct.unpack_from("<d", buf, pos)
            pos += 8
        elif wire == 2:
            ln, pos = decode_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            (val,) = struct.unpack_from("<f", buf, pos)
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def parse_fields(buf: bytes) -> Dict[int, List[Value]]:
    out: Dict[int, List[Value]] = {}
    for field, _, val in iter_fields(buf):
        out.setdefault(field, []).append(val)
    return out


def zigzag_to_int64(v: int) -> int:
    """Plain varint int64 decode (values ≥ 2^63 are negative)."""
    return v - (1 << 64) if v >= (1 << 63) else v
