# zoo-lint: jax-free
"""Wire-frame integrity: CRC trailers + the one corruption exception.

Gray hardware failures — a flipped bit in a NIC ring, a torn read off a
tmpfs segment, a desynchronized stream after a partial write — do not
announce themselves: without a checksum a corrupted length-prefixed
frame either tears the connection somewhere confusing or, worse,
*decodes* into plausible garbage that flows into a model. This module
is the shared detection layer for both wire planes:

* the serving TCP door (``zoo_tpu.serving.server`` ZSRV frames) and
* the shard-exchange data plane (``zoo_tpu.orca.data.plane`` ZSXN
  per-array payloads, shm-lane segments included).

Both planes call :func:`frame_crc` on the exact bytes that cross the
transport and :func:`verify_crc` on receipt. A mismatch raises
:class:`FrameCorrupt` — a :class:`ConnectionError` subclass BY DESIGN:
every existing retry / failover / pool-invalidation path already treats
transport errors as transient, so a corrupt frame is retried on a fresh
connection instead of ever reaching a decoder. Each detection also
lands on the ``zoo_wire_corrupt_frames_total`` counter and in the crash
flight-recorder ring (the first thing a gray-failure postmortem wants).

The checksum is ``zlib.crc32`` (the CRC32C role; zlib's is the one the
stdlib ships and it is plenty for bit-flip detection — this is an
integrity check against faults, not an authenticity check against
adversaries; TLS provides the latter on the serving door).

``ZOO_WIRE_CRC`` (default on) is the kill switch; the trailer itself is
negotiated per connection on both planes, so a peer from a build that
pre-dates this module still interoperates on the plain protocol.

Chaos seam: :func:`corrupt_seam` is the in-transit bit-flip injection
point — production code passes the outbound payload through it AFTER
computing the CRC, so an armed ``wire.corrupt`` fault site simulates
corruption on the wire (CRC no longer matches) exactly like real bit
rot would.
"""

from __future__ import annotations

import os
import zlib
from typing import Optional

__all__ = [
    "FrameCorrupt", "frame_crc", "verify_crc", "wire_crc_enabled",
    "corrupt_seam", "flip_bit", "WIRE_CRC_ENV",
]

WIRE_CRC_ENV = "ZOO_WIRE_CRC"

# the metrics import is LAZY: obs.metrics (indirectly) imports
# resilience, which re-exports FrameCorrupt from here — a module-level
# import would make "import integrity first" a circular-import crash
_corrupt_frames = None


def _corrupt_counter():
    global _corrupt_frames
    if _corrupt_frames is None:
        from zoo_tpu.obs.metrics import counter
        _corrupt_frames = counter(
            "zoo_wire_corrupt_frames_total",
            "Frames whose CRC trailer failed verification, by wire "
            "plane (serving = the ZSRV TCP door, shard = the ZSXN "
            "data plane). Each one is a caught would-have-been "
            "garbage decode: the frame was dropped and the transfer "
            "retried on a fresh connection.",
            labels=("plane",))
    return _corrupt_frames


def wire_crc_enabled() -> bool:  # zoo-lint: config-parse
    """Whether this process wants CRC trailers on its wire frames
    (``ZOO_WIRE_CRC``, default on). Read at connection/negotiation
    time, so a test can toggle it per server/client process."""
    return os.environ.get(WIRE_CRC_ENV, "1") not in ("0", "false", "off")


class FrameCorrupt(ConnectionError):
    """A wire frame failed its CRC check.

    A :class:`ConnectionError` on purpose: retry policies and the HA
    failover path treat it exactly like a reset — drop the (possibly
    desynchronized) connection, redial, re-send. It must NEVER be
    swallowed into a decode attempt; the whole point is that corrupt
    bytes are refused before any decoder sees them."""


def frame_crc(buf) -> int:
    """CRC of the exact bytes that cross the transport."""
    return zlib.crc32(memoryview(buf)) & 0xFFFFFFFF


def verify_crc(buf, expected: int, plane: str,
               context: Optional[str] = None):
    """Raise :class:`FrameCorrupt` (counting + flight-ring event) when
    ``buf`` does not hash to ``expected``. ``plane`` labels the counter
    (``serving`` / ``shard``); ``context`` names the frame for the
    error message and the flight event."""
    got = zlib.crc32(memoryview(buf)) & 0xFFFFFFFF
    if got == (expected & 0xFFFFFFFF):
        return
    _corrupt_counter().labels(plane=plane).inc()
    try:  # telemetry never masks the detection itself
        from zoo_tpu.obs.flight import record_event
        record_event("frame_corrupt", plane=plane,
                     context=context or "", nbytes=len(buf))
    except Exception:  # noqa: BLE001
        pass
    raise FrameCorrupt(
        f"{plane} frame CRC mismatch"
        + (f" ({context})" if context else "")
        + f": got {got:#010x}, trailer says {expected & 0xFFFFFFFF:#010x}"
        f" over {len(buf)} byte(s) — corrupt or desynchronized stream; "
        "dropping the connection and retrying")


def flip_bit(buf, bit: int = 0) -> bytes:
    """``buf`` with one bit flipped — the canonical chaos corruption."""
    out = bytearray(buf)
    if out:
        out[(bit // 8) % len(out)] ^= 1 << (bit % 8)
    return bytes(out)


def corrupt_action(holder=None, site=None, **_ctx):
    """The ready-made fault ACTION chaos tests arm at a corruption
    seam: replaces the outbound payload with a one-bit-flipped COPY
    (never mutating in place — the payload may be a memoryview over
    the sender's live arrays)::

        inject("serving.wire.corrupt", action=corrupt_action, p=0.1)
    """
    if holder is not None:
        holder["buf"] = flip_bit(holder["buf"])


def corrupt_seam(site: str, payload):
    """The in-transit corruption injection point.

    Production senders pass the outbound payload through here AFTER
    computing its CRC. Unarmed (the everyday case) this is one dict
    check. An armed site's action (normally :func:`corrupt_action`)
    receives ``holder`` and may swap ``holder["buf"]`` for corrupted
    bytes — simulating bit rot in transit, which the receiver's CRC
    check then catches."""
    from zoo_tpu.util.resilience import default_injector
    if not default_injector._sites:  # the everyday fast path
        return payload
    holder = {"buf": payload}
    default_injector.fire(site, holder=holder)
    return holder["buf"]
