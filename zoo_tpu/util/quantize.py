"""Absmax int8 narrowing, shared by the wire codec and the KV cache.

One implementation of the scale/narrow/widen triple that PR 6's wire
codec introduced (f32 -> int8 with a recorded absmax scale, widened on
the other side) and the int8 paged KV cache now needs on-device: the
helpers are array-namespace agnostic (``xp=np`` for the host wire path,
``xp=jnp`` inside a jitted executable), so both call sites share the
exact rounding/clipping/zero-guard semantics and a parity test on one
covers the other.

Conventions (identical to the original wire-codec behavior):

* ``scale = absmax / 127`` with an all-zero input mapping to scale 1.0
  (so the narrow path never divides by zero and a zero array round
  trips to exactly zero);
* narrowing is ``clip(rint(x / scale), -127, 127)`` — symmetric, -128
  never produced;
* widening is ``q.astype(f32) * scale``.

``axis=None`` gives the wire codec's per-array scale; the KV quantizer
passes ``axis=-1, keepdims=True`` for a scale per cache row.
"""

from __future__ import annotations

import numpy as np

__all__ = ["absmax_scale", "narrow_int8", "widen_int8"]


def absmax_scale(arr, axis=None, keepdims: bool = False, xp=np):
    """The int8 quantization scale(s) of ``arr``: ``absmax / 127``
    along ``axis`` (None = whole array), with exact-zero slices mapped
    to 1.0. Returns an ``xp`` array (0-d for ``axis=None`` under np —
    callers wanting a python float wrap it in ``float()``)."""
    a = xp.asarray(arr)
    if a.dtype != xp.float32:
        a = a.astype(xp.float32)
    absmax = xp.max(xp.abs(a), axis=axis, keepdims=keepdims)
    return xp.where(absmax > 0, absmax / 127.0,
                    xp.ones_like(absmax))


def narrow_int8(arr, scale, xp=np):
    """``arr`` (f32) -> int8 under ``scale`` (broadcastable): symmetric
    round-to-nearest, clipped to [-127, 127]."""
    a = xp.asarray(arr)
    return xp.clip(xp.rint(a / scale), -127, 127).astype(xp.int8)


def widen_int8(q, scale, xp=np):
    """Invert :func:`narrow_int8`: int8 payload times its scale, f32."""
    return xp.asarray(q).astype(xp.float32) * scale
