"""Reference ``zoo.util.utils`` (``pyzoo/zoo/util/utils.py``):
environment helpers used by the cluster-launch scripts."""

from __future__ import annotations

import os


def detect_conda_env_name() -> str:
    """reference ``utils.py`` — the active conda env name (used to
    conda-pack the driver env for executors; the rebuild's equivalent is
    ``scripts/pack_env.sh``)."""
    name = os.environ.get("CONDA_DEFAULT_ENV")
    if name:
        return name
    prefix = os.environ.get("CONDA_PREFIX")
    if prefix:
        return os.path.basename(prefix)
    raise RuntimeError(
        "no active conda environment detected; the TPU rebuild packages "
        "environments with scripts/pack_env.sh (conda-pack role)")


def convert_to_safe_path(input_path: str, follow_symlinks: bool = True
                         ) -> str:
    """reference ``utils.py`` — canonicalize a path (resolving symlinks
    unless told otherwise) before handing it to native code."""
    if follow_symlinks:
        return os.path.realpath(input_path)
    return os.path.abspath(input_path)


def get_node_ip() -> str:
    """Best-effort routable IP of this host (reference ray utils role)."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
