# zoo-lint: jax-free
"""Shared resilience core: retries, circuit breaking, fault injection.

The reference system leans on Spark for fault tolerance — task retry,
snapshot-resume inside the job (``Topology.scala:1255-1337``), executor
blacklisting. The TPU-native rebuild has no such fabric underneath it, so
the primitives live here and every hot seam wires through them:

* :class:`RetryPolicy` — bounded exponential backoff with full jitter and
  an overall wall-clock deadline (the shape AWS/GRPC clients converged
  on); used by ``ShardExchange.fetch``, the serving TCP client, and
  anything else that talks over a socket.
* :class:`CircuitBreaker` — CLOSED → OPEN → HALF_OPEN state machine for
  load shedding: after ``failure_threshold`` consecutive failures the
  breaker opens and callers are rejected immediately (no queue build-up
  behind a dead model) until ``recovery_timeout`` passes and a probe
  succeeds.
* :class:`FaultInjector` — a process-local registry of named fault sites
  (``inject("shard.fetch", exc=ConnectionError("boom"), times=2)``).
  Production code marks its seams with :func:`fault_point`; chaos tests
  arm sites to force transient/permanent failures without monkeypatching
  internals. When no fault is armed a site costs one dict lookup.
* Heartbeat-file liveness — :func:`touch_heartbeat` /
  :func:`start_heartbeat_thread` let a supervised worker prove it is
  *making progress*, so ``ProcessMonitor`` can treat a hung (not just
  exited) worker as crashed.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple, Type

from zoo_tpu.obs.metrics import counter, gauge
# re-export: FrameCorrupt is transport-layer by nature (a corrupt frame
# is handled exactly like a reset) and every consumer of this module's
# retry/breaker machinery is the audience that must catch it
from zoo_tpu.util.integrity import FrameCorrupt  # noqa: F401

logger = logging.getLogger(__name__)

# Registry wiring (docs/observability.md): PR 1 built this layer, PR 2
# makes it visible at runtime — a live cluster can now answer "how many
# retries fired?" / "is a breaker open?" from GET /metrics.
_retry_attempts = counter(
    "zoo_retry_attempts_total", "RetryPolicy attempts executed "
    "(including each call's first try)")
_retry_giveups = counter(
    "zoo_retry_giveups_total", "Retry budgets exhausted (RetryError raised)")
_breaker_transitions = counter(
    "zoo_breaker_transitions_total",
    "Circuit-breaker state transitions, labelled by the state entered",
    labels=("state",))
_breakers_open = gauge(
    "zoo_breaker_open", "Circuit breakers currently open (or probing "
    "half-open) in this process")
_fault_trips = counter(
    "zoo_fault_injections_total", "Armed fault-site firings",
    labels=("site",))

def _flight(kind: str, **fields):
    """Record into the crash flight-recorder ring (lazy import: the
    flight module lives above us in the obs package and this module is
    imported by nearly everything — the ring must never be a reason
    resilience fails to load)."""
    try:
        from zoo_tpu.obs.flight import record_event
        record_event(kind, **fields)
    except Exception:  # noqa: BLE001 — telemetry never fails the op
        pass


__all__ = [
    "RetryPolicy", "RetryError",
    "Deadline", "DeadlineExceeded",
    "CircuitBreaker", "CircuitOpenError", "FrameCorrupt",
    "FaultInjector", "InjectedFault", "inject", "clear_faults",
    "fault_point", "default_injector",
    "ChaosSchedule", "ChaosEvent",
    "touch_heartbeat", "heartbeat_age", "start_heartbeat_thread",
    "HEARTBEAT_FILE_ENV", "HEARTBEAT_INTERVAL_ENV",
    "env_float", "env_int",
]


def env_float(name: str, default: float) -> float:  # zoo-lint: config-parse
    """``$name`` as a float, falling back to ``default`` on unset, empty,
    or malformed values (with a warning for malformed ones) — the one
    shared parser behind every ``ZOO_*`` numeric knob."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("bad %s=%r; using %s", name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    return int(env_float(name, default))


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------

class RetryError(RuntimeError):
    """Retry budget (attempts or deadline) exhausted; ``__cause__`` is the
    last underlying failure."""

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


class RetryPolicy:
    """Bounded exponential backoff with full jitter and a deadline.

    ``max_attempts``: total tries including the first. ``deadline``:
    overall wall-clock budget in seconds measured from the start of
    :meth:`call` — no attempt starts after it has passed, so a dead peer
    costs at most ``deadline`` (plus one socket timeout), never an
    unbounded hang. ``retry_on``: only these exception types are retried;
    anything else propagates immediately (a ``KeyError`` — wrong shard —
    must not burn the budget meant for flaky networks).

    ``sleep`` and ``rng`` are injectable so tests assert backoff math
    without real sleeping.
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: Optional[float] = None,
                 jitter: bool = True,
                 retry_on: Tuple[Type[BaseException], ...] = (
                     ConnectionError, TimeoutError, OSError),
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] = random.random):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = deadline
        self.jitter = jitter
        self.retry_on = retry_on
        self._sleep = sleep
        self._rng = rng

    def backoff(self, attempt: int) -> float:
        """Delay after the ``attempt``-th failure (attempt counts from 1)."""
        raw = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        return raw * self._rng() if self.jitter else raw

    def call(self, fn: Callable[..., Any], *args, **kwargs) -> Any:
        start = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            _retry_attempts.inc()
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last = e
                if attempt >= self.max_attempts:
                    break
                delay = self.backoff(attempt)
                if self.deadline is not None and \
                        time.monotonic() - start + delay > self.deadline:
                    _retry_giveups.inc()
                    raise RetryError(
                        f"deadline {self.deadline}s exhausted after "
                        f"{attempt} attempt(s): {e!r}", attempt) from e
                logger.debug("retry %d/%d in %.3fs after %r", attempt,
                             self.max_attempts, delay, e)
                self._sleep(delay)
        _retry_giveups.inc()
        _flight("retry_giveup", attempts=self.max_attempts,
                error=repr(last))
        raise RetryError(
            f"gave up after {self.max_attempts} attempt(s): {last!r}",
            self.max_attempts) from last

    def wrap(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return inner


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

class DeadlineExceeded(RuntimeError):
    """A propagated request deadline expired before the work completed.

    Deliberately NOT a :class:`ConnectionError`/:class:`OSError`: retry
    layers must treat an exhausted budget as terminal — another attempt
    can only arrive even later."""


class Deadline:
    """An absolute deadline on the local monotonic clock.

    The serving wire carries *remaining budget* (``deadline_ms``), the
    gRPC convention, because wall clocks disagree across hosts; each
    process re-anchors the budget on its own ``time.monotonic()`` the
    moment the frame arrives. Every stage then derives its wait bound
    from :meth:`remaining` instead of a hardcoded timeout, and a request
    whose budget is gone is dropped instead of computed
    (docs/serving_ha.md)."""

    __slots__ = ("at",)

    def __init__(self, seconds: float):
        self.at = time.monotonic() + float(seconds)

    @classmethod
    def from_ms(cls, ms) -> Optional["Deadline"]:
        """Budget in milliseconds → Deadline; ``None`` stays None (no
        deadline). ``ms <= 0`` is an already-expired deadline, not "no
        deadline" — a zero budget must reject, not hang forever."""
        if ms is None:
            return None
        return cls(float(ms) / 1000.0)

    def remaining(self) -> float:
        """Seconds left (negative once expired)."""
        return self.at - time.monotonic()

    def remaining_ms(self) -> float:
        """Milliseconds left, floored at 0 — the value to re-stamp into
        a forwarded frame."""
        return max(0.0, 1000.0 * self.remaining())

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitOpenError(RuntimeError):
    """Call rejected without being attempted: the breaker is open."""


class CircuitBreaker:
    """CLOSED → OPEN → HALF_OPEN load-shedding state machine.

    CLOSED: calls flow; ``failure_threshold`` *consecutive* failures trip
    the breaker. OPEN: every call is rejected for ``recovery_timeout``
    seconds — the cheap fast-fail that keeps a request queue from piling
    up behind a dead backend. HALF_OPEN: up to ``half_open_max`` probe
    calls are admitted PER PROBE WINDOW; one success closes the breaker,
    one failure reopens it. A probe that never reports back (its caller
    died, or its request expired unexecuted) does NOT wedge the breaker:
    after another ``recovery_timeout`` with no verdict, the probe quota
    refreshes for a new window — without that, one vanished probe left
    the breaker rejecting every call forever. Thread-safe; ``clock`` is
    injectable for tests.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5,
                 recovery_timeout: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.recovery_timeout = float(recovery_timeout)
        self.half_open_max = int(half_open_max)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED          # guarded-by: _lock
        self._failures = 0                 # guarded-by: _lock
        self._opened_at = 0.0              # guarded-by: _lock
        self._probes = 0                   # guarded-by: _lock
        self._half_open_at = 0.0           # guarded-by: _lock

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self):
        now = self._clock()
        if self._state == self.OPEN and \
                now - self._opened_at >= self.recovery_timeout:
            self._state = self.HALF_OPEN
            self._probes = 0
            self._half_open_at = now
            _breaker_transitions.labels(state=self.HALF_OPEN).inc()
        elif self._state == self.HALF_OPEN and \
                now - self._half_open_at >= self.recovery_timeout:
            # every admitted probe vanished without a verdict (caller
            # died, request dropped unexecuted): open a fresh probe
            # window instead of staying wedged shut forever — the
            # quota stays <= half_open_max per window either way
            self._probes = 0
            self._half_open_at = now

    def allow(self) -> bool:
        """May a call proceed right now? (HALF_OPEN admits probes.)"""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and \
                    self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def record_success(self):
        with self._lock:
            self._failures = 0
            if self._state != self.CLOSED:
                logger.info("circuit breaker closing after probe success")
                _breaker_transitions.labels(state=self.CLOSED).inc()
                _flight("breaker_closed")
                _breakers_open.dec()
            self._state = self.CLOSED

    def record_failure(self):
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                if self._state != self.OPEN:
                    logger.warning(
                        "circuit breaker OPEN after %d consecutive "
                        "failure(s); shedding load for %.1fs",
                        self._failures, self.recovery_timeout)
                    _breaker_transitions.labels(state=self.OPEN).inc()
                    _flight("breaker_open",
                            failures=self._failures,
                            recovery_s=self.recovery_timeout)
                    if self._state == self.CLOSED:
                        # CLOSED->OPEN only: a reopening HALF_OPEN
                        # breaker is already counted in the gauge
                        _breakers_open.inc()
                self._state = self.OPEN
                self._opened_at = self._clock()

    def call(self, fn: Callable[..., Any], *args, **kwargs) -> Any:
        if not self.allow():
            with self._lock:  # snapshot for the message, not a race
                failures = self._failures
            raise CircuitOpenError(
                f"circuit open ({failures} consecutive failures); "
                f"retry after {self.recovery_timeout}s")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Default exception raised by an armed fault site."""


class _Fault:
    __slots__ = ("exc", "action", "times", "p", "fired")

    def __init__(self, exc, action, times, p):
        self.exc = exc
        self.action = action
        self.times = times  # None = unlimited
        self.p = p
        self.fired = 0


class FaultInjector:
    """Process-local registry of named fault sites.

    Production code marks a seam with ``injector.fire("shard.fetch")``
    (via the module-level :func:`fault_point`); tests arm it::

        with inject("shard.fetch", exc=ConnectionError("flaky"), times=2):
            ...   # first two fetch attempts raise, third succeeds

    ``action`` is an arbitrary callable run at the site instead of (or
    before) raising — chaos tests use it to SIGKILL the process mid-save.
    ``times=N`` disarms the site after N firings; ``p`` fires
    probabilistically. Unarmed sites cost a single dict lookup.

    **Deterministic replay**: probabilistic (``p < 1``) firings draw from
    the injector's OWN ``random.Random``, seeded from ``seed=`` or
    ``$ZOO_FAULT_SEED`` — so a chaos run that found a bug replays the
    exact same fault schedule (same arm order + same seed = same trips).
    :meth:`reseed` restarts the sequence mid-process. Unseeded injectors
    keep fresh entropy per process, like before.
    """

    def __init__(self, seed: Optional[int] = None):  # zoo-lint: config-parse
        self._lock = threading.Lock()
        self._sites: Dict[str, _Fault] = {}
        if seed is None:
            env = os.environ.get("ZOO_FAULT_SEED")
            seed = int(env) if env else None
        self.fault_seed = seed
        self._rng = random.Random(seed)

    def reseed(self, seed: Optional[int] = None):  # zoo-lint: config-parse
        """Restart the fault schedule (``seed=None`` re-reads
        ``$ZOO_FAULT_SEED``, falling back to fresh entropy)."""
        if seed is None:
            env = os.environ.get("ZOO_FAULT_SEED")
            seed = int(env) if env else None
        self.fault_seed = seed
        self._rng = random.Random(seed)
        return self

    def inject(self, site: str,
               exc: Optional[BaseException] = None,
               times: Optional[int] = None,
               action: Optional[Callable[..., None]] = None,
               p: float = 1.0) -> "_Armed":
        if exc is None and action is None:
            exc = InjectedFault(f"injected fault at {site!r}")
        with self._lock:
            self._sites[site] = _Fault(exc, action, times, p)
        return _Armed(self, site)

    def clear(self, site: Optional[str] = None):
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    def fired(self, site: str) -> int:
        with self._lock:
            f = self._sites.get(site)
            return f.fired if f else 0

    def fire(self, site: str, **ctx):
        """Called from production code at a named seam; no-op unless a
        test armed this site."""
        if not self._sites:  # fast path: nothing armed anywhere
            return
        with self._lock:
            f = self._sites.get(site)
            if f is None:
                return
            if f.times is not None and f.fired >= f.times:
                return
            if f.p < 1.0 and self._rng.random() >= f.p:
                return
            f.fired += 1
            exc, action = f.exc, f.action
        _fault_trips.labels(site=site).inc()
        if action is not None:
            action(site=site, **ctx)
        if exc is not None:
            raise exc


class _Armed:
    """Context-manager handle for one armed site (clears on exit; the
    firing count stays readable afterwards)."""

    def __init__(self, injector: FaultInjector, site: str):
        self._injector = injector
        self.site = site
        self._final: Optional[int] = None

    @property
    def fired(self) -> int:
        if self._final is not None:
            return self._final
        return self._injector.fired(self.site)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._final = self._injector.fired(self.site)
        self._injector.clear(self.site)
        return False


default_injector = FaultInjector()


def inject(site: str, **kwargs) -> _Armed:
    return default_injector.inject(site, **kwargs)


def clear_faults(site: Optional[str] = None):
    default_injector.clear(site)


def fault_point(site: str, **ctx):
    """The instrumentation hook production code places at a seam."""
    default_injector.fire(site, **ctx)


# ---------------------------------------------------------------------------
# deterministic fleet chaos
# ---------------------------------------------------------------------------

class ChaosEvent:
    """One resolved fault on the schedule's timeline: ``kind`` at
    ``t0`` seconds after the run starts, optionally a WINDOW closing at
    ``t1`` (the action is invoked again with ``phase="end"`` — revert
    the fault), plus free-form ``params``."""

    __slots__ = ("kind", "t0", "t1", "params")

    def __init__(self, kind: str, t0: float,
                 t1: Optional[float], params: Dict):
        self.kind = kind
        self.t0 = float(t0)
        self.t1 = None if t1 is None else float(t1)
        self.params = dict(params)

    def as_dict(self) -> Dict:
        return {"kind": self.kind, "t0": round(self.t0, 6),
                "t1": None if self.t1 is None else round(self.t1, 6),
                "params": dict(self.params)}

    def __repr__(self):
        win = f"-{self.t1:g}" if self.t1 is not None else ""
        return f"ChaosEvent({self.kind}@{self.t0:g}{win} {self.params})"


class ChaosSchedule:
    """A seed-driven, replayable sequence of timed faults for a whole
    replica group — :class:`FaultInjector` grown from "one armed site"
    to "a storm with a clock" (docs/fault_tolerance.md).

    **Spec** (``ZOO_CHAOS_SPEC``; ``;``-separated events)::

        kind@T[:key=val[,key=val...]]

    where ``T`` is an instant (``1.5``), a window (``0.5-3.0`` — the
    action runs at both edges, ``phase="start"`` then ``phase="end"``),
    or a seeded draw (``1.0~2.5`` picks a deterministic instant in the
    range; either window edge may be a draw). A param value of ``?``
    draws a deterministic replica index in ``[0, replicas)``. Example::

        slow@0.5-4.0:replica=1,delay_ms=80;kill@2.0:replica=?;
        corrupt@1.0-3.0:p=0.15;drop@1.5:times=2

    **Determinism**: all randomness (time draws, ``?`` targets) comes
    from ``random.Random(seed)`` at CONSTRUCTION — two schedules built
    from the same (spec, seed, replicas) resolve to the identical
    event list (:meth:`resolved`, what the chaos storm asserts), and
    :meth:`run` reseeds the default :class:`FaultInjector` with the
    same seed so probabilistic (``p < 1``) firings replay too.

    **Kinds are opaque**: :meth:`run` dispatches each event to the
    ``actions`` dict the harness supplies (``kind -> fn(event,
    phase)``), so the schedule composes any fault the harness can
    express — SIGKILL via ``ReplicaGroup.kill_replica``, a remote
    per-op delay via the wire ``chaos`` op, a client-side frame
    bit-flip via ``integrity.corrupt_action``, a spill-dir disk-full
    via the ``flight.spill`` site."""

    def __init__(self, spec: Optional[str] = None,  # zoo-lint: config-parse
                 seed: Optional[int] = None,
                 replicas: Optional[int] = None):
        if spec is None:
            spec = os.environ.get("ZOO_CHAOS_SPEC", "")
        if seed is None:
            seed = int(os.environ.get("ZOO_CHAOS_SEED", "0") or 0)
        self.spec = spec
        self.seed = int(seed)
        self.replicas = replicas
        rng = random.Random(self.seed)
        self.events: list = []
        for part in (p.strip() for p in spec.split(";")):
            if not part:
                continue
            self.events.append(self._parse_event(part, rng))
        self.events.sort(key=lambda e: e.t0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _parse_event(self, text: str, rng) -> ChaosEvent:
        def draw(tok: str) -> float:
            if "~" in tok:
                a, b = tok.split("~", 1)
                return rng.uniform(float(a), float(b))
            return float(tok)

        head, _, tail = text.partition(":")
        kind, sep, when = head.partition("@")
        if not sep or not when:
            raise ValueError(
                f"malformed chaos event {text!r} (expected "
                "kind@T[:k=v,...], e.g. slow@0.5-3.0:replica=1,"
                "delay_ms=80)")
        t0, _, t1 = when.partition("-")
        t0 = draw(t0)
        t1 = draw(t1) if t1 else None
        if t1 is not None and t1 < t0:
            raise ValueError(
                f"chaos event {text!r}: window closes before it opens")
        params: Dict = {}
        for kv in tail.split(","):
            if not kv:
                continue
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(
                    f"malformed chaos param {kv!r} in {text!r}")
            k, v = k.strip(), v.strip()
            if v == "?":
                if not self.replicas:
                    raise ValueError(
                        f"chaos param {k}=? needs replicas= at "
                        "schedule construction")
                params[k] = rng.randrange(self.replicas)
            else:
                try:
                    params[k] = int(v)
                except ValueError:
                    try:
                        params[k] = float(v)
                    except ValueError:
                        params[k] = v
        return ChaosEvent(kind.strip(), t0, t1, params)

    def resolved(self) -> list:
        """The fully-resolved fault sequence — every seeded draw
        materialized. Same (spec, seed, replicas) in, same list out:
        THE replay contract the chaos storm asserts."""
        return [e.as_dict() for e in self.events]

    @property
    def horizon(self) -> float:
        """Seconds from start until the last event edge fires."""
        return max((e.t1 if e.t1 is not None else e.t0
                    for e in self.events), default=0.0)

    def run(self, actions: Dict[str, Callable],
            injector: Optional["FaultInjector"] = None
            ) -> "ChaosSchedule":
        """Play the schedule on a daemon thread: each event's action
        (``actions[kind]``) is invoked at ``t0`` with
        ``phase="start"`` and — for windows — at ``t1`` with
        ``phase="end"``. The injector (default: the process-global
        one) is reseeded with the schedule's seed first, so armed
        ``p < 1`` sites draw the same replayable sequence. Action
        errors are logged, never fatal: chaos must not kill the
        harness measuring it."""
        inj = injector if injector is not None else default_injector
        inj.reseed(self.seed)
        timeline = []
        for ev in self.events:
            timeline.append((ev.t0, 0, "start", ev))
            if ev.t1 is not None:
                timeline.append((ev.t1, 1, "end", ev))
        timeline.sort(key=lambda x: (x[0], x[1]))
        self._stop.clear()

        def loop():
            t_start = time.monotonic()
            for t, _o, phase, ev in timeline:
                wait = t - (time.monotonic() - t_start)
                if wait > 0 and self._stop.wait(wait):
                    return
                if self._stop.is_set():
                    return
                fn = actions.get(ev.kind)
                if fn is None:
                    logger.warning("chaos schedule: no action for "
                                   "kind %r — skipped", ev.kind)
                    continue
                try:
                    fn(ev, phase)
                except Exception:  # noqa: BLE001 — chaos never kills
                    logger.exception("chaos action %s(%s) failed",
                                     ev.kind, phase)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="zoo-chaos-schedule")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the timeline to finish; True when it has."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# heartbeat liveness
# ---------------------------------------------------------------------------

HEARTBEAT_FILE_ENV = "ZOO_HEARTBEAT_FILE"
HEARTBEAT_INTERVAL_ENV = "ZOO_HEARTBEAT_INTERVAL"


def touch_heartbeat(path: Optional[str] = None):  # zoo-lint: config-parse
    """Stamp the heartbeat file (mtime + a ``time.monotonic()`` payload).
    ``path`` defaults to ``$ZOO_HEARTBEAT_FILE``; silently a no-op when
    neither is set, so worker code can call it unconditionally.

    The payload is the monotonic clock, not wall time: CLOCK_MONOTONIC
    is system-wide on Linux, so the supervising process on the same host
    computes ages immune to NTP steps — a 30 s clock correction used to
    read as a 30 s-stale heartbeat and could kill a healthy worker."""
    path = path or os.environ.get(HEARTBEAT_FILE_ENV)
    if not path:
        return
    try:
        # write-then-replace: a reader must never see a half-written
        # stamp (a truncated float would parse as an ancient beat and
        # read as a hang)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(repr(time.monotonic()))
        os.replace(tmp, path)
    except OSError as e:  # a missing dir must not kill the worker
        logger.debug("heartbeat touch failed: %s", e)


def heartbeat_age(path: str) -> Optional[float]:
    """Seconds since the heartbeat file was last stamped; None when the
    file does not exist yet (worker still booting). Prefers the
    monotonic payload :func:`touch_heartbeat` writes; an empty or
    foreign file (plain ``touch``) falls back to wall-clock mtime."""
    try:
        with open(path) as f:
            stamp = float(f.read().strip())
        now = time.monotonic()
        if 0.0 <= stamp <= now:  # a stamp from before a reboot is junk
            return now - stamp
    except (OSError, ValueError):
        pass
    try:
        return max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None


def start_heartbeat_thread(path: Optional[str] = None,  # zoo-lint: config-parse
                           interval: Optional[float] = None
                           ) -> Optional[threading.Thread]:
    """Background daemon stamping the heartbeat file every ``interval``
    seconds. Defaults come from ``$ZOO_HEARTBEAT_FILE`` /
    ``$ZOO_HEARTBEAT_INTERVAL``; returns None (no thread) when no file is
    configured — ``init_orca_context`` calls this unconditionally and
    supervised workers opt in through the env their launcher sets.

    Liveness, not progress: a worker stuck inside one XLA dispatch still
    heartbeats. Pair with application-level progress checks where one
    step hanging forever matters.
    """
    path = path or os.environ.get(HEARTBEAT_FILE_ENV)
    if not path:
        return None
    with _beating_lock:
        if path in _beating:  # idempotent: one thread per file
            return _beating[path]
    interval = interval if interval is not None else \
        float(os.environ.get(HEARTBEAT_INTERVAL_ENV, "1.0"))

    def _beat():
        while True:
            touch_heartbeat(path)
            time.sleep(interval)

    t = threading.Thread(target=_beat, daemon=True,
                         name="zoo-heartbeat")
    with _beating_lock:
        _beating[path] = t
    t.start()
    return t


_beating: Dict[str, threading.Thread] = {}
_beating_lock = threading.Lock()
