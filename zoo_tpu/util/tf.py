"""Reference ``zoo.util.tf`` (``pyzoo/zoo/util/tf.py``): TF-graph
export helpers. The rebuild ingests TF models through the GraphDef→JAX
interpreter (``bridges/tf_graph.py``), so ``export_tf`` — "strip a TF1
session's graph to an inference subgraph and save it" — maps to saving
a SavedModel/frozen graph that ``Net.load_tf`` can consume."""

from __future__ import annotations


def export_tf(sess=None, folder: str = None, inputs=None, outputs=None,
              generate_backward: bool = False,
              allow_non_differentiable_input: bool = True):
    """reference ``util/tf.py:50``. With a live TF1 session: freeze the
    relevant subgraph to ``folder`` via TF's own utilities; the result
    loads here through ``Net.load_tf(folder)``."""
    try:
        import tensorflow as tf
    except ImportError as e:  # pragma: no cover - tf ships in the image
        raise RuntimeError(
            "export_tf needs tensorflow to freeze the session graph; "
            "for models already saved, pass the SavedModel/frozen-graph "
            "path straight to zoo_tpu.pipeline.api.net.Net.load_tf") from e
    if sess is None or folder is None or not inputs or not outputs:
        raise ValueError("export_tf(sess, folder, inputs, outputs) all "
                         "required")
    graph_def = tf.compat.v1.graph_util.convert_variables_to_constants(
        sess, sess.graph_def,
        [t.name.split(":")[0] for t in outputs])
    tf.io.write_graph(graph_def, folder, "frozen_inference_graph.pb",
                      as_text=False)
    with open(f"{folder}/graph_meta.txt", "w") as f:
        f.write("inputs: " + ",".join(t.name for t in inputs) + "\n")
        f.write("outputs: " + ",".join(t.name for t in outputs) + "\n")
    return folder


def process_grad(grad):
    """reference ``util/tf.py:28`` tagged gradients for train_op
    discovery — meaningless without the TF1-on-JVM fabric; identity."""
    return grad
