"""Reference ``zoo.util`` compat package (``pyzoo/zoo/util``): TF graph
utilities and environment helpers the reference's example/app scripts
import. Each delegates onto the rebuild's real implementation."""
