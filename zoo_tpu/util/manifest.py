# zoo-lint: jax-free
"""Verified-manifest directory format + bounded retention, shared by
checkpoints and the model registry.

One directory = one immutable artifact: every payload file is fsynced,
listed in ``manifest.json`` with its size + sha256, and the directory is
committed by a single atomic rename — the protocol
:class:`zoo_tpu.orca.learn.ckpt.CheckpointManager` introduced (PR 1) and
:class:`zoo_tpu.serving.registry.ModelRegistry` layers model versions
on. A reader verifies the manifest before trusting the contents; a
mismatch means a torn or bit-rotted artifact that must be quarantined,
never served or restored.

Importable without jax (the serving replicas and chaos smokes stay
jax-free).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Dict, Iterable, List, Optional, Sequence

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_durable(path: str, data: bytes):
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def walk_files(root: str) -> List[str]:
    """Every file under ``root``, as sorted relative paths."""
    out = []
    for dirpath, _, names in os.walk(root):
        for name in names:
            out.append(os.path.relpath(os.path.join(dirpath, name), root))
    return sorted(out)


def write_manifest(root: str, extra: Optional[Dict] = None) -> Dict:
    """Fsync every file under ``root`` and write ``manifest.json``
    vouching for it (size + sha256 per file, plus the ``extra``
    metadata). The caller commits the directory afterwards with one
    atomic rename."""
    manifest: Dict = dict(extra or {})
    manifest["files"] = {}
    for rel in walk_files(root):
        if rel == MANIFEST:
            continue
        full = os.path.join(root, rel)
        with open(full, "rb+") as f:
            os.fsync(f.fileno())
        manifest["files"][rel] = {
            "size": os.path.getsize(full), "sha256": sha256_file(full)}
    write_durable(os.path.join(root, MANIFEST),
                  json.dumps(manifest, indent=1).encode())
    for dirpath, _, _ in os.walk(root):
        fsync_dir(dirpath)
    return manifest


def read_manifest(root: str) -> Optional[Dict]:
    """The parsed manifest, or None when unreadable/absent."""
    try:
        with open(os.path.join(root, MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_manifest(root: str, what: str = "artifact",
                    legacy_ok: bool = False) -> bool:
    """Does ``root`` match its manifest (sizes + checksums)?

    ``legacy_ok``: accept a directory with NO manifest as long as it
    holds any payload — the pre-manifest checkpoint era, whose presence
    implies a completed legacy save. New formats (the model registry)
    must pass ``legacy_ok=False``: a version without a manifest is
    corrupt, full stop. Extra files beyond the manifest (pins, late
    annotations) are allowed — the manifest vouches for what it lists."""
    if not os.path.isdir(root):
        return False
    mpath = os.path.join(root, MANIFEST)
    if not os.path.exists(mpath):
        if legacy_ok:
            return bool(os.listdir(root))
        logger.warning("%s %s: no manifest", what, root)
        return False
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files: Dict[str, Dict] = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        logger.warning("%s %s: unreadable manifest (%s)", what, root, e)
        return False
    present = set(walk_files(root)) - {MANIFEST}
    if set(files) - present:
        logger.warning("%s %s: missing files %s", what, root,
                       sorted(set(files) - present))
        return False
    for rel, meta in files.items():
        full = os.path.join(root, rel)
        if os.path.getsize(full) != meta["size"]:
            logger.warning("%s %s: %s size mismatch", what, root, rel)
            return False
        if sha256_file(full) != meta["sha256"]:
            logger.warning("%s %s: %s checksum mismatch", what, root, rel)
            return False
    return True


def quarantine_dir(path: str, what: str = "artifact") -> Optional[str]:
    """Rename ``path`` to ``path.corrupt`` (``.corrupt.N`` when taken) so
    a failed artifact is kept for forensics but can never be served or
    restored again. Returns the quarantine path, or None when the rename
    lost a race with a concurrent quarantiner (fine — someone moved it)."""
    dest = path + ".corrupt"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}.corrupt.{n}"
    try:
        os.rename(path, dest)
    except OSError as e:
        logger.warning("could not quarantine %s %s: %s", what, path, e)
        return None
    logger.warning("quarantined corrupt/incomplete %s %s -> %s",
                   what, path, os.path.basename(dest))
    return dest


def prune_corrupt(parent: str, keep: int) -> List[str]:
    """Age out quarantined ``*.corrupt*`` directories beyond ``keep``,
    oldest-NUMBER-first (numeric, not lexicographic — ``10.corrupt`` is
    newer forensics than ``2.corrupt``)."""
    import re
    corrupt = sorted(
        (n for n in os.listdir(parent) if ".corrupt" in n),
        key=lambda n: int(re.search(r"\d+", n).group()
                          if re.search(r"\d+", n) else "0"))
    return prune_dirs(parent, corrupt, keep)


def reap_stale_staging(parent: str, *patterns) -> List[str]:
    """Remove staging/stale directories under ``parent`` whose owning
    pid is gone. Each compiled ``pattern`` must capture the pid as
    group 2 (the ``.tmp-<id>-<pid>`` convention). Live pids — including
    ones we cannot signal (another uid) — keep their dirs."""
    removed = []
    for name in os.listdir(parent):
        m = next((p.match(name) for p in patterns if p.match(name)),
                 None)
        if not m:
            continue
        pid = int(m.group(2))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)  # owner still alive: leave its dir
        except ProcessLookupError:
            shutil.rmtree(os.path.join(parent, name),
                          ignore_errors=True)
            removed.append(name)
            logger.info("removed stale staging dir %s (owner pid %d "
                        "is gone)", name, pid)
        except PermissionError:
            pass  # pid exists under another uid: leave it
    return removed


def prune_dirs(parent: str, names_oldest_first: Sequence[str], keep: int,
               protect: Iterable[str] = ()) -> List[str]:
    """Bounded retention: delete directories oldest-first until at most
    ``keep`` remain, never touching ``protect`` members (aliased /
    pinned / newest-verified artifacts — protected entries still count
    toward the bound, they just cannot be the victim). Returns the
    deleted names."""
    protected = set(protect)
    names = list(names_oldest_first)
    removed: List[str] = []
    excess = len(names) - max(0, int(keep))
    for name in names:
        if excess <= 0:
            break
        if name in protected:
            continue
        shutil.rmtree(os.path.join(parent, name), ignore_errors=True)
        removed.append(name)
        excess -= 1
    return removed
