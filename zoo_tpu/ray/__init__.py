"""Reference ``zoo.ray`` compat (``pyzoo/zoo/ray/raycontext.py:323``
``RayContext`` — RayOnSpark boots Ray raylets inside Spark executors).

The TPU rebuild has no Spark executors to nest Ray into: its worker
fabric is the supervised multi-process bootstrap
(``zoo_tpu.orca.bootstrap`` — ProcessMonitor, restart budgets, orphan
kill), and SPMD workers rendezvous through ``jax.distributed``. This
``RayContext`` keeps reference scripts importable and maps the two
lifecycle calls onto that fabric; if a real Ray install is present,
``init`` simply starts/connects a local Ray instead, so Ray-Tune-style
user code keeps working where ray is available.
"""

from __future__ import annotations

from typing import Optional


class RayContext:
    """reference ``raycontext.py:323``."""

    _active: Optional["RayContext"] = None

    def __init__(self, sc=None, redis_port=None, password=None,
                 object_store_memory=None, verbose=False, env=None,
                 extra_params=None, num_ray_nodes=None,
                 ray_node_cpu_cores=None, **_ignored):
        self.sc = sc
        self.object_store_memory = object_store_memory
        self.num_ray_nodes = num_ray_nodes
        self.initialized = False
        RayContext._active = self

    @classmethod
    def get(cls, initialize: bool = True) -> "RayContext":
        ctx = cls._active or cls()
        if initialize and not ctx.initialized:
            ctx.init()
        return ctx

    def init(self, driver_cores: int = 0):
        try:
            import ray
        except ImportError as e:
            raise RuntimeError(
                "RayContext.init: no ray in this environment. The TPU "
                "rebuild's cluster fabric is the supervised bootstrap "
                "(zoo_tpu.orca.bootstrap.launch_local_cluster / "
                "scripts/run_tpu_pod.sh) and AutoML runs on the local "
                "search engine (zoo_tpu.automl.search) — "
                "init_orca_context() alone is enough for those. Install "
                "ray only if your own code calls ray.* APIs directly."
            ) from e
        if not ray.is_initialized():  # pragma: no cover - needs ray
            kwargs = {}
            if self.object_store_memory:
                kwargs["object_store_memory"] = _to_bytes(
                    self.object_store_memory)
            ray.init(**kwargs)
        self.initialized = True
        return self

    def stop(self):
        if self.initialized:  # pragma: no cover - needs ray
            import ray
            ray.shutdown()
            self.initialized = False


def _to_bytes(mem) -> int:
    if isinstance(mem, int):
        return mem
    s = str(mem).lower().strip()
    mult = 1
    for suffix, m in (("g", 1 << 30), ("m", 1 << 20), ("k", 1 << 10),
                      ("b", 1)):
        if s.endswith(suffix):
            s, mult = s[:-len(suffix)], m
            break
    return int(float(s) * mult)
