from zoo_tpu.models.anomalydetection.anomaly_detector import AnomalyDetector

__all__ = ["AnomalyDetector"]
