"""LSTM anomaly detector (reference: Scala
``models/anomalydetection/AnomalyDetector.scala`` + Python wrapper — stacked
LSTMs predicting the next point; anomalies = largest forecast errors).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import LSTM, Dense, Dropout


class AnomalyDetector(Sequential):
    def __init__(self, feature_shape: Tuple[int, int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        super().__init__(name="anomaly_detector")
        for i, (h, d) in enumerate(zip(hidden_layers, dropouts)):
            last = i == len(hidden_layers) - 1
            kwargs = {"input_shape": tuple(feature_shape)} if i == 0 else {}
            self.add(LSTM(h, return_sequences=not last, **kwargs))
            if d:
                self.add(Dropout(d))
        self.add(Dense(1))

    @staticmethod
    def unroll(data: np.ndarray, unroll_length: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(n, features) series → (windows, unroll, features) x and next-
        step y (reference: ``AnomalyDetector.unroll``)."""
        data = np.asarray(data, np.float32)
        if data.ndim == 1:
            data = data[:, None]
        n = len(data) - unroll_length
        x = np.stack([data[i:i + unroll_length] for i in range(n)])
        y = data[unroll_length:, 0]
        return x, y

    def detect_anomalies(self, y_true: np.ndarray, y_pred: np.ndarray,
                         anomaly_size: int) -> List[int]:
        """Indexes of the ``anomaly_size`` largest absolute errors
        (reference: ``detectAnomalies``)."""
        err = np.abs(np.asarray(y_true).ravel() -
                     np.asarray(y_pred).ravel())
        return list(np.argsort(-err)[:anomaly_size])
