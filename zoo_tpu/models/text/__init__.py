"""TFPark text models, TPU-native (reference:
``pyzoo/zoo/tfpark/text/keras/`` — ``text_model.py:21`` TextKerasModel
base; ``ner.py:21`` NER BiLSTM-CRF; ``pos_tagging.py:20`` SequenceTagger;
``intent_extraction.py:20`` IntentEntity multi-task model; all wrap
nlp-architect keras graphs there). Here the same architectures are built
directly on the keras facade's functional API, so they train through the
jitted sharded step like every other zoo model.

Shared input convention (reference parity):
- word indices ``(batch, sequence_length)``
- character indices ``(batch, sequence_length, word_length)``
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from zoo_tpu.models.text.crf import (
    CRF,
    crf_decode,
    crf_negative_log_likelihood,
    unpack_crf,
)
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    LSTM,
    Bidirectional,
    Conv1D,
    Dense,
    Dropout,
    Embedding,
    GlobalMaxPooling1D,
    Reshape,
    TimeDistributed,
    merge,
)

__all__ = ["NER", "SequenceTagger", "IntentEntity", "CRF", "crf_decode",
           "crf_negative_log_likelihood"]


def _char_features(chars_in, seq_len: int, word_length: int,
                   char_vocab_size: int, char_emb_dim: int,
                   out_dim: int):
    """Per-word character features: embed chars, convolve within each
    word, max-pool — the TPU-friendly char encoder (one big batched conv
    instead of a per-word RNN; the reference's nlp-architect models use
    a char Bi-LSTM, same role)."""
    h = Reshape((seq_len * word_length,))(chars_in)
    h = Embedding(char_vocab_size, char_emb_dim)(h)
    h = Reshape((seq_len, word_length, char_emb_dim))(h)
    h = TimeDistributed(Conv1D(out_dim, 3, border_mode="same",
                               activation="relu"))(h)
    return TimeDistributed(GlobalMaxPooling1D())(h)


class NER(Model):
    """Named-entity recognition: BiLSTM tagger with a CRF (default) or
    softmax head (reference ``ner.py:21``; inputs/outputs match its
    docstring: words (B, T) + chars (B, T, word_length) -> tags).

    ``crf_mode="reg"`` (the reference default — full equal-length
    sequences) is supported; ``"pad"`` (explicit lengths) is not.
    Compile with ``model.default_loss()``; decode predictions with
    ``model.predict_tags(...)``.
    """

    def __init__(self, num_entities: int, word_vocab_size: int,
                 char_vocab_size: int, sequence_length: int = 64,
                 word_length: int = 12, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, tagger_lstm_dim: int = 100,
                 dropout: float = 0.5, crf_mode: str = "reg",
                 classifier: str = "crf"):
        if crf_mode != "reg":
            raise ValueError(
                'crf_mode="pad" is not supported; pad to equal length '
                'and use "reg" (the reference default)')
        if classifier not in ("crf", "softmax"):
            raise ValueError("classifier must be 'crf' or 'softmax'")
        self.classifier = classifier
        words = Input(shape=(sequence_length,), name="words")
        chars = Input(shape=(sequence_length, word_length), name="chars")
        w = Embedding(word_vocab_size, word_emb_dim)(words)
        c = _char_features(chars, sequence_length, word_length,
                           char_vocab_size, char_emb_dim, char_emb_dim)
        h = merge([w, c], mode="concat")
        h = Dropout(dropout)(h)
        h = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True))(h)
        h = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True))(h)
        h = Dropout(dropout)(h)
        if classifier == "crf":
            emissions = Dense(num_entities)(h)
            out = CRF()(emissions)
        else:
            out = Dense(num_entities, activation="softmax")(h)
        super().__init__(input=[words, chars], output=out, name="ner")

    def default_loss(self):
        return (crf_negative_log_likelihood if self.classifier == "crf"
                else "sparse_categorical_crossentropy")

    def predict_tags(self, words, chars, batch_size: int = 32):
        packed = self.predict([words, chars], batch_size=batch_size)
        if self.classifier == "crf":
            return np.asarray(crf_decode(packed))
        return np.argmax(packed, axis=-1)

    @staticmethod
    def load_model(path: str) -> "NER":
        return Model.load(path)


class SequenceTagger(Model):
    """POS-tagger / chunker: 3 BiLSTM layers, two softmax heads
    (reference ``pos_tagging.py:20``; ``classifier="crf"`` upgrades the
    chunk head to a CRF as there). Inputs: words, plus chars when
    ``char_vocab_size`` is given."""

    def __init__(self, num_pos_labels: int, num_chunk_labels: int,
                 word_vocab_size: int,
                 char_vocab_size: Optional[int] = None,
                 sequence_length: int = 64, word_length: int = 12,
                 feature_size: int = 100, dropout: float = 0.2,
                 classifier: str = "softmax"):
        classifier = classifier.lower()
        if classifier not in ("softmax", "crf"):
            raise ValueError("classifier should be softmax or crf")
        self.classifier = classifier
        words = Input(shape=(sequence_length,), name="words")
        inputs = [words]
        h = Embedding(word_vocab_size, feature_size)(words)
        if char_vocab_size is not None:
            chars = Input(shape=(sequence_length, word_length),
                          name="chars")
            inputs.append(chars)
            c = _char_features(chars, sequence_length, word_length,
                               char_vocab_size, 30, feature_size)
            h = merge([h, c], mode="concat")
        h = Dropout(dropout)(h)
        for _ in range(3):
            h = Bidirectional(LSTM(feature_size,
                                   return_sequences=True))(h)
        pos = Dense(num_pos_labels, activation="softmax")(h)
        if classifier == "crf":
            chunk = CRF()(Dense(num_chunk_labels)(h))
        else:
            chunk = Dense(num_chunk_labels, activation="softmax")(h)
        super().__init__(input=inputs, output=[pos, chunk],
                         name="sequence_tagger")

    def default_loss(self):
        chunk_loss = (crf_negative_log_likelihood
                      if self.classifier == "crf"
                      else "sparse_categorical_crossentropy")
        return ["sparse_categorical_crossentropy", chunk_loss]

    @staticmethod
    def load_model(path: str) -> "SequenceTagger":
        return Model.load(path)


class IntentEntity(Model):
    """Joint intent classification + slot filling (reference
    ``intent_extraction.py:20``): shared encoder, a sequence-level
    intent head and a per-token entity head."""

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, char_vocab_size: int,
                 sequence_length: int = 64, word_length: int = 12,
                 word_emb_dim: int = 100, char_emb_dim: int = 30,
                 char_lstm_dim: int = 30, tagger_lstm_dim: int = 100,
                 dropout: float = 0.2, classifier: str = "softmax"):
        if classifier not in ("softmax", "crf"):
            raise ValueError("classifier must be 'softmax' or 'crf'")
        self.classifier = classifier
        words = Input(shape=(sequence_length,), name="words")
        chars = Input(shape=(sequence_length, word_length), name="chars")
        w = Embedding(word_vocab_size, word_emb_dim)(words)
        c = _char_features(chars, sequence_length, word_length,
                           char_vocab_size, char_emb_dim, char_lstm_dim)
        h = merge([w, c], mode="concat")
        h = Dropout(dropout)(h)
        h = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True))(h)
        # intent rides the sequence summary; tags ride the full sequence
        intent_feat = Bidirectional(LSTM(tagger_lstm_dim))(h)
        intent = Dense(num_intents, activation="softmax")(intent_feat)
        tag_h = Bidirectional(LSTM(tagger_lstm_dim,
                                   return_sequences=True))(h)
        if classifier == "crf":
            tags = CRF()(Dense(num_entities)(tag_h))
        else:
            tags = Dense(num_entities, activation="softmax")(tag_h)
        super().__init__(input=[words, chars], output=[intent, tags],
                         name="intent_entity")

    def default_loss(self):
        tag_loss = (crf_negative_log_likelihood
                    if self.classifier == "crf"
                    else "sparse_categorical_crossentropy")
        return ["sparse_categorical_crossentropy", tag_loss]

    @staticmethod
    def load_model(path: str) -> "IntentEntity":
        return Model.load(path)
