"""Linear-chain CRF for sequence tagging, as jittable JAX scans.

The reference's NER head is a BiLSTM-CRF (nlp-architect's ``NERCRF``
wrapped by ``pyzoo/zoo/tfpark/text/keras/ner.py:21``; the CRF op comes
from keras-contrib there). TPU-native rebuild: the forward algorithm
(partition function) and Viterbi decoding are ``lax.scan`` over time —
static shapes, no data-dependent Python control flow.

Packing convention: the :class:`CRF` layer appends its (E, E) transition
matrix to the emissions along the time axis — output ``(B, T+E, E)`` —
so the transition params flow to the loss (``crf_negative_log_likelihood``)
and the decoder (``crf_decode``) through the standard ``loss(y, preds)``
interface. ``unpack_crf`` splits them back apart.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer

__all__ = ["CRF", "crf_negative_log_likelihood", "crf_decode",
           "unpack_crf"]


class CRF(Layer):
    """Terminal tagging layer: owns the transition matrix and packs it
    with the emissions (see module docstring)."""

    def build(self, rng, input_shape):
        e = input_shape[-1]
        return {"T": jnp.zeros((e, e), jnp.float32)}

    def call(self, params, inputs, *, training=False, rng=None):
        b, _, e = inputs.shape
        trans = jnp.broadcast_to(params["T"].astype(inputs.dtype),
                                 (b, e, e))
        return jnp.concatenate([inputs, trans], axis=1)

    def compute_output_shape(self, input_shape):
        b, t, e = input_shape
        return (b, None if t is None else t + e, e)


def unpack_crf(packed) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, T+E, E) -> emissions (B, T, E), transitions (E, E)."""
    e = packed.shape[-1]
    return packed[:, :-e, :], packed[0, -e:, :]


def _forward_log_z(emissions, trans):
    """log partition function per sequence: (B, T, E), (E, E) -> (B,)."""

    def step(alpha, em_t):
        # alpha (B, E): logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None, :, :] + em_t[:, None, :]
        return jax.nn.logsumexp(scores, axis=1), None

    alpha0 = emissions[:, 0, :]
    alpha, _ = jax.lax.scan(step, alpha0,
                            jnp.moveaxis(emissions[:, 1:, :], 1, 0))
    return jax.nn.logsumexp(alpha, axis=-1)


def crf_negative_log_likelihood(y_true, packed):
    """Mean negative log-likelihood of the tag sequences (the CRF
    training objective; reference crf_mode='reg' — full equal-length
    sequences)."""
    emissions, trans = unpack_crf(packed)
    emissions = emissions.astype(jnp.float32)
    trans = trans.astype(jnp.float32)
    y = y_true.astype(jnp.int32)
    if y.ndim == emissions.ndim:  # (B, T, 1) labels
        y = y[..., 0]
    b, t, _ = emissions.shape
    em_score = jnp.sum(
        jnp.take_along_axis(emissions, y[..., None], axis=-1)[..., 0],
        axis=1)
    tr_score = jnp.sum(trans[y[:, :-1], y[:, 1:]], axis=1)
    log_z = _forward_log_z(emissions, trans)
    return jnp.mean(log_z - em_score - tr_score)


def crf_decode(packed) -> jnp.ndarray:
    """Viterbi decode: (B, T+E, E) -> best tag path (B, T)."""
    emissions, trans = unpack_crf(packed)
    emissions = emissions.astype(jnp.float32)
    trans = trans.astype(jnp.float32)

    def fwd(score, em_t):
        # score (B, E) best score ending in each tag
        cand = score[:, :, None] + trans[None, :, :]   # (B, E_prev, E)
        best_prev = jnp.argmax(cand, axis=1)           # (B, E)
        return jnp.max(cand, axis=1) + em_t, best_prev

    score0 = emissions[:, 0, :]
    final, back = jax.lax.scan(fwd, score0,
                               jnp.moveaxis(emissions[:, 1:, :], 1, 0))
    last = jnp.argmax(final, axis=-1)                  # (B,)

    def bwd(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=-1)[:, 0]
        return prev, prev

    _, path = jax.lax.scan(bwd, last, back, reverse=True)
    return jnp.concatenate([jnp.moveaxis(path, 0, 1), last[:, None]],
                           axis=1)


crf_negative_log_likelihood._handles_low_precision = True
