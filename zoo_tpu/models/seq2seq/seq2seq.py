"""Generic RNN encoder-decoder with bridge, teacher forcing and greedy
inference.

Rebuild of the reference seq2seq family (Python
``pyzoo/zoo/models/seq2seq/seq2seq.py``; Scala ``models/seq2seq/``
``Seq2seq.scala`` + ``RNNEncoder.scala`` / ``RNNDecoder.scala`` /
``Bridge.scala``): ``RNNEncoder``/``RNNDecoder`` stack recurrent layers,
``Bridge`` maps the encoder's final states to the decoder's initial
states (dense / densenonlinear / custom), the decoder consumes the
target sequence at training time (teacher forcing) and its own outputs
at inference (the reference's ``infer`` loop), and ``generator`` maps
decoder outputs to the final result.

TPU design: both directions are single ``lax.scan`` programs — the
teacher-forced pass hoists each layer's input projection into one
(B·T, in)×(in, gH) MXU matmul, and greedy decoding is ONE compiled scan
whose carry is (states, previous output), not a per-step host loop (the
reference re-runs the whole graph per generated token,
``Seq2seq.scala:114-151``; here max_seq_len steps are one XLA program).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import GRU, LSTM, Dense, SimpleRNN
from zoo_tpu.pipeline.api.keras.layers.recurrent import _Recurrent


def _create_rnn(rnn_type: str, nlayers: int, hidden_size: int):
    """reference ``seq2seq.py`` ``createRNN``."""
    t = rnn_type.lower()
    cells = {"lstm": LSTM, "gru": GRU, "simplernn": SimpleRNN}
    if t not in cells:
        raise ValueError("Only support lstm|gru|simplernn")
    return [cells[t](hidden_size, return_sequences=True)
            for _ in range(nlayers)]


class RNNEncoder:
    """reference ``seq2seq.py`` RNNEncoder: stacked recurrent layers +
    optional embedding. Holds facade layer objects; the Seq2seq core
    drives their cell steps directly."""

    def __init__(self, rnns: Sequence[_Recurrent], embedding=None,
                 input_shape=None):
        self.rnns = list(rnns)
        self.embedding = embedding
        self.input_shape = input_shape

    @classmethod
    def initialize(cls, rnn_type: str, nlayers: int, hidden_size: int,
                   embedding=None, input_shape=None):
        return cls(_create_rnn(rnn_type, nlayers, hidden_size),
                   embedding, input_shape)


class RNNDecoder(RNNEncoder):
    """reference ``seq2seq.py`` RNNDecoder — same structure; the core
    seeds its states from the bridge."""


class Bridge:
    """reference ``seq2seq.py`` Bridge: how encoder final states become
    decoder initial states. ``dense`` / ``densenonlinear`` concat every
    encoder state feature-wise, project to the decoder's total state
    size, and split (``Bridge.scala:38``); ``customized`` applies a
    user keras layer."""

    def __init__(self, bridge_type: str, decoder_hidden_size: int,
                 bridge=None):
        t = bridge_type.lower()
        if t not in ("dense", "densenonlinear", "customized"):
            raise ValueError(
                "bridge_type must be dense|densenonlinear|customized")
        if t == "customized" and bridge is None:
            raise ValueError("customized bridge needs the keras layer")
        self.bridge_type = t
        self.decoder_hidden_size = decoder_hidden_size
        self.bridge = bridge

    @classmethod
    def initialize(cls, bridge_type: str, decoder_hidden_size: int):
        return cls(bridge_type, decoder_hidden_size, None)

    @classmethod
    def initialize_from_keras_layer(cls, bridge):
        return cls("customized", 0, bridge)


def _state_list(carry):
    """Flatten one layer's carry (h or (h, c)) to a list of tensors."""
    return list(carry) if isinstance(carry, tuple) else [carry]


def _pack_state(template, flat: List):
    if isinstance(template, tuple):
        return tuple(flat[:len(template)])
    return flat[0]


class _Seq2seqCore(Layer):
    """The whole encoder→bridge→decoder→generator computation as one
    layer over inputs ``[enc_x, dec_x]``.

    training=True: teacher forcing — the decoder reads ``dec_x``
    (reference ``buildModel``, ``Seq2seq.scala:59``: decoder input IS
    the target sequence at train time).
    training=False: greedy self-feeding — ``dec_x[:, 0]`` is the start
    token and each further step consumes the previous generated output,
    for ``dec_x.shape[1]`` steps (the reference ``infer`` contract).
    """

    def __init__(self, encoder: RNNEncoder, decoder: RNNDecoder,
                 bridge: Optional[Bridge], generator,
                 train_self_feed: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.encoder = encoder
        self.decoder = decoder
        self.bridge = bridge
        self.generator = generator
        # single-input models have no teacher sequence: self-feed in
        # both modes (the derived dec input only sets length/start)
        self.train_self_feed = train_self_feed

    # -- params -----------------------------------------------------------
    def build(self, rng, input_shape):
        enc_shape, dec_shape = input_shape
        params = {}
        ks = jax.random.split(rng, 8)
        feat = enc_shape[-1]
        if self.encoder.embedding is not None:
            params["enc_emb"] = self.encoder.embedding.build(
                ks[6], enc_shape)
            feat = self.encoder.embedding.compute_output_shape(
                enc_shape)[-1]
        for i, cell in enumerate(self.encoder.rnns):
            params[f"enc_{i}"] = cell.build(
                jax.random.fold_in(ks[0], i), (None, None, feat))
            feat = cell.output_dim
        dfeat = dec_shape[-1]
        if self.decoder.embedding is not None:
            params["dec_emb"] = self.decoder.embedding.build(
                ks[7], dec_shape)
            dfeat = self.decoder.embedding.compute_output_shape(
                dec_shape)[-1]
        for i, cell in enumerate(self.decoder.rnns):
            params[f"dec_{i}"] = cell.build(
                jax.random.fold_in(ks[1], i), (None, None, dfeat))
            dfeat = cell.output_dim
        if self.bridge is not None:
            enc_units = sum(
                len(_state_list(c._init_carry(1))) * c.output_dim
                for c in self.encoder.rnns)
            dec_units = sum(
                len(_state_list(c._init_carry(1))) * c.output_dim
                for c in self.decoder.rnns)
            if self.bridge.bridge_type == "customized":
                params["bridge"] = self.bridge.bridge.build(
                    ks[2], (None, enc_units))
            else:
                init = jax.nn.initializers.glorot_uniform()
                params["bridge"] = {
                    "w": init(ks[2], (enc_units, dec_units), jnp.float32),
                    "b": jnp.zeros((dec_units,), jnp.float32)}
        if self.generator is not None:
            params["gen"] = self.generator.build(ks[3], (None, dfeat))
        return params

    # -- pieces -----------------------------------------------------------
    def _run_encoder(self, params, x, training, rng):
        if self.encoder.embedding is not None:
            x = self.encoder.embedding.call(params["enc_emb"], x,
                                            training=training, rng=rng)
        finals = []
        for i, cell in enumerate(self.encoder.rnns):
            p = params[f"enc_{i}"]
            zx = jnp.einsum("btd,dh->bth", x, p["W"]) + p["b"]
            carry0 = cell._init_carry(x.shape[0])

            def body(carry, z, _cell=cell, _p=p):
                carry, h = _cell._step(_p, carry, z)
                return carry, h

            carry, hs = jax.lax.scan(body, carry0,
                                     jnp.swapaxes(zx, 0, 1))
            x = jnp.swapaxes(hs, 0, 1)
            finals.append(carry)
        return x, finals

    def _bridge_states(self, params, enc_finals, training, rng):
        dec_templates = [c._init_carry(1) for c in self.decoder.rnns]
        if self.bridge is None:
            # passthrough (reference: bridge == null) — shapes must match
            return enc_finals
        flat = jnp.concatenate(
            [s for c in enc_finals for s in _state_list(c)], axis=-1)
        if self.bridge.bridge_type == "customized":
            out = self.bridge.bridge.call(params["bridge"], flat,
                                          training=training, rng=rng)
        else:
            out = flat @ params["bridge"]["w"] + params["bridge"]["b"]
            if self.bridge.bridge_type == "densenonlinear":
                out = jnp.tanh(out)
        states, lo = [], 0
        for cell, tmpl in zip(self.decoder.rnns, dec_templates):
            n = len(_state_list(tmpl))
            parts = [out[:, lo + j * cell.output_dim:
                         lo + (j + 1) * cell.output_dim]
                     for j in range(n)]
            lo += n * cell.output_dim
            states.append(_pack_state(tmpl, parts))
        return states

    def _gen_step(self, params, h, training, rng):
        if self.generator is None:
            return h
        return self.generator.call(params["gen"], h, training=training,
                                   rng=rng)

    def _decode_teacher(self, params, dec_x, states, training, rng):
        x = dec_x
        if self.decoder.embedding is not None:
            x = self.decoder.embedding.call(params["dec_emb"], x,
                                            training=training, rng=rng)
        for i, cell in enumerate(self.decoder.rnns):
            p = params[f"dec_{i}"]
            zx = jnp.einsum("btd,dh->bth", x, p["W"]) + p["b"]

            def body(carry, z, _cell=cell, _p=p):
                carry, h = _cell._step(_p, carry, z)
                return carry, h

            _, hs = jax.lax.scan(body, states[i],
                                 jnp.swapaxes(zx, 0, 1))
            x = jnp.swapaxes(hs, 0, 1)
        b, t = x.shape[0], x.shape[1]
        out = self._gen_step(params, x.reshape(b * t, -1), training, rng)
        return out.reshape(b, t, -1)

    def _decode_greedy(self, params, start, n_steps, states, rng):
        """One scan over n_steps; carry = (per-layer states, prev out)."""
        if self.decoder.embedding is not None:
            raise NotImplementedError(
                "greedy decoding through a decoder embedding needs an "
                "argmax→id feedback rule; pass explicit decoder inputs "
                "(teacher mode) or decode int sequences externally")

        def body(carry, _):
            states, prev = carry
            x = prev
            new_states = []
            for i, cell in enumerate(self.decoder.rnns):
                p = params[f"dec_{i}"]
                z = x @ p["W"] + p["b"]
                st, x = cell._step(p, states[i], z)
                new_states.append(st)
            out = self._gen_step(params, x, False, rng)
            return (new_states, out), out

        first_in = start
        _, outs = jax.lax.scan(body, (states, first_in), None,
                               length=n_steps)
        return jnp.swapaxes(outs, 0, 1)

    # -- layer surface ----------------------------------------------------
    def call(self, params, inputs, *, training=False, rng=None):
        enc_x, dec_x = inputs
        _, enc_finals = self._run_encoder(params, enc_x, training, rng)
        states = self._bridge_states(params, enc_finals, training, rng)
        if training and not self.train_self_feed:
            return self._decode_teacher(params, dec_x, states, training,
                                        rng)
        start = dec_x[:, 0]
        if self.decoder.embedding is not None:
            # int-id decoders can't self-feed raw outputs; run teacher
            # mode on whatever ids the caller supplied
            return self._decode_teacher(params, dec_x, states, training,
                                        rng)
        return self._decode_greedy(params, start, dec_x.shape[1], states,
                                   rng)

    def compute_output_shape(self, input_shape):
        enc_shape, dec_shape = input_shape
        d = dec_shape[-1]
        if self.generator is not None:
            d = self.generator.compute_output_shape((None, d))[-1]
        elif self.decoder.rnns:
            d = self.decoder.rnns[-1].output_dim
        return (dec_shape[0], dec_shape[1], d)


class Seq2seq(Model):
    """reference ``seq2seq.py:158`` / ``Seq2seq.scala:50``.

    ``Seq2seq(encoder, decoder, input_shape, output_shape, bridge=None,
    generator=None)`` — a two-input model ``[enc_seq, dec_seq]``:
    teacher forcing at fit time, greedy self-feeding at predict time
    (``dec_seq[:, 0]`` is the start token; the rest of ``dec_seq`` only
    sets the length).

    The pre-round-5 simplified constructor
    ``Seq2seq(input_length=, input_dim=, target_length=, output_dim=,
    rnn_type=, hidden_size=, num_layers=)`` still works and now gets
    the real decoder too: it feeds the learned start token and
    self-feeds for ``target_length`` steps in both modes (it has no
    separate decoder input), with a dense bridge seeding the decoder
    from the encoder state instead of the old context-repeat.
    """

    def __init__(self, encoder=None, decoder=None, input_shape=None,
                 output_shape=None, bridge=None, generator=None, *,
                 input_length: Optional[int] = None,
                 input_dim: Optional[int] = None,
                 target_length: Optional[int] = None,
                 output_dim: Optional[int] = None,
                 rnn_type: str = "lstm", hidden_size: int = 64,
                 num_layers: int = 1, name: str = "seq2seq"):
        if input_length is not None:  # simplified constructor
            encoder = RNNEncoder.initialize(rnn_type, num_layers,
                                            hidden_size)
            decoder = RNNDecoder.initialize(rnn_type, num_layers,
                                            hidden_size)
            bridge = Bridge.initialize("dense", hidden_size)
            generator = Dense(output_dim)
            input_shape = (input_length, input_dim)
            output_shape = (target_length, output_dim)
            self._single_input = True
        else:
            if encoder is None or decoder is None:
                raise ValueError(
                    "Seq2seq needs (encoder, decoder, input_shape, "
                    "output_shape) or the simplified input_length= form")
            if input_shape is None or output_shape is None:
                raise TypeError(
                    "input_shape and output_shape cannot be None")
            self._single_input = False
        self.encoder, self.decoder = encoder, decoder
        self.bridge, self.generator = bridge, generator
        self._out_len = int(output_shape[0])
        self._out_dim = int(output_shape[-1])
        core = _Seq2seqCore(encoder, decoder, bridge, generator,
                            train_self_feed=self._single_input,
                            name=f"{name}_core")
        enc_in = Input(shape=tuple(input_shape), name=f"{name}_enc_in")
        if self._single_input:
            from zoo_tpu.pipeline.api.keras.layers import Lambda
            t, d = self._out_len, self._out_dim
            # the decoder side is derived: a zero start token + length
            dec_node = Lambda(
                lambda x: jnp.zeros(x.shape[:1] + (t, d), x.dtype),
                output_shape=(t, d))(enc_in)
            out = core([enc_in, dec_node])
            super().__init__(input=enc_in, output=out, name=name)
        else:
            dec_in = Input(shape=tuple(output_shape),
                           name=f"{name}_dec_in")
            out = core([enc_in, dec_in])
            super().__init__(input=[enc_in, dec_in], output=out,
                             name=name)
        self._core = core

    # -- reference infer --------------------------------------------------
    def infer(self, input, start_sign, max_seq_len: int = 30,
              stop_sign=None, build_output=None):
        """reference ``Seq2seq.scala:114``: greedy-decode up to
        ``max_seq_len`` steps from ``start_sign``, host-trimmed at
        ``stop_sign``. One compiled scan computes all steps; the
        early-exit is a host-side trim (data-dependent break inside jit
        would force per-step dispatch)."""
        import numpy as np

        if self._single_input:
            raise ValueError(
                "infer(start_sign=, max_seq_len=) needs the two-input "
                "Seq2seq form (encoder, decoder, ...) — the simplified "
                "single-input constructor generates exactly "
                "target_length steps from its internal start token; "
                "call predict(x) instead")
        x = np.asarray(input)
        if x.ndim == 2:
            x = x[None]
        start = np.asarray(start_sign).reshape(1, 1, -1)
        start = np.repeat(start, x.shape[0], axis=0)
        dec = np.concatenate(
            [start, np.zeros((x.shape[0], max_seq_len - 1,
                              start.shape[-1]), start.dtype)], axis=1)
        out = self.predict([x, dec], batch_size=max(1, x.shape[0]))
        out = np.asarray(out)
        if build_output is not None:
            out = np.asarray(build_output(out)) if callable(build_output) \
                else out
        if stop_sign is not None:
            if out.shape[0] != 1:
                raise ValueError(
                    "stop_sign trimming is defined for a single sample "
                    "(the reference infer contract); decode batches "
                    "without stop_sign and trim per row yourself")
            stop = np.asarray(stop_sign).reshape(-1)
            for t in range(out.shape[1]):
                if np.allclose(out[0, t], stop, atol=1e-8):
                    out = out[:, :t + 1]
                    break
        # reference returns [start; generated...]
        return np.concatenate([start.astype(out.dtype), out], axis=1)
