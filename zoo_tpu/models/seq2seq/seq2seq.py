"""Generic RNN encoder-decoder (reference: Scala ``models/seq2seq/``
``Seq2seq.scala`` with RNNEncoder/RNNDecoder/Bridge — LSTM/GRU cells,
optional bridge mapping encoder state to decoder init).

Simplified TPU-native equivalent: encoder RNN consumes the source sequence;
its final state seeds a decoder RNN run for ``target_length`` steps
(context-repeat decoding, no teacher forcing); a TimeDistributed head emits
per-step outputs.
"""

from __future__ import annotations

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import (
    GRU,
    LSTM,
    Dense,
    RepeatVector,
    TimeDistributed,
)


class Seq2seq(Sequential):
    def __init__(self, input_length: int, input_dim: int,
                 target_length: int, output_dim: int,
                 rnn_type: str = "lstm", hidden_size: int = 64,
                 num_layers: int = 1):
        super().__init__(name="seq2seq")
        rnn_type = rnn_type.lower()
        if rnn_type not in ("lstm", "gru"):
            raise ValueError("rnn_type must be lstm | gru")
        cell = LSTM if rnn_type == "lstm" else GRU
        for i in range(num_layers):
            last = i == num_layers - 1
            kwargs = {"input_shape": (input_length, input_dim)} if i == 0 \
                else {}
            self.add(cell(hidden_size, return_sequences=not last, **kwargs))
        self.add(RepeatVector(target_length))
        for i in range(num_layers):
            self.add(cell(hidden_size, return_sequences=True))
        self.add(TimeDistributed(Dense(output_dim)))
