from zoo_tpu.models.seq2seq.seq2seq import (
    Bridge,
    RNNDecoder,
    RNNEncoder,
    Seq2seq,
)

__all__ = ["Seq2seq", "RNNEncoder", "RNNDecoder", "Bridge"]
