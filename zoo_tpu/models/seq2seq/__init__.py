from zoo_tpu.models.seq2seq.seq2seq import Seq2seq

__all__ = ["Seq2seq"]
