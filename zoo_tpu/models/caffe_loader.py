"""Caffe model loader — zero-dependency prototxt + caffemodel ingestion.

Rebuild of the reference's Caffe ingestion
(``zoo/src/main/scala/com/intel/analytics/zoo/models/caffe/CaffeLoader.scala:718``,
surfaced in Python as ``Net.load_caffe`` in
``pyzoo/zoo/pipeline/api/net/net.py``). The reference parses Caffe's
``NetParameter`` protobuf (deploy prototxt for topology, ``.caffemodel``
for weights, matched by layer name) and converts each layer into a BigDL
module. Here the binary is decoded straight from protobuf wire format with
the same minimal codec the ONNX loader uses (field numbers per the public
``caffe.proto``), the deploy prototxt is parsed with a small text-format
reader, and the net is interpreted in JAX as a :class:`KerasNet` — so a
loaded Caffe model predicts and fine-tunes like any other zoo model.

Layout note: Caffe is NCHW end to end; the interpreter keeps NCHW and maps
convolutions onto ``lax.conv_general_dilated`` (MXU-friendly; XLA chooses
the TPU-native layout under jit).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet
from zoo_tpu.tensorboard import proto as wire

# ------------------------------------------------- caffe.proto field ids
# NetParameter
_NET_NAME, _NET_LAYERS_V1, _NET_INPUT, _NET_INPUT_DIM = 1, 2, 3, 4
_NET_INPUT_SHAPE, _NET_LAYER = 8, 100
# BlobShape / BlobProto
_SHAPE_DIM = 1
_BLOB_NUM, _BLOB_CH, _BLOB_H, _BLOB_W = 1, 2, 3, 4
_BLOB_DATA, _BLOB_SHAPE, _BLOB_DDATA = 5, 7, 9
# LayerParameter (the "new" format)
_L_NAME, _L_TYPE, _L_BOTTOM, _L_TOP, _L_BLOBS = 1, 2, 3, 4, 7
_L_PARAMS = {  # sub-message field id -> attr-group name
    104: "concat", 106: "convolution", 108: "dropout", 110: "eltwise",
    117: "inner_product", 118: "lrn", 121: "pooling", 122: "power",
    123: "relu", 125: "softmax", 131: "prelu", 133: "reshape",
    135: "flatten", 139: "batch_norm", 140: "elu", 142: "scale",
    143: "input",
}
# V1LayerParameter (legacy binaries still carry weights in this form)
_V1_BOTTOM, _V1_TOP, _V1_NAME, _V1_TYPE, _V1_BLOBS = 2, 3, 4, 5, 6
_V1_PARAMS = {9: "concat", 10: "convolution", 12: "dropout", 24: "eltwise",
              17: "inner_product", 18: "lrn", 19: "pooling", 21: "power",
              30: "relu", 39: "softmax", 38: "sigmoid", 37: "tanh"}
_V1_TYPE_NAMES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout", 8: "Flatten",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU", 19: "Sigmoid",
    20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split", 23: "TanH",
    25: "Eltwise", 26: "Power", 39: "Deconvolution", 1: "Accuracy",
}


def _floats(vals: List) -> np.ndarray:
    """Repeated float field: packed bytes or scattered fixed32 values."""
    out: List[float] = []
    for v in vals:
        if isinstance(v, bytes):
            out.extend(np.frombuffer(v, "<f4").tolist())
        else:
            out.append(float(v))
    return np.asarray(out, np.float32)


def _ints(vals: List) -> List[int]:
    out: List[int] = []
    for v in vals:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = wire.decode_varint(v, pos)
                out.append(x)
        else:
            out.append(int(v))
    return out


def _parse_blob(buf: bytes) -> np.ndarray:
    f = wire.parse_fields(buf)
    if _BLOB_SHAPE in f:
        dims = _ints(wire.parse_fields(f[_BLOB_SHAPE][0]).get(_SHAPE_DIM, []))
    else:  # legacy num/channels/height/width
        dims = [int(f.get(k, [1])[0])
                for k in (_BLOB_NUM, _BLOB_CH, _BLOB_H, _BLOB_W)]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    if _BLOB_DDATA in f:
        data = np.asarray([float(v) for v in f[_BLOB_DDATA]], np.float32)
    else:
        data = _floats(f.get(_BLOB_DATA, []))
    return data.reshape(dims) if dims else data


# Per-group scalar field numbers we care about (caffe.proto).
_ATTR_FIELDS: Dict[str, Dict[int, str]] = {
    "convolution": {1: "num_output", 2: "bias_term", 3: "pad",
                    4: "kernel_size", 5: "group", 6: "stride", 9: "pad_h",
                    10: "pad_w", 11: "kernel_h", 12: "kernel_w",
                    13: "stride_h", 14: "stride_w", 18: "dilation"},
    "pooling": {1: "pool", 2: "kernel_size", 3: "stride", 4: "pad",
                5: "kernel_h", 6: "kernel_w", 7: "stride_h", 8: "stride_w",
                9: "pad_h", 10: "pad_w", 12: "global_pooling"},
    "inner_product": {1: "num_output", 2: "bias_term", 5: "axis",
                      6: "transpose"},
    "lrn": {1: "local_size", 2: "alpha", 3: "beta", 4: "norm_region",
            5: "k"},
    "batch_norm": {1: "use_global_stats", 2: "moving_average_fraction",
                   3: "eps"},
    "scale": {1: "axis", 2: "num_axes", 4: "bias_term"},
    "concat": {1: "concat_dim", 2: "axis"},
    "eltwise": {1: "operation", 2: "coeff"},
    "dropout": {1: "dropout_ratio"},
    "relu": {1: "negative_slope"},
    "softmax": {2: "axis"},
    "flatten": {1: "axis", 2: "end_axis"},
    "reshape": {1: "shape", 2: "axis", 3: "num_axes"},
    "power": {1: "power", 2: "scale", 3: "shift"},
    "elu": {1: "alpha"},
    "prelu": {2: "channel_shared"},
    "input": {1: "shape"},
}
_REPEATED = {"pad", "kernel_size", "stride", "dilation", "coeff", "shape"}
_FLOAT_ATTRS = {"alpha", "beta", "k", "eps", "moving_average_fraction",
                "dropout_ratio", "negative_slope", "coeff", "power",
                "scale", "shift"}


def _parse_attr_group(group: str, buf: bytes) -> Dict[str, Any]:
    names = _ATTR_FIELDS.get(group, {})
    out: Dict[str, Any] = {}
    for field, wtype, val in wire.iter_fields(buf):
        name = names.get(field)
        if name is None:
            continue
        if name == "shape" and isinstance(val, bytes):
            out.setdefault("shape", []).append(
                _ints(wire.parse_fields(val).get(_SHAPE_DIM, [])))
            continue
        if name in _FLOAT_ATTRS and name not in _REPEATED:
            out[name] = float(val)
        elif name in _REPEATED:
            if isinstance(val, bytes):  # packed ints
                out.setdefault(name, []).extend(_ints([val]))
            else:
                out.setdefault(name, []).append(
                    float(val) if name in _FLOAT_ATTRS else int(val))
        else:
            out[name] = int(val) if not isinstance(val, bytes) else val
    return out


class CaffeLayer:
    __slots__ = ("name", "type", "bottoms", "tops", "blobs", "attrs")

    def __init__(self, name, type_, bottoms, tops, blobs, attrs):
        self.name, self.type = name, type_
        self.bottoms, self.tops = bottoms, tops
        self.blobs: List[np.ndarray] = blobs
        self.attrs: Dict[str, Any] = attrs


def _parse_layer(buf: bytes, v1: bool) -> CaffeLayer:
    f = wire.parse_fields(buf)
    if v1:
        name = f.get(_V1_NAME, [b""])[0].decode()
        type_ = _V1_TYPE_NAMES.get(int(f.get(_V1_TYPE, [0])[0]), "Unknown")
        bottoms = [b.decode() for b in f.get(_V1_BOTTOM, [])]
        tops = [b.decode() for b in f.get(_V1_TOP, [])]
        blobs = [_parse_blob(b) for b in f.get(_V1_BLOBS, [])]
        params = _V1_PARAMS
    else:
        name = f.get(_L_NAME, [b""])[0].decode()
        type_ = f.get(_L_TYPE, [b""])[0].decode()
        bottoms = [b.decode() for b in f.get(_L_BOTTOM, [])]
        tops = [b.decode() for b in f.get(_L_TOP, [])]
        blobs = [_parse_blob(b) for b in f.get(_L_BLOBS, [])]
        params = _L_PARAMS
    attrs: Dict[str, Any] = {}
    for field, group in params.items():
        if field in f:
            attrs.update(_parse_attr_group(group, f[field][0]))
    return CaffeLayer(name, type_, bottoms, tops, blobs, attrs)


class CaffeNetParameter:
    """Parsed NetParameter (binary wire format)."""

    def __init__(self, data: bytes):
        f = wire.parse_fields(data)
        self.name = f.get(_NET_NAME, [b""])[0].decode()
        self.layers = ([_parse_layer(b, False) for b in f.get(_NET_LAYER, [])]
                       or [_parse_layer(b, True)
                           for b in f.get(_NET_LAYERS_V1, [])])
        self.inputs = [b.decode() for b in f.get(_NET_INPUT, [])]
        self.input_shapes: List[Tuple[int, ...]] = []
        for b in f.get(_NET_INPUT_SHAPE, []):
            self.input_shapes.append(tuple(
                _ints(wire.parse_fields(b).get(_SHAPE_DIM, []))))
        dims = _ints(f.get(_NET_INPUT_DIM, []))
        if dims and not self.input_shapes:
            self.input_shapes = [tuple(dims[i:i + 4])
                                 for i in range(0, len(dims), 4)]


# ----------------------------------------------- prototxt (text format)

_TOKEN = re.compile(r"""
    (?P<brace>[{}])            |
    (?P<name>[A-Za-z_][\w.]*)\s*:?\s* |
    (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*') |
    (?P<number>[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?)
""", re.VERBOSE)


def _tokenize_prototxt(text: str):
    text = re.sub(r"#[^\n]*", "", text)
    pos = 0
    while pos < len(text):
        if text[pos].isspace() or text[pos] == ",":
            pos += 1
            continue
        m = _TOKEN.match(text, pos)
        if not m:
            raise ValueError(f"prototxt parse error at offset {pos}: "
                             f"{text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup == "brace":
            yield ("brace", m.group("brace"))
        elif m.lastgroup == "name":
            yield ("name", m.group("name"))
        elif m.lastgroup == "string":
            yield ("value", m.group("string")[1:-1])
        else:
            n = m.group("number")
            yield ("value", float(n) if ("." in n or "e" in n.lower())
                   else int(n))


def parse_prototxt(text: str) -> Dict[str, List]:
    """Parse protobuf text format into nested {field: [values...]} dicts.
    Every field is a list (protobuf fields may repeat)."""
    tokens = list(_tokenize_prototxt(text))
    pos = 0

    def message():
        nonlocal pos
        out: Dict[str, List] = {}
        while pos < len(tokens):
            kind, val = tokens[pos]
            if kind == "brace" and val == "}":
                pos += 1
                return out
            assert kind == "name", f"expected field name, got {val!r}"
            field = val
            pos += 1
            kind, val = tokens[pos]
            if kind == "brace" and val == "{":
                pos += 1
                out.setdefault(field, []).append(message())
            else:
                pos += 1
                if val in ("true", "false"):
                    val = val == "true"
                out.setdefault(field, []).append(val)
        return out

    return message()


_BOOL = {"true": True, "false": False, True: True, False: False,
         0: False, 1: True}

# V1 text-format layer-type enum names → new-format type strings.
_V1_ENUM_NAMES = {
    "CONVOLUTION": "Convolution", "DECONVOLUTION": "Deconvolution",
    "POOLING": "Pooling", "INNER_PRODUCT": "InnerProduct", "RELU": "ReLU",
    "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "CONCAT": "Concat", "DROPOUT": "Dropout", "ELTWISE": "Eltwise",
    "DATA": "Data", "FLATTEN": "Flatten", "SIGMOID": "Sigmoid",
    "TANH": "TanH", "SPLIT": "Split", "SLICE": "Slice", "POWER": "Power",
    "ACCURACY": "Accuracy", "ABSVAL": "AbsVal", "EXP": "Exp",
    "HDF5_DATA": "HDF5Data", "IMAGE_DATA": "ImageData",
    "MEMORY_DATA": "MemoryData", "DUMMY_DATA": "DummyData",
}


def _prototxt_layers(net: Dict[str, List]) -> List[CaffeLayer]:
    layers = []
    for ld in net.get("layer", net.get("layers", [])):
        name = ld.get("name", [""])[0]
        type_ = str(ld.get("type", [""])[0])
        # V1 prototxts carry SCREAMING_CASE enum names; map only known enum
        # names so new-format all-caps types (ELU, BNLL, LRN) pass through.
        type_ = _V1_ENUM_NAMES.get(type_, type_)
        attrs: Dict[str, Any] = {}
        for group in _ATTR_FIELDS:
            sub = ld.get(group + "_param")
            if sub:
                for k, v in sub[0].items():
                    if k == "shape":
                        attrs["shape"] = [
                            [int(d) for d in s.get("dim", [])] for s in v]
                    elif k in _REPEATED:
                        attrs[k] = [x for x in v]
                    else:
                        attrs[k] = v[0]
        # pooling `pool: MAX` comes through as the enum name string
        if "pool" in attrs and isinstance(attrs["pool"], str):
            attrs["pool"] = {"MAX": 0, "AVE": 1, "STOCHASTIC": 2}[
                attrs["pool"]]
        if "operation" in attrs and isinstance(attrs["operation"], str):
            attrs["operation"] = {"PROD": 0, "SUM": 1, "MAX": 2}[
                attrs["operation"]]
        phase = [i.get("phase", [None])[0] for i in ld.get("include", [])]
        if phase and "TRAIN" in phase:
            continue  # deploy graph only (reference skips train-only layers)
        layers.append(CaffeLayer(
            name, type_, list(ld.get("bottom", [])), list(ld.get("top", [])),
            [], attrs))
    return layers


# ------------------------------------------------------------- JAX ops

_SKIP = {"Data", "DummyData", "ImageData", "HDF5Data", "MemoryData",
         "Accuracy", "Silence", "ArgMax", "SoftmaxWithLoss"}


def _pair(attrs, base, default=0):
    h = attrs.get(base + "_h")
    w = attrs.get(base + "_w")
    if h is not None or w is not None:
        return int(h or default), int(w or default)
    v = attrs.get(base, default)
    if isinstance(v, (list, tuple)):
        v = list(v) or [default]
        return (int(v[0]), int(v[-1]))
    return int(v), int(v)


def _conv(layer: CaffeLayer, w, b, x, transpose=False):
    kh, kw = _pair(layer.attrs, "kernel_size")
    sh, sw = _pair(layer.attrs, "stride", 1)
    ph, pw = _pair(layer.attrs, "pad", 0)
    dil = layer.attrs.get("dilation", [1])
    d = int(dil[0]) if isinstance(dil, (list, tuple)) else int(dil)
    groups = int(layer.attrs.get("group", 1))
    w = jnp.asarray(w).reshape((-1,) + tuple(w.shape[-3:]))
    if transpose:
        # Caffe Deconvolution weight is (in, out/g, kh, kw); expressed as a
        # fractionally-strided conv: dilate the input by the stride, flip
        # the kernel spatially, regroup to OIHW = (out, in/g, kh, kw), and
        # pad with (k_eff - 1 - p) so out = (i-1)*s + k_eff - 2p.
        cin = x.shape[1]
        wt = w.reshape(groups, cin // groups, -1, kh, kw)
        wt = jnp.transpose(wt, (0, 2, 1, 3, 4)).reshape(
            (-1, cin // groups, kh, kw))[:, :, ::-1, ::-1]
        keh, kew = d * (kh - 1) + 1, d * (kw - 1) + 1
        out = lax.conv_general_dilated(
            x, wt, window_strides=(1, 1),
            padding=[(keh - 1 - ph,) * 2, (kew - 1 - pw,) * 2],
            lhs_dilation=(sh, sw), rhs_dilation=(d, d),
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    else:
        out = lax.conv_general_dilated(
            x, w, window_strides=(sh, sw), padding=[(ph, ph), (pw, pw)],
            rhs_dilation=(d, d), feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if b is not None:
        out = out + jnp.asarray(b).reshape(1, -1, 1, 1)
    return out


def _pool(layer: CaffeLayer, x):
    if _BOOL.get(layer.attrs.get("global_pooling", False), False):
        kh, kw = x.shape[2], x.shape[3]
        sh = sw = 1
        ph = pw = 0
    else:
        kh, kw = _pair(layer.attrs, "kernel_size")
        sh, sw = _pair(layer.attrs, "stride", 1)
        ph, pw = _pair(layer.attrs, "pad", 0)
    mode = int(layer.attrs.get("pool", 0))
    # Caffe uses ceil-mode output sizing: pad the right/bottom edge so the
    # last partial window is kept (CaffeLoader preserves this).
    def ceil_extra(size, k, s, p):
        out = int(np.ceil((size + 2 * p - k) / s)) + 1
        if (out - 1) * s >= size + p:
            out -= 1
        return max(0, (out - 1) * s + k - size - p)
    eh = ceil_extra(x.shape[2], kh, sh, ph)
    ew = ceil_extra(x.shape[3], kw, sw, pw)
    pads = [(0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew)]
    if mode == 0:
        y = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, kh, kw),
                              (1, 1, sh, sw), pads)
    else:
        # Caffe AVE divides by the window area clipped to the *padded*
        # extent (padded zeros count; only the ceil-mode overflow beyond
        # height+pad is excluded) — pooling_layer.cpp pool_size semantics.
        s = lax.reduce_window(x, 0.0, lax.add, (1, 1, kh, kw),
                              (1, 1, sh, sw), pads)
        ones = jnp.pad(jnp.ones_like(x),
                       [(0, 0), (0, 0), (ph, ph), (pw, pw)],
                       constant_values=1.0)
        ones = jnp.pad(ones, [(0, 0), (0, 0), (0, eh), (0, ew)])
        cnt = lax.reduce_window(ones, 0.0, lax.add, (1, 1, kh, kw),
                                (1, 1, sh, sw), "VALID")
        y = s / cnt
    return y


def _lrn(layer: CaffeLayer, x):
    size = int(layer.attrs.get("local_size", 5))
    alpha = float(layer.attrs.get("alpha", 1.0))
    beta = float(layer.attrs.get("beta", 0.75))
    k = float(layer.attrs.get("k", 1.0))
    sq = x * x
    half = size // 2
    if int(layer.attrs.get("norm_region", 0)) == 1:  # WITHIN_CHANNEL
        acc = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, size, size), (1, 1, 1, 1),
            [(0, 0), (0, 0), (half, half), (half, half)])
        return x / jnp.power(k + alpha / (size * size) * acc, beta)
    acc = lax.reduce_window(sq, 0.0, lax.add, (1, size, 1, 1), (1, 1, 1, 1),
                            [(0, 0), (half, half), (0, 0), (0, 0)])
    return x / jnp.power(k + alpha / size * acc, beta)


def _eltwise(layer: CaffeLayer, *xs):
    op = int(layer.attrs.get("operation", 1))
    if op == 0:
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out
    if op == 2:
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out
    coeff = layer.attrs.get("coeff") or [1.0] * len(xs)
    return sum(float(c) * x for c, x in zip(coeff, xs))


class CaffeNet(KerasNet):
    """A Caffe net as a trainable KerasNet: layer blobs are the params."""

    def __init__(self, layers: List[CaffeLayer], inputs: List[str],
                 input_shapes: List[Tuple[int, ...]],
                 name: Optional[str] = None):
        super().__init__(name=name or "caffe")
        self.caffe_layers = [l for l in layers if l.type not in _SKIP]
        self.inputs = list(inputs)
        self._built_shapes = [
            (None,) + tuple(s[1:]) if s else (None,)
            for s in (input_shapes or [()] * len(self.inputs))]
        w = {}
        for l in self.caffe_layers:
            for i, blob in enumerate(l.blobs):
                w[f"{l.name}/b{i}"] = jnp.asarray(blob, jnp.float32)
        self.params = {"caffe": {"w": w}}

    @property
    def layers(self):
        return []

    def _input_shapes(self):
        return self._built_shapes

    def _init_params(self, rng, input_shapes):
        return self.params

    def _forward(self, params, inputs, *, training, rng, collect):
        w = params["caffe"]["w"]
        env: Dict[str, Any] = {}
        for name, val in zip(self.inputs, inputs):
            env[name] = val
        out_names: List[str] = []
        for l in self.caffe_layers:
            if l.type == "Input":
                continue
            blobs = [w.get(f"{l.name}/b{i}") for i in range(8)]
            blobs = [b for b in blobs if b is not None]
            missing = [b for b in l.bottoms if b not in env]
            if missing:
                raise KeyError(
                    f"Caffe layer {l.name!r} ({l.type}) references "
                    f"undefined bottom blob(s) {missing}; defined: "
                    f"{sorted(env)}")
            xs = [env[b] for b in l.bottoms]
            y = self._apply(l, blobs, xs, training)
            tops = l.tops or [l.name]
            if isinstance(y, tuple):
                for t, v in zip(tops, y):
                    env[t] = v
                    out_names.append(t)
            else:
                env[tops[0]] = y
                out_names.append(tops[0])
            for b in l.bottoms:
                if b in out_names:
                    out_names.remove(b)
        outs = [env[n] for n in dict.fromkeys(out_names)]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def _apply(self, l: CaffeLayer, blobs, xs, training):
        t = l.type
        x = xs[0] if xs else None
        if t in ("Convolution",):
            bias = blobs[1] if len(blobs) > 1 and _BOOL.get(
                l.attrs.get("bias_term", True), True) else None
            return _conv(l, blobs[0], bias, x)
        if t == "Deconvolution":
            bias = blobs[1] if len(blobs) > 1 else None
            return _conv(l, blobs[0], bias, x, transpose=True)
        if t == "InnerProduct":
            axis = int(l.attrs.get("axis", 1))
            mat = jnp.asarray(blobs[0])
            mat = mat.reshape(mat.shape[0], -1)  # (out, in)
            flat = x.reshape(x.shape[:axis] + (-1,))
            y = jnp.matmul(flat, mat.T)
            if len(blobs) > 1 and _BOOL.get(l.attrs.get("bias_term", True),
                                            True):
                y = y + blobs[1].reshape(-1)
            return y
        if t == "ReLU":
            slope = float(l.attrs.get("negative_slope", 0.0))
            return jax.nn.leaky_relu(x, slope) if slope else jax.nn.relu(x)
        if t == "PReLU":
            a = blobs[0].reshape((1, -1) + (1,) * (x.ndim - 2))
            return jnp.where(x >= 0, x, a * x)
        if t == "ELU":
            return jax.nn.elu(x, float(l.attrs.get("alpha", 1.0)))
        if t == "Sigmoid":
            return jax.nn.sigmoid(x)
        if t == "TanH":
            return jnp.tanh(x)
        if t == "BNLL":
            return jax.nn.softplus(x)
        if t == "Power":
            p = float(l.attrs.get("power", 1.0))
            s = float(l.attrs.get("scale", 1.0))
            sh = float(l.attrs.get("shift", 0.0))
            y = s * x + sh
            return y if p == 1.0 else jnp.power(y, p)
        if t == "AbsVal":
            return jnp.abs(x)
        if t == "Exp":
            return jnp.exp(x)
        if t == "Log":
            return jnp.log(x)
        if t == "Pooling":
            return _pool(l, x)
        if t == "LRN":
            return _lrn(l, x)
        if t == "BatchNorm":
            mean, var = blobs[0].reshape(-1), blobs[1].reshape(-1)
            scale = float(np.asarray(blobs[2]).reshape(-1)[0]) \
                if len(blobs) > 2 else 1.0
            if scale != 0:
                mean, var = mean / scale, var / scale
            eps = float(l.attrs.get("eps", 1e-5))
            shape = (1, -1) + (1,) * (x.ndim - 2)
            return (x - mean.reshape(shape)) * lax.rsqrt(
                var.reshape(shape) + eps)
        if t == "Scale":
            shape = (1, -1) + (1,) * (x.ndim - 2)
            y = x * blobs[0].reshape(shape)
            if len(blobs) > 1:  # bias blob present iff bias_term was set
                y = y + blobs[1].reshape(shape)
            return y
        if t == "Bias":
            shape = (1, -1) + (1,) * (x.ndim - 2)
            return x + blobs[0].reshape(shape)
        if t == "Concat":
            axis = int(l.attrs.get("axis", l.attrs.get("concat_dim", 1)))
            return jnp.concatenate(xs, axis=axis)
        if t == "Eltwise":
            return _eltwise(l, *xs)
        if t == "Dropout":
            return x  # deploy-time identity (reference maps the same)
        if t == "Softmax":
            return jax.nn.softmax(x, axis=int(l.attrs.get("axis", 1)))
        if t == "Flatten":
            axis = int(l.attrs.get("axis", 1))
            return x.reshape(x.shape[:axis] + (-1,))
        if t == "Reshape":
            shape = l.attrs.get("shape", [[-1]])
            dims = list(shape[0] if isinstance(shape[0], (list, tuple))
                        else shape)
            out = [x.shape[i] if d == 0 else int(d)
                   for i, d in enumerate(dims)]
            return x.reshape(out)
        if t == "Split":
            return tuple(x for _ in (l.tops or [l.name]))
        if t == "Slice":
            n = len(l.tops)
            axis = int(l.attrs.get("axis", 1))
            return tuple(jnp.split(x, n, axis=axis))
        raise NotImplementedError(
            f"Caffe layer type {t!r} (layer {l.name!r}) has no JAX mapping "
            "in zoo_tpu.models.caffe_loader")


def load_caffe(def_path: Optional[str], model_path: str) -> CaffeNet:
    """Load a Caffe model (reference ``Net.load_caffe(def_path, model_path)``,
    backed by ``CaffeLoader.loadCaffe``).

    ``model_path`` is the binary ``.caffemodel``; ``def_path`` the deploy
    prototxt. If ``def_path`` is None the topology embedded in the binary is
    used directly (the common case for nets serialized with weights)."""
    with open(model_path, "rb") as f:
        binary = CaffeNetParameter(f.read())
    weights = {l.name: l.blobs for l in binary.layers}
    if def_path is None:
        layers, inputs, shapes = (binary.layers, binary.inputs,
                                  binary.input_shapes)
        if not inputs:
            inp = [l for l in binary.layers if l.type == "Input"]
            inputs = [t for l in inp for t in l.tops]
            shapes = [tuple(s) for l in inp
                      for s in l.attrs.get("shape", [])]
    else:
        with open(def_path) as f:
            net = parse_prototxt(f.read())
        layers = _prototxt_layers(net)
        for l in layers:  # weights matched by layer name (reference: same)
            l.blobs = weights.get(l.name, [])
        inputs = [str(v) for v in net.get("input", [])]
        shapes = [tuple(int(d) for d in s.get("dim", []))
                  for s in net.get("input_shape", [])]
        dims = [int(v) for v in net.get("input_dim", [])]
        if dims and not shapes:
            shapes = [tuple(dims[i:i + 4]) for i in range(0, len(dims), 4)]
        if not inputs:
            inp = [l for l in layers if l.type == "Input"]
            inputs = [t for l in inp for t in l.tops]
            shapes = [tuple(s) for l in inp
                      for s in l.attrs.get("shape", [])]
    return CaffeNet(layers, inputs, shapes, name=binary.name or "caffe")
