"""ResNet image classifiers.

Rebuild of the reference's image-classification model configs (Scala
``models/image/imageclassification`` + the ResNet-50 training example
``zoo/.../examples/resnet``; the dogs-vs-cats app fine-tunes ResNet via the
Keras-style API — ``apps/dogs-vs-cats``, a BASELINE.md target).

TPU-first: NHWC throughout (inputs are ``dim_ordering="tf"``), BatchNorm
over the trailing channel axis, stride-2 convs instead of pooling where
possible — the canonical v1.5 layout XLA fuses best. Residual adds are
functional-graph ``Merge(sum)`` nodes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer, get_initializer
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    Activation,
    BatchNormalization,
    Conv2D,
    Dense,
    GlobalAveragePooling2D,
    MaxPooling2D,
    ZeroPadding2D,
    merge,
)


class SpaceToDepthStem(Layer):
    """The 7x7/s2 stem conv computed as a 4x4/s1 conv over 2x2
    space-to-depth input — mathematically identical, but the 3-channel
    7x7 strided conv maps terribly onto the MXU (measured ~1% peak on
    v5e) while the 12-channel dense form tiles cleanly. Standard public
    TPU formulation (MLPerf ResNet). Params keep the canonical
    (7, 7, 3, filters) HWIO shape — the weight VALUES interchange with a
    plain conv stem, but the position+type checkpoint key differs, so a
    checkpoint written by one stem variant only loads into the same
    variant (build with ``ResNet(..., stem="conv")`` to load conv-stem
    checkpoints)."""

    def __init__(self, filters: int = 64, init="glorot_uniform", **kwargs):
        super().__init__(**kwargs)
        self.filters = int(filters)
        self.init = get_initializer(init)

    def build(self, rng, input_shape):
        cin = input_shape[3]
        return {"W": self.init(rng, (7, 7, cin, self.filters), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        w = params["W"].astype(x.dtype)
        b, h, wd, c = x.shape
        # kernel tap k covers pixel 2i-2+k (SAME pad (2,3) at k=7, s=2);
        # an 8-tap window over 4 super-pixels covers 2i-2..2i+5 — pad one
        # zero tap at the end, then fold (dy, dx) into channels
        w8 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
        w4 = w8.reshape(4, 2, 4, 2, c, self.filters) \
            .transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, self.filters)
        xs = x.reshape(b, h // 2, 2, wd // 2, 2, c) \
            .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wd // 2, 4 * c)
        return jax.lax.conv_general_dilated(
            xs, w4, (1, 1), ((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def compute_output_shape(self, input_shape):
        n, h, w, _ = input_shape
        return (n, None if h is None else h // 2,
                None if w is None else w // 2, self.filters)


def _conv_bn(x, filters, k, stride=1, act=True, name=None):
    h = Conv2D(filters, k, k, subsample=(stride, stride),
               border_mode="same", dim_ordering="tf", bias=False)(x)
    h = BatchNormalization()(h)
    if act:
        h = Activation("relu")(h)
    return h


def _basic_block(x, filters, stride=1, downsample=False):
    h = _conv_bn(x, filters, 3, stride)
    h = _conv_bn(h, filters, 3, 1, act=False)
    shortcut = x
    if downsample:
        shortcut = _conv_bn(x, filters, 1, stride, act=False)
    out = merge([h, shortcut], mode="sum")
    return Activation("relu")(out)


def _bottleneck(x, filters, stride=1, downsample=False):
    h = _conv_bn(x, filters, 1, 1)
    h = _conv_bn(h, filters, 3, stride)
    h = _conv_bn(h, filters * 4, 1, 1, act=False)
    shortcut = x
    if downsample:
        shortcut = _conv_bn(x, filters * 4, 1, stride, act=False)
    out = merge([h, shortcut], mode="sum")
    return Activation("relu")(out)


class ResNet(Model):
    def __init__(self, class_num: int, blocks: Sequence[int],
                 bottleneck: bool, input_shape=(224, 224, 3),
                 stem_pool: bool = True, stem: str = "auto",
                 name: str = "resnet"):
        """``stem``: "s2d" (space-to-depth 7x7/s2, the TPU-fast form),
        "conv" (plain 7x7/s2 — use to load checkpoints from conv-stem
        builds), or "auto" (s2d when the spatial dims are even)."""
        if stem not in ("auto", "s2d", "conv"):
            raise ValueError(f"unknown stem: {stem!r}")
        if stem == "auto":
            stem = ("s2d" if input_shape[0] % 2 == 0
                    and input_shape[1] % 2 == 0 else "conv")
        x_in = Input(shape=tuple(input_shape), name="image")
        if stem == "s2d":
            h = SpaceToDepthStem(64)(x_in)
            h = BatchNormalization()(h)
            h = Activation("relu")(h)
        else:
            h = _conv_bn(x_in, 64, 7, stride=2)
        if stem_pool:
            h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                             dim_ordering="tf")(h)
        block = _bottleneck if bottleneck else _basic_block
        filters = 64
        for stage, n in enumerate(blocks):
            for i in range(n):
                stride = 2 if stage > 0 and i == 0 else 1
                downsample = (i == 0)
                h = block(h, filters, stride=stride, downsample=downsample)
            filters *= 2
        h = GlobalAveragePooling2D(dim_ordering="tf")(h)
        out = Dense(class_num, activation="softmax")(h)
        Model.__init__(self, input=x_in, output=out, name=name)


def resnet18(class_num: int, input_shape=(224, 224, 3)) -> ResNet:
    return ResNet(class_num, (2, 2, 2, 2), bottleneck=False,
                  input_shape=input_shape, name="resnet18")


def resnet50(class_num: int, input_shape=(224, 224, 3)) -> ResNet:
    return ResNet(class_num, (3, 4, 6, 3), bottleneck=True,
                  input_shape=input_shape, name="resnet50")
