"""ResNet image classifiers.

Rebuild of the reference's image-classification model configs (Scala
``models/image/imageclassification`` + the ResNet-50 training example
``zoo/.../examples/resnet``; the dogs-vs-cats app fine-tunes ResNet via the
Keras-style API — ``apps/dogs-vs-cats``, a BASELINE.md target).

TPU-first: NHWC throughout (inputs are ``dim_ordering="tf"``), BatchNorm
over the trailing channel axis, stride-2 convs instead of pooling where
possible — the canonical v1.5 layout XLA fuses best. Residual adds are
functional-graph ``Merge(sum)`` nodes.
"""

from __future__ import annotations

from typing import Sequence

from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    Activation,
    BatchNormalization,
    Conv2D,
    Dense,
    GlobalAveragePooling2D,
    MaxPooling2D,
    ZeroPadding2D,
    merge,
)


def _conv_bn(x, filters, k, stride=1, act=True, name=None):
    h = Conv2D(filters, k, k, subsample=(stride, stride),
               border_mode="same", dim_ordering="tf", bias=False)(x)
    h = BatchNormalization()(h)
    if act:
        h = Activation("relu")(h)
    return h


def _basic_block(x, filters, stride=1, downsample=False):
    h = _conv_bn(x, filters, 3, stride)
    h = _conv_bn(h, filters, 3, 1, act=False)
    shortcut = x
    if downsample:
        shortcut = _conv_bn(x, filters, 1, stride, act=False)
    out = merge([h, shortcut], mode="sum")
    return Activation("relu")(out)


def _bottleneck(x, filters, stride=1, downsample=False):
    h = _conv_bn(x, filters, 1, 1)
    h = _conv_bn(h, filters, 3, stride)
    h = _conv_bn(h, filters * 4, 1, 1, act=False)
    shortcut = x
    if downsample:
        shortcut = _conv_bn(x, filters * 4, 1, stride, act=False)
    out = merge([h, shortcut], mode="sum")
    return Activation("relu")(out)


class ResNet(Model):
    def __init__(self, class_num: int, blocks: Sequence[int],
                 bottleneck: bool, input_shape=(224, 224, 3),
                 stem_pool: bool = True, name: str = "resnet"):
        x_in = Input(shape=tuple(input_shape), name="image")
        h = _conv_bn(x_in, 64, 7, stride=2)
        if stem_pool:
            h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same",
                             dim_ordering="tf")(h)
        block = _bottleneck if bottleneck else _basic_block
        filters = 64
        for stage, n in enumerate(blocks):
            for i in range(n):
                stride = 2 if stage > 0 and i == 0 else 1
                downsample = (i == 0)
                h = block(h, filters, stride=stride, downsample=downsample)
            filters *= 2
        h = GlobalAveragePooling2D(dim_ordering="tf")(h)
        out = Dense(class_num, activation="softmax")(h)
        Model.__init__(self, input=x_in, output=out, name=name)


def resnet18(class_num: int, input_shape=(224, 224, 3)) -> ResNet:
    return ResNet(class_num, (2, 2, 2, 2), bottleneck=False,
                  input_shape=input_shape, name="resnet18")


def resnet50(class_num: int, input_shape=(224, 224, 3)) -> ResNet:
    return ResNet(class_num, (3, 4, 6, 3), bottleneck=True,
                  input_shape=input_shape, name="resnet50")
