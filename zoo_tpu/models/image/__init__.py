from zoo_tpu.models.image.resnet import ResNet, resnet18, resnet50

__all__ = ["ResNet", "resnet18", "resnet50"]
