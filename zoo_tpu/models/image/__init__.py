from zoo_tpu.models.image.objectdetection import (  # noqa: F401
    SSD,
    ObjectDetector,
    decode_boxes,
    generate_anchors,
    nms,
)
from zoo_tpu.models.image.resnet import ResNet, resnet18, resnet50  # noqa: F401,E501

__all__ = ["ResNet", "resnet18", "resnet50", "SSD", "ObjectDetector",
           "generate_anchors", "decode_boxes", "nms"]
