from zoo_tpu.models.image.imageclassification import (  # noqa: F401
    ImageClassifier,
    LabelOutput,
    create_image_classifier,
    densenet121,
    image_classification_preprocess,
    inception_v1,
    mobilenet_v1,
    mobilenet_v2,
    squeezenet,
    vgg16,
    vgg19,
)
from zoo_tpu.models.image.objectdetection import (  # noqa: F401
    SSD,
    ObjectDetector,
    decode_boxes,
    encode_targets,
    generate_anchors,
    multibox_loss,
    nms,
)
from zoo_tpu.models.image.resnet import ResNet, resnet18, resnet50  # noqa: F401,E501

__all__ = ["ResNet", "resnet18", "resnet50", "SSD", "ObjectDetector",
           "generate_anchors", "decode_boxes", "nms", "encode_targets", "multibox_loss",
           "ImageClassifier", "LabelOutput", "create_image_classifier",
           "image_classification_preprocess", "inception_v1", "vgg16",
           "vgg19", "mobilenet_v1", "mobilenet_v2", "squeezenet",
           "densenet121"]
