"""Image-classification model zoo + ImageClassifier pipeline wrapper.

Rebuild of the reference's image-classification family
(``pyzoo/zoo/models/image/imageclassification/image_classification.py``,
Scala ``models/image/imageclassification/ImageClassifier.scala`` and its
per-model ``ImageClassificationConfig`` preprocessing table). The
reference distributes these architectures as pretrained BigDL model files
and only ships loader + config code; the rebuild defines the
architectures natively on the Keras layer zoo so they train and serve on
TPU (NHWC, BN on the channel axis, depthwise convs on the MXU via
``feature_group_count``).

Families (same as the reference's zoo catalogue): Inception-v1
(GoogLeNet), VGG-16/19, MobileNet v1/v2, SqueezeNet, DenseNet-121.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from zoo_tpu.feature.common import ChainedPreprocessing
from zoo_tpu.feature.image import (
    ImageCenterCrop,
    ImageChannelNormalize,
    ImageMatToTensor,
    ImageResize,
)
from zoo_tpu.pipeline.api.keras.engine.topology import Input, KerasNet, Model
from zoo_tpu.pipeline.api.keras.layers import (
    Activation,
    AveragePooling2D,
    BatchNormalization,
    Conv2D,
    Dense,
    DepthwiseConvolution2D,
    Dropout,
    Flatten,
    GlobalAveragePooling2D,
    MaxPooling2D,
    merge,
)

_TF = {"dim_ordering": "tf"}


def _conv_bn(x, filters, k, stride=1, act="relu", name=None):
    h = Conv2D(filters, k, k, subsample=(stride, stride),
               border_mode="same", bias=False, **_TF)(x)
    h = BatchNormalization()(h)
    if act:
        h = Activation(act)(h)
    return h


# ------------------------------------------------------------ Inception v1

def _inception_module(x, c1, c3r, c3, c5r, c5, pp):
    b1 = Conv2D(c1, 1, 1, activation="relu", border_mode="same", **_TF)(x)
    b2 = Conv2D(c3r, 1, 1, activation="relu", border_mode="same", **_TF)(x)
    b2 = Conv2D(c3, 3, 3, activation="relu", border_mode="same", **_TF)(b2)
    b3 = Conv2D(c5r, 1, 1, activation="relu", border_mode="same", **_TF)(x)
    b3 = Conv2D(c5, 5, 5, activation="relu", border_mode="same", **_TF)(b3)
    b4 = MaxPooling2D((3, 3), strides=(1, 1), border_mode="same", **_TF)(x)
    b4 = Conv2D(pp, 1, 1, activation="relu", border_mode="same", **_TF)(b4)
    return merge([b1, b2, b3, b4], mode="concat", concat_axis=-1)


def inception_v1(class_num: int, input_shape=(224, 224, 3)) -> Model:
    """GoogLeNet (reference zoo's `inception-v1` catalogue entry; the
    Scala training example lives in ``zoo/.../examples/inception``)."""
    x_in = Input(shape=tuple(input_shape), name="image")
    h = Conv2D(64, 7, 7, subsample=(2, 2), activation="relu",
               border_mode="same", **_TF)(x_in)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = Conv2D(64, 1, 1, activation="relu", border_mode="same", **_TF)(h)
    h = Conv2D(192, 3, 3, activation="relu", border_mode="same", **_TF)(h)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _inception_module(h, 64, 96, 128, 16, 32, 32)     # 3a
    h = _inception_module(h, 128, 128, 192, 32, 96, 64)   # 3b
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _inception_module(h, 192, 96, 208, 16, 48, 64)    # 4a
    h = _inception_module(h, 160, 112, 224, 24, 64, 64)   # 4b
    h = _inception_module(h, 128, 128, 256, 24, 64, 64)   # 4c
    h = _inception_module(h, 112, 144, 288, 32, 64, 64)   # 4d
    h = _inception_module(h, 256, 160, 320, 32, 128, 128)  # 4e
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _inception_module(h, 256, 160, 320, 32, 128, 128)  # 5a
    h = _inception_module(h, 384, 192, 384, 48, 128, 128)  # 5b
    h = GlobalAveragePooling2D(**_TF)(h)
    h = Dropout(0.4)(h)
    out = Dense(class_num, activation="softmax")(h)
    return Model(input=x_in, output=out, name="inception-v1")


# ------------------------------------------------------------------- VGG

def _vgg(class_num, cfg, input_shape, name):
    x_in = Input(shape=tuple(input_shape), name="image")
    h = x_in
    for block in cfg:
        for filters in block:
            h = Conv2D(filters, 3, 3, activation="relu",
                       border_mode="same", **_TF)(h)
        h = MaxPooling2D((2, 2), strides=(2, 2), **_TF)(h)
    h = Flatten()(h)
    h = Dense(4096, activation="relu")(h)
    h = Dropout(0.5)(h)
    h = Dense(4096, activation="relu")(h)
    h = Dropout(0.5)(h)
    out = Dense(class_num, activation="softmax")(h)
    return Model(input=x_in, output=out, name=name)


def vgg16(class_num: int, input_shape=(224, 224, 3)) -> Model:
    return _vgg(class_num, [[64] * 2, [128] * 2, [256] * 3, [512] * 3,
                            [512] * 3], input_shape, "vgg-16")


def vgg19(class_num: int, input_shape=(224, 224, 3)) -> Model:
    return _vgg(class_num, [[64] * 2, [128] * 2, [256] * 4, [512] * 4,
                            [512] * 4], input_shape, "vgg-19")


# ------------------------------------------------------------- MobileNet

def _dw_block(x, filters, stride, alpha):
    h = DepthwiseConvolution2D(3, 3, subsample=(stride, stride),
                               border_mode="same", bias=False, **_TF)(x)
    h = BatchNormalization()(h)
    h = Activation("relu")(h)
    h = Conv2D(int(filters * alpha), 1, 1, border_mode="same", bias=False,
               **_TF)(h)
    h = BatchNormalization()(h)
    return Activation("relu")(h)


def mobilenet_v1(class_num: int, alpha: float = 1.0,
                 input_shape=(224, 224, 3)) -> Model:
    x_in = Input(shape=tuple(input_shape), name="image")
    h = _conv_bn(x_in, int(32 * alpha), 3, stride=2)
    for filters, stride in ((64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                            (512, 2), (512, 1), (512, 1), (512, 1), (512, 1),
                            (512, 1), (1024, 2), (1024, 1)):
        h = _dw_block(h, filters, stride, alpha)
    h = GlobalAveragePooling2D(**_TF)(h)
    h = Dropout(0.001)(h)
    out = Dense(class_num, activation="softmax")(h)
    return Model(input=x_in, output=out, name="mobilenet")


def _inverted_residual(x, cin, cout, stride, expand):
    h = x
    if expand != 1:
        h = _conv_bn(h, cin * expand, 1, act="relu")
    h = DepthwiseConvolution2D(3, 3, subsample=(stride, stride),
                               border_mode="same", bias=False, **_TF)(h)
    h = BatchNormalization()(h)
    h = Activation("relu")(h)
    h = Conv2D(cout, 1, 1, border_mode="same", bias=False, **_TF)(h)
    h = BatchNormalization()(h)
    if stride == 1 and cin == cout:
        h = merge([h, x], mode="sum")
    return h


def mobilenet_v2(class_num: int, input_shape=(224, 224, 3)) -> Model:
    x_in = Input(shape=tuple(input_shape), name="image")
    h = _conv_bn(x_in, 32, 3, stride=2)
    cin = 32
    for expand, cout, n, stride in ((1, 16, 1, 1), (6, 24, 2, 2),
                                    (6, 32, 3, 2), (6, 64, 4, 2),
                                    (6, 96, 3, 1), (6, 160, 3, 2),
                                    (6, 320, 1, 1)):
        for i in range(n):
            h = _inverted_residual(h, cin, cout, stride if i == 0 else 1,
                                   expand)
            cin = cout
    h = _conv_bn(h, 1280, 1)
    h = GlobalAveragePooling2D(**_TF)(h)
    out = Dense(class_num, activation="softmax")(h)
    return Model(input=x_in, output=out, name="mobilenet-v2")


# ------------------------------------------------------------- SqueezeNet

def _fire(x, squeeze, expand):
    s = Conv2D(squeeze, 1, 1, activation="relu", border_mode="same",
               **_TF)(x)
    e1 = Conv2D(expand, 1, 1, activation="relu", border_mode="same",
                **_TF)(s)
    e3 = Conv2D(expand, 3, 3, activation="relu", border_mode="same",
                **_TF)(s)
    return merge([e1, e3], mode="concat", concat_axis=-1)


def squeezenet(class_num: int, input_shape=(224, 224, 3)) -> Model:
    x_in = Input(shape=tuple(input_shape), name="image")
    h = Conv2D(64, 3, 3, subsample=(2, 2), activation="relu",
               border_mode="same", **_TF)(x_in)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _fire(h, 16, 64)
    h = _fire(h, 16, 64)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _fire(h, 32, 128)
    h = _fire(h, 32, 128)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    h = _fire(h, 48, 192)
    h = _fire(h, 48, 192)
    h = _fire(h, 64, 256)
    h = _fire(h, 64, 256)
    h = Dropout(0.5)(h)
    h = Conv2D(class_num, 1, 1, activation="relu", border_mode="same",
               **_TF)(h)
    h = GlobalAveragePooling2D(**_TF)(h)
    out = Activation("softmax")(h)
    return Model(input=x_in, output=out, name="squeezenet")


# -------------------------------------------------------------- DenseNet

def _dense_block(x, n_layers, growth):
    for _ in range(n_layers):
        h = BatchNormalization()(x)
        h = Activation("relu")(h)
        h = Conv2D(4 * growth, 1, 1, border_mode="same", bias=False,
                   **_TF)(h)
        h = BatchNormalization()(h)
        h = Activation("relu")(h)
        h = Conv2D(growth, 3, 3, border_mode="same", bias=False, **_TF)(h)
        x = merge([x, h], mode="concat", concat_axis=-1)
    return x


def _transition(x, channels):
    h = BatchNormalization()(x)
    h = Activation("relu")(h)
    h = Conv2D(channels, 1, 1, border_mode="same", bias=False, **_TF)(h)
    return AveragePooling2D((2, 2), strides=(2, 2), **_TF)(h)


def densenet121(class_num: int, growth: int = 32,
                input_shape=(224, 224, 3)) -> Model:
    x_in = Input(shape=tuple(input_shape), name="image")
    h = _conv_bn(x_in, 64, 7, stride=2)
    h = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same", **_TF)(h)
    channels = 64
    for i, n_layers in enumerate((6, 12, 24, 16)):
        h = _dense_block(h, n_layers, growth)
        channels += n_layers * growth
        if i < 3:
            channels //= 2
            h = _transition(h, channels)
    h = BatchNormalization()(h)
    h = Activation("relu")(h)
    h = GlobalAveragePooling2D(**_TF)(h)
    out = Dense(class_num, activation="softmax")(h)
    return Model(input=x_in, output=out, name="densenet-121")


# --------------------------------------------------- configs + classifier

_ZOO = {"inception-v1": inception_v1, "vgg-16": vgg16, "vgg-19": vgg19,
        "mobilenet": mobilenet_v1, "mobilenet-v2": mobilenet_v2,
        "squeezenet": squeezenet, "densenet-121": densenet121}

# Per-family deploy preprocessing (reference
# ``ImageClassificationConfig.scala`` preprocessors: resize-256 →
# center-crop-224 → channel-normalize with the family's training stats).
_IMAGENET_MEAN = (123.68, 116.78, 103.94)
_CONFIGS = {
    "inception-v1": dict(resize=256, crop=224, mean=_IMAGENET_MEAN,
                         std=(1.0, 1.0, 1.0)),
    "vgg-16": dict(resize=256, crop=224, mean=_IMAGENET_MEAN,
                   std=(1.0, 1.0, 1.0)),
    "vgg-19": dict(resize=256, crop=224, mean=_IMAGENET_MEAN,
                   std=(1.0, 1.0, 1.0)),
    "mobilenet": dict(resize=256, crop=224, mean=(127.5, 127.5, 127.5),
                      std=(127.5, 127.5, 127.5)),
    "mobilenet-v2": dict(resize=256, crop=224, mean=(127.5, 127.5, 127.5),
                         std=(127.5, 127.5, 127.5)),
    "squeezenet": dict(resize=256, crop=224, mean=_IMAGENET_MEAN,
                       std=(1.0, 1.0, 1.0)),
    "densenet-121": dict(resize=256, crop=224, mean=_IMAGENET_MEAN,
                         std=(58.4, 57.1, 57.4)),
}


def image_classification_preprocess(model_name: str) -> ChainedPreprocessing:
    """The deploy-time transform chain for a zoo model family (reference:
    ``ImageClassificationConfig`` ``preprocessor``)."""
    cfg = _CONFIGS[model_name]
    mb, mg, mr = cfg["mean"][2], cfg["mean"][1], cfg["mean"][0]
    sb, sg, sr = cfg["std"][2], cfg["std"][1], cfg["std"][0]
    return ChainedPreprocessing([
        ImageResize(cfg["resize"], cfg["resize"]),
        ImageCenterCrop(cfg["crop"], cfg["crop"]),
        ImageChannelNormalize(mb, mg, mr, sb, sg, sr),
        ImageMatToTensor(format="NHWC"),
    ])


def create_image_classifier(model_name: str, class_num: int = 1000):
    """Build a zoo architecture by catalogue name."""
    if model_name not in _ZOO:
        raise ValueError(f"unknown image-classification model "
                         f"{model_name!r}; have {sorted(_ZOO)}")
    return _ZOO[model_name](class_num)


class LabelOutput:
    """Attach sorted (label, prob) lists to each feature (reference:
    ``LabelOutput`` transformer in ``image_classification.py``)."""

    def __init__(self, label_map: dict, clses: str = "classes",
                 probs: str = "probs", top_k: int = 5):
        self.label_map = label_map
        self.clses, self.probs, self.top_k = clses, probs, int(top_k)

    def __call__(self, feature):
        logits = np.asarray(feature["predict"]).reshape(-1)
        order = np.argsort(-logits)[:self.top_k]
        feature[self.clses] = [self.label_map.get(int(i), str(int(i)))
                               for i in order]
        feature[self.probs] = logits[order].tolist()
        return feature


class ImageClassifier:
    """Classification model + its deploy pipeline (reference:
    ``ImageClassifier.load_model`` / ``predict_image_set``)."""

    def __init__(self, model: KerasNet, model_name: Optional[str] = None,
                 label_map: Optional[dict] = None):
        self.model = model
        self.model_name = model_name or getattr(model, "name", None)
        self.label_map = label_map or {}

    @classmethod
    def create(cls, model_name: str, class_num: int = 1000,
               label_map: Optional[dict] = None) -> "ImageClassifier":
        return cls(create_image_classifier(model_name, class_num),
                   model_name, label_map)

    @staticmethod
    def load_model(path: str, label_map: Optional[dict] = None
                   ) -> "ImageClassifier":
        return ImageClassifier(KerasNet.load(path), label_map=label_map)

    def save_model(self, path: str):
        self.model.save(path)

    def predict_image_set(self, image_set, top_k: int = 5):
        if self.model_name in _CONFIGS:
            chain = image_classification_preprocess(self.model_name)
        else:  # unknown family: still resize so mixed-size sets stack
            chain = ChainedPreprocessing([
                ImageResize(224, 224), ImageMatToTensor(format="NHWC")])
        # transform on copies: transformers mutate features in place and
        # predict must not destroy the caller's original images
        from zoo_tpu.feature.image import ImageFeature, ImageSet
        work = ImageSet([ImageFeature(image=np.asarray(f["image"]).copy())
                         for f in image_set.features])
        transformed = work.transform(chain)
        x = np.stack(
            [np.asarray(f["tensor"]) for f in transformed.features])
        probs = np.asarray(self.model.predict(x))
        labeler = LabelOutput(self.label_map, top_k=top_k)
        for f, p in zip(image_set.features, probs):
            f["predict"] = p
            labeler(f)
        return image_set
