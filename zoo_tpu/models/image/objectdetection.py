"""SSD object detection (model-zoo parity).

Rebuild of the reference's object-detection family (Python
``pyzoo/zoo/models/image/objectdetection/object_detector.py:1``, Scala
``models/image/objectdetection`` — SSD-VGG/MobileNet configs with
multibox heads, anchor decoding and NMS postprocessing). The TPU design:
a conv backbone emits multi-scale feature maps, shared conv heads predict
per-anchor class scores and box deltas, and decoding+NMS runs as jnp ops
(top-k based NMS, fixed shapes — no data-dependent control flow, so the
whole predict path jits).

Detection output follows the reference's ``ImageDetection`` layout:
per image, (N, 6) rows of [label, score, x1, y1, x2, y2] normalized.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from zoo_tpu.pipeline.api.keras.engine.base import Layer
from zoo_tpu.pipeline.api.keras.engine.topology import KerasNet


def generate_anchors(feature_sizes: Sequence[int],
                     scales: Sequence[float],
                     aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5)
                     ) -> np.ndarray:
    """All anchors over all scales, (A, 4) as [cx, cy, w, h] normalized
    (reference: SSD prior-box generation)."""
    out = []
    for fs, scale in zip(feature_sizes, scales):
        step = 1.0 / fs
        for i in range(fs):
            for j in range(fs):
                cx, cy = (j + 0.5) * step, (i + 0.5) * step
                for ar in aspect_ratios:
                    w = scale * np.sqrt(ar)
                    h = scale / np.sqrt(ar)
                    out.append([cx, cy, w, h])
    return np.asarray(out, np.float32)


def decode_boxes(anchors: jnp.ndarray, deltas: jnp.ndarray,
                 variance: Tuple[float, float] = (0.1, 0.2)) -> jnp.ndarray:
    """SSD delta decoding → [x1, y1, x2, y2] (reference variances)."""
    cxcy = anchors[:, :2] + deltas[:, :2] * variance[0] * anchors[:, 2:]
    wh = anchors[:, 2:] * jnp.exp(deltas[:, 2:] * variance[1])
    return jnp.concatenate([cxcy - wh / 2, cxcy + wh / 2], axis=-1)


def iou_matrix(boxes_a: jnp.ndarray, boxes_b: jnp.ndarray) -> jnp.ndarray:
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    inter = jnp.prod(jnp.clip(rb - lt, 0, None), axis=-1)
    area_a = jnp.prod(boxes_a[:, 2:] - boxes_a[:, :2], axis=-1)
    area_b = jnp.prod(boxes_b[:, 2:] - boxes_b[:, :2], axis=-1)
    return inter / jnp.clip(area_a[:, None] + area_b[None, :] - inter,
                            1e-8, None)


def nms(boxes: jnp.ndarray, scores: jnp.ndarray, top_k: int = 100,
        iou_threshold: float = 0.45
        ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fixed-shape greedy NMS: take top_k by score, then suppress
    iteratively via a lax.scan over rank order (compiler-friendly — no
    dynamic shapes; suppressed entries keep score 0)."""
    k = min(top_k, scores.shape[0])
    top_scores, idx = jax.lax.top_k(scores, k)
    top_boxes = boxes[idx]
    ious = iou_matrix(top_boxes, top_boxes)

    def body(keep_mask, i):
        keep_i = keep_mask[i]
        # suppress later boxes overlapping box i (only if i survives)
        suppress = (ious[i] > iou_threshold) & \
            (jnp.arange(k) > i) & keep_i
        return keep_mask & ~suppress, None

    keep, _ = jax.lax.scan(body, jnp.ones((k,), bool), jnp.arange(k))
    return top_boxes, jnp.where(keep, top_scores, 0.0), idx


def encode_targets(anchors: np.ndarray, gt_boxes: np.ndarray,
                   gt_labels: np.ndarray, iou_threshold: float = 0.5,
                   variance: Tuple[float, float] = (0.1, 0.2)
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """SSD target assignment for ONE image (host-side, numpy).

    ``gt_boxes``: (G, 4) [x1,y1,x2,y2] normalized; ``gt_labels``: (G,)
    ints >= 1 (0 is background). Returns (cls_t (A,), box_t (A, 4)):
    each anchor matched to its best-IoU ground truth when IoU >=
    threshold (plus the best anchor per gt, the reference's bipartite
    step), others background. Box targets are the inverse of
    ``decode_boxes``'s delta transform."""
    A = anchors.shape[0]
    cls_t = np.zeros((A,), np.int32)
    box_t = np.zeros((A, 4), np.float32)
    if len(gt_boxes) == 0:
        return cls_t, box_t
    ax1y1 = anchors[:, :2] - anchors[:, 2:] / 2
    ax2y2 = anchors[:, :2] + anchors[:, 2:] / 2
    lt = np.maximum(ax1y1[:, None], gt_boxes[None, :, :2])
    rb = np.minimum(ax2y2[:, None], gt_boxes[None, :, 2:])
    inter = np.prod(np.clip(rb - lt, 0, None), axis=-1)
    area_a = np.prod(anchors[:, 2:], axis=-1)
    area_g = np.prod(gt_boxes[:, 2:] - gt_boxes[:, :2], axis=-1)
    iou = inter / (area_a[:, None] + area_g[None] - inter + 1e-9)
    best_gt = iou.argmax(axis=1)
    best_iou = iou.max(axis=1)
    pos = best_iou >= iou_threshold
    # bipartite step: every gt claims its single best UNCLAIMED anchor,
    # even when that IoU is under the threshold — claiming without the
    # exclusion would let a later gt steal an earlier one's only anchor
    # and leave that object unmatched entirely
    claimed = set()
    for g in range(len(gt_boxes)):
        for a in np.argsort(-iou[:, g]):
            a = int(a)
            if a not in claimed:
                claimed.add(a)
                best_gt[a] = g
                pos[a] = True
                break
    matched = gt_boxes[best_gt]
    cxcy_g = (matched[:, :2] + matched[:, 2:]) / 2
    wh_g = matched[:, 2:] - matched[:, :2]
    d_xy = (cxcy_g - anchors[:, :2]) / (anchors[:, 2:] * variance[0])
    d_wh = np.log(np.clip(wh_g / anchors[:, 2:], 1e-6, None)) / variance[1]
    box_t[pos] = np.concatenate([d_xy, d_wh], axis=-1)[pos]
    cls_t[pos] = gt_labels[best_gt[pos]]
    return cls_t, box_t


def multibox_loss(cls_logits, box_deltas, cls_t, box_t,
                  neg_pos_ratio: int = 3):
    """SSD multibox loss (one batch, jittable): softmax CE over matched
    anchors + hard-negative-mined background anchors (``neg_pos_ratio``
    negatives per positive, picked by loss rank — the reference's
    MultiBox mining) and smooth-L1 on positive box deltas."""
    logp = jax.nn.log_softmax(cls_logits.astype(jnp.float32), axis=-1)
    ce = -jnp.take_along_axis(logp, cls_t[..., None], axis=-1)[..., 0]
    pos = cls_t > 0                               # (B, A)
    n_pos = jnp.maximum(pos.sum(axis=1), 1)
    # hard negative mining: rank background anchors by their CE
    neg_ce = jnp.where(pos, -jnp.inf, ce)
    rank = jnp.argsort(jnp.argsort(-neg_ce, axis=1), axis=1)
    n_neg = jnp.minimum(neg_pos_ratio * n_pos,
                        pos.shape[1] - n_pos)
    neg = rank < n_neg[:, None]
    cls_loss = jnp.where(pos | neg, ce, 0.0).sum(axis=1) / n_pos
    diff = jnp.abs(box_deltas.astype(jnp.float32) - box_t)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff ** 2, diff - 0.5).sum(-1)
    box_loss = jnp.where(pos, sl1, 0.0).sum(axis=1) / n_pos
    return (cls_loss + box_loss).mean()


class _MultiBoxHead(Layer):
    """Shared conv head on one feature map: per-anchor class scores and
    box deltas."""

    def __init__(self, n_anchors: int, n_classes: int, **kwargs):
        super().__init__(**kwargs)
        self.n_anchors = n_anchors
        self.n_classes = n_classes

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        init = jax.nn.initializers.glorot_uniform()
        a = self.n_anchors
        return {
            "cls_w": init(k1, (3, 3, cin, a * self.n_classes), jnp.float32),
            "cls_b": jnp.zeros((a * self.n_classes,), jnp.float32),
            "box_w": init(k2, (3, 3, cin, a * 4), jnp.float32),
            "box_b": jnp.zeros((a * 4,), jnp.float32),
        }

    def call(self, params, inputs, *, training=False, rng=None):
        conv = lambda w, b: jax.lax.conv_general_dilated(  # noqa: E731
            inputs, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
        b = inputs.shape[0]
        cls = conv(params["cls_w"], params["cls_b"]).reshape(
            b, -1, self.n_classes)
        box = conv(params["box_w"], params["box_b"]).reshape(b, -1, 4)
        return cls, box


class SSD(KerasNet):
    """Compact SSD over a strided conv backbone. ``predict_detections``
    returns the reference-layout rows."""

    def __init__(self, n_classes: int, input_size: int = 128,
                 feature_channels: Sequence[int] = (32, 64, 128),
                 aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5),
                 name: Optional[str] = None):
        super().__init__(name=name or "ssd")
        self.n_classes = int(n_classes)  # including background class 0
        self.input_size = int(input_size)
        self.channels = list(feature_channels)
        self.aspect_ratios = list(aspect_ratios)
        # backbone stride 4 stem + one stride-2 stage per scale; SAME
        # padding yields ceil(in/stride), so sizes must ceil-divide or the
        # anchor count mismatches the head outputs on odd maps
        self.feature_sizes = []
        fs = -(-self.input_size // 4)
        for _ in self.channels:
            fs = -(-fs // 2)
            self.feature_sizes.append(fs)
        self.scales = [0.15 + 0.35 * i / max(len(self.channels) - 1, 1)
                       for i in range(len(self.channels))]
        self.anchors = generate_anchors(self.feature_sizes, self.scales,
                                        self.aspect_ratios)
        self._heads = [_MultiBoxHead(len(self.aspect_ratios),
                                     self.n_classes,
                                     name=f"head{i}")
                       for i in range(len(self.channels))]

    @property
    def layers(self):
        return self._heads

    def _input_shapes(self):
        return [(None, self.input_size, self.input_size, 3)]

    def _init_params(self, rng, input_shapes):
        init = jax.nn.initializers.glorot_uniform()
        params = {}
        ks = jax.random.split(rng, 2 + 2 * len(self.channels))
        params["stem_w"] = init(ks[0], (7, 7, 3, 16), jnp.float32)
        params["stem_b"] = jnp.zeros((16,), jnp.float32)
        cin = 16
        for i, c in enumerate(self.channels):
            params[f"conv{i}_w"] = init(ks[1 + i], (3, 3, cin, c),
                                        jnp.float32)
            params[f"conv{i}_b"] = jnp.zeros((c,), jnp.float32)
            cin = c
        for i, head in enumerate(self._heads):
            shape = (None, self.feature_sizes[i], self.feature_sizes[i],
                     self.channels[i])
            params[self._key_of(head)] = head.build(
                ks[1 + len(self.channels) + i], shape)
        return params

    def _forward(self, params, inputs, *, training, rng, collect):
        x = inputs[0]
        conv = lambda x, w, b, s: jax.nn.relu(  # noqa: E731
            jax.lax.conv_general_dilated(
                x, w, (s, s), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + b)
        x = conv(x, params["stem_w"], params["stem_b"], 4)
        cls_all, box_all = [], []
        for i, head in enumerate(self._heads):
            x = conv(x, params[f"conv{i}_w"], params[f"conv{i}_b"], 2)
            cls, box = head.call(params[self._key_of(head)], x,
                                 training=training)
            cls_all.append(cls)
            box_all.append(box)
        return jnp.concatenate(cls_all, 1), jnp.concatenate(box_all, 1)

    # -- training ---------------------------------------------------------
    def fit_detection(self, images: np.ndarray, boxes_list: List,
                      labels_list: List, epochs: int = 10,
                      batch_size: int = 16, lr: float = 1e-3,
                      iou_threshold: float = 0.5, seed: int = 0,
                      verbose: int = 0) -> List[float]:
        """Train the detector end-to-end with the SSD multibox loss.

        ``boxes_list[i]``: (G_i, 4) normalized [x1,y1,x2,y2] ground-truth
        boxes for image i; ``labels_list[i]``: (G_i,) int labels >= 1.
        Target assignment runs host-side once (``encode_targets``); the
        jitted step is pure fixed-shape tensor math. Returns per-epoch
        mean losses. (reference role: the SSD fine-tuning loop of
        ``apps/object-detection`` / Scala SSD examples.)"""
        import optax

        self.build()
        n = len(images)
        # a batch larger than the dataset would make the step range empty
        # and silently train nothing
        batch_size = min(batch_size, n)
        cls_t = np.zeros((n, self.anchors.shape[0]), np.int32)
        box_t = np.zeros((n, self.anchors.shape[0], 4), np.float32)
        for i in range(n):
            cls_t[i], box_t[i] = encode_targets(
                self.anchors, np.asarray(boxes_list[i], np.float32),
                np.asarray(labels_list[i], np.int32),
                iou_threshold=iou_threshold)
        tx = optax.adam(lr)
        params = self._place(self.params)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, imgs, ct, bt):
            def loss_fn(p):
                cls, box = self._forward(p, [imgs], training=True,
                                         rng=None, collect=None)
                return multibox_loss(cls, box, ct, bt)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        imgs_all = np.asarray(images, np.float32)
        rs = np.random.RandomState(seed)
        history = []
        for epoch in range(epochs):
            order = rs.permutation(n)
            losses = []
            for s in range(0, n - batch_size + 1, batch_size):
                idx = order[s:s + batch_size]
                params, opt_state, loss = step(
                    params, opt_state, jnp.asarray(imgs_all[idx]),
                    jnp.asarray(cls_t[idx]), jnp.asarray(box_t[idx]))
                losses.append(float(np.asarray(loss)))
            history.append(float(np.mean(losses)))
            if verbose:
                print(f"epoch {epoch + 1}/{epochs} "
                      f"multibox_loss={history[-1]:.4f}")
        self.params = jax.tree_util.tree_map(np.asarray, params)
        self._jit_detect = None  # weights changed; detection must retrace
        return history

    # -- detection --------------------------------------------------------
    def predict_detections(self, images: np.ndarray,
                           score_threshold: float = 0.3,
                           iou_threshold: float = 0.45,
                           top_k: int = 50) -> List[np.ndarray]:
        """Per image: (k, 6) rows [label, score, x1, y1, x2, y2]; rows with
        score 0 are suppressed/below-threshold padding (fixed shapes keep
        the whole path jittable — the reference trims host-side too)."""
        self.build()
        params = self._place(self.params)
        anchors = jnp.asarray(self.anchors)

        key = (score_threshold, iou_threshold, top_k)
        cached = getattr(self, "_jit_detect", None)
        if cached is not None and cached[0] == key:
            out = np.asarray(cached[1](params,
                                       jnp.asarray(images, jnp.float32)))
            return [det[det[:, 1] > 0] for det in out]

        @jax.jit
        def detect(params, imgs):
            cls, box = self._forward(params, [imgs], training=False,
                                     rng=None, collect=None)
            probs = jax.nn.softmax(cls, axis=-1)

            def per_image(p, d):
                decoded = decode_boxes(anchors, d)
                best_cls = jnp.argmax(p[:, 1:], axis=-1) + 1  # skip bg
                best_score = jnp.max(p[:, 1:], axis=-1)
                boxes, scores, idx = nms(decoded, best_score, top_k,
                                         iou_threshold)
                labels = best_cls[idx].astype(jnp.float32)
                scores = jnp.where(scores >= score_threshold, scores, 0.0)
                return jnp.concatenate(
                    [labels[:, None], scores[:, None], boxes], axis=-1)

            return jax.vmap(per_image)(probs, box)

        self._jit_detect = (key, detect)  # avoid recompiling per call
        out = np.asarray(detect(params, jnp.asarray(images,
                                                    jnp.float32)))
        return [det[det[:, 1] > 0] for det in out]


class ObjectDetector:
    """reference: ``object_detector.py`` ``ObjectDetector.load_model`` +
    ``predict_image_set`` — wraps a detection model with the ImageSet
    pipeline."""

    def __init__(self, model: SSD, label_map: Optional[dict] = None):
        self.model = model
        self.label_map = label_map or {}

    def predict_image_set(self, image_set, score_threshold: float = 0.3):
        import cv2

        size = self.model.input_size
        imgs = []
        for f in image_set.features:
            img = cv2.resize(np.asarray(f["image"]), (size, size))
            imgs.append(img.astype(np.float32) / 255.0)
        dets = self.model.predict_detections(
            np.stack(imgs), score_threshold=score_threshold)
        for f, det in zip(image_set.features, dets):
            f["predict"] = det
        return image_set

    @staticmethod
    def load_model(path: str, label_map: Optional[dict] = None
                   ) -> "ObjectDetector":
        model = KerasNet.load(path)
        return ObjectDetector(model, label_map)
