"""Recommender base: shared recommend-for-user/item helpers.

Rebuild of the reference's ``Recommender`` base (Scala
``models/recommendation/Recommender.scala``, Python
``pyzoo/zoo/models/recommendation/__init__.py``):
``predict_user_item_pair`` and ``recommend_for_user/item`` over
(user, item, label) triples.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np


@dataclasses.dataclass
class UserItemFeature:
    """A (user, item) pair plus optional label (reference:
    ``UserItemFeature`` in ``models/recommendation/__init__.py``)."""

    user_id: int
    item_id: int
    label: int = 1


@dataclasses.dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


class Recommender:
    """Mixin over a Keras-facade model whose input is (batch, 2) int pairs."""

    def predict_user_item_pair(self, pairs: Sequence[UserItemFeature],
                               batch_size: int = 256
                               ) -> List[UserItemPrediction]:
        x = np.array([[p.user_id, p.item_id] for p in pairs], np.int32)
        probs = self.predict(x, batch_size=batch_size)
        cls = probs.argmax(axis=-1)
        return [UserItemPrediction(p.user_id, p.item_id, int(c),
                                   float(pr[c]))
                for p, c, pr in zip(pairs, cls, probs)]

    def recommend_for_user(self, pairs: Sequence[UserItemFeature],
                           max_items: int) -> List[UserItemPrediction]:
        """Top-N items per user among the candidate pairs (reference:
        ``recommendForUser``)."""
        preds = self.predict_user_item_pair(pairs)
        by_user = {}
        for pr in preds:
            by_user.setdefault(pr.user_id, []).append(pr)
        out = []
        for user, lst in by_user.items():
            lst.sort(key=lambda p: -p.probability)
            out.extend(lst[:max_items])
        return out

    def recommend_for_item(self, pairs: Sequence[UserItemFeature],
                           max_users: int) -> List[UserItemPrediction]:
        preds = self.predict_user_item_pair(pairs)
        by_item = {}
        for pr in preds:
            by_item.setdefault(pr.item_id, []).append(pr)
        out = []
        for item, lst in by_item.items():
            lst.sort(key=lambda p: -p.probability)
            out.extend(lst[:max_users])
        return out
