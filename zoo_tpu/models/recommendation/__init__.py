from zoo_tpu.models.recommendation.neuralcf import NeuralCF
from zoo_tpu.models.recommendation.recommender import Recommender, UserItemFeature
from zoo_tpu.models.recommendation.session_recommender import SessionRecommender
from zoo_tpu.models.recommendation.wide_and_deep import ColumnFeatureInfo, WideAndDeep

__all__ = ["NeuralCF", "Recommender", "UserItemFeature", "WideAndDeep",
           "ColumnFeatureInfo", "SessionRecommender"]
