from zoo_tpu.models.recommendation.neuralcf import NeuralCF
from zoo_tpu.models.recommendation.recommender import Recommender, UserItemFeature

__all__ = ["NeuralCF", "Recommender", "UserItemFeature"]
