"""Session-based recommender (reference: Scala
``models/recommendation/SessionRecommender.scala`` — GRU over the item
session, optional user-history attention-free average, softmax over items).
"""

from __future__ import annotations

from zoo_tpu.models.recommendation.recommender import Recommender
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    GRU,
    Dense,
    Embedding,
    Lambda,
    merge,
)


class SessionRecommender(Model, Recommender):
    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers=(40, 20), session_length: int = 10,
                 include_history: bool = False, mlp_hidden_layers=(40, 20),
                 history_length: int = 5):
        self.item_count = item_count
        sess = Input(shape=(session_length,), name="session")
        inputs = [sess]
        h = Embedding(item_count + 1, item_embed)(sess)
        for i, units in enumerate(rnn_hidden_layers):
            last = i == len(rnn_hidden_layers) - 1
            h = GRU(units, return_sequences=not last)(h)
        if include_history:
            hist = Input(shape=(history_length,), name="history")
            inputs.append(hist)
            g = Embedding(item_count + 1, item_embed)(hist)
            g = Lambda(lambda v: v.mean(axis=1))(g)
            for units in mlp_hidden_layers:
                g = Dense(units, activation="relu")(g)
            h = merge([h, g], mode="concat")
        out = Dense(item_count + 1, activation="softmax")(h)
        Model.__init__(self, input=inputs if include_history else sess,
                       output=out, name="session_recommender")

    def recommend_for_session(self, sessions, max_items: int = 5):
        """Top-k next items per session (reference:
        ``recommendForSession``)."""
        import numpy as np

        probs = self.predict(sessions)
        top = np.argsort(-probs, axis=1)[:, :max_items]
        return [[(int(i), float(p[i])) for i in row]
                for row, p in zip(top, probs)]
