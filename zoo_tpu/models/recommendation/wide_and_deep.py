"""Wide & Deep recommender.

Rebuild of the reference's WideAndDeep (Scala
``models/recommendation/WideAndDeep.scala:365``, Python
``pyzoo/zoo/models/recommendation/wide_and_deep.py`` with ``ColumnFeatureInfo``).

Input layout (single int/float matrix, columns in order):
``[wide_base..., wide_cross..., indicator..., embed..., continuous...]`` —
the flattened form of the reference's assembled feature tensor.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax.numpy as jnp

from zoo_tpu.models.recommendation.recommender import Recommender
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    Dense,
    Embedding,
    Lambda,
    merge,
)


@dataclasses.dataclass
class ColumnFeatureInfo:
    """reference: ``ColumnFeatureInfo`` in
    ``pyzoo/zoo/models/recommendation/wide_and_deep.py``."""

    wide_base_cols: List[str] = dataclasses.field(default_factory=list)
    wide_base_dims: List[int] = dataclasses.field(default_factory=list)
    wide_cross_cols: List[str] = dataclasses.field(default_factory=list)
    wide_cross_dims: List[int] = dataclasses.field(default_factory=list)
    indicator_cols: List[str] = dataclasses.field(default_factory=list)
    indicator_dims: List[int] = dataclasses.field(default_factory=list)
    embed_cols: List[str] = dataclasses.field(default_factory=list)
    embed_in_dims: List[int] = dataclasses.field(default_factory=list)
    embed_out_dims: List[int] = dataclasses.field(default_factory=list)
    continuous_cols: List[str] = dataclasses.field(default_factory=list)

    @property
    def feature_cols(self) -> List[str]:
        return (self.wide_base_cols + self.wide_cross_cols +
                self.indicator_cols + self.embed_cols +
                self.continuous_cols)


class WideAndDeep(Model, Recommender):
    def __init__(self, class_num: int, column_info: ColumnFeatureInfo,
                 model_type: str = "wide_n_deep",
                 hidden_layers: Sequence[int] = (40, 20, 10)):
        if model_type not in ("wide_n_deep", "wide", "deep"):
            raise ValueError("model_type must be wide_n_deep | wide | deep")
        self.column_info = column_info
        self.model_type = model_type
        ci = column_info

        n_wide = len(ci.wide_base_cols) + len(ci.wide_cross_cols)
        n_ind = len(ci.indicator_cols)
        n_embed = len(ci.embed_cols)
        n_cont = len(ci.continuous_cols)
        total = n_wide + n_ind + n_embed + n_cont
        x = Input(shape=(total,), name="wnd_input")

        towers = []
        offset = 0
        if model_type in ("wide", "wide_n_deep") and n_wide:
            # wide: one-hot(sparse) linear layer == per-column embedding of
            # output size class_num, summed
            wide_parts = []
            for i, dim in enumerate(list(ci.wide_base_dims) +
                                    list(ci.wide_cross_dims)):
                col = Lambda(lambda v, j=offset + i: v[:, j],
                             output_shape=(None,))(x)
                wide_parts.append(Embedding(dim + 1, class_num,
                                            init="zero")(col))
            towers.append(wide_parts[0] if len(wide_parts) == 1
                          else merge(wide_parts, mode="sum"))
        offset += n_wide

        if model_type in ("deep", "wide_n_deep"):
            deep_parts = []
            for i, dim in enumerate(ci.indicator_dims):
                col = Lambda(lambda v, j=offset + i: v[:, j],
                             output_shape=(None,))(x)
                # indicator = one-hot passthrough == identity embedding
                eye = (lambda key, shape, dtype=jnp.float32:
                       jnp.eye(shape[0], shape[1], dtype=dtype))
                deep_parts.append(Embedding(dim + 1, dim + 1,
                                            init=eye)(col))
            off2 = offset + n_ind
            for i, (din, dout) in enumerate(zip(ci.embed_in_dims,
                                                ci.embed_out_dims)):
                col = Lambda(lambda v, j=off2 + i: v[:, j],
                             output_shape=(None,))(x)
                deep_parts.append(Embedding(din + 1, dout)(col))
            off3 = off2 + n_embed
            if n_cont:
                deep_parts.append(Lambda(
                    lambda v: v[:, off3:off3 + n_cont].astype(jnp.float32),
                    output_shape=(n_cont,))(x))
            h = deep_parts[0] if len(deep_parts) == 1 else merge(
                deep_parts, mode="concat")
            for units in hidden_layers:
                h = Dense(units, activation="relu")(h)
            towers.append(Dense(class_num)(h))

        out = towers[0] if len(towers) == 1 else merge(towers, mode="sum")
        from zoo_tpu.pipeline.api.keras.layers import Activation
        out = Activation("softmax")(out)
        Model.__init__(self, input=x, output=out, name="wide_and_deep")
