"""Neural Collaborative Filtering.

Rebuild of the reference's NCF (Python
``pyzoo/zoo/models/recommendation/neuralcf.py:30``, Scala
``models/recommendation/NeuralCF.scala``; exercised by
``apps/recommendation-ncf`` — the PR1 parity target in BASELINE.md).

Architecture (matching the reference): user/item embeddings feed an MLP
tower; optionally a GMF (element-wise product of separate MF embeddings)
branch is concatenated before the softmax head. Input is an int array of
shape ``(batch, 2)`` holding ``[user_id, item_id]`` (ids are 1-based in the
reference's MovieLens pipeline; pass ``zero_based_ids=False`` to keep that
convention — one extra embedding row absorbs the offset).

TPU notes: both towers are embedding-lookup + small matmuls — the whole
step fuses into a handful of MXU calls; the softmax head and crossentropy
fuse into the backward pass. Embedding tables shard over the ``fsdp`` axis
when present.
"""

from __future__ import annotations

from typing import Sequence

from zoo_tpu.models.recommendation.recommender import Recommender
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import (
    Dense,
    Embedding,
    Lambda,
    Merge,
    merge,
)


class NeuralCF(Model, Recommender):
    def __init__(self, user_count: int, item_count: int, class_num: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20,
                 zero_based_ids: bool = True):
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.include_mf = include_mf
        offset = 0 if zero_based_ids else 1

        pair = Input(shape=(2,), name="user_item")
        user_id = Lambda(lambda x: x[:, 0], output_shape=(None,))(pair)
        item_id = Lambda(lambda x: x[:, 1], output_shape=(None,))(pair)

        mlp_user = Embedding(user_count + offset, user_embed)(user_id)
        mlp_item = Embedding(item_count + offset, item_embed)(item_id)
        h = merge([mlp_user, mlp_item], mode="concat")
        for units in hidden_layers:
            h = Dense(units, activation="relu")(h)

        if include_mf:
            mf_user = Embedding(user_count + offset, mf_embed)(user_id)
            mf_item = Embedding(item_count + offset, mf_embed)(item_id)
            gmf = Merge(mode="mul")([mf_user, mf_item])
            h = merge([gmf, h], mode="concat")

        out = Dense(class_num, activation="softmax")(h)
        Model.__init__(self, input=pair, output=out, name="neuralcf")
