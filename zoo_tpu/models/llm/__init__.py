from zoo_tpu.models.llm.llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    llama3_8b_config,
    llama_param_count,
    tiny_llama_config,
)
from zoo_tpu.models.llm.moe_llama import (  # noqa: F401
    MoELlama,
    place_moe_params,
)

__all__ = ["Llama", "LlamaConfig", "llama3_8b_config",
           "tiny_llama_config", "llama_param_count", "MoELlama",
           "place_moe_params"]
