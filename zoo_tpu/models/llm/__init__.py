from zoo_tpu.models.llm.llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    llama3_8b_config,
    llama_param_count,
    tiny_llama_config,
)

__all__ = ["Llama", "LlamaConfig", "llama3_8b_config",
           "tiny_llama_config", "llama_param_count"]
