"""Llama-family decoder-only LM (BASELINE.md stretch row: "Llama-3-8B …
FSDP-style shard over ICI").

Net-new vs the reference (its largest attention model is BERT,
``BERT.scala:402``): a modern decoder stack — RMSNorm pre-norm, rotary
position embeddings, grouped-query attention, SwiGLU MLP, no biases —
built in the same mega-layer idiom as ``TransformerLayer``
(``self_attention.py``): one Layer owning stacked per-block params run
under ``lax.scan``, so compile time is O(1) in depth and the (n_block,
d_in, d_out) weight stacking gives ``parallel.plans.leaf_sharding`` its
natural FSDP/TP axes (fsdp shards the block axis or the largest matmul
dim; model shards the matmul output dim — Megatron column style).

Attention rides ``ops.attention.dot_product_attention`` — the Pallas
flash kernel at long sequence, the XLA-fused dense path otherwise — or,
with ``attention_impl="ring"``, the sequence-parallel ring kernel over
the mesh ``seq`` axis (``parallel/ring_attention.py``), which carries
the unrepeated GQA kv heads around the ICI ring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from zoo_tpu.ops.attention import dot_product_attention
from zoo_tpu.pipeline.api.keras.engine.base import Layer, get_initializer


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 32000
    hidden: int = 4096
    n_block: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    intermediate: int = 14336
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_head


def llama3_8b_config() -> LlamaConfig:
    """Llama-3-8B shapes (public architecture card)."""
    return LlamaConfig(vocab=128256, hidden=4096, n_block=32, n_head=32,
                       n_kv_head=8, intermediate=14336,
                       rope_theta=500000.0)


def tiny_llama_config(vocab: int = 256) -> LlamaConfig:
    """Test/dryrun config: same topology, toy widths."""
    return LlamaConfig(vocab=vocab, hidden=64, n_block=2, n_head=4,
                       n_kv_head=2, intermediate=128, rope_theta=10000.0)


def llama_param_count(cfg: LlamaConfig) -> int:
    """Analytic parameter count (embed + blocks + final norm + lm head)."""
    h, kv = cfg.hidden, cfg.n_kv_head * cfg.head_dim
    per_block = (h * h + 2 * h * kv + h * h      # q, k, v, o
                 + 3 * h * cfg.intermediate      # w1 (gate), w3 (up), w2
                 + 2 * h)                        # two RMSNorm gains
    total = cfg.vocab * h + cfg.n_block * per_block + h
    if not cfg.tie_embeddings:
        total += cfg.vocab * h
    return total


def resolve_attention_impl(impl: str, seq_len: int) -> str:  # zoo-lint: config-parse
    """Concrete kernel for an ``attention_impl`` request at ``seq_len``.

    ``"auto"`` picks the Pallas flash kernel from
    ``ZOO_LLAMA_FLASH_MIN_SEQ`` tokens up (default 512 — the measured
    v5e crossover vs the fused dense path) when running on TPU
    hardware, else the dense path. BENCH_r05 showed the s4096 MFU
    falloff (0.44 → 0.35) exactly because the old auto check keyed on
    the backend *name* and the bench platform registered as ``axon``;
    resolving here (by sequence length, against ``pallas.on_tpu()``'s
    device_kind probe) makes the choice explicit and lets the bench
    record it per row. ``"dense"``/``"flash"``/``"ring"`` pass through
    untouched; ``ZOO_LLAMA_ATTN_IMPL`` force-overrides auto for A/B
    runs without a code change."""
    import os
    if impl != "auto":
        return impl
    forced = os.environ.get("ZOO_LLAMA_ATTN_IMPL", "")
    if forced:
        return forced
    from zoo_tpu.ops.pallas import on_tpu
    min_seq = int(os.environ.get("ZOO_LLAMA_FLASH_MIN_SEQ", "512"))
    return "flash" if seq_len >= min_seq and on_tpu() else "dense"


def _rms_norm(x, gain, eps):
    # f32 island for the moment/rsqrt only; the normalized tensor drops
    # to the compute dtype BEFORE the gain multiply, so autodiff saves a
    # bf16 residual — keeping the f32 product alive across the backward
    # pass was measured to carry 100MB/block of f32 through the scan
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    norm = (xf * inv).astype(x.dtype)
    return norm * gain.astype(x.dtype)


def rope_frequencies(head_dim: int, seq_len: int, theta: float):
    """(T, D/2) cos/sin tables, f32."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    ang = jnp.outer(t, inv)  # (T, D/2)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate (B, H, T, D) by per-position angles (HF rotate-half
    convention)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[None, None, :, :].astype(x.dtype)
    sin = sin[None, None, :, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


class Llama(Layer):
    """Decoder-only Llama LM as one mega-layer: int ids (B, T) →
    logits (B, T, vocab) (``lm_head=True``, default) or hidden states
    (B, T, hidden)."""

    def __init__(self, config: Optional[LlamaConfig] = None,
                 lm_head: bool = True, init="glorot_uniform",
                 attention_impl: str = "auto", remat: bool = False,
                 mesh=None, **kwargs):
        """``remat`` controls the per-block ``jax.checkpoint`` policy:

        * ``False`` — store all block activations (fastest when they fit);
        * ``True`` — full remat: backward recomputes the whole block, so
          a train step costs ~4x forward FLOPs instead of ~3x (a hard
          0.75x MFU ceiling) for O(1) activation memory in depth;
        * ``"dots"`` — save matmul/attention outputs, recompute only the
          cheap elementwise chains (``dots_with_no_batch_dims_saveable``):
          nearly the memory relief of full remat with none of the MXU
          recompute — the right default for training configs that
          otherwise OOM. Measured on v5e (768-hidden, S=512, B=64):
          full remat 0.32 MFU, "dots" 0.42, no-remat OOM.

        ``attention_impl="ring"``: sequence-parallel ring attention over
        the mesh ``seq`` axis (``parallel/ring_attention.py``) — shard
        the token axis of the inputs over ``seq`` and context length
        scales with the number of chips. Needs a mesh with a ``seq``
        axis: pass ``mesh=`` or set one via
        ``init_orca_context(mesh_axes={..., "seq": k})``. GQA note: the
        ring kernel wants equal q/kv heads, so kv heads are broadcast
        before the ring (same math as the dense path)."""
        super().__init__(**kwargs)
        self.cfg = config or LlamaConfig()
        if self.cfg.hidden % self.cfg.n_head:
            raise ValueError("hidden must divide by n_head")
        if self.cfg.n_head % self.cfg.n_kv_head:
            raise ValueError("n_head must divide by n_kv_head")
        self.lm_head = lm_head
        self.init = get_initializer(init)
        self.attention_impl = attention_impl
        if remat not in (False, True, "dots"):
            # any other truthy value would silently fall through to full
            # -block remat, quietly costing ~0.1 MFU vs "dots"
            raise ValueError(
                f"remat must be False, True or 'dots', got {remat!r}")
        self.remat = remat
        self.mesh = mesh

    def _seq_mesh(self):
        mesh = self.mesh
        if mesh is None:
            from zoo_tpu.common.context import get_runtime_context
            ctx = get_runtime_context(required=False)
            mesh = getattr(ctx, "mesh", None) if ctx else None
        # explicit meshes get the same validation as ambient ones: a
        # missing/size-1 seq axis must fail HERE, not as a cryptic
        # unresolved-axis error inside shard_map
        if mesh is None or "seq" not in mesh.axis_names \
                or mesh.shape.get("seq", 1) <= 1:
            raise ValueError(
                'attention_impl="ring" needs a mesh with a seq axis > 1; '
                "pass mesh= or init_orca_context(mesh_axes={'seq': k})")
        return mesh

    # -- params -----------------------------------------------------------
    def _mlp_block_params(self, k_gate, k_up):
        """The MLP half's weights — a separate hook so MoE variants can
        swap in expert banks without materializing (and discarding) the
        dense SwiGLU weights. Key derivation unchanged from round 2 so
        existing checkpoints keep their values."""
        c = self.cfg
        return {
            "w_gate": self.init(k_gate, (c.hidden, c.intermediate),
                                jnp.float32),
            "w_up": self.init(k_up, (c.hidden, c.intermediate),
                              jnp.float32),
            "w_down": self.init(
                jax.random.fold_in(k_up, 1), (c.intermediate, c.hidden),
                jnp.float32),
        }

    def _block_params(self, rng):
        c = self.cfg
        kv = c.n_kv_head * c.head_dim
        ks = jax.random.split(rng, 6)
        p = {
            "wq": self.init(ks[0], (c.hidden, c.hidden), jnp.float32),
            "wk": self.init(ks[1], (c.hidden, kv), jnp.float32),
            "wv": self.init(ks[2], (c.hidden, kv), jnp.float32),
            "wo": self.init(ks[3], (c.hidden, c.hidden), jnp.float32),
            "attn_norm": jnp.ones((c.hidden,), jnp.float32),
            "mlp_norm": jnp.ones((c.hidden,), jnp.float32),
        }
        p.update(self._mlp_block_params(ks[4], ks[5]))
        return p

    def build(self, rng, input_shape):
        c = self.cfg
        k_embed, k_blocks, k_head = jax.random.split(rng, 3)
        blocks = jax.vmap(self._block_params)(
            jax.random.split(k_blocks, c.n_block))
        params = {
            "embed": self.init(k_embed, (c.vocab, c.hidden), jnp.float32)
            * 0.02 * (3.0 ** 0.5),  # small-embed init, LM convention
            "blocks": blocks,
            "final_norm": jnp.ones((c.hidden,), jnp.float32),
        }
        if self.lm_head and not c.tie_embeddings:
            params["head"] = self.init(k_head, (c.hidden, c.vocab),
                                       jnp.float32)
        return params

    # -- forward ----------------------------------------------------------
    def _attn_part(self, p, h, cos, sin):
        c = self.cfg
        B, T, _ = h.shape
        x = _rms_norm(h, p["attn_norm"], c.rms_eps)
        q = (x @ p["wq"]).reshape(B, T, c.n_head, c.head_dim)
        k = (x @ p["wk"]).reshape(B, T, c.n_kv_head, c.head_dim)
        v = (x @ p["wv"]).reshape(B, T, c.n_kv_head, c.head_dim)
        q = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)
        k = apply_rope(k.transpose(0, 2, 1, 3), cos, sin)
        v = v.transpose(0, 2, 1, 3)
        impl = resolve_attention_impl(self.attention_impl, T)
        # trace-time record (T is static): bench rows and tests read the
        # concrete kernel the auto mode landed on for this shape
        self.last_attention_impl = impl
        if impl == "ring":
            # GQA-aware kernel: the ring carries the unrepeated kv heads
            from zoo_tpu.parallel.ring_attention import ring_attention
            a = ring_attention(self._seq_mesh(), q, k, v, causal=True)
        else:
            # GQA passes the unrepeated kv heads straight through: the
            # flash kernel maps query heads onto their group's kv head
            # in its index maps, the dense path broadcasts internally
            a = dot_product_attention(q, k, v, causal=True, impl=impl)
        a = a.transpose(0, 2, 1, 3).reshape(B, T, c.hidden)
        return h + a @ p["wo"]

    def _mlp_part(self, p, h):
        c = self.cfg
        x = _rms_norm(h, p["mlp_norm"], c.rms_eps)
        f = (jax.nn.silu(x @ p["w_gate"]) * (x @ p["w_up"])) @ p["w_down"]
        return h + f

    def _block(self, p, h, cos, sin):
        return self._mlp_part(p, self._attn_part(p, h, cos, sin))

    def call(self, params, inputs, *, training=False, rng=None):
        c = self.cfg
        ids = inputs.astype(jnp.int32)
        h = jnp.take(params["embed"], ids, axis=0)
        cos, sin = rope_frequencies(c.head_dim, ids.shape[1], c.rope_theta)

        # prevent_cse=False: lax.scan already prevents CSE; the default
        # barriers would block fusions in every block iteration
        if self.remat == "dots":
            # Checkpoint ONLY the MLP half under the dots policy. The
            # attention half stays un-rematted: a whole-block remat
            # cannot reach the residuals inside the flash kernel's
            # custom_vjp, so it re-runs the attention forward per block
            # in the backward pass (~7% of step time at S=512); leaving
            # the half un-checkpointed lets autodiff keep exactly the
            # kernel residuals (q, k, v, o, lse) instead
            mlp_fn = jax.checkpoint(
                self._mlp_part, prevent_cse=False,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)

            def block_fn(p, h, cos, sin):
                return mlp_fn(p, self._attn_part(p, h, cos, sin))
        elif self.remat:
            block_fn = jax.checkpoint(self._block, prevent_cse=False)
        else:
            block_fn = self._block

        def body(carry, blk):
            return block_fn(blk, carry, cos, sin), None

        h, _ = jax.lax.scan(body, h, params["blocks"])
        h = _rms_norm(h, params["final_norm"], c.rms_eps)
        if not self.lm_head:
            return h
        head = (params["embed"].T if c.tie_embeddings
                else params["head"])
        return h @ head.astype(h.dtype)

    def compute_output_shape(self, input_shape):
        b, t = input_shape
        return (b, t, self.cfg.vocab if self.lm_head else self.cfg.hidden)
