"""Mixture-of-Experts Llama variant (expert-parallel, Mixtral-style).

Net-new vs the reference (SURVEY §2.10: EP absent upstream). Every
block's SwiGLU MLP becomes a top-k-routed expert bank
(``ops/moe.py``); expert weights gain a leading E dim sharded over the
mesh ``expert`` axis, so the token dispatch/combine einsums lower to
ICI all-to-alls under GSPMD. Attention half, RoPE, norms and the scanned
block loop are inherited from :class:`Llama` unchanged.

Training note: the router's load-balance auxiliary loss must reach the
optimizer — use :meth:`call_with_aux` inside the train step (the plain
``call`` drops it, which is correct for inference). ``__graft_entry__``
exercises the full EP train step on the dryrun mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from zoo_tpu.models.llm.llama import (
    Llama,
    LlamaConfig,
    _rms_norm,
    rope_frequencies,
)
from zoo_tpu.ops.moe import init_moe_params, moe_ffn

__all__ = ["MoELlama", "place_moe_params"]


class MoELlama(Llama):
    def __init__(self, config: Optional[LlamaConfig] = None,
                 n_experts: int = 8, top_k: int = 2,
                 capacity_factor: float = 1.25,
                 aux_loss_weight: float = 0.01, **kwargs):
        super().__init__(config, **kwargs)
        self.n_experts = int(n_experts)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.aux_loss_weight = float(aux_loss_weight)

    # -- params -----------------------------------------------------------
    def _mlp_block_params(self, k_gate, k_up):
        # the hook exists so the dense SwiGLU weights are never
        # materialized: at 8B shapes that's ~0.7GB of glorot samples
        # built and thrown away per build() otherwise
        c = self.cfg
        return init_moe_params(k_gate, c.hidden, c.intermediate,
                               self.n_experts, init=self.init)

    # -- forward ----------------------------------------------------------
    def _mlp_part(self, p, h):
        y, aux = self._moe_part(p, h)
        return y  # inference path: aux loss dropped

    def _moe_part(self, p, h):
        c = self.cfg
        x = _rms_norm(h, p["mlp_norm"], c.rms_eps)
        moe_p = {k: p[k] for k in ("router", "w_gate", "w_up", "w_down")}
        y, aux = moe_ffn(moe_p, x, top_k=self.top_k,
                         capacity_factor=self.capacity_factor,
                         aux_loss_weight=self.aux_loss_weight)
        return h + y, aux

    def call_with_aux(self, params, inputs):
        """(logits, total_aux_loss) — the training forward. Add the aux
        term to the task loss so the router learns to balance load.
        Honors the inherited ``remat`` setting the same way Llama.call
        does: "dots" checkpoints only the MoE half (the flash kernel's
        custom_vjp keeps its own residuals), True remats the whole
        block."""
        c = self.cfg
        ids = inputs.astype(jnp.int32)
        h = jnp.take(params["embed"], ids, axis=0)
        cos, sin = rope_frequencies(c.head_dim, ids.shape[1],
                                    c.rope_theta)

        if self.remat == "dots":
            moe_fn = jax.checkpoint(
                self._moe_part, prevent_cse=False,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)

            def block_fn(blk, h):
                return moe_fn(blk, self._attn_part(blk, h, cos, sin))
        elif self.remat:
            def _whole(blk, h):
                return self._moe_part(blk,
                                      self._attn_part(blk, h, cos, sin))
            block_fn = jax.checkpoint(_whole, prevent_cse=False)
        else:
            def block_fn(blk, h):
                return self._moe_part(blk,
                                      self._attn_part(blk, h, cos, sin))

        def body(carry, blk):
            h, aux = carry
            h, a = block_fn(blk, h)
            return (h, aux + a), None

        (h, aux), _ = jax.lax.scan(body, (h, jnp.float32(0)),
                                   params["blocks"])
        h = _rms_norm(h, params["final_norm"], c.rms_eps)
        if not self.lm_head:
            return h, aux
        head = (params["embed"].T if c.tie_embeddings else params["head"])
        return h @ head.astype(h.dtype), aux


def place_moe_params(params, mesh):
    """Device-put an :class:`MoELlama` params tree: expert banks sharded
    over the ``expert`` axis (blocks are stacked, so the leading dim is
    the layer stack and E is dim 1); everything else replicated (compose
    with fsdp/model via ``parallel.plans`` when those axes are active)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zoo_tpu.parallel.mesh import replicated_sharding

    expert_keys = {"w_gate", "w_up", "w_down"}

    def place(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in expert_keys and x.ndim == 4:
            return jax.device_put(
                x, NamedSharding(mesh, P(None, "expert", None, None)))
        return jax.device_put(x, replicated_sharding(mesh))

    return jax.tree_util.tree_map_with_path(place, params)
