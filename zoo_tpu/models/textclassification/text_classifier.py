"""Text classifier (reference: Scala
``models/textclassification/TextClassifier.scala``, Python
``pyzoo/zoo/models/textclassification/__init__.py`` — token ids →
Embedding → CNN/LSTM/GRU encoder → softmax)."""

from __future__ import annotations

from typing import Optional

from zoo_tpu.pipeline.api.keras.engine.topology import Sequential
from zoo_tpu.pipeline.api.keras.layers import (
    GRU,
    LSTM,
    Conv1D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GlobalMaxPooling1D,
)


class TextClassifier(Sequential):
    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, vocab: int = 5000,
                 encoder: str = "cnn", encoder_output_dim: int = 256,
                 hidden_drop: float = 0.2):
        super().__init__(name="text_classifier")
        encoder = encoder.lower()
        if encoder not in ("cnn", "lstm", "gru"):
            raise ValueError("encoder must be cnn | lstm | gru")
        self.class_num = class_num
        self.add(Embedding(vocab, token_length,
                           input_shape=(sequence_length,)))
        if encoder == "cnn":
            self.add(Conv1D(encoder_output_dim, 5, activation="relu"))
            self.add(GlobalMaxPooling1D())
        elif encoder == "lstm":
            self.add(LSTM(encoder_output_dim))
        else:
            self.add(GRU(encoder_output_dim))
        if hidden_drop:
            self.add(Dropout(hidden_drop))
        self.add(Dense(128, activation="relu"))
        self.add(Dense(class_num, activation="softmax"))
