from zoo_tpu.models.textclassification.text_classifier import TextClassifier

__all__ = ["TextClassifier"]
