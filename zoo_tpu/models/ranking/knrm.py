"""KNRM — kernel-pooling neural ranking.

Rebuild of the reference's KNRM (Scala ``models/textmatching/KNRM.scala``,
Python ``pyzoo/zoo/models/textmatching/knrm.py``): query/doc token ids →
shared embedding → cosine interaction matrix → RBF kernel pooling →
linear+sigmoid score.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from zoo_tpu.pipeline.api.keras.engine.base import Layer
from zoo_tpu.pipeline.api.keras.engine.topology import Input, Model
from zoo_tpu.pipeline.api.keras.layers import Dense, Embedding, Lambda


class _KernelPooling(Layer):
    """RBF kernel pooling over the interaction matrix (reference:
    ``KNRM.scala`` kernel loop with mu from 1 down by 0.2, sigma 0.1/0.001
    for the exact-match kernel)."""

    def __init__(self, kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001, **kwargs):
        super().__init__(**kwargs)
        self.kernel_num = kernel_num
        self.sigma = sigma
        self.exact_sigma = exact_sigma

    def call(self, params, inputs, *, training=False, rng=None):
        # inputs: (B, Tq, Td) cosine similarities
        mus, sigmas = [], []
        for i in range(self.kernel_num):
            mu = 1.0 - 2.0 * i / max(self.kernel_num - 1, 1)
            mus.append(mu)
            sigmas.append(self.exact_sigma if i == 0 else self.sigma)
        mu = jnp.asarray(mus)[None, None, None, :]
        sg = jnp.asarray(sigmas)[None, None, None, :]
        k = jnp.exp(-((inputs[..., None] - mu) ** 2) / (2 * sg ** 2))
        # sum over doc, log1p, sum over query (reference pooling:
        # ``knrm.py:110-114`` uses log(sum + 1), which keeps the pooled
        # features bounded — a bare log saturates the sigmoid head and
        # kills the gradient through the clipped BCE)
        pooled = jnp.sum(k, axis=2)
        pooled = jnp.log1p(pooled)
        return jnp.sum(pooled, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.kernel_num)


class KNRM(Model):
    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: int = 5000, embed_size: int = 50,
                 kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001):
        self.text1_length = text1_length
        self.text2_length = text2_length
        pair = Input(shape=(text1_length + text2_length,), name="qd_pair")
        q_ids = Lambda(lambda v: v[:, :text1_length],
                       output_shape=(text1_length,))(pair)
        d_ids = Lambda(lambda v: v[:, text1_length:],
                       output_shape=(text2_length,))(pair)
        embed = Embedding(vocab_size, embed_size)  # shared weights
        q = embed(q_ids)
        d = embed(d_ids)

        def _interact(args):
            qe, de = args
            qe = qe / jnp.maximum(jnp.linalg.norm(qe, axis=-1,
                                                  keepdims=True), 1e-8)
            de = de / jnp.maximum(jnp.linalg.norm(de, axis=-1,
                                                  keepdims=True), 1e-8)
            return jnp.einsum("bqe,bde->bqd", qe, de)

        from zoo_tpu.pipeline.api.keras.layers import Merge

        class _Interaction(Merge):
            def __init__(self, **kw):
                super().__init__(mode="dot", **kw)

            def call(self, params, inputs, *, training=False, rng=None):
                return _interact(inputs)

            def compute_output_shape(self, input_shape):
                return (input_shape[0][0], text1_length, text2_length)

        sim = _Interaction()([q, d])
        pooled = _KernelPooling(kernel_num, sigma, exact_sigma)(sim)
        out = Dense(1, activation="sigmoid")(pooled)
        Model.__init__(self, input=pair, output=out, name="knrm")
