from zoo_tpu.models.ranking.knrm import KNRM

__all__ = ["KNRM"]
