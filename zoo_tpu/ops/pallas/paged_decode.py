"""Paged flash-decode: single-query attention through a block table.

The decode hot path is memory-bound — each generated token must stream
every live K/V byte of its sequence out of HBM exactly once, so the
roofline that matters is HBM bytes/token, not FLOPs. PR 7's decode
executable paid that bill twice: ``cache[block_table]`` materializes a
gathered ``(slots, ctx, heads, d)`` copy of every sequence's K/V in HBM
*before* the attention math reads it back. This kernel is the
PagedAttention/flash-decoding rebuild (Kwon et al., SOSP '23; Dao et
al., 2023):

* **paged** — K/V blocks are read directly where they live, routed by a
  scalar-prefetched block table in the ``BlockSpec`` index maps, so the
  per-sequence gather copy never exists;
* **flash** — online-softmax accumulation in VMEM scratch, never a
  ``(ctx,)`` score row in HBM;
* **split-KV** — the sequence axis is cut into ``num_splits`` grid
  programs that each produce a partial ``(acc, m, l)``; a tiny jnp
  epilogue merges them with the standard log-sum-exp correction. At
  decode there is ONE query per sequence, so without the split the
  kernel exposes only ``slots x kv_heads`` programs of parallelism —
  splitting the KV length is what keeps the cores busy at low
  occupancy (the flash-decoding observation);
* **GQA-aware** — the ``group = n_head / n_kv_head`` query heads that
  share a KV head are batched into one ``(group, d) @ (d, block)``
  matmul, so each K/V block is streamed once per KV head, not once per
  query head.

Blocks past a sequence's live length are skipped via ``pl.when`` (no
MXU work, no DMA consumed), and a fully-dead split contributes
``m=-inf, l=0`` which the epilogue drops — inactive slots (position 0,
table full of trash-block zeros) produce garbage that the engine never
reads, exactly like the dense path.

Off-TPU the kernel runs under the Pallas interpreter (exact, slow), so
the CPU test rig asserts token identity against the dense-gather
reference on the same code path TPU hardware compiles.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret


def _kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, *rest,
            n_kv, block_size, bps, scale, quantized):
    """One (slot, kv-head, split) program; the innermost grid axis walks
    the split's ``bps`` table entries with the online-softmax carry in
    VMEM scratch. ``quantized`` adds two per-(block, row) scale refs
    after ``v_ref`` and the int8 K/V stream is widened IN REGISTER —
    HBM moves half the bytes, the math runs in f32 exactly like the
    dense fallback's gather-then-widen."""
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    acc_ref, m_ref, l_ref, m_scr, l_scr, a_scr = rest
    sh = pl.program_id(0)
    split = pl.program_id(1)
    j = pl.program_id(2)
    s = sh // n_kv
    pos = pos_ref[s]
    start = (split * bps + j) * block_size

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        a_scr[...] = jnp.zeros_like(a_scr)

    # whole block past the live length: skip — no matmul, and (because
    # the index map clamps dead entries to block 0) no fresh DMA either
    @pl.when(start <= pos)
    def _step():
        q = q_ref[0, 0]                       # (group, D)
        k = k_ref[0, :, 0, :]                 # (block, D)
        v = v_ref[0, :, 0, :]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s_ = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (group, block)
        col = start + jax.lax.broadcasted_iota(jnp.int32, s_.shape, 1)
        mask = col <= pos
        s_ = jnp.where(mask, s_, -jnp.inf)
        m_prev = m_scr[:, :1]                 # (group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1, keepdims=True))
        safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(mask, s_ - safe, -jnp.inf))
        corr = jnp.where(jnp.isfinite(m_prev),
                         jnp.exp(m_prev - safe), 0.0)
        l_scr[:, :1] = corr * l_scr[:, :1] + \
            jnp.sum(p, axis=-1, keepdims=True)
        a_scr[...] = a_scr[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new

    @pl.when(j == bps - 1)
    def _finish():
        acc_ref[0, 0, 0] = a_scr[...].astype(acc_ref.dtype)
        m_ref[0, 0, 0] = jnp.broadcast_to(m_scr[:, :1],
                                          m_ref.shape[3:])
        l_ref[0, 0, 0] = jnp.broadcast_to(l_scr[:, :1],
                                          l_ref.shape[3:])


def resolve_num_splits(table_width: int,  # zoo-lint: config-parse
                       requested: Optional[int] = None) -> int:
    """Largest divisor of ``table_width`` not exceeding the request
    (``ZOO_LLM_DECODE_SPLITS``, default 4): splits must tile the table
    exactly so every grid program walks the same number of entries."""
    if requested is None:
        requested = int(os.environ.get("ZOO_LLM_DECODE_SPLITS", "4"))
    requested = max(1, min(int(requested), table_width))
    for d in range(requested, 0, -1):
        if table_width % d == 0:
            return d
    return 1


def paged_flash_decode(q: jnp.ndarray, k_cache: jnp.ndarray,
                       v_cache: jnp.ndarray, block_tables: jnp.ndarray,
                       positions: jnp.ndarray, *,
                       k_scale: Optional[jnp.ndarray] = None,
                       v_scale: Optional[jnp.ndarray] = None,
                       scale: Optional[float] = None,
                       num_splits: Optional[int] = None,
                       interpret: Optional[bool] = None) -> jnp.ndarray:
    """Single-query paged attention for one decode tick.

    ``q``: (S, H, D) — one query per slot; ``k_cache``/``v_cache``:
    (num_blocks, block_size, H_kv, D); ``block_tables``: (S, W) int32;
    ``positions``: (S,) int32 — the cache index the slot's incoming
    token was written at (tokens ``0..position`` are attended).
    Returns (S, H, D) in ``q``'s dtype.

    An int8 cache passes ``k_scale``/``v_scale`` — per-(block, row,
    kv-head) absmax scales, shape (num_blocks, block_size, H_kv) — and
    each block stream is dequantized in VMEM right after the DMA, so
    the HBM roofline sees int8 bytes while the softmax math stays f32
    (a bf16 cache needs no scales; the matmuls widen it natively).
    """
    S, H, D = q.shape
    n_blocks, block_size, n_kv, _ = k_cache.shape
    quantized = k_scale is not None
    if quantized and v_scale is None or not quantized \
            and v_scale is not None:
        raise ValueError("k_scale and v_scale travel together")
    if H % n_kv:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads "
                         f"({n_kv})")
    group = H // n_kv
    W = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / float(D) ** 0.5
    interpret = _resolve_interpret(interpret)
    splits = resolve_num_splits(W, num_splits)
    bps = W // splits

    q4 = q.reshape(S, n_kv, group, D)
    bt = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    def _entry(sh, sp, j, bt_ref, pos_ref):
        # dead entries (whole block past the live length) are clamped to
        # block 0 so the pipeline re-fetches the already-resident trash
        # block instead of streaming a block the kernel will skip
        idx = sp * bps + j
        s = sh // n_kv
        live = idx * block_size <= pos_ref[s]
        return jnp.where(live, bt_ref[s, idx], 0)

    kernel = functools.partial(
        _kernel, n_kv=n_kv, block_size=block_size, bps=bps, scale=scale,
        quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, group, D),
                     lambda sh, sp, j, bt_ref, pos_ref:
                     (sh // n_kv, sh % n_kv, 0, 0)),
        pl.BlockSpec((1, block_size, 1, D),
                     lambda sh, sp, j, bt_ref, pos_ref:
                     (_entry(sh, sp, j, bt_ref, pos_ref), 0,
                      sh % n_kv, 0)),
        pl.BlockSpec((1, block_size, 1, D),
                     lambda sh, sp, j, bt_ref, pos_ref:
                     (_entry(sh, sp, j, bt_ref, pos_ref), 0,
                      sh % n_kv, 0)),
    ]
    operands = [q4, k_cache, v_cache]
    if quantized:
        # the scale rows ride the exact same block-table routing as
        # their K/V block (dead entries clamp to the trash block too)
        for s_arr in (k_scale, v_scale):
            if s_arr.shape != (n_blocks, block_size, n_kv):
                raise ValueError(
                    f"scale shape {s_arr.shape} != "
                    f"{(n_blocks, block_size, n_kv)}")
            in_specs.append(pl.BlockSpec(
                (1, block_size, 1),
                lambda sh, sp, j, bt_ref, pos_ref:
                (_entry(sh, sp, j, bt_ref, pos_ref), 0, sh % n_kv)))
            operands.append(s_arr.astype(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S * n_kv, splits, bps),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, 1, group, D),
                         lambda sh, sp, j, bt_ref, pos_ref:
                         (sh // n_kv, sh % n_kv, sp, 0, 0)),
            pl.BlockSpec((1, 1, 1, group, _LANES),
                         lambda sh, sp, j, bt_ref, pos_ref:
                         (sh // n_kv, sh % n_kv, sp, 0, 0)),
            pl.BlockSpec((1, 1, 1, group, _LANES),
                         lambda sh, sp, j, bt_ref, pos_ref:
                         (sh // n_kv, sh % n_kv, sp, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, D), jnp.float32),
        ],
    )
    # (slot*kv_head, split) programs are independent — mark them
    # parallel so Mosaic can spread them over cores (megacore); only
    # the innermost block walk carries the VMEM softmax state and must
    # stay sequential. Without this the whole grid serializes and the
    # split-KV axis adds epilogue cost without its parallelism.
    params_cls = getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        compiler_params=params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        out_shape=[
            jax.ShapeDtypeStruct((S, n_kv, splits, group, D),
                                 jnp.float32),
            jax.ShapeDtypeStruct((S, n_kv, splits, group, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((S, n_kv, splits, group, _LANES),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(bt, pos, *operands)

    # split-KV epilogue: merge the per-split partial softmaxes with the
    # log-sum-exp correction (dead splits carry m=-inf/l=0 and drop out)
    m0 = m[..., 0]                                  # (S, n_kv, splits, G)
    l0 = l[..., 0]
    m_max = jnp.max(m0, axis=2, keepdims=True)
    m_safe = jnp.where(jnp.isfinite(m_max), m_max, 0.0)
    alpha = jnp.where(jnp.isfinite(m0), jnp.exp(m0 - m_safe), 0.0)
    l_tot = jnp.sum(alpha * l0, axis=2)             # (S, n_kv, G)
    o = jnp.sum(alpha[..., None] * acc, axis=2) / \
        jnp.where(l_tot == 0.0, 1.0, l_tot)[..., None]
    return o.astype(q.dtype).reshape(S, H, D)
