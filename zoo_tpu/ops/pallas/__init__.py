"""Pallas TPU kernels for the hot ops.

The reference reaches native compute through JVM bindings (BigDL MKL-DNN,
libtensorflow JNI — SURVEY §2.9); here the native layer is Pallas kernels
compiled by Mosaic for the TPU's MXU/VPU:

- ``flash_attention`` — blockwise online-softmax attention (net-new vs the
  reference's dense ``TransformerLayer.scala:279`` math; required for the
  long-context path, SURVEY §5.7).
- ``quantized_matmul`` / ``quantize_int8`` — int8 inference path, the TPU
  equivalent of the reference's OpenVINO VNNI int8 story
  (``examples/vnni``, SURVEY §2.9(4)).
- ``fused_apply_sgd`` / ``fused_apply_adam`` — fused optimizer update, the
  TPU equivalent of BigDL's slice-wise parameter-manager "aggregate +
  apply" step (``docs/docs/wp-bigdl.md:146-160``).

Every kernel takes ``interpret=None`` and auto-falls-back to the Pallas
interpreter off-TPU so the hermetic CPU-mesh test rig (tests/conftest.py)
exercises the same code path CI-side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANES = 128       # VPU lane width; minor dim of every scratch carrier
SUBLANES = 8      # f32 sublane count


def on_tpu() -> bool:  # zoo-lint: config-parse
    """True when the default device is TPU hardware.

    Checks the device_kind, not just the backend name: experimental TPU
    platform registrations (BENCH_r05 ran on a backend named ``axon``
    whose devices report ``device_kind="TPU v5 lite"``) would otherwise
    silently demote every Pallas kernel to the interpreter — and the
    attention ``auto`` mode to the dense path, the measured s4096 MFU
    falloff. ``ZOO_PALLAS_FORCE_INTERPRET=1`` is the kill switch if a
    TPU-kind platform cannot take Mosaic kernels."""
    import os
    if os.environ.get("ZOO_PALLAS_FORCE_INTERPRET", "") in ("1", "true"):
        return False
    if jax.default_backend() == "tpu":
        return True
    try:
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:  # noqa: BLE001 — no backend at all
        return False
    return "tpu" in str(kind).lower()


def resolve_interpret(interpret) -> bool:
    """None → interpret off-TPU, compile on TPU."""
    if interpret is None:
        return not on_tpu()
    return bool(interpret)


def pad_dim(x, axis: int, mult: int):
    """Zero-pad ``axis`` of ``x`` up to a multiple of ``mult``."""
    rem = (-x.shape[axis]) % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads)


from zoo_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402
from zoo_tpu.ops.pallas.paged_decode import paged_flash_decode  # noqa: E402
from zoo_tpu.ops.pallas.paged_prefill import paged_flash_prefill  # noqa: E402
from zoo_tpu.ops.pallas.quant import (  # noqa: E402
    quantize_int8, quantized_matmul, quantized_dense,
    fused_quantized_matmul, resolve_int8_matmul,
    quantize_conv_weights, quantized_conv2d)
from zoo_tpu.ops.pallas.conv import (  # noqa: E402
    conv2d, conv2d_int8, resolve_conv_impl)
from zoo_tpu.ops.pallas.fused_optim import (  # noqa: E402
    fused_apply_sgd, fused_apply_adam)
from zoo_tpu.ops.pallas.fused_block import fused_bottleneck  # noqa: E402

__all__ = ["flash_attention", "paged_flash_decode",
           "paged_flash_prefill", "quantize_int8",
           "quantized_matmul", "fused_quantized_matmul",
           "resolve_int8_matmul",
           "quantized_dense", "quantize_conv_weights", "quantized_conv2d",
           "conv2d", "conv2d_int8", "resolve_conv_impl",
           "fused_apply_sgd", "fused_apply_adam", "fused_bottleneck",
           "on_tpu", "resolve_interpret"]
