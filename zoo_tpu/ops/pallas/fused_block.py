"""Fused ResNet-bottleneck forward kernel (Pallas, TPU).

One kernel computes ``relu(x + (relu(conv3x3(relu(x @ w1)))) @ w3)`` —
the full identity bottleneck (1x1 reduce, 3x3, 1x1 expand, residual
add, with BN folded into the weights as scale/shift at inference) —
reading ``x`` from HBM once and writing ``y`` once. The grid is over
batch tiles; each program holds K whole images in VMEM, so the 3x3's
halo is just zero padding at image edges (no cross-program exchange).

Measured on v5e (bf16, batch 128, 100-rep scanned chains, forward;
the dev chip is SHARED, so ranges over repeated sessions):

=========  ==================  =========  =========  ==========
stage      geometry            XLA TF/s   fused      ratio
=========  ==================  =========  =========  ==========
conv2_x    56x56,  256->64     43-55      50-91      1.0-1.65x
conv3_x    28x28,  512->128    71-79      60-76      0.8-1.0x
conv4_x    14x14, 1024->256    79-87      79-86      ~1.0x
conv5_x    7x7,  2048->512     50-56      (K=0: XLA fallback)
=========  ==================  =========  =========  ==========

The conv2_x ratio tracks available HBM bandwidth: the kernel is
HBM-bound at ~182 FLOP/byte intensity, so at the session-measured
~250 GB/s (bench ``cal_hbm_gbs``; a third of the 819 spec on this
shared/tunneled chip) its ceiling is ~48 TF/s and it sits at XLA
parity, while sessions with more headroom measured 74-91 TF/s vs
XLA's 45-55 (1.65x) — XLA's version of the block is stuck near 55
regardless because its narrow-N (64-lane) 1x1 matmuls starve the MXU.
At the deeper stages XLA's own producer-consumer fusion is already
excellent. Model-level training economics are thin (conv2_x is ~19%
of ResNet-50 FLOPs and backward stays on XLA), so the stock ResNet
keeps XLA convs; this op is for inference paths and early-stage-heavy
CNNs on chips with healthy HBM bandwidth.

No reference counterpart (the reference's conv fusion lives inside
MKL-DNN); geometry follows ``models/image/resnet.py`` bottlenecks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_VMEM_BUDGET = 12 << 20  # leave headroom under the 16MB scoped limit


def _xla_block(x, w1, w2, w3):
    """Reference semantics (also the off-TPU and fallback path)."""
    dn = ("NHWC", "HWIO", "NHWC")
    cin, cmid = w1.shape
    t1 = jax.nn.relu(jax.lax.conv_general_dilated(
        x, w1.reshape(1, 1, cin, cmid), (1, 1), "SAME",
        dimension_numbers=dn))
    t2 = jax.nn.relu(jax.lax.conv_general_dilated(
        t1, w2, (1, 1), "SAME", dimension_numbers=dn))
    z3 = jax.lax.conv_general_dilated(
        t2, w3.reshape(1, 1, cmid, cin), (1, 1), "SAME",
        dimension_numbers=dn)
    return jax.nn.relu(x + z3)


def _pick_k(batch: int, h: int, w: int, cin: int, cmid: int) -> int:
    """Largest power-of-two batch tile whose working set fits VMEM
    (double-buffered in/out blocks + padded-plane scratch + weights)."""
    weights = (cin * cmid + 9 * cmid * cmid + cmid * cin) * 2
    for k in (16, 8, 4, 2, 1):
        if batch % k:
            continue
        per_img = (2 * h * w * cin * 2        # x in + y out (bf16)
                   + (h + 2) * (w + 2) * cmid * 2   # padded t plane
                   + 2 * h * w * cmid * 4)    # t1 + f32 acc live values
        if 2 * k * per_img + 2 * weights <= _VMEM_BUDGET:
            return k
    return 0


def _kernel(x_ref, w1_ref, w2_ref, w3_ref, y_ref, t_scr, *, k, h, w,
            cin, cmid):
    xin = x_ref[:].reshape(k * h * w, cin)
    t1 = jnp.maximum(
        jnp.dot(xin, w1_ref[:], preferred_element_type=jnp.float32),
        0.0).astype(jnp.bfloat16)
    t_scr[:] = jnp.zeros_like(t_scr)
    t_scr[:, 1:h + 1, 1:w + 1, :] = t1.reshape(k, h, w, cmid)
    acc = jnp.zeros((k * h * w, cmid), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            win = t_scr[:, dy:dy + h, dx:dx + w, :]
            acc = acc + jnp.dot(win.reshape(k * h * w, cmid),
                                w2_ref[dy, dx],
                                preferred_element_type=jnp.float32)
    t2 = jnp.maximum(acc, 0.0).astype(jnp.bfloat16)
    z3 = jnp.dot(t2, w3_ref[:],
                 preferred_element_type=jnp.float32).astype(jnp.bfloat16)
    y_ref[:] = jnp.maximum(z3 + xin, 0.0).reshape(k, h * w, cin)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_bottleneck(x: jnp.ndarray, w1: jnp.ndarray, w2: jnp.ndarray,
                     w3: jnp.ndarray,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """``relu(x + expand(relu(conv3x3(relu(reduce(x))))))`` fused.

    ``x``: (B, H, W, Cin) bf16/f32; ``w1``: (Cin, Cmid); ``w2``:
    (3, 3, Cmid, Cmid) HWIO; ``w3``: (Cmid, Cin). Follows the package
    interpret contract (``interpret=None`` → Pallas interpreter
    off-TPU, compiled kernel on TPU); on TPU a geometry exceeding the
    kernel's VMEM plan falls back to the XLA composition.

    Differentiable via ``jax.custom_vjp``: the backward RECOMPUTES the
    XLA composition's residuals and reuses its VJP (the kernel writes
    only ``y``, so t1/t2 are not available to save — exporting them
    would double the HBM writes the fusion exists to avoid). Training
    cost is therefore fused_fwd + ~1 extra XLA forward vs the all-XLA
    block; with the conv2_x fused speedup at most 1.65x of one forward,
    the net train-step delta is negative — measured and documented in
    the module docstring. Train with the stock XLA convs; this op's
    win is inference.
    """
    return _fused_bottleneck_impl(x, w1, w2, w3, interpret)


def _fused_bottleneck_fwd(x, w1, w2, w3, interpret):
    return _fused_bottleneck_impl(x, w1, w2, w3, interpret), \
        (x, w1, w2, w3)


def _fused_bottleneck_bwd(interpret, res, g):
    x, w1, w2, w3 = res
    _, vjp = jax.vjp(_xla_block, x, w1, w2, w3)
    return vjp(g.astype(x.dtype))


fused_bottleneck.defvjp(_fused_bottleneck_fwd, _fused_bottleneck_bwd)


def _fused_bottleneck_impl(x: jnp.ndarray, w1: jnp.ndarray,
                           w2: jnp.ndarray, w3: jnp.ndarray,
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from zoo_tpu.ops.pallas import resolve_interpret

    b, h, w, cin = x.shape
    cmid = w1.shape[1]
    interpret = resolve_interpret(interpret)
    if interpret:
        # the interpreter has no VMEM; any batch tile works — keep it
        # small so CPU tests stay fast
        k = 1 if b % 2 else 2
    else:
        k = _pick_k(b, h, w, cin, cmid)
        if k == 0:  # geometry exceeds the kernel's VMEM plan
            return _xla_block(x, w1, w2, w3)

    dtype = jnp.bfloat16
    xf = x.astype(dtype).reshape(b, h * w, cin)
    kern = functools.partial(_kernel, k=k, h=h, w=w, cin=cin, cmid=cmid)
    y = pl.pallas_call(
        kern,
        grid=(b // k,),
        in_specs=[
            pl.BlockSpec((k, h * w, cin), lambda i: (i, 0, 0)),
            pl.BlockSpec((cin, cmid), lambda i: (0, 0)),
            pl.BlockSpec((3, 3, cmid, cmid), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((cmid, cin), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((k, h * w, cin), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h * w, cin), dtype),
        scratch_shapes=[pltpu.VMEM((k, h + 2, w + 2, cmid), dtype)],
        interpret=interpret,
    )(xf, w1.astype(dtype), w2.astype(dtype), w3.astype(dtype))
    return y.reshape(b, h, w, cin).astype(x.dtype)
