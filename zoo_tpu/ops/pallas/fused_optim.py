"""Fused optimizer-apply Pallas kernels.

The reference's parameter sync is BigDL's PS-style AllReduce: gradients
are sliced N ways, each "parameter manager" task aggregates its slice and
*applies the optimizer to that slice in the same task* before broadcasting
the updated slice back (``docs/docs/wp-bigdl.md:146-160``,
``Topology.scala:1204``). The TPU mapping (SURVEY §2.9(1)) is
reduce_scatter + fused-apply + all_gather; these kernels are the
"fused-apply" leg — a single VMEM-resident elementwise pass per slice
instead of separate mul/add HBM round-trips. Use under ``shard_map`` so
each chip updates only its parameter shard.

Tensors of any shape are viewed as padded (rows, 128) tiles; scalars
(lr, step) ride in SMEM so changing them does not recompile.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret

_BLOCK_ROWS = 256


def _as_tiles(x):
    n = x.size
    rows = -(-n // _LANES)
    pad_rows = (-rows) % _BLOCK_ROWS
    flat = jnp.pad(x.reshape(-1), (0, rows * _LANES - n))
    tiles = flat.reshape(rows, _LANES)
    if pad_rows:
        tiles = jnp.pad(tiles, ((0, pad_rows), (0, 0)))
    return tiles


def _from_tiles(tiles, like):
    return tiles.reshape(-1)[:like.size].reshape(like.shape).astype(
        like.dtype)


def _sgd_kernel(lr_ref, mom_ref, wd_ref, p_ref, g_ref, buf_ref,
                p_out, buf_out):
    lr = lr_ref[0]
    momentum = mom_ref[0]
    wd = wd_ref[0]
    g = g_ref[...] + wd * p_ref[...]
    buf = momentum * buf_ref[...] + g
    p_out[...] = p_ref[...] - lr * buf
    buf_out[...] = buf


def fused_apply_sgd(param: jnp.ndarray, grad: jnp.ndarray,
                    momentum_buf: jnp.ndarray, lr,
                    momentum: float = 0.0, weight_decay: float = 0.0,
                    interpret: Optional[bool] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fused SGD(+momentum, +L2) step; returns (param, momentum_buf)."""
    interpret = _resolve_interpret(interpret)
    p = _as_tiles(param.astype(jnp.float32))
    g = _as_tiles(grad.astype(jnp.float32))
    b = _as_tiles(momentum_buf.astype(jnp.float32))
    scalars = [jnp.asarray([v], jnp.float32)
               for v in (lr, momentum, weight_decay)]
    rows = p.shape[0]
    grid = (rows // _BLOCK_ROWS,)
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    new_p, new_b = pl.pallas_call(
        _sgd_kernel,
        grid=grid,
        in_specs=[sspec, sspec, sspec, spec, spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 2,
        interpret=interpret,
    )(*scalars, p, g, b)
    return _from_tiles(new_p, param), _from_tiles(new_b, momentum_buf)


def _adam_kernel(lr_ref, b1_ref, b2_ref, eps_ref, wd_ref, bc1_ref, bc2_ref,
                 p_ref, g_ref, m_ref, v_ref, p_out, m_out, v_out):
    lr = lr_ref[0]
    b1 = b1_ref[0]
    b2 = b2_ref[0]
    eps = eps_ref[0]
    wd = wd_ref[0]
    bc1 = bc1_ref[0]     # 1 / (1 - b1^t)
    bc2 = bc2_ref[0]     # 1 / (1 - b2^t)
    g = g_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    m_hat = m * bc1
    v_hat = v * bc2
    # AdamW-style decoupled decay (the reference's AdamWeightDecay,
    # pipeline/api/keras/optimizers/AdamWeightDecay.scala).
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p_ref[...]
    p_out[...] = p_ref[...] - lr * update
    m_out[...] = m
    v_out[...] = v


def reference_apply_adam(param: jnp.ndarray, grad: jnp.ndarray,
                         m: jnp.ndarray, v: jnp.ndarray, step,
                         lr, beta1: float = 0.9, beta2: float = 0.999,
                         eps: float = 1e-8, weight_decay: float = 0.0
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The same AdamW math as :func:`fused_apply_adam`, in plain jnp —
    the GSPMD-friendly form. A ``pallas_call`` has no SPMD partitioning
    rule, so inside an FSDP/TP-sharded train step the kernel would force
    XLA to gather every shard it touches; this elementwise chain
    partitions trivially (each device updates only its slice) and XLA
    fuses it into one VMEM pass anyway. ``fused_apply_adam`` dispatches
    here whenever the active mesh spans more than one device."""
    step = jnp.asarray(step, jnp.float32)
    b1, b2 = jnp.float32(beta1), jnp.float32(beta2)
    p32, g = param.astype(jnp.float32), grad.astype(jnp.float32)
    m = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v.astype(jnp.float32) + (1.0 - b2) * g * g
    m_hat = m * (1.0 / (1.0 - b1 ** step))
    v_hat = v * (1.0 / (1.0 - b2 ** step))
    update = m_hat / (jnp.sqrt(v_hat) + jnp.float32(eps)) \
        + jnp.float32(weight_decay) * p32
    new_p = (p32 - jnp.asarray(lr, jnp.float32) * update).astype(
        param.dtype)
    return new_p, m, v


def _mesh_active() -> bool:
    """True when the runtime context's mesh spans >1 device — the
    sharded-step case where the elementwise reference path must be used
    (see :func:`reference_apply_adam`)."""
    from zoo_tpu.common.context import get_runtime_context
    ctx = get_runtime_context(required=False)
    return ctx is not None and getattr(ctx.mesh, "size", 1) > 1


def fused_apply_adam(param: jnp.ndarray, grad: jnp.ndarray,
                     m: jnp.ndarray, v: jnp.ndarray, step,
                     lr, beta1: float = 0.9, beta2: float = 0.999,
                     eps: float = 1e-8, weight_decay: float = 0.0,
                     interpret: Optional[bool] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One fused Adam(W) step; returns (param, m, v). ``step`` is 1-based.

    Under a >1-device mesh the update runs as the partitionable
    elementwise reference chain instead of the Pallas kernel (same math;
    each device updates its own parameter shard — the reference's
    "apply optimizer to the aggregated slice in-task" done by GSPMD)."""
    if _mesh_active():
        return reference_apply_adam(param, grad, m, v, step, lr,
                                    beta1=beta1, beta2=beta2, eps=eps,
                                    weight_decay=weight_decay)
    interpret = _resolve_interpret(interpret)
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 / (1.0 - jnp.float32(beta1) ** step)
    bc2 = 1.0 / (1.0 - jnp.float32(beta2) ** step)
    pt = _as_tiles(param.astype(jnp.float32))
    gt = _as_tiles(grad.astype(jnp.float32))
    mt = _as_tiles(m.astype(jnp.float32))
    vt = _as_tiles(v.astype(jnp.float32))
    scalars = [jnp.asarray([x], jnp.float32).astype(jnp.float32)
               for x in (lr, beta1, beta2, eps, weight_decay)]
    scalars += [bc1.reshape(1), bc2.reshape(1)]
    rows = pt.shape[0]
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    new_p, new_m, new_v = pl.pallas_call(
        _adam_kernel,
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[sspec] * 7 + [spec] * 4,
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct(pt.shape, jnp.float32)] * 3,
        interpret=interpret,
    )(*scalars, pt, gt, mt, vt)
    return (_from_tiles(new_p, param), _from_tiles(new_m, m),
            _from_tiles(new_v, v))
