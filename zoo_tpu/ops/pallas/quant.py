"""Int8 quantized inference kernels (Pallas/MXU).

TPU equivalent of the reference's int8/VNNI inference story: OpenVINO
int8-calibrated models loaded via ``doLoadOpenVINOInt8``
(``pipeline/inference/InferenceModel.scala:283``) and the ``examples/vnni``
benchmarks, which claim ~4x model-size reduction and up to ~2x speedup
(``docs/docs/wp-bigdl.md:192-196``, SURVEY §6). Here weights are stored
int8 per-output-channel symmetric, activations are dynamically quantized
per-row, and the matmul runs int8×int8→int32 on the MXU with dequant fused
into the epilogue.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zoo_tpu.common import knobs
from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import SUBLANES as _SUBLANES
from zoo_tpu.ops.pallas import pad_dim as _pad_dim
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret


def quantize_int8(x: jnp.ndarray, axis: int = -1):
    """Symmetric per-slice int8 quantization along ``axis``.

    Returns ``(values int8, scale f32)`` with ``scale`` shaped like ``x``
    reduced over ``axis`` (keepdims). ``x ≈ values * scale``.
    """
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _qmm_kernel(x_ref, w_ref, xs_ref, ws_ref, o_ref, acc_scr, *, num_k):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(ki == num_k - 1)
    def _finish():
        xs = xs_ref[:, :1]          # (bm, 1) per-row activation scale
        ws = ws_ref[:1, :]          # (1, bn) per-column weight scale
        o_ref[...] = (acc_scr[...].astype(jnp.float32) * xs * ws
                      ).astype(o_ref.dtype)


def quantized_matmul(x_q: jnp.ndarray, w_q: jnp.ndarray,
                     x_scale: jnp.ndarray, w_scale: jnp.ndarray,
                     out_dtype=jnp.float32,
                     block_m: int = 128, block_n: int = 128,
                     block_k: int = 128,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """(M,K)int8 @ (K,N)int8 → (M,N)``out_dtype`` with fused dequant.

    ``x_scale``: (M, 1) or (M,) per-row; ``w_scale``: (1, N) or (N,)
    per-output-channel.
    """
    m, k = x_q.shape
    k2, n = w_q.shape
    assert k == k2, (x_q.shape, w_q.shape)
    interpret = _resolve_interpret(interpret)

    x_scale = x_scale.reshape(m).astype(jnp.float32)
    w_scale = w_scale.reshape(n).astype(jnp.float32)

    xp = _pad_dim(_pad_dim(x_q, 0, block_m), 1, block_k)
    wp = _pad_dim(_pad_dim(w_q, 0, block_k), 1, block_n)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    # Scales ride in lane/sublane-padded carriers (see flash_attention's
    # lse trick): x per-row → (Mp, LANES) use col 0; w per-col →
    # (SUBLANES, Np) use row 0.
    xs = jnp.broadcast_to(_pad_dim(x_scale, 0, block_m)[:, None],
                          (mp, _LANES))
    ws = jnp.broadcast_to(_pad_dim(w_scale, 0, block_n)[None, :],
                          (_SUBLANES, np_))

    num_k = kp // block_k
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, num_k=num_k),
        grid=(mp // block_m, np_ // block_n, num_k),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((block_m, _LANES), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((_SUBLANES, block_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.int32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * kp,
            bytes_accessed=mp * kp + kp * np_ + mp * np_ * 4,
            transcendentals=0),
        interpret=interpret,
    )(xp, wp, xs, ws)
    return out[:m, :n]


# Past this K extent the fused kernel's VMEM-resident activation row
# block (f32 copy + int8 copy per 128-row block, ~5 bytes/element) would
# crowd out the weight/accumulator tiles; the two-pass path takes over.
# Also keeps the int32 accumulator exact: 127*127*8192 ≈ 1.3e8 << 2^31.
_FUSED_MAX_K = 8192


def _fqmm_kernel(x_ref, w_ref, ws_ref, o_ref, acc_scr, xq_scr, xs_scr,
                 *, num_k, block_k):
    """Fused quantize→int8-MXU-dot→dequant. The float activation row
    block rides in VMEM across the whole (j, k) inner grid (constant
    index map); on first touch of a row block it is quantized ONCE into
    int8/scale scratch, every k step then feeds the MXU from scratch,
    and the epilogue applies per-row × per-column scales in-register —
    the paged-kernel in-register dequant idiom applied to the GEMM."""
    ki = pl.program_id(2)

    @pl.when((pl.program_id(1) == 0) & (ki == 0))
    def _quantize():
        # Per-row dynamic symmetric quantization over the FULL K extent
        # (grid pads K with zeros, which never move a row's absmax).
        xf = x_ref[...].astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
        scale = jnp.where(amax == 0, 1.0, amax / 127.0).astype(
            jnp.float32)
        xs_scr[...] = jnp.broadcast_to(scale, xs_scr.shape)
        xq_scr[...] = jnp.clip(jnp.round(xf / scale), -127, 127
                               ).astype(jnp.int8)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += jax.lax.dot_general(
        xq_scr[:, pl.ds(ki * block_k, block_k)], w_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)

    @pl.when(ki == num_k - 1)
    def _finish():
        xs = xs_scr[:, :1]          # (bm, 1) per-row activation scale
        ws = ws_ref[:1, :]          # (1, bn) per-column weight scale
        o_ref[...] = (acc_scr[...].astype(jnp.float32) * xs * ws
                      ).astype(o_ref.dtype)


def fused_quantized_matmul(x: jnp.ndarray, w_q: jnp.ndarray,
                           w_scale: jnp.ndarray,
                           out_dtype=None,
                           block_m: int = 128, block_n: int = 128,
                           block_k: int = 128,
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    """(M,K)float @ (K,N)int8 → (M,N) in ONE ``pallas_call``: per-row
    activation quantization, int8×int8→int32 MXU K-loop, and the
    per-row×per-channel dequant epilogue all fused — no separate XLA
    quantize pass materializing an int8 activation copy in HBM.

    Matches the two-pass reference ``quantize_int8(x, -1)`` +
    :func:`quantized_matmul` exactly up to borderline activation
    rounding (XLA may rewrite ``x / scale`` as ``x * (1/scale)``,
    flipping ties by one int8 step; the int32 accumulation and f32
    epilogue are otherwise identical — measured max diff is one
    dequantized ULP). Falls back to the two-pass path when K exceeds
    ``_FUSED_MAX_K`` (the activation row block must fit VMEM)."""
    m, k = x.shape
    k2, n = w_q.shape
    assert k == k2, (x.shape, w_q.shape)
    out_dtype = out_dtype or x.dtype
    if k > _FUSED_MAX_K:
        x_q, x_scale = quantize_int8(x, axis=-1)
        return quantized_matmul(
            x_q, w_q, x_scale, w_scale, out_dtype=out_dtype,
            block_m=block_m, block_n=block_n, block_k=block_k,
            interpret=interpret).astype(out_dtype)
    interpret = _resolve_interpret(interpret)

    w_scale = w_scale.reshape(n).astype(jnp.float32)
    xp = _pad_dim(_pad_dim(x, 0, block_m), 1, block_k)
    wp = _pad_dim(_pad_dim(w_q, 0, block_k), 1, block_n)
    mp, kp = xp.shape
    np_ = wp.shape[1]
    ws = jnp.broadcast_to(_pad_dim(w_scale, 0, block_n)[None, :],
                          (_SUBLANES, np_))

    num_k = kp // block_k
    out = pl.pallas_call(
        functools.partial(_fqmm_kernel, num_k=num_k, block_k=block_k),
        grid=(mp // block_m, np_ // block_n, num_k),
        in_specs=[
            # full-K activation row block; constant in (j, k) so it
            # stays VMEM-resident while its quantization is reused
            pl.BlockSpec((block_m, kp), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((_SUBLANES, block_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((block_m, block_n), jnp.int32),
            pltpu.VMEM((block_m, kp), jnp.int8),
            pltpu.VMEM((block_m, _LANES), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * kp,
            bytes_accessed=mp * kp * x.dtype.itemsize + kp * np_
            + mp * np_ * 4,
            transcendentals=0),
        interpret=interpret,
    )(xp, wp, ws)
    return out[:m, :n]


def resolve_int8_matmul(impl: Optional[str] = None) -> str:
    """The one int8-GEMM dispatch rule: ``"fused"`` (one-pallas_call
    quantize+dot+dequant) or ``"unfused"`` (XLA quantize pass +
    :func:`quantized_matmul`). ``impl=None`` reads ``ZOO_INT8_MATMUL``
    (``auto`` → fused)."""
    impl = impl or knobs.value("ZOO_INT8_MATMUL")
    if impl == "auto":
        return "fused"
    if impl not in ("fused", "unfused"):
        raise ValueError(f"unknown int8 matmul impl {impl!r} "
                         "(expected auto|fused|unfused)")
    return impl


def quantized_dense(x: jnp.ndarray, w_q: jnp.ndarray,
                    w_scale: jnp.ndarray,
                    bias: Optional[jnp.ndarray] = None,
                    impl: Optional[str] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """f32/bf16 activations × int8 weights: dynamic per-row activation
    quantization + int8 MXU matmul. The InferenceModel int8 path calls
    this for Dense layers after ``quantize()``. Backend selected by
    :func:`resolve_int8_matmul` (default: the fused single-kernel
    path)."""
    x2 = x.reshape(-1, x.shape[-1])
    if resolve_int8_matmul(impl) == "fused":
        y = fused_quantized_matmul(x2, w_q, w_scale,
                                   out_dtype=x.dtype,
                                   interpret=interpret)
    else:
        x_q, x_scale = quantize_int8(x2, axis=-1)
        y = quantized_matmul(x_q, w_q, x_scale, w_scale,
                             out_dtype=x.dtype, interpret=interpret)
    if bias is not None:
        y = y + bias
    return y.reshape(*x.shape[:-1], w_q.shape[1])


def quantize_conv_weights(w: jnp.ndarray):
    """HWIO conv weights -> (int8 HWIO, per-output-channel scale (O,))."""
    kh, kw, ci, o = w.shape
    flat_q, scale = quantize_int8(w.reshape(kh * kw * ci, o), axis=0)
    return flat_q.reshape(w.shape), scale.reshape(o)


def quantized_conv2d(x: jnp.ndarray, w_q: jnp.ndarray,
                     w_scale: jnp.ndarray, strides=(1, 1),
                     padding: str = "SAME",
                     bias: Optional[jnp.ndarray] = None,
                     impl: Optional[str] = None,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """f32/bf16 NHWC activations × int8 HWIO weights: per-image dynamic
    activation quantization + int8 conv with int32 accumulation, dequant
    fused into the epilogue. Extends the int8 inference story from Dense
    to conv nets — the reference's headline int8 use (SSD/VGG inference,
    ``wp-bigdl.md:192-196``).

    The integer conv itself routes through the one conv dispatch point
    (:func:`zoo_tpu.ops.pallas.conv.resolve_conv_impl`): the implicit-
    GEMM Pallas kernel on supported shapes on TPU, the XLA reference
    conv otherwise — so int8 and conv-impl selection compose instead of
    bypassing each other. Off-TPU the reference runs in f32 on the SAME
    quantized integer values (bit-identical inputs; only the
    accumulator differs), so the CPU test mesh exercises the true
    quantization error."""
    from zoo_tpu.ops.pallas.conv import conv2d_int8

    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(1, 2, 3),
                   keepdims=True)
    x_scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    x_q = jnp.clip(jnp.round(x.astype(jnp.float32) / x_scale),
                   -127, 127)
    y = conv2d_int8(x_q, w_q, x_scale, w_scale.astype(jnp.float32),
                    strides=tuple(strides), padding=padding,
                    impl=impl, interpret=interpret)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)
