"""Blockwise flash attention as Pallas TPU kernels (fwd + bwd).

Net-new capability vs the reference: its only attention is the dense
O(T^2)-memory math in ``TransformerLayer.scala:279`` / ``BERT.scala:402``
(SURVEY §5.7 "long-context: absent"). This kernel never materialises the
(T, T) score matrix in HBM: the q-block stays resident in VMEM while k/v
blocks stream through the innermost grid dimension with the online-softmax
running max/denominator carried in VMEM scratch. The backward pass is the
standard two-kernel flash recomputation (dk/dv sweep, then dq sweep) using
the saved logsumexp.

Layout (B, H, T, D), batch*heads collapsed to one leading grid axis.
Causal masking is in-kernel (fully-masked blocks are skipped via
``pl.when`` so the causal path does ~half the FLOPs); arbitrary additive
masks should use the dense path in ``zoo_tpu.ops.attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import pad_dim as _pad_to
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, kv_len, q_len,
                block_q, block_k, num_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    off = kv_len - q_len  # end-aligned causal (matches the dense path)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: the whole k-block is in the future of the whole q-block →
    # skip (the grid still steps but no MXU work is issued).
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1 + off

    @pl.when(run)
    def _step():
        # operands stay in their input dtype (bf16): the MXU multiplies
        # bf16 natively with f32 accumulation via preferred_element_type —
        # casting inputs to f32 here costs ~4x matmul throughput
        q = q_ref[0]                              # (bq, D)
        k = k_ref[0]                              # (bk, D)
        v = v_ref[0]                              # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk) f32

        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        mask = col < kv_len                        # key-padding mask
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, col <= row + off)
        s = jnp.where(mask, s, -jnp.inf)

        m_prev = m_scr[:, :1]                      # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # All-masked rows keep m=-inf; exp(-inf - -inf) would be NaN.
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(mask, s - safe_m, -jnp.inf))
        corr = jnp.where(jnp.isfinite(m_prev),
                         jnp.exp(m_prev - safe_m), 0.0)
        l_new = corr * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new
        l_scr[:, :1] = l_new

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[...] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)
        m = m_scr[:, :1]
        lse = jnp.where(jnp.isfinite(m), m + jnp.log(
            jnp.where(l == 0.0, 1.0, l)), -jnp.inf)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _kv_index(b, hq, hkv):
    """Collapsed (batch*head) index of the kv head serving q-head row
    ``b``: GQA groups of ``hq // hkv`` query heads share one kv head."""
    if hq == hkv:
        return b
    return (b // hq) * hkv + (b % hq) // (hq // hkv)


def _fwd(q, k, v, scale, causal, kv_len, q_len, block_q, block_k,
         hq, hkv, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    num_q = pl.cdiv(tq, block_q)
    num_k = pl.cdiv(tk, block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, kv_len=kv_len,
        q_len=q_len, block_q=block_q, block_k=block_k, num_k=num_k)
    grid = (bh, num_q, num_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (_kv_index(b, hq, hkv), j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (_kv_index(b, hq, hkv), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, num_q * block_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, num_q * block_q, _LANES),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o[:, :tq], lse[:, :tq, 0]


# --------------------------------------------------------------- backward

def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_scr, dv_scr, *,
                 scale, causal, kv_len, q_len, block_q, block_k, num_q,
                 rep):
    # grid (B*Hkv, num_k, rep, num_q): dk/dv accumulate over BOTH the
    # q-blocks and the `rep` query heads of this kv head's GQA group —
    # the (r, qi) loops are innermost so the output block stays resident
    ki = pl.program_id(1)
    r = pl.program_id(2)
    qi = pl.program_id(3)
    off = kv_len - q_len

    @pl.when(jnp.logical_and(r == 0, qi == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        # q-block entirely before k-block → p == 0 there, skip.
        run = qi * block_q + block_q - 1 >= ki * block_k - off

    @pl.when(run)
    def _step():
        # bf16 matmul operands + f32 accumulation (see _fwd_kernel note)
        q = q_ref[0]                               # (bq, D)
        k = k_ref[0]                               # (bk, D)
        v = v_ref[0]
        do = do_ref[0]                             # (bq, D)
        lse = lse_ref[0][:, :1]                    # (bq, 1)
        delta = delta_ref[0][:, :1]                # (bq, 1)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, col <= row + off)
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)
        p = jnp.where(jnp.isfinite(lse), p, 0.0)

        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # p^T @ dO (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        ds = p * (dp - delta) * scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # ds^T @ q (bk, D)

    @pl.when(jnp.logical_and(r == rep - 1, qi == num_q - 1))
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale, causal, kv_len, q_len,
               block_q, block_k, num_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    off = kv_len - q_len

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1 + off

    @pl.when(run)
    def _step():
        # bf16 matmul operands + f32 accumulation (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            mask = jnp.logical_and(mask, col <= row + off)
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)
        p = jnp.where(jnp.isfinite(lse), p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd(scale, causal, kv_len, q_len, block_q, block_k, hq, hkv,
         interpret, res, g):
    q, k, v, o, lse = res
    bh, tq, d = q.shape
    bhkv = k.shape[0]
    tk = k.shape[1]
    num_q = pl.cdiv(tq, block_q)
    num_k = pl.cdiv(tk, block_k)
    rep = hq // hkv

    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                  # (BH, Tq)
    # Broadcast lse/delta into lane-padded (BH, Tq, LANES) blocks.
    lse_b = _pad_to(jnp.broadcast_to(lse[..., None],
                                     (bh, tq, _LANES)), 1, block_q)
    delta_b = _pad_to(jnp.broadcast_to(delta[..., None],
                                       (bh, tq, _LANES)), 1, block_q)
    qp = _pad_to(q, 1, block_q)
    gp = _pad_to(g, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)

    def _q_row(bkv, r):
        # q-head row served by kv row ``bkv`` at group offset ``r``
        if rep == 1:
            return bkv
        return (bkv // hkv) * hq + (bkv % hkv) * rep + r

    dkdv = functools.partial(
        _dkdv_kernel, scale=scale, causal=causal, kv_len=kv_len,
        q_len=q_len, block_q=block_q, block_k=block_k, num_q=num_q,
        rep=rep)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bhkv, num_k, rep, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, i, r, j: (_q_row(b, r), j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, r, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, r, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda b, i, r, j: (_q_row(b, r), j, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, r, j: (_q_row(b, r), j, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, r, j: (_q_row(b, r), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, r, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, r, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhkv, num_k * block_k, d), k.dtype),
            jax.ShapeDtypeStruct((bhkv, num_k * block_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, gp, lse_b, delta_b)

    dqk = functools.partial(
        _dq_kernel, scale=scale, causal=causal, kv_len=kv_len,
        q_len=q_len, block_q=block_q, block_k=block_k, num_k=num_k)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (_kv_index(b, hq, hkv), j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (_kv_index(b, hq, hkv), j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, num_q * block_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, gp, lse_b, delta_b)

    return dq[:, :tq], dk[:, :tk], dv[:, :tk]


# -------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, scale, causal, kv_len, q_len, block_q, block_k,
           hq, hkv, interpret):
    o, _ = _fwd(q, k, v, scale, causal, kv_len, q_len, block_q, block_k,
                hq, hkv, interpret)
    return o


def _flash_fwd(q, k, v, scale, causal, kv_len, q_len, block_q, block_k,
               hq, hkv, interpret):
    o, lse = _fwd(q, k, v, scale, causal, kv_len, q_len, block_q, block_k,
                  hq, hkv, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, kv_len, q_len, block_q, block_k, hq, hkv,
               interpret, res, g):
    return _bwd(scale, causal, kv_len, q_len, block_q, block_k, hq, hkv,
                interpret, res, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over (B, H, T, D); differentiable, O(T) memory.

    GQA-native: ``k``/``v`` may carry FEWER heads than ``q`` (grouped /
    multi-query attention) as long as ``H_q %% H_kv == 0`` — the kernel
    index-maps each query head onto its group's kv head, so the kv
    tensors are never materialized repeated (1/rep the HBM streaming and
    saved-residual footprint vs a ``jnp.repeat`` caller).

    Default 512x512 blocks: measured on v5e at (64, 12, 512, 64) causal,
    512/512 runs fwd+bwd ~2.9x faster than 128/128 (the per-block
    mask/softmax elementwise amortizes over bigger MXU tiles; the f32
    scratch block is 1MB — well within VMEM).

    Off-TPU this runs the same kernels under the Pallas interpreter
    (slow but exact), so the CPU test mesh exercises the TPU code path.
    """
    b, h, tq, d = q.shape
    hkv = k.shape[1]
    tk = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads "
                         f"({hkv})")
    if scale is None:
        scale = 1.0 / float(d) ** 0.5
    interpret = _resolve_interpret(interpret)
    # clamp to the (8-aligned) sequence length: Mosaic requires the
    # sublane block dim to be a multiple of 8, and _pad_to pads the
    # sequence up to the block size
    block_q = min(block_q, max(8, -(-tq // 8) * 8))
    block_k = min(block_k, max(8, -(-tk // 8) * 8))

    qf = _pad_to(q.reshape(b * h, tq, d), 1, block_q)
    kf = _pad_to(k.reshape(b * hkv, tk, d), 1, block_k)
    vf = _pad_to(v.reshape(b * hkv, tk, d), 1, block_k)
    o = _flash(qf, kf, vf, float(scale), bool(causal), int(tk), int(tq),
               int(block_q), int(block_k), int(h), int(hkv), interpret)
    return o[:, :tq].reshape(b, h, tq, d)
