"""Paged flash-prefill: a chunk of queries through a block table.

The PR 10 chunk executable bounded the prefill stall, but its attention
still ran the dense reference: every chunk call gathers the FULL table
width of cache (``cache[block_table]`` → a ``(ctx, heads, d)`` copy per
layer, broadcast over the chunk rows) before the masked softmax reads it
back — the exact double-billing the paged flash-decode kernel removed
from the decode path. This kernel is the prefill/verify counterpart:

* **paged** — K/V blocks are streamed IN PLACE through a
  scalar-prefetched block table (dead entries clamp to the resident
  trash block 0, so no DMA is wasted on blocks past the live length);
* **flash** — online-softmax accumulation in VMEM scratch per chunk
  row, never a ``(ctx,)`` score row in HBM;
* **chunk-causal** — each query row carries its own cache position and
  attends every resident column ``<= position``: causal within the
  chunk AND over everything earlier ticks wrote, because the chunk's
  own K/V are appended to the cache *before* the kernel runs (same
  ordering as the dense chunk path);
* **batched** — the leading axis is sequences: the chunked-prefill
  executable calls it with one sequence, the speculative-decode VERIFY
  executable with every slot's ``k + 1`` candidate rows at once; both
  shapes compile exactly once;
* **GQA-aware + int8** — the ``n_head / n_kv_head`` query heads of a
  KV head are batched per block stream, and an int8 cache hands the
  kernel its per-row absmax scales for in-register dequant after the
  DMA (HBM moves int8 bytes; the math stays f32, exactly like the
  dense path's gather-then-widen).

There is no split-KV axis: unlike decode (one query per sequence), a
chunk exposes ``rows x kv_heads`` programs of parallelism already, and
prefill is compute-bound — the sequential walk over table entries keeps
the online-softmax carry in VMEM with zero merge epilogue.

Off-TPU the kernel runs under the Pallas interpreter (exact, slow); the
CPU suite asserts token identity against the dense-gather reference on
the same code path TPU hardware compiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret


def _kernel(bt_ref, pos_sref, q_ref, pos_ref, k_ref, v_ref, *rest,
            n_kv, block_size, group, width, scale, quantized):
    """One (sequence*kv-head, table-entry) program; the innermost grid
    axis walks the table with the online-softmax carry in VMEM scratch.
    Rows = chunk positions x the kv head's query group."""
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    out_ref, m_scr, l_scr, a_scr = rest
    j = pl.program_id(1)
    C = pos_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        a_scr[...] = jnp.zeros_like(a_scr)

    # rows attend columns <= their own position; positions are
    # nondecreasing per chunk, so a block wholly past the LAST row's
    # position is dead for every row — skip (the index map already
    # clamped its DMA to the resident trash block)
    pos_row = pos_ref[0, :]                                   # (C,)
    # (C*group, 1) per-row positions: row r covers chunk index r//group
    prow = jnp.broadcast_to(pos_row[:, None],
                            (C, group)).reshape(C * group, 1)
    start = j * block_size

    @pl.when(start <= pos_row[C - 1])
    def _step():
        q = q_ref[0, 0].reshape(C * group, q_ref.shape[-1])
        k = k_ref[0, :, 0, :]                                 # (block, D)
        v = v_ref[0, :, 0, :]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s_ = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (rows, block)
        col = start + jax.lax.broadcasted_iota(jnp.int32, s_.shape, 1)
        mask = col <= prow
        s_ = jnp.where(mask, s_, -jnp.inf)
        m_prev = m_scr[:, :1]                            # (rows, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1, keepdims=True))
        safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(mask, s_ - safe, -jnp.inf))
        corr = jnp.where(jnp.isfinite(m_prev),
                         jnp.exp(m_prev - safe), 0.0)
        l_scr[:, :1] = corr * l_scr[:, :1] + \
            jnp.sum(p, axis=-1, keepdims=True)
        a_scr[...] = a_scr[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_new

    @pl.when(j == width - 1)
    def _finish():
        l = l_scr[:, :1]
        out = a_scr[...] / jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = out.reshape(out_ref.shape[2:]).astype(
            out_ref.dtype)


def paged_flash_prefill(q: jnp.ndarray, k_cache: jnp.ndarray,
                        v_cache: jnp.ndarray,
                        block_tables: jnp.ndarray,
                        positions: jnp.ndarray, *,
                        k_scale: Optional[jnp.ndarray] = None,
                        v_scale: Optional[jnp.ndarray] = None,
                        scale: Optional[float] = None,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Chunk-of-queries paged attention over a resident cache.

    ``q``: (S, C, H, D) — C query rows per sequence (a prefill chunk,
    or a verify pass's k+1 candidate rows); ``k_cache``/``v_cache``:
    (num_blocks, block_size, H_kv, D); ``block_tables``: (S, W) int32;
    ``positions``: (S, C) int32 — the cache index each row's token was
    written at, NONDECREASING per sequence (row r attends every column
    ``<= positions[s, r]``, which covers causal-within-chunk plus the
    resident prefix). Returns (S, C, H, D) in ``q``'s dtype.

    An int8 cache passes ``k_scale``/``v_scale`` (per-(block, row,
    kv-head) absmax, shape (num_blocks, block_size, H_kv)); each block
    stream is widened in VMEM right after the DMA."""
    S, C, H, D = q.shape
    n_blocks, block_size, n_kv, _ = k_cache.shape
    quantized = k_scale is not None
    if quantized and v_scale is None or not quantized \
            and v_scale is not None:
        raise ValueError("k_scale and v_scale travel together")
    if H % n_kv:
        raise ValueError(f"q heads ({H}) must be a multiple of kv "
                         f"heads ({n_kv})")
    if positions.shape != (S, C):
        raise ValueError(f"positions shape {positions.shape} != "
                         f"{(S, C)}")
    group = H // n_kv
    W = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / float(D) ** 0.5
    interpret = _resolve_interpret(interpret)

    # (S, n_kv, C, group, D): one program streams a kv head's blocks
    # against its C*group query rows
    q5 = q.reshape(S, C, n_kv, group, D).transpose(0, 2, 1, 3, 4)
    bt = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    def _entry(sk, j, bt_ref, pos_ref):
        # dead entries (whole block past the last row's position) clamp
        # to block 0 so the pipeline re-fetches the resident trash
        # block instead of streaming a block the kernel will skip
        s = sk // n_kv
        live = j * block_size <= pos_ref[s, C - 1]
        return jnp.where(live, bt_ref[s, j], 0)

    kernel = functools.partial(
        _kernel, n_kv=n_kv, block_size=block_size, group=group,
        width=W, scale=scale, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, C, group, D),
                     lambda sk, j, bt_ref, pos_ref:
                     (sk // n_kv, sk % n_kv, 0, 0, 0)),
        # the positions again as a VMEM operand: the kernel needs the
        # (C,) row vector for masking, and SMEM scalar-prefetch reads
        # are scalar-only
        pl.BlockSpec((1, C),
                     lambda sk, j, bt_ref, pos_ref: (sk // n_kv, 0)),
        pl.BlockSpec((1, block_size, 1, D),
                     lambda sk, j, bt_ref, pos_ref:
                     (_entry(sk, j, bt_ref, pos_ref), 0, sk % n_kv, 0)),
        pl.BlockSpec((1, block_size, 1, D),
                     lambda sk, j, bt_ref, pos_ref:
                     (_entry(sk, j, bt_ref, pos_ref), 0, sk % n_kv, 0)),
    ]
    operands = [q5, pos, k_cache, v_cache]
    if quantized:
        for s_arr in (k_scale, v_scale):
            if s_arr.shape != (n_blocks, block_size, n_kv):
                raise ValueError(
                    f"scale shape {s_arr.shape} != "
                    f"{(n_blocks, block_size, n_kv)}")
            in_specs.append(pl.BlockSpec(
                (1, block_size, 1),
                lambda sk, j, bt_ref, pos_ref:
                (_entry(sk, j, bt_ref, pos_ref), 0, sk % n_kv)))
            operands.append(s_arr.astype(jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S * n_kv, W),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, C, group, D),
                         lambda sk, j, bt_ref, pos_ref:
                         (sk // n_kv, sk % n_kv, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((C * group, _LANES), jnp.float32),
            pltpu.VMEM((C * group, _LANES), jnp.float32),
            pltpu.VMEM((C * group, D), jnp.float32),
        ],
    )
    # (sequence*kv_head) programs are independent — parallel over
    # cores; the table walk carries the VMEM softmax state and must
    # stay sequential
    params_cls = getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
    (out,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        compiler_params=params_cls(
            dimension_semantics=("parallel", "arbitrary")),
        out_shape=[
            jax.ShapeDtypeStruct((S, n_kv, C, group, D), q.dtype),
        ],
        interpret=interpret,
    )(bt, pos, *operands)
    return out.transpose(0, 2, 1, 3, 4).reshape(S, C, H, D)
