"""Implicit-GEMM convolution kernels (Pallas/MXU) and the one conv
dispatch point.

The training-side roofline stalls on convs: the XLA conv path measures
~0.197 MFU at ResNet-50's dominant shapes (BENCH_r05) while the MXU
sits idle between im2col materializations. These kernels lower the
exact 1x1/3x3 shapes ``bench_conv_roofline`` measures to implicit GEMM
— no im2col buffer ever exists in HBM:

* **1x1**: a tiled matmul over the flattened spatial axis (stride
  handled by pre-slicing rows/cols, which for k=1 is exactly SAME and
  VALID semantics);
* **3x3 (stride 1)**: the whole spatially-padded input image streams
  through VMEM once per batch element; the kernel walks the 9 taps as
  static halo-shifted views of that resident block and accumulates all
  taps into one f32/int32 register accumulator feeding the same MXU
  call.

:func:`resolve_conv_impl` is the single selection rule (flash-style:
Pallas on TPU, ``lax.conv`` reference off-TPU, ``ZOO_CONV_IMPL``
override) used by the Keras conv layers and the int8 conv path, so
float/int8 and impl selection compose.

Every kernel runs off-TPU under Pallas interpret mode
(``ZOO_PALLAS_FORCE_INTERPRET=1`` or ``interpret=True``), which is how
the parity suites gate correctness on the CPU test mesh.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from zoo_tpu.common import knobs
from zoo_tpu.ops.pallas import LANES as _LANES
from zoo_tpu.ops.pallas import SUBLANES as _SUBLANES
from zoo_tpu.ops.pallas import on_tpu as _on_tpu
from zoo_tpu.ops.pallas import pad_dim as _pad_dim
from zoo_tpu.ops.pallas import resolve_interpret as _resolve_interpret

__all__ = [
    "conv2d",
    "conv2d_int8",
    "resolve_conv_impl",
    "pallas_conv_supported",
]

_DN = ("NHWC", "HWIO", "NHWC")


def pallas_conv_supported(kernel: Tuple[int, int],
                          strides: Tuple[int, int] = (1, 1),
                          dilation: Tuple[int, int] = (1, 1)) -> bool:
    """Shapes the implicit-GEMM kernels cover: any-stride 1x1 (pre-
    sliced to a pure GEMM) and stride-1 3x3 (halo-walk). Everything
    else is the reference conv's job."""
    if tuple(dilation) != (1, 1):
        return False
    k = tuple(kernel)
    if k == (1, 1):
        return True
    return k == (3, 3) and tuple(strides) == (1, 1)


def resolve_conv_impl(impl: Optional[str] = None, *,
                      kernel: Tuple[int, int],
                      strides: Tuple[int, int] = (1, 1),
                      dilation: Tuple[int, int] = (1, 1)) -> str:
    """The one conv dispatch rule → ``"pallas"`` or ``"reference"``.

    ``impl=None`` reads ``ZOO_CONV_IMPL`` (``auto`` | ``pallas`` |
    ``reference``). ``auto`` picks the Pallas implicit-GEMM kernel on
    TPU for supported shapes and the XLA reference conv everywhere
    else; an explicit ``pallas`` on an unsupported shape fails loudly
    rather than silently falling back."""
    impl = impl or knobs.value("ZOO_CONV_IMPL")
    if impl not in ("auto", "pallas", "reference"):
        raise ValueError(f"unknown conv impl {impl!r} "
                         "(expected auto|pallas|reference)")
    supported = pallas_conv_supported(kernel, strides, dilation)
    if impl == "pallas":
        if not supported:
            raise ValueError(
                f"ZOO_CONV_IMPL=pallas but kernel={tuple(kernel)} "
                f"strides={tuple(strides)} dilation={tuple(dilation)} "
                "is outside the implicit-GEMM kernel's envelope "
                "(1x1 any stride, 3x3 stride 1)")
        return "pallas"
    if impl == "reference":
        return "reference"
    return "pallas" if (supported and _on_tpu()) else "reference"


def _spatial_pads(h: int, w: int, kh: int, kw: int,
                  strides: Tuple[int, int], padding: str):
    """XLA-convention SAME/VALID pads + output spatial dims."""
    sh, sw = strides
    padding = padding.upper()
    if padding == "VALID":
        return (0, 0), (0, 0), (h - kh) // sh + 1, (w - kw) // sw + 1
    if padding != "SAME":
        raise ValueError(f"unsupported padding {padding!r}")
    oh = -(-h // sh)
    ow = -(-w // sw)
    th = max((oh - 1) * sh + kh - h, 0)
    tw = max((ow - 1) * sw + kw - w, 0)
    return (th // 2, th - th // 2), (tw // 2, tw - tw // 2), oh, ow


def _conv_kernel(x_ref, w_ref, o_ref, *, taps, oh, ow):
    """Float implicit GEMM: all taps accumulate into one register
    accumulator; each tap is a static halo-shifted view of the
    VMEM-resident image block, flattened to (OH*OW, C) for the MXU."""
    c = x_ref.shape[-1]
    acc = jnp.zeros((oh * ow, o_ref.shape[-1]), jnp.float32)
    for t, (dy, dx) in enumerate(taps):
        xt = x_ref[0, dy:dy + oh, dx:dx + ow, :].reshape(oh * ow, c)
        acc += jax.lax.dot_general(
            xt, w_ref[t], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(1, oh, ow, -1).astype(o_ref.dtype)


def _conv_kernel_q(x_ref, w_ref, xs_ref, ws_ref, o_ref, *, taps, oh, ow):
    """Int8 implicit GEMM: int8×int8→int32 tap accumulation, per-image
    activation scale × per-output-channel weight scale dequant fused
    into the epilogue (the paged-kernel in-register dequant idiom)."""
    c = x_ref.shape[-1]
    acc = jnp.zeros((oh * ow, o_ref.shape[-1]), jnp.int32)
    for t, (dy, dx) in enumerate(taps):
        xt = x_ref[0, dy:dy + oh, dx:dx + ow, :].reshape(oh * ow, c)
        acc += jax.lax.dot_general(
            xt, w_ref[t], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * xs_ref[:1, :1] * ws_ref[:1, :]
    o_ref[...] = y.reshape(1, oh, ow, -1).astype(o_ref.dtype)


def _conv2d_pallas(x, w, strides, padding, interpret, *,
                   x_scale=None, w_scale=None, out_dtype=None,
                   block_n: int = 128):
    """Shared Pallas driver for the float and int8 implicit-GEMM conv.

    Grid (N, O/block_n); the padded image block has a constant index
    map over the output-channel axis so it stays VMEM-resident while
    every O tile reads it. Quantized when ``x_scale``/``w_scale`` are
    given (x then carries int8-range values)."""
    quant = x_scale is not None
    n, h, w_dim, c = x.shape
    kh, kw, _, o = w.shape
    sh, sw = strides
    if (kh, kw) == (1, 1):
        # stride pre-slice: for k=1 SAME never pads, so slicing rows/
        # cols IS the strided conv and the kernel runs stride-1
        if (sh, sw) != (1, 1):
            x = x[:, ::sh, ::sw, :]
        _, oh, ow, _ = x.shape
        taps = ((0, 0),)
    else:
        (ph0, ph1), (pw0, pw1), oh, ow = _spatial_pads(
            h, w_dim, kh, kw, strides, padding)
        if (ph0, ph1, pw0, pw1) != (0, 0, 0, 0):
            x = jnp.pad(x, ((0, 0), (ph0, ph1), (pw0, pw1), (0, 0)))
        taps = tuple((dy, dx) for dy in range(kh) for dx in range(kw))
    hp, wp = x.shape[1], x.shape[2]

    # channel axes pad to the lane width; O-pad columns are sliced off
    x = _pad_dim(x, 3, _LANES)
    cp = x.shape[3]
    wt = _pad_dim(_pad_dim(w, 2, _LANES), 3, block_n)
    op = wt.shape[3]
    wt = wt.reshape(kh * kw, cp, op)

    if quant:
        x = x.astype(jnp.int8)
        kernel = functools.partial(_conv_kernel_q, taps=taps,
                                   oh=oh, ow=ow)
        xs = jnp.broadcast_to(
            x_scale.reshape(n, 1).astype(jnp.float32), (n, _LANES))
        ws = jnp.broadcast_to(
            _pad_dim(w_scale.reshape(o).astype(jnp.float32), 0,
                     block_n)[None, :], (_SUBLANES, op))
        extra_in = [xs, ws]
        extra_specs = [
            pl.BlockSpec((1, _LANES), lambda ni, j: (ni, 0)),
            pl.BlockSpec((_SUBLANES, block_n), lambda ni, j: (0, j)),
        ]
        out_dtype = out_dtype or jnp.float32
    else:
        kernel = functools.partial(_conv_kernel, taps=taps,
                                   oh=oh, ow=ow)
        extra_in, extra_specs = [], []
        out_dtype = out_dtype or x.dtype

    out = pl.pallas_call(
        kernel,
        grid=(n, op // block_n),
        in_specs=[
            pl.BlockSpec((1, hp, wp, cp), lambda ni, j: (ni, 0, 0, 0)),
            pl.BlockSpec((kh * kw, cp, block_n),
                         lambda ni, j: (0, 0, j)),
            *extra_specs,
        ],
        out_specs=pl.BlockSpec((1, oh, ow, block_n),
                               lambda ni, j: (ni, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, op), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * oh * ow * len(taps) * cp * op,
            bytes_accessed=(n * hp * wp * cp * x.dtype.itemsize
                            + kh * kw * cp * op + n * oh * ow * op * 4),
            transcendentals=0),
        interpret=_resolve_interpret(interpret),
    )(x, wt, *extra_in)
    return out[..., :o]


def conv2d(x: jnp.ndarray, w: jnp.ndarray,
           strides: Tuple[int, int] = (1, 1), padding: str = "SAME",
           impl: Optional[str] = None,
           interpret: Optional[bool] = None) -> jnp.ndarray:
    """NHWC float conv2d behind the one dispatch point. The reference
    path is byte-for-byte the `lax.conv_general_dilated` call the conv
    layers always made; the Pallas path is the implicit-GEMM kernel."""
    kh, kw = int(w.shape[0]), int(w.shape[1])
    chosen = resolve_conv_impl(impl, kernel=(kh, kw),
                               strides=tuple(strides))
    if chosen == "reference":
        return jax.lax.conv_general_dilated(
            x, w, tuple(strides), padding.upper(),
            dimension_numbers=_DN)
    return _conv2d_pallas(x, w, tuple(strides), padding, interpret)


def conv2d_int8(x_q: jnp.ndarray, w_q: jnp.ndarray,
                x_scale: jnp.ndarray, w_scale: jnp.ndarray,
                strides: Tuple[int, int] = (1, 1),
                padding: str = "SAME",
                impl: Optional[str] = None,
                interpret: Optional[bool] = None) -> jnp.ndarray:
    """Int8 NHWC conv with fused dequant → f32.

    ``x_q`` carries int8-range values (already rounded/clipped; any
    float dtype), ``x_scale`` the (N,1,1,1) per-image activation scale,
    ``w_scale`` the (O,) per-output-channel weight scale. The Pallas
    path accumulates int8×int8→int32 on the MXU with dequant in the
    epilogue; the reference path keeps the historical XLA behavior
    (true int8 conv on TPU, f32 conv on the same integer values
    off-TPU)."""
    kh, kw = int(w_q.shape[0]), int(w_q.shape[1])
    chosen = resolve_conv_impl(impl, kernel=(kh, kw),
                               strides=tuple(strides))
    if chosen == "pallas":
        return _conv2d_pallas(x_q, w_q, tuple(strides), padding,
                              interpret, x_scale=x_scale,
                              w_scale=w_scale)
    if jax.default_backend() == "tpu":
        y = jax.lax.conv_general_dilated(
            x_q.astype(jnp.int8), w_q, tuple(strides), padding.upper(),
            dimension_numbers=_DN,
            preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        y = jax.lax.conv_general_dilated(
            x_q.astype(jnp.float32), w_q.astype(jnp.float32),
            tuple(strides), padding.upper(), dimension_numbers=_DN)
    return y * x_scale * w_scale
