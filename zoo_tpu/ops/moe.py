"""Mixture-of-Experts feed-forward with expert parallelism.

Net-new vs the reference (SURVEY §2.10 lists EP as absent upstream); the
TPU-native formulation is the public GShard/Switch dense-dispatch recipe:
token→expert routing becomes one-hot dispatch/combine einsums, expert
weights carry an ``E`` (expert) leading dim sharded over the mesh
``expert`` axis, and GSPMD inserts the token all-to-alls from the
sharding annotations — no hand-written collectives, fixed shapes
throughout (capacity-factor token dropping keeps the dispatch tensor
static for XLA).

Functional core only; ``models/llm/moe_llama.py`` wires it into the
Llama block.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["init_moe_params", "moe_ffn", "moe_param_specs",
           "expert_capacity"]


def expert_capacity(n_tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots; multiples of 8 keep TPU tiling happy."""
    cap = int(np.ceil(top_k * n_tokens * capacity_factor / n_experts))
    return max(8, -(-cap // 8) * 8)


def init_moe_params(rng, hidden: int, intermediate: int, n_experts: int,
                    init=None) -> Dict[str, jnp.ndarray]:
    init = init or jax.nn.initializers.glorot_uniform()
    ks = jax.random.split(rng, 4)
    return {
        "router": init(ks[0], (hidden, n_experts), jnp.float32),
        "w_gate": init(ks[1], (n_experts, hidden, intermediate),
                       jnp.float32),
        "w_up": init(ks[2], (n_experts, hidden, intermediate),
                     jnp.float32),
        "w_down": init(ks[3], (n_experts, intermediate, hidden),
                       jnp.float32),
    }


def moe_param_specs(n_experts: int) -> Dict[str, Tuple]:
    """PartitionSpec tuples for :func:`init_moe_params` output: expert
    weights sharded over the ``expert`` mesh axis, router replicated."""
    from jax.sharding import PartitionSpec as P

    return {"router": P(None, None),
            "w_gate": P("expert", None, None),
            "w_up": P("expert", None, None),
            "w_down": P("expert", None, None)}


def moe_ffn(params: Dict, x: jnp.ndarray, *, top_k: int = 2,
            capacity_factor: Optional[float] = None,
            aux_loss_weight: float = 0.01, group_size: int = 512
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE SwiGLU feed-forward over tokens.

    ``x``: (B, T, H) → returns (y, aux_loss) where ``aux_loss`` is the
    Switch-style load-balancing term (already weighted); add it to the
    task loss. Tokens routed past an expert's capacity are dropped
    (standard GShard semantics — the residual connection carries them).
    ``capacity_factor=None`` reads ``ZOO_MOE_CAPACITY`` (default 1.25).

    Tokens are routed within fixed ``group_size`` GROUPS (GShard's 2-D
    dispatch): the dispatch/combine tensors are (g, G, E, C_g) with
    C_g ∝ G/E, so memory is linear in token count — a single global
    dispatch would be O(N²) and OOM at real sequence lengths. Capacity
    (and therefore dropping) is per-group.
    """
    if capacity_factor is None:
        from zoo_tpu.common import knobs
        capacity_factor = float(knobs.value("ZOO_MOE_CAPACITY"))
    B, T, H = x.shape
    E = params["router"].shape[1]
    N = B * T
    G = min(int(group_size), N)
    n_groups = -(-N // G)
    pad = n_groups * G - N
    xf = x.reshape(N, H)
    if pad:
        xf = jnp.concatenate(
            [xf, jnp.zeros((pad, H), x.dtype)], axis=0)
    xg = xf.reshape(n_groups, G, H)
    # padded rows must not claim capacity slots or bias the aux loss
    valid = (jnp.arange(n_groups * G) < N).astype(jnp.float32) \
        .reshape(n_groups, G)
    C = expert_capacity(G, E, top_k, capacity_factor)

    logits = jnp.einsum("gnh,he->gne", xg.astype(jnp.float32),
                        params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                # (g, G, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)      # (g, G, k)
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)
    gate_vals = gate_vals * valid[..., None]

    # position of each (token, slot) in its expert's per-group queue.
    # Slot-major flattening makes top-1 choices win capacity over
    # top-2 spillover.
    oh = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)    # (g, G, k, E)
    oh = oh * valid[..., None, None]
    flat = oh.transpose(0, 2, 1, 3).reshape(n_groups, top_k * G, E)
    pos = (jnp.cumsum(flat, axis=1) - 1.0) * flat
    pos = pos.reshape(n_groups, top_k, G, E).transpose(0, 2, 1, 3)
    keep = (pos < C) & (oh > 0)                            # (g, G, k, E)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                          dtype=jnp.float32) * keep[..., None]
    combine = (slot * gate_vals[..., None, None]).sum(2)   # (g, G, E, C)
    dispatch = (combine > 0).astype(x.dtype)

    # dispatch → per-expert batches; with dispatch sharded on the group
    # (token) dim and the (E, g, C, H) result sharded on E, GSPMD lowers
    # this einsum to the token all-to-all
    expert_in = jnp.einsum("gnec,gnh->egch", dispatch, xg)
    a = jax.nn.silu(jnp.einsum("egch,ehf->egcf", expert_in,
                               params["w_gate"].astype(x.dtype)))
    b = jnp.einsum("egch,ehf->egcf", expert_in,
                   params["w_up"].astype(x.dtype))
    out_e = jnp.einsum("egcf,efh->egch", a * b,
                       params["w_down"].astype(x.dtype))
    y = jnp.einsum("egch,gnec->gnh", out_e, combine.astype(x.dtype))
    y = y.reshape(n_groups * G, H)[:N]

    # Switch load-balance loss: E * sum_e f_e * P_e  (f = token fraction
    # routed top-1 to e, P = mean router prob for e); 1.0 at uniform.
    # Means run over VALID tokens only.
    top1 = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32) \
        * valid[..., None]
    denom = jnp.maximum(valid.sum(), 1.0)
    f = top1.sum((0, 1)) / denom
    pm = (probs * valid[..., None]).sum((0, 1)) / denom
    aux = E * jnp.sum(f * pm) * aux_loss_weight
    return y.reshape(B, T, H), aux.astype(jnp.float32)
