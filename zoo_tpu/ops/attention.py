"""Attention kernels.

The reference's only attention is the dense O(T^2) math inside
``TransformerLayer.scala:279`` / ``BERT.scala:402`` (no flash attention, no
context parallelism — SURVEY §5.7). Here the dense path is written so XLA
fuses softmax into the matmuls; the ring/context-parallel variant lives in
``zoo_tpu.parallel.ring_attention`` and shares this per-block math.

Layout: (batch, heads, seq, head_dim) throughout — heads-second is the
TPU-friendly layout (seq × head_dim trailing = MXU tiles).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          mask: Optional[jnp.ndarray] = None,
                          causal: bool = False,
                          dropout_p: float = 0.0,
                          dropout_rng=None,
                          scale: Optional[float] = None,
                          impl: str = "auto") -> jnp.ndarray:
    """Scaled dot-product attention over (B, H, T, D) tensors.

    ``mask``: optional (B, 1, 1, T) or (B, 1, T, T) additive-style boolean
    mask (True = attend). ``causal`` adds the autoregressive triangle (the
    reference's ``bidirectional=False`` TransformerLayer mode).

    ``impl``: "dense" (XLA-fused O(T^2) math), "flash" (the Pallas
    blockwise kernel, zoo_tpu.ops.pallas.flash_attention), or "auto" —
    flash on TPU when it applies (no arbitrary mask, no dropout),
    dense otherwise.

    GQA: ``k``/``v`` may carry fewer heads than ``q`` (``H_q % H_kv ==
    0``). The flash kernel consumes the unrepeated kv heads natively;
    the dense path broadcasts the groups here.
    """
    flash_ok = mask is None and dropout_p == 0.0
    if impl == "auto":
        # ONE owner for the flash-vs-dense policy (threshold, TPU
        # probe, env overrides): resolve_attention_impl. flash from
        # S>=512 up — with 512x512 blocks the kernel beats the dense
        # path there (measured v5e, B=64 H=12 D=64: fwd 3.3 vs 4.9 ms)
        # and it avoids materializing the f32 T^2 scores that dominate
        # the dense path's HBM traffic; at shorter seq the fused dense
        # path is faster (BERT-base S=128 dense 1.4x flash on v5e).
        # Lazy import: llama.py imports this module at load time.
        from zoo_tpu.models.llm.llama import resolve_attention_impl
        impl = resolve_attention_impl("auto", q.shape[-2]) \
            if flash_ok else "dense"
    if impl == "flash":
        if not flash_ok:
            raise ValueError("flash attention supports causal masking only "
                             "(no arbitrary mask / dropout); use the dense "
                             "impl for those")
        from zoo_tpu.ops.pallas import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    if k.shape[1] != q.shape[1]:  # GQA on the dense path: broadcast
        if q.shape[1] % k.shape[1]:
            raise ValueError(f"q heads ({q.shape[1]}) must be a multiple "
                             f"of kv heads ({k.shape[1]})")
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / float(d) ** 0.5
    # QK^T rides the MXU in the input dtype; the softmax runs in an f32
    # island (bf16 exp/normalize loses attention mass), then drops back
    # for the PV matmul
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale

    neg = jnp.finfo(jnp.float32).min
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(tri, scores, neg)
    if mask is not None:
        scores = jnp.where(mask, scores, neg)

    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    if dropout_p > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def split_heads(x: jnp.ndarray, n_head: int) -> jnp.ndarray:
    """(B, T, H*D) -> (B, H, T, D)."""
    b, t, hd = x.shape
    return x.reshape(b, t, n_head, hd // n_head).transpose(0, 2, 1, 3)


def merge_heads(x: jnp.ndarray) -> jnp.ndarray:
    """(B, H, T, D) -> (B, T, H*D)."""
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)
