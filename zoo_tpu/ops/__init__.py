from zoo_tpu.ops.attention import dot_product_attention

__all__ = ["dot_product_attention"]
