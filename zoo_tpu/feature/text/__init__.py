"""Text pipeline: TextFeature / TextSet with the reference's transform chain.

Rebuild of the reference text stack (Python
``pyzoo/zoo/feature/text/text_set.py:1`` + ``text_feature.py``, Scala
``feature/text/TextSet.scala`` ~797 LoC): corpus → ``tokenize`` →
``normalize`` → ``word2idx`` → ``shape_sequence`` → ``generate_sample``,
plus word-index persistence, random split, relation pairs/lists for
QA-ranking (KNRM), and GloVe embedding-matrix loading. The reference runs
the chain as Spark transformers over an RDD; here it is a thread-pooled map
over local features (the XShards layer provides partitioned parallelism) —
the output feeds estimators as dense int arrays, which is what the TPU
input pipeline wants.
"""

from __future__ import annotations

import json
import os
import random as _random
import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TextFeature", "TextSet", "LocalTextSet", "DistributedTextSet",
    "load_glove_matrix",
]


class TextFeature(dict):
    """Keyed record flowing through the chain (reference:
    ``text_feature.py`` — keys text/uri/label/tokens/indexedTokens/sample)."""

    def __init__(self, text: Optional[str] = None, label: Optional[int] = None,
                 uri: Optional[str] = None):
        super().__init__()
        if text is not None:
            self["text"] = text
        if label is not None:
            self["label"] = int(label)
        if uri is not None:
            self["uri"] = uri

    def get_text(self):
        return self.get("text")

    def get_label(self):
        return self.get("label")

    def keys_(self):
        return list(self.keys())


_TOKEN_RE = re.compile(r"[^a-zA-Z0-9]+")


class TextSet:
    """Factory namespace + shared chain implementation."""

    def __init__(self, features: List[TextFeature]):
        self.features = features
        self.word_index: Optional[Dict[str, int]] = None

    # -- constructors ------------------------------------------------------
    @classmethod
    def read(cls, path: str) -> "LocalTextSet":
        """Directory layout ``path/<category>/*.txt`` exactly like the
        reference's ``TextSet.read`` (label = sorted category position)."""
        feats = []
        cats = sorted(d for d in os.listdir(path)
                      if os.path.isdir(os.path.join(path, d)))
        for label, cat in enumerate(cats):
            cdir = os.path.join(path, cat)
            for fname in sorted(os.listdir(cdir)):
                fpath = os.path.join(cdir, fname)
                if os.path.isfile(fpath):
                    with open(fpath, encoding="utf-8", errors="ignore") as f:
                        feats.append(TextFeature(f.read(), label, fpath))
        return LocalTextSet(feats)

    @classmethod
    def read_csv(cls, path: str) -> "LocalTextSet":
        """uri,text csv (reference ``read_csv``; no header)."""
        feats = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                uri, _, text = line.rstrip("\n").partition(",")
                feats.append(TextFeature(text, uri=uri))
        return LocalTextSet(feats)

    @classmethod
    def from_relation_pairs(cls, relations, corpus1: "TextSet",
                            corpus2: "TextSet") -> "LocalTextSet":
        """Pairwise ranking set: each relation (id1, id2, label) joins the
        indexed tokens of both corpora into one feature whose sample is
        [tokens1 ++ tokens2] (reference ``from_relation_pairs``)."""
        c1 = {f["uri"]: f for f in corpus1.features}
        c2 = {f["uri"]: f for f in corpus2.features}
        feats = []
        for (id1, id2, label) in relations:
            f1, f2 = c1[id1], c2[id2]
            nf = TextFeature(label=int(label))
            nf["indexedTokens"] = np.concatenate(
                [np.asarray(f1["indexedTokens"]),
                 np.asarray(f2["indexedTokens"])])
            feats.append(nf)
        out = LocalTextSet(feats)
        out.word_index = corpus1.word_index
        return out

    @classmethod
    def from_relation_lists(cls, relations, corpus1: "TextSet",
                            corpus2: "TextSet") -> "LocalTextSet":
        """Listwise ranking set: all of a query's candidates grouped into
        ONE feature — ``indexedTokens`` (k, L1+L2) and ``label`` (k,) — so
        list-level metrics (NDCG/MAP) evaluate per query (reference
        ``from_relation_lists``)."""
        c1 = {f["uri"]: f for f in corpus1.features}
        c2 = {f["uri"]: f for f in corpus2.features}
        grouped: Dict[str, List] = {}
        for (id1, id2, label) in relations:
            grouped.setdefault(id1, []).append((id2, int(label)))
        feats = []
        for id1, cands in grouped.items():
            t1 = np.asarray(c1[id1]["indexedTokens"])
            rows = [np.concatenate([t1,
                                    np.asarray(c2[id2]["indexedTokens"])])
                    for id2, _ in cands]
            nf = TextFeature(uri=id1)
            nf["indexedTokens"] = np.stack(rows)
            nf["label"] = np.asarray([l for _, l in cands], np.int32)
            feats.append(nf)
        out = LocalTextSet(feats)
        out.word_index = corpus1.word_index
        return out

    # -- chain -------------------------------------------------------------
    def tokenize(self) -> "TextSet":
        """reference ``Tokenizer.scala``: split on non-alphanumerics."""
        for f in self.features:
            f["tokens"] = [t for t in _TOKEN_RE.split(f.get("text", ""))
                           if t]
        return self

    def normalize(self) -> "TextSet":
        """Lower-case and strip non-alphabetical tokens (reference
        ``Normalizer.scala``)."""
        for f in self.features:
            f["tokens"] = [t.lower() for t in f.get("tokens", [])
                           if not t.isdigit()]
        return self

    def generate_word_index_map(self, remove_topN: int = 0,
                                max_words_num: int = -1,
                                min_freq: int = 1,
                                existing_map: Optional[Dict] = None
                                ) -> Dict[str, int]:
        """Frequency-ranked word→index map, 1-based after dropping the
        ``remove_topN`` most frequent words (reference ``word2idx``
        semantics; index 0 is reserved for padding)."""
        if existing_map:
            self.word_index = dict(existing_map)
            return self.word_index
        counts = Counter()
        for f in self.features:
            counts.update(f.get("tokens", []))
        ranked = [w for w, c in counts.most_common() if c >= min_freq]
        ranked = ranked[remove_topN:]
        if max_words_num > 0:
            ranked = ranked[:max_words_num]
        self.word_index = {w: i + 1 for i, w in enumerate(ranked)}
        return self.word_index

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict] = None) -> "TextSet":
        self.generate_word_index_map(remove_topN, max_words_num, min_freq,
                                     existing_map)
        wi = self.word_index
        for f in self.features:
            f["indexedTokens"] = np.asarray(
                [wi[t] for t in f.get("tokens", []) if t in wi], np.int32)
        return self

    def shape_sequence(self, len: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        """Pad/truncate to fixed length (reference ``SequenceShaper``;
        ``trunc_mode`` pre|post)."""
        L = len
        for f in self.features:
            seq = np.asarray(f["indexedTokens"], np.int32)
            if seq.shape[0] > L:
                seq = seq[-L:] if trunc_mode == "pre" else seq[:L]
            elif seq.shape[0] < L:
                pad = np.full((L - seq.shape[0],), pad_element, np.int32)
                seq = np.concatenate([seq, pad])
            f["indexedTokens"] = seq
        return self

    def generate_sample(self) -> "TextSet":
        for f in self.features:
            f["sample"] = (np.asarray(f["indexedTokens"], np.int32),
                           f.get("label"))
        return self

    def transform(self, fn) -> "TextSet":
        self.features = [fn(f) for f in self.features]
        return self

    # -- accessors ---------------------------------------------------------
    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self.word_index

    def save_word_index(self, path: str):
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.word_index, f)

    def load_word_index(self, path: str) -> "TextSet":
        with open(path, encoding="utf-8") as f:
            self.word_index = json.load(f)
        return self

    def set_word_index(self, vocab: Dict[str, int]) -> "TextSet":
        self.word_index = dict(vocab)
        return self

    def get_texts(self) -> List[str]:
        return [f.get("text") for f in self.features]

    def get_uris(self) -> List[str]:
        return [f.get("uri") for f in self.features]

    def get_labels(self) -> List[int]:
        return [f.get("label") for f in self.features]

    def get_predicts(self) -> List:
        return [f.get("predict") for f in self.features]

    def get_samples(self) -> List[Tuple[np.ndarray, Optional[int]]]:
        return [f["sample"] for f in self.features]

    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """(x, y) batch arrays for estimator ``fit`` (the driver-side
        equivalent of the reference's Sample RDD)."""
        xs = np.stack([np.asarray(f["indexedTokens"], np.int32)
                       for f in self.features])
        labels = [f.get("label") for f in self.features]
        ys = None if any(l is None for l in labels) \
            else np.asarray(labels, np.int32)
        return xs, ys

    def random_split(self, weights: Sequence[float],
                     seed: int = 42) -> List["LocalTextSet"]:
        rs = _random.Random(seed)
        idx = list(range(len(self.features)))
        rs.shuffle(idx)
        total = float(sum(weights))
        outs, lo = [], 0
        for i, w in enumerate(weights):
            hi = len(idx) if i == len(weights) - 1 \
                else lo + int(round(len(idx) * w / total))
            part = LocalTextSet([self.features[j] for j in idx[lo:hi]])
            part.word_index = self.word_index
            outs.append(part)
            lo = hi
        return outs

    def is_local(self) -> bool:
        return True

    def is_distributed(self) -> bool:
        return False

    def __len__(self):
        return len(self.features)


class LocalTextSet(TextSet):
    """reference: ``LocalTextSet`` — construct from texts (+labels)."""

    def __init__(self, features=None, texts: Optional[Sequence[str]] = None,
                 labels: Optional[Sequence[int]] = None):
        if features is None:
            features = [TextFeature(t, None if labels is None else labels[i])
                        for i, t in enumerate(texts or [])]
        super().__init__(list(features))


class DistributedTextSet(LocalTextSet):
    """reference ``DistributedTextSet`` (RDD-backed there). The rebuild
    processes text shard-wise per host; the distributed/local split is a
    placement detail, so this IS the local set under the reference's
    other name."""


def load_glove_matrix(path: str, word_index: Dict[str, int],
                      dim: Optional[int] = None) -> np.ndarray:
    """GloVe txt → (vocab+1, dim) matrix aligned to ``word_index`` (row 0 =
    padding). Missing words stay zero (reference ``WordEmbedding`` +
    ``TextSet.word2idx`` interplay)."""
    vocab = max(word_index.values()) + 1
    matrix = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            w, vec = parts[0], parts[1:]
            if dim is None:
                dim = len(vec)
            if matrix is None:
                matrix = np.zeros((vocab, dim), np.float32)
            i = word_index.get(w)
            if i is not None and i < vocab:
                matrix[i] = np.asarray(vec[:dim], np.float32)
    if matrix is None:
        matrix = np.zeros((vocab, dim or 50), np.float32)
    return matrix
