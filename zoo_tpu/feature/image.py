"""Distributed image pipeline (SURVEY §2 #21).

Rebuild of ``ImageSet`` / ``ImagePreprocessing``
(``feature/image/ImageSet.scala``, Python mirrors
``pyzoo/zoo/feature/image/imageset.py:21`` and
``imagePreprocessing.py:25-375``). The reference wraps BigDL's OpenCV
transformers running in Spark tasks; here transformers are cv2/numpy
callables over HWC uint8/float32 arrays (BGR, OpenCV's order — kept for
behavioral parity), fanned out over XShards workers by
``DistributedImageSet``. ``ImageSetToSample`` + ``to_arrays`` produce the
CHW float tensors the keras facade/Estimators consume (TPU note: conv
layers transpose to NHWC internally; CHW here is the reference's contract,
conversion is one cheap transpose at batch assembly).
"""

from __future__ import annotations

import glob as _glob
import os
import random
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from zoo_tpu.feature.common import Preprocessing

try:
    import cv2
except ImportError:  # pragma: no cover - cv2 is in the image
    cv2 = None


class ImageFeature(dict):
    """Mutable record flowing through the pipeline (reference:
    ``ImageFeature``): keys ``image`` (HWC ndarray), ``label``, ``uri``,
    plus whatever transformers attach (e.g. ``sample``, ``predict``)."""

    def __init__(self, image=None, label=None, uri: Optional[str] = None):
        super().__init__()
        if image is not None:
            self["image"] = image
        if label is not None:
            self["label"] = label
        if uri is not None:
            self["uri"] = uri


class ImagePreprocessing(Preprocessing):
    """Base: transforms ``ImageFeature`` in place via :meth:`map_image`."""

    def map_image(self, img: np.ndarray) -> np.ndarray:
        return img

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        feature["image"] = self.map_image(feature["image"])
        return feature


# ---------------------------------------------------------- transformers

class ImageBytesToMat(ImagePreprocessing):
    """Decode raw encoded bytes (jpg/png) to an HWC BGR mat (reference:
    ``imagePreprocessing.py:33``)."""

    def __call__(self, feature: ImageFeature) -> ImageFeature:
        buf = np.frombuffer(feature["bytes"], dtype=np.uint8)
        feature["image"] = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        return feature


class ImageResize(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:53``."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def map_image(self, img):
        return cv2.resize(img, (self.w, self.h))


class ImageAspectScale(ImagePreprocessing):
    """Scale the short side to ``min_size`` capping the long side at
    ``max_size`` (reference: ``imagePreprocessing.py:211``)."""

    def __init__(self, min_size: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.min_size, self.max_size = min_size, max_size
        self.mult = scale_multiple_of

    def map_image(self, img):
        return self._scale(img, self.min_size)

    def _scale(self, img, min_size):
        h, w = img.shape[:2]
        short, long_ = min(h, w), max(h, w)
        scale = min(min_size / short, self.max_size / long_)
        nh, nw = int(round(h * scale)), int(round(w * scale))
        if self.mult > 1:
            nh = (nh // self.mult) * self.mult
            nw = (nw // self.mult) * self.mult
        return cv2.resize(img, (max(nw, 1), max(nh, 1)))


class ImageRandomAspectScale(ImageAspectScale):
    """reference: ``imagePreprocessing.py:232`` — min_size drawn from a
    list of scales per image. The draw stays local so the transformer is
    stateless and safe to share across XShards workers."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000):
        super().__init__(min_size=scales[0], max_size=max_size)
        self.scales = list(scales)

    def map_image(self, img):
        return self._scale(img, random.choice(self.scales))


class ImageBrightness(ImagePreprocessing):
    """Add a uniform delta in [delta_low, delta_high] (reference:
    ``imagePreprocessing.py:71``)."""

    def __init__(self, delta_low: float, delta_high: float):
        self.low, self.high = delta_low, delta_high

    def map_image(self, img):
        delta = random.uniform(self.low, self.high)
        return np.clip(img.astype(np.float32) + delta, 0, 255)


class ImageHue(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:145``."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0):
        self.low, self.high = delta_low, delta_high

    def map_image(self, img):
        hsv = cv2.cvtColor(img.astype(np.uint8), cv2.COLOR_BGR2HSV).astype(
            np.float32)
        hsv[..., 0] = (hsv[..., 0] +
                       random.uniform(self.low, self.high) / 2.0) % 180
        return cv2.cvtColor(hsv.astype(np.uint8), cv2.COLOR_HSV2BGR)


class ImageSaturation(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:155``."""

    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5):
        self.low, self.high = delta_low, delta_high

    def map_image(self, img):
        hsv = cv2.cvtColor(img.astype(np.uint8), cv2.COLOR_BGR2HSV).astype(
            np.float32)
        hsv[..., 1] = np.clip(
            hsv[..., 1] * random.uniform(self.low, self.high), 0, 255)
        return cv2.cvtColor(hsv.astype(np.uint8), cv2.COLOR_HSV2BGR)


class ImageChannelOrder(ImagePreprocessing):
    """BGR↔RGB flip (reference: ``imagePreprocessing.py:165``)."""

    def map_image(self, img):
        return img[..., ::-1].copy()


class ImageColorJitter(ImagePreprocessing):
    """Random brightness/saturation/hue in random order (reference:
    ``imagePreprocessing.py:173``)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32.0,
                 saturation_prob=0.5, saturation_lower=0.5,
                 saturation_upper=1.5, hue_prob=0.5, hue_delta=18.0):
        self.ops = [
            (brightness_prob, ImageBrightness(-brightness_delta,
                                              brightness_delta)),
            (saturation_prob, ImageSaturation(saturation_lower,
                                              saturation_upper)),
            (hue_prob, ImageHue(-hue_delta, hue_delta)),
        ]

    def map_image(self, img):
        ops = list(self.ops)
        random.shuffle(ops)
        for prob, op in ops:
            if random.random() < prob:
                img = op.map_image(img.astype(np.uint8))
        return img


class ImageChannelNormalize(ImagePreprocessing):
    """(x - mean) / std per channel, BGR order (reference:
    ``imagePreprocessing.py:81``)."""

    def __init__(self, mean_b: float, mean_g: float, mean_r: float,
                 std_b: float = 1.0, std_g: float = 1.0, std_r: float = 1.0):
        self.mean = np.array([mean_b, mean_g, mean_r], np.float32)
        self.std = np.array([std_b, std_g, std_r], np.float32)

    def map_image(self, img):
        return (img.astype(np.float32) - self.mean) / self.std


class PerImageNormalize(ImagePreprocessing):
    """(x - min) / (max - min) per image (reference:
    ``imagePreprocessing.py:98``)."""

    def map_image(self, img):
        img = img.astype(np.float32)
        lo, hi = img.min(), img.max()
        return (img - lo) / max(hi - lo, 1e-8)


class ImagePixelNormalize(ImagePreprocessing):
    """Subtract a per-pixel mean array (reference:
    ``imagePreprocessing.py:244``)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def map_image(self, img):
        return img.astype(np.float32) - self.means.reshape(img.shape)


class ImageCenterCrop(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:270``."""

    def __init__(self, crop_width: int, crop_height: int):
        self.w, self.h = crop_width, crop_height

    def map_image(self, img):
        h, w = img.shape[:2]
        y0 = max((h - self.h) // 2, 0)
        x0 = max((w - self.w) // 2, 0)
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageRandomCrop(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:255``."""

    def __init__(self, crop_width: int, crop_height: int):
        self.w, self.h = crop_width, crop_height

    def map_image(self, img):
        h, w = img.shape[:2]
        y0 = random.randint(0, max(h - self.h, 0))
        x0 = random.randint(0, max(w - self.w, 0))
        return img[y0:y0 + self.h, x0:x0 + self.w]


class ImageFixedCrop(ImagePreprocessing):
    """Crop by explicit box; normalized coords when ``normalized``
    (reference: ``imagePreprocessing.py:284``)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def map_image(self, img):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = int(x1 * w), int(x2 * w)
            y1, y2 = int(y1 * h), int(y2 * h)
        return img[int(y1):int(y2), int(x1):int(x2)]


class ImageExpand(ImagePreprocessing):
    """Pad to a random larger canvas (SSD-style augmentation, reference:
    ``imagePreprocessing.py:301``)."""

    def __init__(self, means_b: float = 123, means_g: float = 117,
                 means_r: float = 104, max_expand_ratio: float = 4.0):
        self.mean = np.array([means_b, means_g, means_r], np.float32)
        self.max_ratio = max_expand_ratio

    def map_image(self, img):
        ratio = random.uniform(1.0, self.max_ratio)
        h, w = img.shape[:2]
        nh, nw = int(h * ratio), int(w * ratio)
        out = np.empty((nh, nw, img.shape[2]), np.float32)
        out[:] = self.mean
        y0 = random.randint(0, nh - h)
        x0 = random.randint(0, nw - w)
        out[y0:y0 + h, x0:x0 + w] = img
        return out


class ImageFiller(ImagePreprocessing):
    """Fill a box with a constant (reference: ``imagePreprocessing.py:319``,
    cutout-style)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 value: int = 255):
        self.box, self.value = (x1, y1, x2, y2), value

    def map_image(self, img):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img = img.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return img


class ImageHFlip(ImagePreprocessing):
    """reference: ``imagePreprocessing.py:334``."""

    def map_image(self, img):
        return img[:, ::-1].copy()


class ImageMirror(ImagePreprocessing):
    """Random horizontal flip with probability 0.5 (reference:
    ``imagePreprocessing.py:343``)."""

    def map_image(self, img):
        return img[:, ::-1].copy() if random.random() < 0.5 else img


class ImageRandomPreprocessing(ImagePreprocessing):
    """Apply inner preprocessing with probability p (reference:
    ``imagePreprocessing.py:375``)."""

    def __init__(self, preprocessing: ImagePreprocessing, prob: float):
        self.inner = preprocessing
        self.prob = prob

    def __call__(self, feature):
        return self.inner(feature) if random.random() < self.prob \
            else feature


class ImageMatToTensor(ImagePreprocessing):
    """HWC → CHW float32 tensor under key ``tensor`` (reference:
    ``imagePreprocessing.py:120``; ``toRGB`` flips the channel order)."""

    def __init__(self, to_rgb: bool = False, format: str = "NCHW"):
        self.to_rgb = to_rgb
        self.format = format

    def __call__(self, feature):
        img = feature["image"].astype(np.float32)
        if self.to_rgb:
            img = img[..., ::-1]
        if self.format == "NCHW":
            img = np.transpose(img, (2, 0, 1))
        feature["tensor"] = np.ascontiguousarray(img)
        return feature


class ImageSetToSample(ImagePreprocessing):
    """Terminal step: attach ``sample`` = (tensor, label) (reference:
    ``imagePreprocessing.py:133``)."""

    def __init__(self, input_keys: Sequence[str] = ("tensor",),
                 target_keys: Optional[Sequence[str]] = ("label",)):
        self.input_keys = list(input_keys)
        self.target_keys = list(target_keys) if target_keys else None

    def __call__(self, feature):
        xs = [feature[k] for k in self.input_keys]
        ys = None
        if self.target_keys and self.target_keys[0] in feature:
            ys = feature[self.target_keys[0]]
        feature["sample"] = (xs[0] if len(xs) == 1 else tuple(xs), ys)
        return feature


# -------------------------------------------------------------- ImageSet

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


class ImageSet:
    """Collection of ImageFeatures (reference: ``imageset.py:21``).
    ``read`` from a file/dir/glob; ``transform`` applies a Preprocessing
    over a worker pool; ``to_arrays`` assembles (x, y) for training."""

    def __init__(self, features: List[ImageFeature]):
        self.features = features

    @classmethod
    def read(cls, path: str, with_label: bool = False,
             resize_height: int = -1, resize_width: int = -1) -> "ImageSet":
        """Dir layout: flat files, or ``path/<label>/*.jpg`` when
        ``with_label`` (the reference derives the label map the same way,
        ``imageset.py:54``)."""
        files: List[Tuple[str, Optional[int]]] = []
        label_map = {}
        if os.path.isdir(path) and with_label:
            classes = sorted(d for d in os.listdir(path)
                             if os.path.isdir(os.path.join(path, d)))
            label_map = {c: i for i, c in enumerate(classes)}
            for c in classes:
                for f in sorted(os.listdir(os.path.join(path, c))):
                    if f.lower().endswith(_IMG_EXTS):
                        files.append((os.path.join(path, c, f),
                                      label_map[c]))
        elif os.path.isdir(path):
            for f in sorted(os.listdir(path)):
                if f.lower().endswith(_IMG_EXTS):
                    files.append((os.path.join(path, f), None))
        else:
            for f in sorted(_glob.glob(path)) or [path]:
                files.append((f, None))
        feats = []
        for f, lbl in files:
            img = cv2.imread(f, cv2.IMREAD_COLOR)
            if img is None:
                continue
            if resize_height > 0 and resize_width > 0:
                img = cv2.resize(img, (resize_width, resize_height))
            feats.append(ImageFeature(image=img, label=lbl, uri=f))
        out = cls(feats)
        out.label_map = label_map
        return out

    @classmethod
    def from_arrays(cls, images: Sequence[np.ndarray],
                    labels: Optional[Sequence] = None) -> "ImageSet":
        feats = [ImageFeature(image=img,
                              label=None if labels is None else labels[i])
                 for i, img in enumerate(images)]
        return cls(feats)

    def transform(self, transformer: Preprocessing) -> "ImageSet":
        self.features = [transformer(f) for f in self.features]
        return self

    def get_image(self, key: str = "image") -> List[np.ndarray]:
        return [f[key] for f in self.features]

    def get_label(self) -> List:
        return [f.get("label") for f in self.features]

    def get_predict(self, key: str = "predict") -> List:
        return [f.get(key) for f in self.features]

    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Stack ``sample`` entries into (x, y) batch arrays."""
        xs = np.stack([f["sample"][0] for f in self.features])
        ys = None
        if self.features and self.features[0]["sample"][1] is not None:
            ys = np.asarray([f["sample"][1] for f in self.features])
        return xs, ys

    def random_split(self, weights: Sequence[float]) -> List["ImageSet"]:
        idx = np.random.permutation(len(self.features))
        w = np.asarray(weights, np.float64)
        bounds = np.cumsum(w / w.sum() * len(idx)).astype(int)
        out, lo = [], 0
        for hi in bounds:
            out.append(ImageSet([self.features[i] for i in idx[lo:hi]]))
            lo = hi
        return out

    def to_xshards(self, num_shards: Optional[int] = None):
        from zoo_tpu.orca.data.shard import LocalXShards
        from zoo_tpu.common.context import default_cores
        n = num_shards or default_cores()
        chunks = np.array_split(np.arange(len(self.features)), max(n, 1))
        return LocalXShards([[self.features[i] for i in c] for c in chunks])

    def __len__(self):
        return len(self.features)
