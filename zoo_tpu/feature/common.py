"""Preprocessing transformer algebra (SURVEY §2 #27).

Rebuild of ``pyzoo/zoo/feature/common.py:94-240``: small composable
transforms shared by NNFrames, the model zoo, and the data pipelines. In
the reference each class is a Py4J handle to a Scala ``Preprocessing``
running inside Spark tasks; here each is a plain callable over numpy, and
chains run in XShards workers or inline. ``a > b`` or
``ChainedPreprocessing([a, b])`` composes.
"""

from __future__ import annotations

import csv as _csv
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np


class Preprocessing:
    """Base transformer: ``__call__`` maps one element; ``apply`` maps an
    iterable (reference: ``Preprocessing`` with ``transform``)."""

    def __call__(self, x):
        raise NotImplementedError

    def apply(self, data):
        return [self(x) for x in data]

    # reference composes with ChainedPreprocessing; `>` sugar added here
    def __gt__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])


class ChainedPreprocessing(Preprocessing):
    """reference: ``common.py:122``."""

    def __init__(self, transformers: Sequence[Preprocessing]):
        flat: List[Preprocessing] = []
        for t in transformers:
            if isinstance(t, ChainedPreprocessing):
                flat.extend(t.transformers)
            else:
                flat.append(t)
        self.transformers = flat

    def __call__(self, x):
        for t in self.transformers:
            x = t(x)
        return x


class Lambda(Preprocessing):
    def __init__(self, fn: Callable):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)


class ScalarToTensor(Preprocessing):
    """reference: ``common.py:136``."""

    def __call__(self, x):
        return np.asarray(x, dtype=np.float32)


class SeqToTensor(Preprocessing):
    """Sequence of numbers → 1-D tensor of ``size`` (reference:
    ``common.py:145``)."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = tuple(size) if size else None

    def __call__(self, x):
        arr = np.asarray(x, dtype=np.float32)
        if self.size:
            arr = arr.reshape(self.size)
        return arr


class SeqToMultipleTensors(Preprocessing):
    """Flat sequence split into several tensors of the given sizes
    (reference: ``common.py:155``, used for multi-input models)."""

    def __init__(self, sizes: Sequence[Sequence[int]]):
        self.sizes = [tuple(s) for s in sizes]

    def __call__(self, x):
        arr = np.asarray(x, dtype=np.float32).reshape(-1)
        outs, pos = [], 0
        for s in self.sizes:
            n = int(np.prod(s))
            outs.append(arr[pos:pos + n].reshape(s))
            pos += n
        return tuple(outs)


class ArrayToTensor(Preprocessing):
    """reference: ``common.py:165``."""

    def __init__(self, size: Optional[Sequence[int]] = None):
        self.size = tuple(size) if size else None

    def __call__(self, x):
        arr = np.asarray(x, dtype=np.float32)
        return arr.reshape(self.size) if self.size else arr


class TensorToSample(Preprocessing):
    """reference: ``common.py:200`` — terminal step producing an
    (features, label) Sample; here label defaults to None."""

    def __call__(self, x):
        if isinstance(x, tuple) and len(x) == 2:
            return x
        return (x, None)


class FeatureLabelPreprocessing(Preprocessing):
    """Pair transformer: apply one preprocessing to features, another to
    labels (reference: ``common.py:186``)."""

    def __init__(self, feature_transformer: Preprocessing,
                 label_transformer: Preprocessing):
        self.feature_transformer = feature_transformer
        self.label_transformer = label_transformer

    def __call__(self, xy: Tuple[Any, Any]):
        x, y = xy
        return (self.feature_transformer(x), self.label_transformer(y))


class ToTuple(Preprocessing):
    """reference: ``common.py:219``."""

    def __call__(self, x):
        return x if isinstance(x, tuple) else (x,)


class SampleToMiniBatch(Preprocessing):
    """Batch a list of (features, label) samples (reference:
    ``common.py:229``); ``apply`` yields stacked minibatches."""

    def __init__(self, batch_size: int, drop_remainder: bool = False):
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def __call__(self, samples):
        xs = np.stack([np.asarray(s[0]) for s in samples])
        ys = None
        if samples and samples[0][1] is not None:
            ys = np.stack([np.asarray(s[1]) for s in samples])
        return (xs, ys)

    def apply(self, data):
        data = list(data)
        out = []
        for i in range(0, len(data), self.batch_size):
            chunk = data[i:i + self.batch_size]
            if self.drop_remainder and len(chunk) < self.batch_size:
                break
            out.append(self(chunk))
        return out


# ------------------------------------------------------------- relations

@dataclass(frozen=True)
class Relation:
    """QA-ranking relation (reference: ``common.py:30``)."""
    id1: str
    id2: str
    label: int


class Relations:
    """reference: ``common.py:52`` — csv/parquet readers for relations."""

    @staticmethod
    def read(path: str) -> List[Relation]:
        out = []
        with open(path, newline="") as f:
            for row in _csv.reader(f):
                if len(row) >= 3:
                    out.append(Relation(row[0], row[1], int(row[2])))
        return out

    @staticmethod
    def read_parquet(path: str) -> List[Relation]:
        import pyarrow.parquet as pq
        tb = pq.read_table(path).to_pydict()
        return [Relation(str(a), str(b), int(c)) for a, b, c in
                zip(tb["id1"], tb["id2"], tb["label"])]


class FeatureSet:
    """reference ``zoo.feature.common.FeatureSet`` (Scala
    ``feature/FeatureSet.scala:52`` — the tiered training-sample cache).
    The capability lives in ``zoo_tpu.orca.data.cache`` (TieredSampleCache
    / CachedDataset + DoubleBufferedIterator feed); this name adapts the
    reference's ``FeatureSet.rdd/ndarrays(...).cache()`` construction."""

    def __init__(self, dataset):
        self.dataset = dataset

    @staticmethod
    def ndarrays(arrays, memory_type: str = "DRAM"):
        from zoo_tpu.orca.data.cache import CachedDataset

        # the reference's PMEM/DIRECT tiers (Optane / off-heap) have no
        # TPU-host analog; both mean "bigger than DRAM", which the cache
        # models as a DISK_n spill budget
        store = memory_type.upper()
        if store in ("PMEM", "DIRECT"):
            store = "DISK_2"
        return FeatureSet(CachedDataset(arrays, store=store))

    def cache(self):
        return self

    def __iter__(self):
        return iter(self.dataset)
