"""Reference import path ``zoo.feature.image3d.transformation``
(``pyzoo/zoo/feature/image3d/transformation.py``) — the 3D transforms
live in the package root here."""

from zoo_tpu.feature.image3d import (  # noqa: F401
    AffineTransform3D,
    CenterCrop3D,
    Crop3D,
    RandomCrop3D,
    Rotate3D,
)
