"""3D (volumetric) image transforms (SURVEY §2 #21, ``feature/image3d``).

Rebuild of the reference's 3D medical-image ops (Scala
``feature/image3d/*`` — Crop3D/Rotate3D/AffineTransform3D, ~450 LoC)
on scipy.ndimage over (D, H, W) float arrays.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from zoo_tpu.feature.image import ImagePreprocessing


class Crop3D(ImagePreprocessing):
    """Crop a (depth, height, width) patch at ``start`` (reference:
    Crop3D.scala)."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(start)
        self.patch = tuple(patch_size)

    def map_image(self, img):
        z, y, x = self.start
        d, h, w = self.patch
        return img[z:z + d, y:y + h, x:x + w]


class RandomCrop3D(ImagePreprocessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch = tuple(patch_size)

    def map_image(self, img):
        import random
        d, h, w = self.patch
        z = random.randint(0, max(img.shape[0] - d, 0))
        y = random.randint(0, max(img.shape[1] - h, 0))
        x = random.randint(0, max(img.shape[2] - w, 0))
        return img[z:z + d, y:y + h, x:x + w]


class CenterCrop3D(ImagePreprocessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch = tuple(patch_size)

    def map_image(self, img):
        d, h, w = self.patch
        z = max((img.shape[0] - d) // 2, 0)
        y = max((img.shape[1] - h) // 2, 0)
        x = max((img.shape[2] - w) // 2, 0)
        return img[z:z + d, y:y + h, x:x + w]


class Rotate3D(ImagePreprocessing):
    """Rotate by Euler angles (radians) about the three axes (reference:
    Rotate3D.scala)."""

    def __init__(self, rotation_angles: Sequence[float]):
        self.angles = tuple(rotation_angles)

    def map_image(self, img):
        from scipy.ndimage import rotate
        out = img.astype(np.float32)
        for angle, axes in zip(self.angles, [(1, 2), (0, 2), (0, 1)]):
            if angle:
                out = rotate(out, np.degrees(angle), axes=axes,
                             reshape=False, order=1, mode="nearest")
        return out


class AffineTransform3D(ImagePreprocessing):
    """Apply a 3x3 affine matrix + translation (reference:
    AffineTransform3D.scala)."""

    def __init__(self, matrix: np.ndarray,
                 translation: Optional[Sequence[float]] = None):
        self.matrix = np.asarray(matrix, np.float64).reshape(3, 3)
        self.translation = (np.zeros(3) if translation is None
                            else np.asarray(translation, np.float64))

    def map_image(self, img):
        from scipy.ndimage import affine_transform
        center = (np.asarray(img.shape, np.float64) - 1) / 2.0
        offset = center - self.matrix @ center + self.translation
        return affine_transform(img.astype(np.float32), self.matrix,
                                offset=offset, order=1, mode="nearest")
