"""Compiled-HLO sharding-quality checks.

A sharding regression that silently replicates everything still *runs*
and produces finite loss — the only place the difference is visible
before you pay for 8 chips is the compiled HLO's collective mix. These
helpers inspect the optimized module text of a compiled step and assert
the collectives the intended parallelism plan implies:

- pure DP: gradients all-reduce; **no** all-gather (a full-parameter
  all-gather under DP means params were accidentally sharded or the
  batch sharding leaked into the params);
- FSDP/ZeRO: all-gather (weights into the consuming op) **and** a grad
  reduction (reduce-scatter, or all-reduce on backends whose SPMD
  partitioner didn't pattern-match the scatter form);
- ring/sequence parallel: collective-permute (the ring hop).

Reference semantics being checked: the slice-wise parameter-server
update of ``Topology.scala:1204`` (reduce-scatter + apply + all-gather)
is what XLA's SPMD partitioner emits for a ZeRO-sharded step.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Optional

__all__ = ["collective_counts", "assert_collectives", "CollectiveError",
           "entry_output_shapes", "shaped_ops", "assert_fsdp_sharded"]

# async pairs (all-reduce-start/-done) and channel-suffixed forms all
# reduce to the base op name; "-start" lines carry the operands so count
# only those plus the plain sync form
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start)?\b")


class CollectiveError(AssertionError):
    """A compiled step's collective mix contradicts the intended plan."""


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count collective instructions in optimized HLO module text.

    Counts instruction definitions (lines containing ``= <op>`` or the
    fused/async start forms), merging async ``-start`` with sync forms.
    """
    counts: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        # instruction lines look like  "%name = type op(...)"; skip
        # metadata/backend-config mentions by requiring the op token to
        # follow an "= " or " = " assignment on the line
        if "=" not in line:
            continue
        rhs = line.split("=", 1)[1]
        m = _COLLECTIVE_RE.search(rhs)
        if not m:
            continue
        if m.group(2) is None and "-done" in rhs[:m.start() + 24]:
            continue  # the -done half of an async pair
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def _text_of(compiled) -> str:
    if isinstance(compiled, str):
        return compiled
    return compiled.as_text()


def assert_collectives(compiled, *, require: Iterable[str] = (),
                       require_any: Optional[Iterable[str]] = None,
                       forbid: Iterable[str] = (),
                       label: str = "step") -> Dict[str, int]:
    """Assert the collective mix of a compiled executable (or HLO text).

    ``require``: ops that must each appear at least once.
    ``require_any``: at least one op of this set must appear.
    ``forbid``: ops that must not appear at all.
    Returns the counts for further custom assertions.
    """
    counts = collective_counts(_text_of(compiled))
    missing = [op for op in require if counts.get(op, 0) == 0]
    if missing:
        raise CollectiveError(
            f"{label}: expected collective(s) {missing} absent from the "
            f"compiled HLO (found {counts or 'none'}) — the sharding "
            "spec did not produce the intended parallelism")
    if require_any is not None:
        opts = list(require_any)
        if not any(counts.get(op, 0) for op in opts):
            raise CollectiveError(
                f"{label}: none of {opts} present in the compiled HLO "
                f"(found {counts or 'none'}) — the sharding spec did "
                "not produce the intended parallelism")
    bad = {op: counts[op] for op in forbid if counts.get(op, 0)}
    if bad:
        raise CollectiveError(
            f"{label}: forbidden collective(s) {bad} present in the "
            "compiled HLO — under this plan they indicate accidental "
            "resharding (e.g. a full-parameter all-gather in pure DP)")
    return counts


# -- FSDP output lint -------------------------------------------------------
# After SPMD partitioning every shape in the module text is the PER-DEVICE
# local shape. A ZeRO-sharded parameter therefore never appears at its
# full global shape in the entry computation's *outputs*: transient
# full-shape all-gathers feeding a matmul are the plan working as
# intended, but a full-shape entry OUTPUT means the updated parameter (or
# its optimizer moment) was gathered into a replicated tensor and carried
# that way — "FSDP that isn't": it runs, the loss is finite, and every
# device holds (and re-gathers) the whole model.

_SHAPE_RE = re.compile(r"\b(?:[a-z]+\d*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")


def _parse_dims(text: str):
    """Every tensor shape in ``text`` as a tuple of ints (scalars = ())."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dims = m.group(1)
        out.append(tuple(int(d) for d in dims.split(",")) if dims else ())
    return out


def entry_output_shapes(hlo_text: str):
    """Per-device output shapes of the module's entry computation, from
    the ``ENTRY ... -> (...)`` signature."""
    for line in hlo_text.splitlines():
        ls = line.strip()
        if ls.startswith("ENTRY") and "->" in ls:
            return _parse_dims(ls.split("->", 1)[1])
    return []


def shaped_ops(hlo_text: str, op: str):
    """``(instruction_name, output_shape)`` for every instruction whose
    opcode matches ``op`` (async ``-start`` forms included)."""
    out = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(2)
        om = re.search(rf"\b{re.escape(op)}(-start)?\(", rhs)
        if not om:
            continue
        shapes = _parse_dims(rhs[:om.start()])
        out.append((m.group(1), shapes[-1] if shapes else ()))
    return out


def assert_fsdp_sharded(compiled, sharded_shapes,
                        replicated_shapes=(), *, local_shapes=(),
                        label: str = "fsdp step") -> None:
    """Assert the compiled FSDP step keeps its sharded parameters
    sharded end to end.

    ``sharded_shapes``: global shapes of params/moments the plan shards.
    ``replicated_shapes``: global shapes the plan deliberately
    replicates. ``local_shapes``: the per-device shard shapes the
    partitioned module legitimately carries. A sharded global shape
    that collides with either set is skipped — the text lint cannot
    tell two same-shaped tensors apart (e.g. a global ``(8,)`` bias vs
    the per-device half of a ``(16,)`` one).
    ``zoo_tpu.parallel.plans.fsdp_lint_shapes`` builds all three lists
    from a params pytree.

    Fails with :class:`CollectiveError` naming (a) the entry outputs
    that came back at full global shape and (b) the all-gather
    instructions that produce tensors of those shapes — together, the
    classic silent "FSDP that isn't" signature.
    """
    text = _text_of(compiled)
    skip = {tuple(s) for s in replicated_shapes} | \
        {tuple(s) for s in local_shapes}
    watch = {tuple(s) for s in sharded_shapes
             if tuple(s) and tuple(s) not in skip}
    if not watch:
        return
    outs = entry_output_shapes(text)
    bad_outs = [(i, s) for i, s in enumerate(outs) if s in watch]
    if not bad_outs:
        return
    gathers = [(name, s) for name, s in shaped_ops(text, "all-gather")
               if s in {s for _, s in bad_outs}]
    raise CollectiveError(
        f"{label}: {len(bad_outs)} entry output(s) carry FULL-shape "
        f"supposedly-FSDP-sharded tensors {sorted({s for _, s in bad_outs})} "
        f"(output indices {[i for i, _ in bad_outs]}); full-parameter "
        f"all-gather op(s): "
        f"{[n for n, _ in gathers] or '(produced without all-gather)'} "
        "— the step gathered ZeRO shards into replicated tensors "
        "(\"FSDP that isn't\"): per-device memory is back to the full "
        "model and every step re-gathers it")
