"""Compiled-HLO sharding-quality checks — moved.

PR 8 shipped this module as the fsdp-only lint; the checks now live in
:mod:`zoo_tpu.analysis.hlo`, generalized to plan-aware sharding
(megatron/tp entry layouts), donation, and host-transfer contracts.
This path keeps the original import surface working.
"""

from zoo_tpu.analysis.hlo import (  # noqa: F401
    CollectiveError,
    HloContractError,
    assert_collectives,
    assert_donated,
    assert_fsdp_sharded,
    assert_host_transfer,
    assert_plan_sharded,
    collective_counts,
    donation_findings,
    entry_layout,
    entry_output_shapes,
    host_transfer_findings,
    input_output_aliases,
    shaped_ops,
    sharding_findings,
)

__all__ = ["collective_counts", "assert_collectives", "CollectiveError",
           "entry_output_shapes", "shaped_ops", "assert_fsdp_sharded",
           "HloContractError", "assert_donated", "assert_host_transfer",
           "assert_plan_sharded", "donation_findings", "entry_layout",
           "host_transfer_findings", "input_output_aliases",
           "sharding_findings"]
