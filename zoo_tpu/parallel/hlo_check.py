"""Compiled-HLO sharding-quality checks.

A sharding regression that silently replicates everything still *runs*
and produces finite loss — the only place the difference is visible
before you pay for 8 chips is the compiled HLO's collective mix. These
helpers inspect the optimized module text of a compiled step and assert
the collectives the intended parallelism plan implies:

- pure DP: gradients all-reduce; **no** all-gather (a full-parameter
  all-gather under DP means params were accidentally sharded or the
  batch sharding leaked into the params);
- FSDP/ZeRO: all-gather (weights into the consuming op) **and** a grad
  reduction (reduce-scatter, or all-reduce on backends whose SPMD
  partitioner didn't pattern-match the scatter form);
- ring/sequence parallel: collective-permute (the ring hop).

Reference semantics being checked: the slice-wise parameter-server
update of ``Topology.scala:1204`` (reduce-scatter + apply + all-gather)
is what XLA's SPMD partitioner emits for a ZeRO-sharded step.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Optional

__all__ = ["collective_counts", "assert_collectives", "CollectiveError"]

# async pairs (all-reduce-start/-done) and channel-suffixed forms all
# reduce to the base op name; "-start" lines carry the operands so count
# only those plus the plain sync form
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start)?\b")


class CollectiveError(AssertionError):
    """A compiled step's collective mix contradicts the intended plan."""


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count collective instructions in optimized HLO module text.

    Counts instruction definitions (lines containing ``= <op>`` or the
    fused/async start forms), merging async ``-start`` with sync forms.
    """
    counts: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        # instruction lines look like  "%name = type op(...)"; skip
        # metadata/backend-config mentions by requiring the op token to
        # follow an "= " or " = " assignment on the line
        if "=" not in line:
            continue
        rhs = line.split("=", 1)[1]
        m = _COLLECTIVE_RE.search(rhs)
        if not m:
            continue
        if m.group(2) is None and "-done" in rhs[:m.start() + 24]:
            continue  # the -done half of an async pair
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def _text_of(compiled) -> str:
    if isinstance(compiled, str):
        return compiled
    return compiled.as_text()


def assert_collectives(compiled, *, require: Iterable[str] = (),
                       require_any: Optional[Iterable[str]] = None,
                       forbid: Iterable[str] = (),
                       label: str = "step") -> Dict[str, int]:
    """Assert the collective mix of a compiled executable (or HLO text).

    ``require``: ops that must each appear at least once.
    ``require_any``: at least one op of this set must appear.
    ``forbid``: ops that must not appear at all.
    Returns the counts for further custom assertions.
    """
    counts = collective_counts(_text_of(compiled))
    missing = [op for op in require if counts.get(op, 0) == 0]
    if missing:
        raise CollectiveError(
            f"{label}: expected collective(s) {missing} absent from the "
            f"compiled HLO (found {counts or 'none'}) — the sharding "
            "spec did not produce the intended parallelism")
    if require_any is not None:
        opts = list(require_any)
        if not any(counts.get(op, 0) for op in opts):
            raise CollectiveError(
                f"{label}: none of {opts} present in the compiled HLO "
                f"(found {counts or 'none'}) — the sharding spec did "
                "not produce the intended parallelism")
    bad = {op: counts[op] for op in forbid if counts.get(op, 0)}
    if bad:
        raise CollectiveError(
            f"{label}: forbidden collective(s) {bad} present in the "
            "compiled HLO — under this plan they indicate accidental "
            "resharding (e.g. a full-parameter all-gather in pure DP)")
    return counts
