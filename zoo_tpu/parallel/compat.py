"""jax API compatibility shims for the parallel subsystem.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` (with ``check_rep`` renamed ``check_vma``)
across the jax versions this repo meets in the wild; the baked-in
toolchain here ships 0.4.x where only the experimental spelling exists.
One shim keeps every call site on the new-style signature.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    """New-style ``jax.shard_map`` when available, else the experimental
    one. ``check`` maps to ``check_vma`` (new) / ``check_rep`` (old);
    default False — the replication checker predates several collective
    patterns used here (ring ppermute, pipeline stages) and rejects
    valid programs on old jax."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
        except TypeError:  # pre-rename top-level export
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)
    from jax.experimental.shard_map import shard_map as legacy
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check)
