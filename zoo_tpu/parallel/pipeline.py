"""Pipeline parallelism: GPipe-style microbatching over the ``pipe`` axis.

Net-new vs the reference (SURVEY §2.10 lists PP as absent upstream). The
TPU-native formulation: layer stages live on consecutive devices along
the mesh ``pipe`` axis (params sharded on their leading stage dim),
microbatches stream through a ``shard_map`` whose per-step hop is a
``ppermute`` — the canonical scaling-book pipeline, steady-state bubble
(S-1)/(M+S-1). Everything is a fixed-shape ``lax.scan``; autodiff flows
through ``ppermute``/``psum``, so ``jax.grad`` of a pipelined loss just
works (the backward pipeline is the transposed permute).

Composition: the microbatch row dim is sharded over the mesh's data
axes inside the ``shard_map`` (each data replica pipelines only its
batch shard; without the spec the batch would silently replicate and
every replica would redo the whole batch), so ``data×pipe`` meshes
behave like DP over pipelined workers. fsdp/tensor sharding applies
within a stage.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stages"]


def stack_stages(tree, n_stages: int):
    """Reshape each leaf's leading layer dim L into (S, L/S): a stack of
    per-stage parameter slices for :func:`pipeline_apply`."""
    def reshape(a):
        if a.ndim == 0 or a.shape[0] % n_stages:
            raise ValueError(
                f"leading dim {a.shape} must divide into {n_stages} "
                "stages")
        return a.reshape(n_stages, a.shape[0] // n_stages, *a.shape[1:])
    return jax.tree_util.tree_map(reshape, tree)


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh: Mesh,
                   n_microbatch: int, axis: str = "pipe"):
    """Apply ``n_stages`` chained stages to ``x`` with GPipe scheduling.

    ``stage_params``: pytree whose leaves lead with the stage dim S
    (see :func:`stack_stages`); ``stage_fn(params_slice, h) -> h`` runs
    ONE stage (e.g. scans its sub-blocks). ``x``: (B, ...) with
    B % n_microbatch == 0; activations keep x's shape through stages.
    Returns the final-stage output, replicated over the ``pipe`` axis.
    """
    n_stages = mesh.shape[axis]
    if n_stages <= 1:
        raise ValueError(f"mesh axis {axis!r} must be > 1 for a pipeline")
    B = x.shape[0]
    if B % n_microbatch:
        raise ValueError(f"batch {B} not divisible into {n_microbatch} "
                         "microbatches")
    mbs = x.reshape(n_microbatch, B // n_microbatch, *x.shape[1:])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    n_steps = n_microbatch + n_stages - 1

    def worker(params, mbs):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = lax.axis_index(axis)
        state = jnp.zeros_like(mbs[0])
        ys = jnp.zeros_like(mbs)
        # the carry becomes device-varying after the first ppermute; the
        # all-zero initial value must be marked varying up front or the
        # scan's carry types mismatch (shard_map vma check)
        try:
            state = lax.pcast(state, (axis,), to="varying")
            ys = lax.pcast(ys, (axis,), to="varying")
        except (AttributeError, TypeError):
            pass  # older jax: no vma tracking, nothing to mark

        def body(carry, t):
            state, ys = carry
            mb_t = lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_microbatch - 1), keepdims=False)
            h = jnp.where(idx == 0, mb_t, state)
            out = stage_fn(params, h)
            # the last stage completes microbatch j = t - (S-1)
            j = t - (n_stages - 1)
            updated = lax.dynamic_update_index_in_dim(
                ys, out, jnp.maximum(j, 0), 0)
            valid = (idx == n_stages - 1) & (j >= 0)
            ys = jnp.where(valid, updated, ys)
            state = lax.ppermute(out, axis, perm)
            return (state, ys), None

        (_, ys), _ = lax.scan(body, (state, ys), jnp.arange(n_steps))
        # only the last stage holds real outputs; psum replicates them
        # across the pipe group (others contribute zeros)
        return lax.psum(ys, axis)

    from zoo_tpu.parallel.mesh import data_axes

    specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    # microbatch ROW dim sharded over the data axes: each data replica
    # pipelines its own batch shard (P() here would replicate the batch
    # into every replica, which then redundantly computes all of it)
    daxes = data_axes(mesh)
    mb_spec = P(None, daxes if daxes else None)
    from zoo_tpu.parallel.compat import shard_map
    fn = shard_map(worker, mesh=mesh, in_specs=(specs, mb_spec),
                   out_specs=mb_spec)
    ys = fn(stage_params, mbs)
    return ys.reshape(B, *ys.shape[2:])
