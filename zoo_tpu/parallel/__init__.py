from zoo_tpu.parallel.mesh import (
    build_mesh,
    batch_sharding,
    replicated_sharding,
    fsdp_param_sharding,
    host_local_to_global,
    mesh_axes_from_env,
    publish_mesh_metrics,
    DEFAULT_AXES,
)
from zoo_tpu.parallel.pipeline import pipeline_apply, stack_stages

__all__ = [
    "build_mesh",
    "batch_sharding",
    "replicated_sharding",
    "fsdp_param_sharding",
    "host_local_to_global",
    "mesh_axes_from_env",
    "publish_mesh_metrics",
    "DEFAULT_AXES",
    "pipeline_apply",
    "stack_stages",
]
