"""Ring attention: context-parallel attention over the ``seq`` mesh axis.

Net-new subsystem (SURVEY §5.7 — the reference has NO long-context support;
its max sequence length is a plain hyperparameter on dense O(T²) attention).
This module scales sequence length across chips: Q/K/V are sharded over the
``seq`` axis; each device holds one block and K/V blocks rotate around the
ICI ring via ``lax.ppermute`` while a streaming (flash-style) softmax
accumulates — memory O(T/n per device), comm overlapped with compute by XLA.

Math: the standard online-softmax recurrence
    m' = max(m, rowmax(S));  l' = l·e^{m-m'} + rowsum(e^{S-m'})
    o' = o·e^{m-m'} + e^{S-m'}·V
applied once per incoming K/V block; causal masking is by global position
index, with the block origin tracked alongside the rotating K/V.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float] = None):
    """Runs INSIDE shard_map. q: (B, Hq, Tl, D); k/v: (B, Hkv, Tl, D)
    with Hq a multiple of Hkv (GQA): the ring carries the UNREPEATED
    kv blocks — the group broadcast happens locally per block, so ICI
    traffic and resident K/V stay O(Hkv), not O(Hq)."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    rep = h // k.shape[1]
    scale = scale if scale is not None else 1.0 / float(d) ** 0.5

    q_pos = my_idx * tl + jnp.arange(tl)

    def block(q, k_blk, v_blk, src_idx, m, l, o):
        if rep > 1:  # GQA: local broadcast only
            k_blk = jnp.repeat(k_blk, rep, axis=1)
            v_blk = jnp.repeat(v_blk, rep, axis=1)
        # bf16 matmul operands, f32 scores/statistics: the online-softmax
        # running max/denominator/accumulator stay f32 across ring rounds
        # (same numerics as the dense path's f32 softmax island and the
        # flash kernel's f32 scratch) — bf16 accumulation loses ~1e-2
        # relative mass over long rings
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src_idx * tl + jnp.arange(tl)
            allowed = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(allowed, s, jnp.finfo(s.dtype).min)
        # s is always finite (masking writes finfo.min, not -inf) and round
        # 0 visits the local block whose causal diagonal is always allowed,
        # so m is finite from round 0 on; exp(-inf - finite) = 0 covers the
        # initial carry.
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        # rotate first, then accumulate: round 0 handles the local block
        # outside the loop, so exactly n-1 rotations happen in total (no
        # wasted final permute whose result would be discarded)
        k_blk, v_blk, src_idx, m, l, o = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src_idx = jax.lax.ppermute(src_idx, axis_name, perm)
        m, l, o = block(q, k_blk, v_blk, src_idx, m, l, o)
        return k_blk, v_blk, src_idx, m, l, o

    m0 = jnp.full((b, h, tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    m, l, o = block(q, k, v, my_idx, m0, l0, o0)
    carry = (k, v, my_idx, m, l, o)
    carry = jax.lax.fori_loop(0, n - 1, body, carry)
    _, _, _, m, l, o = carry
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(mesh: Mesh, q, k, v, *, causal: bool = False,
                   seq_axis: str = "seq"):
    """Context-parallel attention of global (B, H, T, D) arrays sharded on
    the T axis over ``seq_axis``. Returns output with q's sharding.
    ``k``/``v`` may carry fewer (grouped/GQA) heads than ``q`` — the ring
    rotates the small kv blocks and broadcasts per group locally.

    The reference equivalent does not exist; use this wherever a
    transformer's sequence no longer fits one chip.
    """
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"q heads ({q.shape[1]}) must be a multiple of "
                         f"kv heads ({k.shape[1]})")
    spec = P(None, None, seq_axis, None)
    from zoo_tpu.parallel.compat import shard_map
    fn = shard_map(
        partial(_ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False)
    return fn(q, k, v)
