"""Parameter-placement plans: DP / ZeRO(fsdp) / TP over the global mesh.

Net-new vs the reference (SURVEY §2.10: the reference is data-parallel
only). The plan maps every parameter leaf to a NamedSharding:

- ``data`` axis: batch only — params replicated across it (classic DP; the
  reference's AllReduceParameter semantics).
- ``fsdp`` axis: ZeRO-3 — each param's largest divisible dim is sharded;
  XLA all-gathers weights into the consuming op and reduce-scatters grads,
  which is exactly the reference's slice-wise PS update
  (``wp-bigdl.md:146-160``) done by the compiler.
- ``model`` axis: tensor parallel for 2-D matmul weights — output-dim
  sharding (megatron "column") by default, falling back to input-dim
  ("row") when only that divides; XLA inserts the psum.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zoo_tpu.parallel.mesh import pick_divisible_dim, replicated_sharding


def leaf_sharding(mesh: Mesh, shape) -> NamedSharding:
    """Choose a sharding for one parameter tensor under the mesh's fsdp and
    model axes (both may be active at once for 2-D weights)."""
    fsdp = mesh.shape.get("fsdp", 1) if "fsdp" in mesh.axis_names else 1
    model = mesh.shape.get("model", 1) if "model" in mesh.axis_names else 1
    spec = [None] * len(shape)

    if model > 1 and len(shape) >= 2:
        if shape[-1] % model == 0:      # column parallel (output dim)
            spec[-1] = "model"
        elif shape[-2] % model == 0:    # row parallel (input dim)
            spec[-2] = "model"

    if fsdp > 1 and shape:
        taken = tuple(i for i, s in enumerate(spec) if s is not None)
        best = pick_divisible_dim(shape, fsdp, taken)
        if best is not None:
            spec[best] = "fsdp"

    if all(s is None for s in spec):
        return replicated_sharding(mesh)
    return NamedSharding(mesh, P(*spec))


def place_params(params, mesh: Optional[Mesh]):
    """Device-put a whole params pytree according to the plan."""
    if mesh is None:
        return params
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, leaf_sharding(mesh, np.shape(x))), params)
