"""Parameter-placement plans: DP / ZeRO(fsdp) / TP over the global mesh.

Net-new vs the reference (SURVEY §2.10: the reference is data-parallel
only). A *plan* maps every parameter leaf to a NamedSharding:

- ``data`` axis: batch only — params replicated across it (classic DP; the
  reference's AllReduceParameter semantics).
- ``fsdp`` axis: ZeRO-3 — each param's largest divisible dim is sharded;
  XLA all-gathers weights into the consuming op and reduce-scatters grads,
  which is exactly the reference's slice-wise PS update
  (``wp-bigdl.md:146-160``) done by the compiler.
- ``model`` axis: tensor parallel for 2-D matmul weights — output-dim
  sharding (megatron "column") by default, falling back to input-dim
  ("row") when only that divides; XLA inserts the psum.

Shape-only placement cannot tell a q-projection from an o-projection, so
this module also keeps a small **plan registry**: named rules keyed on
the *leaf name* that encode the megatron pairing for known model
families (llama / BERT-style transformer blocks: column-parallel into
the heads, row-parallel back out, so activations stay head-sharded
between the two matmuls with ONE psum per block half). ``plan="auto"``
(the default everywhere) applies the name rules where a leaf name
matches and falls back to :func:`leaf_sharding` elsewhere — models the
registry has never heard of keep today's behavior exactly.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zoo_tpu.parallel.mesh import pick_divisible_dim, replicated_sharding


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if axis in mesh.axis_names else 1


def leaf_sharding(mesh: Mesh, shape) -> NamedSharding:
    """Choose a sharding for one parameter tensor under the mesh's fsdp and
    model axes (both may be active at once for 2-D weights)."""
    fsdp = _axis_size(mesh, "fsdp")
    model = _axis_size(mesh, "model")
    spec = [None] * len(shape)

    if model > 1 and len(shape) >= 2:
        if shape[-1] % model == 0:      # column parallel (output dim)
            spec[-1] = "model"
        elif shape[-2] % model == 0:    # row parallel (input dim)
            spec[-2] = "model"

    if fsdp > 1 and shape:
        taken = tuple(i for i, s in enumerate(spec) if s is not None)
        best = pick_divisible_dim(shape, fsdp, taken)
        if best is not None:
            spec[best] = "fsdp"

    if all(s is None for s in spec):
        return replicated_sharding(mesh)
    return NamedSharding(mesh, P(*spec))


# -- plan registry ----------------------------------------------------------
# rule(mesh, name, shape) -> Optional[NamedSharding]; None = "not mine",
# fall through to the next rule / the shape-based default
_PLAN_REGISTRY: Dict[str, Callable] = {}

#: megatron pairing for transformer blocks: which matmul dim the
#: ``model`` axis splits, keyed by the leaf name conventions of
#: zoo_tpu's llama (wq/wk/wv/wo, w_gate/w_up/w_down) and the BERT/GPT
#: TransformerLayer (qkv_w/proj_w, fc1_w/fc2_w). -1 = column (output
#: dim, into the heads), -2 = row (input dim, out of the heads — XLA
#: psums the partial sums back), so activations stay head-sharded
#: between the pair with one psum per half-block.
_TP_COLUMN = ("wq", "wk", "wv", "w_gate", "w_up", "qkv_w", "fc1_w")
_TP_ROW = ("wo", "w_down", "proj_w", "fc2_w")


def register_plan(name: str):
    """Decorator: register a named sharding rule. The rule sees
    ``(mesh, leaf_name, shape)`` and returns a NamedSharding or None to
    decline the leaf."""
    def deco(fn):
        _PLAN_REGISTRY[name] = fn
        return fn
    return deco


def get_plan(name: str) -> Callable:
    if name not in _PLAN_REGISTRY:
        raise KeyError(
            f"unknown sharding plan {name!r}; registered: "
            f"{sorted(_PLAN_REGISTRY)}")
    return _PLAN_REGISTRY[name]


def _fill_fsdp(mesh: Mesh, shape, spec) -> NamedSharding:
    """Add the fsdp axis to whatever the TP rule chose, on the largest
    still-free divisible dim (never the leading stacked-blocks dim of a
    scanned stack when another dim divides — the scan unstacks it)."""
    fsdp = _axis_size(mesh, "fsdp")
    if fsdp > 1:
        taken = tuple(i for i, s in enumerate(spec) if s is not None)
        best = pick_divisible_dim(shape, fsdp, taken)
        if best is not None:
            spec[best] = "fsdp"
    if all(s is None for s in spec):
        return replicated_sharding(mesh)
    return NamedSharding(mesh, P(*spec))


@register_plan("transformer")
def _transformer_rule(mesh: Mesh, name: str,
                      shape) -> Optional[NamedSharding]:
    """Tensor-parallel rule for llama/BERT attention+MLP blocks: column
    into the head/ffn dim, row back out, norms/embeddings replicated
    across ``model`` (fsdp still shards them)."""
    model = _axis_size(mesh, "model")
    if model <= 1 or len(shape) < 2:
        return None
    leaf = name.rsplit("/", 1)[-1].rsplit(".", 1)[-1]
    spec = [None] * len(shape)
    if leaf in _TP_COLUMN and shape[-1] % model == 0:
        spec[-1] = "model"
    elif leaf in _TP_ROW and shape[-2] % model == 0:
        spec[-2] = "model"
    else:
        return None
    return _fill_fsdp(mesh, list(shape), spec)


@register_plan("default")
def _default_rule(mesh: Mesh, name: str, shape) -> NamedSharding:
    return leaf_sharding(mesh, shape)


#: the params key the keras seam stacks a homogeneous run of layers
#: under when ``plan="pipeline"`` (leaves gain a leading layer dim the
#: pipeline rule shards over the ``pipe`` axis)
PIPE_BODY_KEY = "__pipe_body__"

#: expert-stacked FFN leaf names (``ops/moe.py`` ``init_moe_params``
#: layout: E-leading stacks; the router stays replicated so every
#: device computes identical routing decisions)
_MOE_EXPERT_LEAVES = ("w_gate", "w_up", "w_down")


@register_plan("pipeline")
def _pipeline_rule(mesh: Mesh, name: str,
                   shape) -> Optional[NamedSharding]:
    """GPipe plan: stage-stacked body leaves (leading layer dim, under
    ``PIPE_BODY_KEY``) shard dim 0 over the ``pipe`` axis — contiguous
    stage-major ownership, exactly the ``stack_stages`` split the
    microbatch schedule consumes — with fsdp filling a remaining dim.
    Head/tail leaves decline and fall through to :func:`leaf_sharding`
    (replicated over ``pipe``, fsdp/model-sharded as usual)."""
    pipe = _axis_size(mesh, "pipe")
    if pipe <= 1 or not shape or PIPE_BODY_KEY not in name:
        return None
    if shape[0] % pipe != 0:
        return None
    spec = [None] * len(shape)
    spec[0] = "pipe"
    return _fill_fsdp(mesh, list(shape), spec)


@register_plan("moe")
def _moe_rule(mesh: Mesh, name: str, shape) -> Optional[NamedSharding]:
    """Expert-parallel plan: E-leading expert FFN stacks shard dim 0
    over the ``expert`` axis (each device holds its experts only; the
    capacity-bounded dispatch/combine collectives move tokens, not
    weights). Router and every non-expert leaf decline to
    :func:`leaf_sharding`."""
    ep = _axis_size(mesh, "expert")
    if ep <= 1 or len(shape) < 3:
        return None
    leaf = name.rsplit("/", 1)[-1].rsplit(".", 1)[-1]
    if leaf not in _MOE_EXPERT_LEAVES or shape[0] % ep != 0:
        return None
    spec = [None] * len(shape)
    spec[0] = "expert"
    return _fill_fsdp(mesh, list(shape), spec)


def named_leaf_sharding(mesh: Mesh, name: str, shape,
                        plan: str = "auto") -> NamedSharding:
    """Sharding for one named parameter leaf under ``plan``.

    ``"auto"`` tries the transformer name rule first (it declines
    unknown names), then the shape-based default — the resolution every
    fit/serving path uses unless a caller pins an explicit plan."""
    shape = tuple(shape)
    if plan == "auto":
        s = _transformer_rule(mesh, name, shape)
        return s if s is not None else leaf_sharding(mesh, shape)
    s = get_plan(plan)(mesh, name, shape)
    return s if s is not None else leaf_sharding(mesh, shape)


def _leaf_name(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def sharding_tree(params, mesh: Mesh, plan: str = "auto"):
    """The NamedSharding pytree the plan assigns to ``params`` — the
    explicit ``in_shardings``/``out_shardings`` input for a jitted step
    (no device_put happens here)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: named_leaf_sharding(
            mesh, _leaf_name(path), np.shape(x), plan), params)


def place_params(params, mesh: Optional[Mesh], plan: str = "auto"):
    """Device-put a whole params pytree according to the plan."""
    if mesh is None:
        return params
    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.device_put(
            x, named_leaf_sharding(mesh, _leaf_name(path),
                                   np.shape(x), plan)), params)


def shardings_of(tree, mesh: Mesh):
    """The concrete shardings carried by an already-placed pytree,
    normalized for use as explicit jit shardings: leaves that are not
    mesh-placed jax Arrays (host numpy, scalars, single-device arrays)
    map to the replicated sharding."""
    rep = replicated_sharding(mesh)

    def of(x):
        s = getattr(x, "sharding", None)
        if isinstance(s, NamedSharding) and s.mesh == mesh:
            return s
        return rep

    return jax.tree_util.tree_map(of, tree)


def ensure_placed(tree, mesh: Mesh):
    """Commit every leaf that is not already mesh-placed to the
    replicated sharding, so the tree's shardings and
    :func:`shardings_of` agree exactly (explicit in_shardings + donation
    want zero surprise reshards)."""
    rep = replicated_sharding(mesh)

    def fix(x):
        s = getattr(x, "sharding", None)
        if isinstance(s, NamedSharding) and s.mesh == mesh:
            return x
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(fix, tree)


def plan_lint_shapes(params, mesh: Mesh, plan: str = "auto"):
    """``(sharded, replicated, local)`` global/per-device shape lists
    for the compiled-HLO sharding lint
    (:func:`zoo_tpu.analysis.hlo.assert_plan_sharded`):
    ``sharded``/``replicated`` are the plan's global shapes, ``local``
    the per-device shard shapes the partitioned module legitimately
    carries (the lint skips collisions against both). Plan-agnostic —
    any leaf the plan shards on ANY mesh axis (fsdp ZeRO shards and
    megatron column/row shards alike) lands in ``sharded``."""
    sharded, replicated, local = [], [], []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(np.shape(leaf))
        sh = named_leaf_sharding(mesh, _leaf_name(path), shape, plan)
        if any(s is not None for s in sh.spec):
            sharded.append(shape)
            local.append(tuple(sh.shard_shape(shape)))
        else:
            replicated.append(shape)
    return sharded, replicated, local


#: back-compat name (PR 8 shipped the fsdp-only lint)
fsdp_lint_shapes = plan_lint_shapes


def estimate_collective_bytes(params, mesh: Mesh,
                              plan: str = "auto", *,
                              activation_bytes: int = 0,
                              n_microbatch: Optional[int] = None
                              ) -> Dict[str, int]:
    """Per-STEP collective traffic the plan implies, in bytes (the
    static estimate behind ``zoo_mesh_collective_bytes_total``; actual
    traffic is XLA's business, but the plan's lower bound is what
    capacity planning needs):

    - fsdp: every sharded param is all-gathered into its consuming op in
      forward AND backward (2x full bytes x (n-1)/n) and its grad
      reduce-scattered once (1x);
    - data: every replicated-trainable grad is all-reduced — ring cost
      2 x bytes x (n-1)/n;
    - pipe/expert: stage/expert-sharded leaves never move — their bytes
      drop to the per-device shard before the data-axis terms apply.
      The *activation* traffic those axes add instead (microbatch
      hand-offs over the GPipe ring; capacity-bounded MoE
      dispatch+combine) is estimated from ``activation_bytes`` — the
      full-batch activation bytes at the cut — when the caller can
      supply it (0 ⇒ those terms stay 0; the keys are always present).
    """
    fsdp = _axis_size(mesh, "fsdp")
    data = _axis_size(mesh, "data")
    pipe = _axis_size(mesh, "pipe")
    expert = _axis_size(mesh, "expert")
    out = {"all_gather": 0, "reduce_scatter": 0, "all_reduce": 0,
           "ppermute": 0, "all_to_all": 0}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        nbytes = int(np.prod(np.shape(leaf), dtype=np.int64)) * \
            np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        spec = named_leaf_sharding(mesh, _leaf_name(path),
                                   np.shape(leaf), plan).spec
        axes = [a for s in spec if s is not None
                for a in ((s,) if isinstance(s, str) else s)]
        if "pipe" in axes and pipe > 1:
            nbytes //= pipe
        if "expert" in axes and expert > 1:
            nbytes //= expert
        if "fsdp" in axes and fsdp > 1:
            frac = (fsdp - 1) / fsdp
            out["all_gather"] += int(2 * nbytes * frac)
            out["reduce_scatter"] += int(nbytes * frac)
        elif data > 1:
            out["all_reduce"] += int(2 * nbytes * (data - 1) / data)
    if activation_bytes:
        if pipe > 1:
            # fill/drain ring: (n_mb + S - 1) scan steps each ppermute
            # one microbatch activation, forward and backward
            n_mb = n_microbatch or pipe
            out["ppermute"] += int(
                2 * (n_mb + pipe - 1) * activation_bytes // max(n_mb, 1))
        if expert > 1:
            # dispatch + combine all_to_all, forward and backward
            out["all_to_all"] += int(4 * activation_bytes)
    return out
