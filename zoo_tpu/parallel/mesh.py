"""Device-mesh construction and sharding helpers.

This module is the rebuild's replacement for the reference's entire
"communication backend" zoo — BigDL's Spark-shuffle parameter-server
AllReduce (``Topology.scala:1204``, design ``docs/docs/wp-bigdl.md:140-160``),
torch DDP over gloo (``torch_runner.py:136-149``), TF MultiWorkerMirrored
(``tf_runner.py:280-313``), Horovod, MXNet kvstore and MPI. On TPU all of
those collapse into one thing: a ``jax.sharding.Mesh`` over the ICI torus,
with XLA emitting the collectives (psum / reduce-scatter / all-gather) from
sharding annotations. The reference's slice-wise PS update *is*
reduce-scatter + apply + all-gather, which is exactly what GSPMD emits for a
batch-sharded grad + optionally ZeRO-sharded optimizer state.

Axis-name convention (used by every sharding plan in zoo_tpu):

- ``data``  — data parallel (batch axis)
- ``fsdp``  — ZeRO-3 style parameter sharding (combines with ``data``)
- ``model`` — tensor parallel (net-new vs the reference, SURVEY §2.10)
- ``seq``   — sequence/context parallel (ring attention, net-new, SURVEY §5.7)
- ``expert`` — expert parallel (MoE token all-to-all, ``ops/moe.py``)
- ``pipe``  — pipeline parallel (GPipe microbatching,
  ``parallel/pipeline.py``)
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_AXES = ("data", "fsdp", "model", "seq", "expert", "pipe")


def mesh_axes_from_env() -> Optional[Dict[str, int]]:  # zoo-lint: config-parse
    """Mesh layout from the ``ZOO_MESH_<AXIS>`` env knobs (e.g.
    ``ZOO_MESH_FSDP=8``, ``ZOO_MESH_DATA=-1``) — the deployment-wide
    default ``init_orca_context`` applies when the caller passes no
    ``mesh_axes``. None when no knob is set (pure-DP default)."""
    axes: Dict[str, int] = {}
    for name in DEFAULT_AXES:
        v = os.environ.get(f"ZOO_MESH_{name.upper()}")
        if v:
            axes[name] = int(v)
    return axes or None


def publish_mesh_metrics(mesh: Mesh) -> None:
    """Export ``zoo_mesh_axis_size{axis=...}`` gauges for the live mesh
    (every axis, including size-1 ones — a scrape can tell "axis unused"
    from "axis missing")."""
    from zoo_tpu.obs.metrics import gauge
    g = gauge("zoo_mesh_axis_size",
              "Device-mesh axis sizes of the active runtime context",
              labels=("axis",))
    for name in mesh.axis_names:
        g.labels(axis=name).set(float(mesh.shape.get(name, 1)))


def _factor_shape(n_devices: int, axis_sizes: Dict[str, int],
                  axis_names: Sequence[str]) -> Tuple[int, ...]:
    """Resolve a full mesh shape: explicitly sized axes keep their size, at
    most one ``-1`` axis absorbs the remaining devices, others default 1."""
    shape = []
    wildcard = None
    used = 1
    for i, name in enumerate(axis_names):
        size = axis_sizes.get(name, 1)
        if size != -1 and size <= 0:
            raise ValueError(f"mesh axis {name!r} must have positive size "
                             f"or -1, got {size}")
        if size == -1:
            if wildcard is not None:
                raise ValueError("only one mesh axis may be -1")
            wildcard = i
            shape.append(1)
        else:
            shape.append(int(size))
            used *= int(size)
    if n_devices % used != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by requested axes {axis_sizes}")
    if wildcard is not None:
        shape[wildcard] = n_devices // used
    elif used != n_devices:
        raise ValueError(
            f"mesh axes {axis_sizes} cover {used} devices but {n_devices} present")
    return tuple(shape)


def build_mesh(devices=None,
               axis_sizes: Optional[Dict[str, int]] = None,
               axis_names: Sequence[str] = None) -> Mesh:
    """Build a :class:`jax.sharding.Mesh`.

    ``axis_sizes`` maps axis name -> size; one axis may be ``-1`` to absorb
    all remaining devices. Default: pure data parallel over every device —
    the reference's only strategy (SURVEY §2.10).

    ``jax.make_mesh`` is used when available so that axis order is optimized
    for ICI topology (data axis outermost rides the full torus).
    """
    devices = list(devices if devices is not None else jax.devices())
    axis_names = tuple(axis_names or DEFAULT_AXES)
    axis_sizes = dict(axis_sizes or {"data": -1})
    for name in axis_sizes:
        if name not in axis_names:
            raise ValueError(f"unknown mesh axis {name!r}; known: {axis_names}")
    shape = _factor_shape(len(devices), axis_sizes, axis_names)
    # Auto axis types = classic GSPMD propagation. jax>=0.9 make_mesh defaults
    # to Explicit sharding-in-types, which turns mixed dp/fsdp matmuls into
    # hard sharding-conflict errors; the framework owns its shardings and
    # wants the compiler to resolve intermediates.
    try:
        auto = (jax.sharding.AxisType.Auto,) * len(axis_names)
        return jax.make_mesh(shape, axis_names, devices=devices,
                             axis_types=auto)
    except (TypeError, AttributeError):
        arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, axis_names)


def data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Mesh axes the batch dimension is sharded over: data plus fsdp (ZeRO
    shards params over the same replicas that shard the batch)."""
    return tuple(a for a in ("data", "fsdp")
                 if a in mesh.axis_names and mesh.shape.get(a, 1) > 1)


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Sharding for a batch tensor: dim 0 split over (data, fsdp), rest
    replicated. This is the rebuild of BigDL's "each worker gets its RDD
    partition of the minibatch" (``wp-bigdl.md:131-145``)."""
    axes = data_axes(mesh)
    spec = [axes if axes else None] + [None] * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def stacked_batch_sharding(mesh: Mesh, ndim: int = 3) -> NamedSharding:
    """Sharding for a (k, batch, ...) superbatch feeding the scanned
    multi-step train loop: the scan dim is replicated, the batch dim is
    split over (data, fsdp)."""
    axes = data_axes(mesh)
    spec = [None, axes if axes else None] + [None] * (ndim - 2)
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pick_divisible_dim(shape: Tuple[int, ...], size: int,
                       taken=()) -> Optional[int]:
    """Largest dim of ``shape`` divisible by ``size`` and not in ``taken``
    (shared by the fsdp and combined fsdp×tp placement policies)."""
    best, best_size = None, 0
    for i, d in enumerate(shape):
        if i not in taken and d % size == 0 and d > best_size:
            best, best_size = i, d
    return best


def fsdp_param_sharding(mesh: Mesh, shape: Tuple[int, ...],
                        axis: str = "fsdp") -> NamedSharding:
    """ZeRO-3-style sharding for one parameter: split the largest divisible
    dimension over ``axis``; replicate if nothing divides. The reference's
    PS-style slice-wise update (``Topology.scala:1204``) sharded the *flat*
    parameter vector N ways; on TPU we shard per-tensor so XLA can fuse the
    all-gather into the consuming matmul."""
    size = mesh.shape.get(axis, 1)
    if size <= 1 or not shape:
        return replicated_sharding(mesh)
    best = pick_divisible_dim(shape, size)
    if best is None:
        return replicated_sharding(mesh)
    spec = [None] * len(shape)
    spec[best] = axis
    return NamedSharding(mesh, P(*spec))


def shard_params(params, mesh: Mesh, axis: str = "fsdp"):
    """Apply :func:`fsdp_param_sharding` across a whole pytree of params."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, fsdp_param_sharding(mesh, x.shape, axis)),
        params)


def host_local_to_global(mesh: Mesh, pspec: P, host_local: "np.ndarray"):
    """Assemble a globally-sharded jax.Array from per-process host data.

    Rebuild of the reference's hard part #1 (SURVEY §7.4): Spark partitions →
    executor-local BigDL tensors becomes per-host numpy shards →
    ``jax.make_array_from_process_local_data`` (no driver-side collect)."""
    if jax.process_count() == 1:
        return jax.device_put(host_local, NamedSharding(mesh, pspec))
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, pspec), host_local)


def validate_batch_size(batch_size: int, mesh: Mesh) -> int:
    """Preserve the reference's invariant ``batch_size % total_cores == 0``
    (``tf_dataset.py:188`` enforces it for TF1 feeds) as
    ``batch_size % (data axes size) == 0``."""
    denom = 1
    for a in data_axes(mesh):
        denom *= mesh.shape[a]
    if batch_size % denom != 0:
        raise ValueError(
            f"batch_size ({batch_size}) must be divisible by the number of "
            f"data-parallel shards ({denom})")
    return batch_size // denom
