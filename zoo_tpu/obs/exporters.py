"""Exporters: Prometheus HTTP endpoint, health probe, JSONL snapshots.

:class:`MetricsExporter` binds a loopback HTTP server (ephemeral port by
default) serving:

* ``GET /metrics``  — the registry in Prometheus text format 0.0.4;
* ``GET /healthz``  — liveness JSON; reuses the resilience layer's
  heartbeat file (``$ZOO_HEARTBEAT_FILE``): a stale heartbeat turns the
  probe 503 so an external supervisor sees a hung process exactly like
  ``ProcessMonitor`` does;
* ``GET /cluster``  — the last multihost-aggregated snapshot (JSON),
  populated by :func:`zoo_tpu.obs.aggregate.aggregate_cluster`.

Loopback by default for the same reason the serving door is: there is no
authentication on these endpoints; bind ``0.0.0.0`` only on a trusted
network. :func:`write_snapshot` appends one JSON line per call to a
snapshot file — the offline-analysis sibling of ``/metrics`` — and
:func:`start_snapshot_thread` does so periodically.

``validate_prometheus_text`` is the syntax checker behind
``scripts/check_metrics_export.py`` and the e2e tests: a small
line-grammar + histogram-consistency pass, not a full client.
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from zoo_tpu.common.knobs import value as _knob_value
from zoo_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = [
    "MetricsExporter", "write_snapshot", "start_snapshot_thread",
    "validate_prometheus_text",
]

logger = logging.getLogger(__name__)


def _heartbeat_health(stale_after: Optional[float]) -> Dict:  # zoo-lint: config-parse
    """Liveness verdict from the resilience heartbeat file, when one is
    configured; a process with no heartbeat file is healthy by virtue of
    answering at all. Imported lazily — resilience imports our metrics
    module, so a top-level import here would be a cycle."""
    from zoo_tpu.util.resilience import (
        HEARTBEAT_FILE_ENV,
        HEARTBEAT_INTERVAL_ENV,
        heartbeat_age,
    )

    path = os.environ.get(HEARTBEAT_FILE_ENV)
    if not path:
        return {"ok": True, "heartbeat": None}
    age = heartbeat_age(path)
    if stale_after is None:
        interval = float(os.environ.get(HEARTBEAT_INTERVAL_ENV, "1.0"))
        stale_after = max(10.0, 3.0 * interval)
    if age is None:  # not stamped yet: booting, not hung
        return {"ok": True, "heartbeat": None, "stale_after": stale_after}
    return {"ok": age <= stale_after, "heartbeat_age": age,
            "stale_after": stale_after}


class MetricsExporter:
    """``MetricsExporter().start()`` → scrape ``/metrics`` until
    ``stop()``. Serves the process-global registry unless another one is
    passed."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 healthz_stale_after: Optional[float] = None):
        self.registry = registry or get_registry()
        self._stale_after = healthz_stale_after
        self._cluster_view: Optional[Dict] = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = outer.registry.render_prometheus().encode()
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    try:
                        health = _heartbeat_health(outer._stale_after)
                    except Exception as e:  # noqa: BLE001 — probe, not crash
                        health = {"ok": False, "error": repr(e)}
                    # attach the SLO watchdog's last verdict (when one
                    # runs in this process) so group health probes see
                    # burn-rate breaches; a breach only turns the
                    # probe 503 under the explicit ZOO_SLO_FAIL_HEALTHZ
                    # opt-in — an SLO burn is an alert, not a death
                    try:
                        from zoo_tpu.obs.slo import last_status
                        slo = last_status()
                        if slo is not None:
                            health["slo"] = slo
                            if not slo.get("ok", True) and \
                                    _knob_value(
                                        "ZOO_SLO_FAIL_HEALTHZ"):
                                health["ok"] = False
                    except Exception:  # noqa: BLE001 — probe, not crash
                        pass
                    # disaggregation role (ZOO_LLM_ROLE — the knob a
                    # ReplicaGroup injects per seat): external probes
                    # and routing see the pool topology on the same
                    # door that says the seat is alive
                    try:
                        health["role"] = _knob_value("ZOO_LLM_ROLE")
                    except Exception:  # noqa: BLE001 — probe, not crash
                        pass
                    self._reply(200 if health.get("ok") else 503,
                                json.dumps(health).encode(),
                                "application/json")
                elif path == "/cluster":
                    view = outer._cluster_view
                    if view is None:
                        # default to this process's latest
                        # aggregate_cluster() result (lazy import:
                        # aggregate is a sibling that loads after us)
                        from zoo_tpu.obs.aggregate import last_cluster_view
                        view = last_cluster_view()
                    if view is None:
                        self._reply(404, b'{"error": "no cluster view '
                                    b'aggregated yet"}', "application/json")
                    else:
                        self._reply(200, json.dumps(view).encode(),
                                    "application/json")
                else:
                    self._reply(404, b"not found", "text/plain")

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are not stderr news
                logger.debug("exporter: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def set_cluster_view(self, merged: Dict):
        self._cluster_view = merged

    def start(self) -> "MetricsExporter":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="zoo-metrics-exporter")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


# ------------------------------------------------------- JSONL snapshots

def write_snapshot(path: str, registry: Optional[MetricsRegistry] = None,
                   extra: Optional[Dict] = None) -> Dict:
    """Append one JSON line — ``{ts, host, pid, metrics}`` — to ``path``
    and return the record. The offline sibling of ``/metrics``: grep-able
    history instead of a live scrape."""
    registry = registry or get_registry()
    rec = {"ts": time.time(), "host": socket.gethostname(),
           "pid": os.getpid(), "metrics": registry.snapshot()}
    if extra:
        rec["extra"] = extra
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec, separators=(",", ":"), default=str) + "\n")
    return rec


def start_snapshot_thread(path: str, interval: float = 30.0,
                          registry: Optional[MetricsRegistry] = None
                          ) -> threading.Thread:
    """Daemon thread appending a snapshot line every ``interval``
    seconds (dies with the process; the torn final line a kill can leave
    is skipped by any JSONL reader worth the name)."""

    def _run():
        while True:
            time.sleep(interval)
            try:
                write_snapshot(path, registry)
            except OSError as e:
                logger.warning("metrics snapshot failed: %s", e)

    t = threading.Thread(target=_run, daemon=True, name="zoo-obs-snapshot")
    t.start()
    return t


# ---------------------------------------------- text-format validation

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
    r' (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|\+Inf|NaN)$')
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) .*$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")
_HIST_SUFFIX = re.compile(r"_(bucket|sum|count)$")


def validate_prometheus_text(text: str) -> List[str]:
    """Syntax + histogram-consistency check of one exposition payload.
    Returns a list of human-readable problems (empty = valid)."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    buckets: Dict[str, List[float]] = {}  # series key -> cumulative counts
    counts: Dict[str, float] = {}
    if text and not text.endswith("\n"):
        errors.append("payload must end with a newline")
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            if _HELP_RE.match(line) or _TYPE_RE.match(line):
                m = _TYPE_RE.match(line)
                if m:
                    if m.group(1) in types:
                        errors.append(
                            f"line {i}: duplicate TYPE for {m.group(1)}")
                    types[m.group(1)] = m.group(2)
            elif line.startswith(("# HELP", "# TYPE")):
                errors.append(f"line {i}: malformed comment: {line!r}")
            continue  # other comments are legal free text
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {i}: malformed sample: {line!r}")
            continue
        name = m.group(1)
        base = _HIST_SUFFIX.sub("", name)
        family = name if name in types else base
        if family not in types:
            errors.append(f"line {i}: sample {name} has no # TYPE line")
            continue
        if types[family] == "histogram":
            labels = m.group(3) or ""
            key = base + "{" + \
                re.sub(r'le="[^"]*",?', "", labels).strip(",") + "}"
            val = float(m.group(4).replace("+Inf", "inf"))
            if name.endswith("_bucket"):
                le = re.search(r'le="([^"]*)"', labels)
                if not le:
                    errors.append(f"line {i}: histogram bucket without le")
                    continue
                buckets.setdefault(key, []).append(val)
                if le.group(1) == "+Inf":
                    counts["inf:" + key] = val
            elif name.endswith("_count"):
                counts["count:" + key] = val
    for key, series in buckets.items():
        if series != sorted(series):
            errors.append(
                f"{key}: bucket counts are not cumulative: {series}")
        if "inf:" + key not in counts:
            errors.append(f"{key}: histogram is missing the +Inf bucket")
        elif counts.get("count:" + key) != counts["inf:" + key]:
            errors.append(
                f"{key}: _count ({counts.get('count:' + key)}) != +Inf "
                f"bucket ({counts['inf:' + key]})")
    return errors
