# zoo-lint: jax-free
"""Crash flight recorder: a bounded ring of recent structured events
plus a postmortem bundle dump.

A serving replica that dies takes its last seconds of state — queue
depths, shed reasons, breaker flips, the streams it was decoding — to
the grave; the logs say *that* it died, never *what it was doing*.
This module is the black box:

* :func:`record_event` appends one structured event (``kind`` + fields)
  to a bounded per-process ring (``ZOO_OBS_FLIGHT_CAP``, default 512;
  0 disables). Producers across the stack feed it: engine tick
  summaries and stream lifecycles, admission sheds with their reason,
  circuit-breaker transitions, retry give-ups, SLO breach flips.
* When ``$ZOO_OBS_POSTMORTEM_DIR`` is set (a :class:`ReplicaGroup`
  sets it per replica), every event is ALSO appended to a
  ``flight-<pid>.jsonl`` spill file and flushed — so even a SIGKILL,
  which no handler can catch, leaves the ring's contents on disk up to
  the last flushed event; the supervisor packages that spill into a
  bundle afterwards (:meth:`ReplicaGroup.harvest_postmortems`).
* :func:`dump_bundle` writes the full postmortem — ring contents,
  metrics-registry snapshot, resolved ``ZOO_*`` config, the spans open
  at death, the last SLO verdict — as one atomic JSON file.
  :func:`install_crash_handlers` arms it on unhandled-exception exit
  and fatal-but-catchable signals (chaining whatever handler was
  already installed, e.g. the serving drain); the training guardian
  calls it on its rc-75 preemption exit, and the serving wire exposes
  it live as ``op=debug_dump``.

Stdlib + :mod:`zoo_tpu.obs` only — every layer may import this.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

from zoo_tpu.obs.metrics import counter, get_registry
from zoo_tpu.obs.tracing import active_spans, iter_jsonl

__all__ = [
    "FlightRecorder", "flight_recorder", "record_event",
    "dump_bundle", "install_crash_handlers", "read_spill",
    "FLIGHT_CAP_ENV", "POSTMORTEM_DIR_ENV",
]

logger = logging.getLogger(__name__)

FLIGHT_CAP_ENV = "ZOO_OBS_FLIGHT_CAP"
POSTMORTEM_DIR_ENV = "ZOO_OBS_POSTMORTEM_DIR"

_events_total = counter(
    "zoo_flight_events_total", "Events recorded into the flight ring, "
    "by kind", labels=("kind",))
_dumps_total = counter(
    "zoo_flight_dumps_total", "Postmortem bundles written, by reason",
    labels=("reason",))
_kind_children: Dict[str, object] = {}  # signal-safe label-child cache


def _config_snapshot() -> Dict[str, str]:
    """The resolved knob surface: every ZOO_* / JAX_* env var — what an
    operator needs to know about how the dead process was configured."""
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(("ZOO_", "JAX_", "XLA_"))}


class FlightRecorder:
    """One process's ring buffer + spill + bundle writer."""

    def __init__(self, capacity: Optional[int] = None,  # zoo-lint: config-parse
                 spill_dir: Optional[str] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(FLIGHT_CAP_ENV, "512"))
            except ValueError:
                capacity = 512
        self.capacity = max(0, capacity)
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity or 1)
        # REENTRANT: the crash handlers call record()/dump() from a
        # signal frame that may have interrupted this very thread
        # mid-record (the spill write is a wide window); a plain Lock
        # would deadlock the process right when the postmortem matters
        self._lock = threading.RLock()
        self._dump_seq = 0
        if spill_dir is None:
            spill_dir = os.environ.get(POSTMORTEM_DIR_ENV)
        self.spill_dir = spill_dir
        self.spill_path: Optional[str] = None
        self._spill_f = None
        if spill_dir and self.capacity:
            try:
                os.makedirs(spill_dir, exist_ok=True)
                self.spill_path = os.path.join(
                    spill_dir, f"flight-{os.getpid()}.jsonl")
                self._spill_f = open(self.spill_path, "a",
                                     encoding="utf-8")
            except OSError as e:  # a bad dir must not kill the worker
                logger.warning("flight spill disabled: %s", e)
                self._spill_f = None

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, kind: str, **fields):
        """Append one event (never raises; telemetry must not fail the
        instrumented operation)."""
        if not self.capacity:
            return
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
        # per-kind child cached OUTSIDE the metrics family lock: the
        # crash handler records from a signal frame, and re-entering
        # the family's plain Lock mid-interrupt would deadlock; a dict
        # get is atomic under the GIL (install_crash_handlers pre-warms
        # its kinds so the handler never takes the creation path)
        child = _kind_children.get(kind)
        if child is None:
            child = _kind_children.setdefault(
                kind, _events_total.labels(kind=kind))
        child.inc()
        f = self._spill_f
        if f is not None:
            try:
                # chaos seam: a full spill dir (disk-full gray failure)
                # must degrade to dropped spill lines, never kill the
                # instrumented operation — the storm arms this site
                # with an OSError to prove it (lazy import: this module
                # sits below resilience in the layering)
                from zoo_tpu.util.resilience import fault_point
                fault_point("flight.spill")
                with self._lock:
                    f.write(json.dumps(ev, separators=(",", ":"),
                                       default=str) + "\n")
                    f.flush()
            except (OSError, ValueError, ImportError) as e:
                # ImportError: interpreter teardown mid-record — the
                # spill line is lost, the process must not care
                logger.debug("flight spill write dropped: %s", e)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def snapshot_bundle(self, reason: str) -> Dict:
        """The postmortem payload: ring + metrics + config + open spans
        + last SLO verdict. Also what the wire ``op=debug_dump`` serves
        live."""
        try:
            metrics = get_registry().snapshot()
        except Exception as e:  # noqa: BLE001 — a bundle with no
            # metrics still beats no bundle
            metrics = {"error": repr(e)}
        try:
            from zoo_tpu.obs.slo import last_status
            slo = last_status()
        except Exception:  # noqa: BLE001
            slo = None
        return {"reason": reason, "ts": time.time(),
                "host": socket.gethostname(), "pid": os.getpid(),
                "argv": list(sys.argv),
                "ring": self.events(),
                "metrics": metrics,
                "config": _config_snapshot(),
                "active_spans": active_spans(),
                "slo": slo}

    def dump(self, reason: str,  # zoo-lint: config-parse
             dir_path: Optional[str] = None) -> Optional[str]:
        """Write the bundle atomically (tmp + rename) into ``dir_path``
        (default: the spill dir / ``$ZOO_OBS_POSTMORTEM_DIR``). Returns
        the path, or None when no directory is configured or the write
        failed — dumping is best-effort by contract: it runs on the way
        DOWN and must never mask the original failure."""
        dir_path = dir_path or self.spill_dir \
            or os.environ.get(POSTMORTEM_DIR_ENV)
        if not dir_path:
            return None
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        path = os.path.join(
            dir_path,
            f"postmortem-{socket.gethostname()}-{os.getpid()}-{seq}.json")
        try:
            os.makedirs(dir_path, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.snapshot_bundle(reason), f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001
            logger.warning("postmortem dump failed: %s", e)
            return None
        _dumps_total.labels(reason=reason).inc()
        return path

    def close(self):
        f, self._spill_f = self._spill_f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


# ------------------------------------------------------------ singleton

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-global recorder (created on first use from the env;
    :func:`reset_for_tests` rebuilds it after env changes)."""
    global _recorder
    r = _recorder
    if r is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            r = _recorder
    return r


def reset_for_tests():
    global _recorder, _handlers_installed
    with _recorder_lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None
    _handlers_installed = False


def record_event(kind: str, **fields):
    """Module-level shorthand every producer calls."""
    flight_recorder().record(kind, **fields)


def dump_bundle(reason: str,
                dir_path: Optional[str] = None) -> Optional[str]:
    return flight_recorder().dump(reason, dir_path)


def read_spill(path: str) -> List[dict]:
    """Parse one spill file, torn-tail tolerant (the producer may have
    been SIGKILLed mid-write)."""
    return list(iter_jsonl(path))


# -------------------------------------------------------- crash handlers

_handlers_installed = False


def install_crash_handlers(dir_path: Optional[str] = None,
                           signals: Optional[tuple] = None) -> bool:
    """Dump a bundle on the ways a process can die that CAN be caught:
    unhandled exception (``sys.excepthook``) and fatal-but-catchable
    signals (default SIGTERM + SIGINT). Existing handlers are CHAINED,
    not replaced — the serving drain handler still drains, the default
    Int/Term disposition still kills. SIGKILL cannot be caught by
    design; the continuously-flushed spill file is its postmortem.
    Main-thread only for the signal half; returns False elsewhere."""
    global _handlers_installed
    if _handlers_installed:
        return True
    rec = flight_recorder()
    if not rec.enabled:
        return False

    # pre-warm the label children the handlers will inc, so the signal
    # frame never takes the metrics family's (non-reentrant) creation
    # lock
    for k in ("fatal_signal", "unhandled_exception"):
        _kind_children.setdefault(k, _events_total.labels(kind=k))

    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            rec.record("unhandled_exception", error=repr(exc),
                       type=exc_type.__name__)
            rec.dump("unhandled_exception", dir_path)
        except Exception:  # noqa: BLE001 — never mask the real crash
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook

    import signal as _signal
    sigs = signals if signals is not None else (
        _signal.SIGTERM, _signal.SIGINT)
    try:
        for s in sigs:
            prev = _signal.getsignal(s)

            def handler(signum, frame, _prev=prev):
                try:
                    rec.record("fatal_signal", signum=int(signum))
                    rec.dump(f"signal-{int(signum)}", dir_path)
                except Exception:  # noqa: BLE001
                    pass
                if callable(_prev):
                    _prev(signum, frame)
                elif _prev == _signal.SIG_DFL:
                    # re-deliver with the default disposition so the
                    # exit code still says "killed by signal"
                    _signal.signal(signum, _signal.SIG_DFL)
                    _signal.raise_signal(signum)

            _signal.signal(s, handler)
    except ValueError:  # not the main thread: excepthook half only
        _handlers_installed = True
        return False
    _handlers_installed = True
    return True
