"""zoo_tpu.obs — unified telemetry: metrics, traces, exporters, cluster view.

The observability layer the reference platform never had in one place
(its instruments were a serving ``Timer``, optimizer wall-clock logs and
TensorBoard summaries, each blind to the others — SURVEY §5.1). Four
pieces:

* :mod:`zoo_tpu.obs.metrics`    — process-global registry of Counters /
  Gauges / Histograms with labels; near-zero-cost when disabled.
* :mod:`zoo_tpu.obs.tracing`    — ``span("name", **attrs)`` JSONL trace
  events with cross-host trace-id propagation over the JAX
  coordination service.
* :mod:`zoo_tpu.obs.exporters`  — loopback HTTP ``/metrics`` (Prometheus
  text) + ``/healthz`` (heartbeat freshness) + ``/cluster``; JSONL
  snapshot writer for offline analysis.
* :mod:`zoo_tpu.obs.aggregate`  — workers publish snapshots into the KV
  store; the merge sums counters, max/mins gauges, bucket-merges
  histograms into one cluster view.
* :mod:`zoo_tpu.obs.timeline`   — joins the fleet's per-process trace
  files by request trace id into one per-request timeline
  (Chrome-trace / text rendering; ``scripts/trace_timeline.py``).
* :mod:`zoo_tpu.obs.flight`     — crash flight recorder: bounded ring
  of recent structured events, continuously spilled to disk, dumped as
  a postmortem bundle on crash/preemption (and served live over the
  serving wire as ``op=debug_dump``).
* :mod:`zoo_tpu.obs.slo`        — SLO watchdog: rolling-window
  burn-rate evaluation over the registry (``zoo_slo_*`` gauges,
  breach events into the flight ring, ``/healthz`` attachment).

Every layer of the stack records here: retries/breakers/fault trips
(``util.resilience``), checkpoint save/restore/verify
(``orca.learn.ckpt``), shard-exchange fetches and rebalance barriers
(``orca.data.plane``), serving queue/batch/stage latency
(``serving.server``), per-phase step times (``common.profiling``),
worker restarts (``orca.bootstrap``) and the bench harness. See
``docs/observability.md``.
"""

# metrics must import first: the other submodules (and every instrumented
# zoo_tpu module) depend on it, and exporters lazily re-enters zoo_tpu
# code that imports us back
from zoo_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StatTimer,
    counter,
    gauge,
    get_registry,
    histogram,
)
from zoo_tpu.obs.tracing import (  # noqa: F401
    TRACE_DIR_ENV,
    ambient_trace_id,
    current_span_id,
    current_trace_id,
    emit_event,
    emit_span,
    new_trace_id,
    read_trace,
    set_trace_id,
    share_trace_id,
    span,
    stop_tracing,
    trace_context,
    trace_to,
    tracing_enabled,
)
from zoo_tpu.obs.exporters import (  # noqa: F401
    MetricsExporter,
    start_snapshot_thread,
    validate_prometheus_text,
    write_snapshot,
)
from zoo_tpu.obs.aggregate import (  # noqa: F401
    aggregate_cluster,
    last_cluster_view,
    merge_snapshots,
)
from zoo_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    dump_bundle,
    flight_recorder,
    install_crash_handlers,
    record_event,
)
from zoo_tpu.obs.slo import SLORule, SLOWatchdog  # noqa: F401
from zoo_tpu.obs.slo import last_status as slo_last_status  # noqa: F401
from zoo_tpu.obs.timeline import (  # noqa: F401
    build_timeline,
    merge_timeline,
    to_chrome_trace,
)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "StatTimer", "counter", "gauge", "get_registry", "histogram",
    "TRACE_DIR_ENV", "current_trace_id", "read_trace", "set_trace_id",
    "share_trace_id", "span", "stop_tracing", "trace_to", "tracing_enabled",
    "trace_context", "ambient_trace_id", "current_span_id",
    "new_trace_id", "emit_span", "emit_event",
    "MetricsExporter", "start_snapshot_thread", "validate_prometheus_text",
    "write_snapshot",
    "aggregate_cluster", "last_cluster_view", "merge_snapshots",
    "FlightRecorder", "flight_recorder", "record_event", "dump_bundle",
    "install_crash_handlers",
    "SLORule", "SLOWatchdog", "slo_last_status",
    "build_timeline", "merge_timeline", "to_chrome_trace",
]
