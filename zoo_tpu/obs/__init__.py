"""zoo_tpu.obs — unified telemetry: metrics, traces, exporters, cluster view.

The observability layer the reference platform never had in one place
(its instruments were a serving ``Timer``, optimizer wall-clock logs and
TensorBoard summaries, each blind to the others — SURVEY §5.1). Four
pieces:

* :mod:`zoo_tpu.obs.metrics`    — process-global registry of Counters /
  Gauges / Histograms with labels; near-zero-cost when disabled.
* :mod:`zoo_tpu.obs.tracing`    — ``span("name", **attrs)`` JSONL trace
  events with cross-host trace-id propagation over the JAX
  coordination service.
* :mod:`zoo_tpu.obs.exporters`  — loopback HTTP ``/metrics`` (Prometheus
  text) + ``/healthz`` (heartbeat freshness) + ``/cluster``; JSONL
  snapshot writer for offline analysis.
* :mod:`zoo_tpu.obs.aggregate`  — workers publish snapshots into the KV
  store; the merge sums counters, max/mins gauges, bucket-merges
  histograms into one cluster view.

Every layer of the stack records here: retries/breakers/fault trips
(``util.resilience``), checkpoint save/restore/verify
(``orca.learn.ckpt``), shard-exchange fetches and rebalance barriers
(``orca.data.plane``), serving queue/batch/stage latency
(``serving.server``), per-phase step times (``common.profiling``),
worker restarts (``orca.bootstrap``) and the bench harness. See
``docs/observability.md``.
"""

# metrics must import first: the other submodules (and every instrumented
# zoo_tpu module) depend on it, and exporters lazily re-enters zoo_tpu
# code that imports us back
from zoo_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StatTimer,
    counter,
    gauge,
    get_registry,
    histogram,
)
from zoo_tpu.obs.tracing import (  # noqa: F401
    TRACE_DIR_ENV,
    current_trace_id,
    read_trace,
    set_trace_id,
    share_trace_id,
    span,
    stop_tracing,
    trace_to,
    tracing_enabled,
)
from zoo_tpu.obs.exporters import (  # noqa: F401
    MetricsExporter,
    start_snapshot_thread,
    validate_prometheus_text,
    write_snapshot,
)
from zoo_tpu.obs.aggregate import (  # noqa: F401
    aggregate_cluster,
    last_cluster_view,
    merge_snapshots,
)

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "StatTimer", "counter", "gauge", "get_registry", "histogram",
    "TRACE_DIR_ENV", "current_trace_id", "read_trace", "set_trace_id",
    "share_trace_id", "span", "stop_tracing", "trace_to", "tracing_enabled",
    "MetricsExporter", "start_snapshot_thread", "validate_prometheus_text",
    "write_snapshot",
    "aggregate_cluster", "last_cluster_view", "merge_snapshots",
]
