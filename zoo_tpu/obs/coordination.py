"""The JAX coordination-service KV client, in one place.

Present whenever ``jax.distributed.initialize`` ran — exactly the
multi-process case. The obs control planes (trace-id propagation,
snapshot aggregation) and the data plane's ``rebalance_shards`` all ride
it rather than XLA device collectives: key-value ops work on every
backend (CPU included) and the blocking gets carry timeouts, so a dead
peer becomes a raised error instead of an eternal barrier. The import
reaches into ``jax._src`` — when that internal path moves, this is the
single spot to fix.
"""

from __future__ import annotations

__all__ = ["coordination_client"]


def coordination_client():
    """The KV client, or None outside an initialized multi-process
    cluster (callers raise their own, context-specific error)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None
