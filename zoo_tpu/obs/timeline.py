"""Per-request timeline reconstruction from per-process trace files.

Every process in the serving fleet writes its own
``trace-<host>-<pid>.jsonl`` (:mod:`zoo_tpu.obs.tracing`); a request's
trace id rides the wire (``trace`` field on the ZSXN frames,
``X-Zoo-Trace`` on the HTTP front end) and every hop stamps its spans
with it — client attempts, hedged duplicates, admission, prefill
chunks, engine lifecycle, sheds. This module joins those files back
into ONE timeline per request:

* :func:`load_events` — all trace events under a directory (or an
  explicit file list), torn/truncated lines skipped (a SIGKILLed
  replica tears its last line by design);
* :func:`group_traces` — events bucketed by trace id;
* :func:`build_timeline` — one trace's events folded into spans:
  ``B``/``E`` pairs matched by span id (a ``B`` whose ``E`` never came
  — the killed replica's in-flight work — survives as an OPEN span),
  ``X`` complete spans and ``I`` instants pass through;
* :func:`to_chrome_trace` — the same timeline as Chrome
  ``chrome://tracing`` / Perfetto JSON (one ``pid`` row per process,
  so a failover reads as the request hopping rows);
* :func:`render_text` — a terminal tree for quick triage.

``scripts/trace_timeline.py`` is the CLI over these.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence

from zoo_tpu.obs.tracing import iter_jsonl

__all__ = [
    "load_events", "group_traces", "build_timeline", "merge_timeline",
    "to_chrome_trace", "render_text",
]


def load_events(path: str, files: Optional[Sequence[str]] = None
                ) -> List[dict]:
    """Every trace event under directory ``path`` (or just ``files``),
    each annotated with its source ``file`` — the per-process identity
    that distinguishes a killed replica's spans from its successor's
    when the pid was recycled. Torn lines are skipped, never raised."""
    if files is None:
        if not os.path.isdir(path):
            return []
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("trace-") and f.endswith(".jsonl"))
    events: List[dict] = []
    for fpath in files:
        fname = os.path.basename(fpath)
        for ev in iter_jsonl(fpath):
            ev.setdefault("file", fname)
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events


def group_traces(events: Iterable[dict]) -> Dict[str, List[dict]]:
    """Events bucketed by trace id (events without one are dropped —
    they belong to no request)."""
    out: Dict[str, List[dict]] = {}
    for ev in events:
        tid = ev.get("trace")
        if tid:
            out.setdefault(tid, []).append(ev)
    return out


def build_timeline(events: Iterable[dict]) -> List[dict]:
    """Fold one trace's raw events into timeline entries, sorted by
    start time. Each entry::

        {"name", "ts", "dur_s" | None, "span", "parent", "pid",
         "file", "kind": "span" | "instant", "open": bool,
         "ok": bool, "attrs": {...}}

    ``open=True`` marks a ``B`` whose ``E`` never arrived — exactly
    what a mid-stream SIGKILL leaves behind; its partial work is still
    on the timeline instead of vanishing with the process."""
    begins: Dict[str, dict] = {}
    out: List[dict] = []
    for ev in events:
        kind = ev.get("ev")
        if kind == "B":
            sid = ev.get("span")
            entry = {"name": ev.get("name"), "ts": ev.get("ts", 0.0),
                     "dur_s": None, "span": sid,
                     "parent": ev.get("parent"), "pid": ev.get("pid"),
                     "file": ev.get("file"), "kind": "span",
                     "open": True, "ok": True,
                     "attrs": ev.get("attrs") or {}}
            out.append(entry)
            if sid:
                begins[sid] = entry
        elif kind == "E":
            entry = begins.pop(ev.get("span"), None)
            if entry is None:
                # E without its B (the B was the torn line): synthesize
                # a zero-width closed span so the end is still visible
                out.append({"name": ev.get("name"),
                            "ts": ev.get("ts", 0.0),
                            "dur_s": ev.get("dur_s", 0.0),
                            "span": ev.get("span"), "parent": None,
                            "pid": ev.get("pid"), "file": ev.get("file"),
                            "kind": "span", "open": False,
                            "ok": bool(ev.get("ok", True)), "attrs": {}})
            else:
                entry["dur_s"] = ev.get("dur_s")
                entry["open"] = False
                entry["ok"] = bool(ev.get("ok", True))
        elif kind == "X":
            out.append({"name": ev.get("name"), "ts": ev.get("ts", 0.0),
                        "dur_s": ev.get("dur_s", 0.0),
                        "span": ev.get("span"),
                        "parent": ev.get("parent"),
                        "pid": ev.get("pid"), "file": ev.get("file"),
                        "kind": "span", "open": False,
                        "ok": bool(ev.get("ok", True)),
                        "attrs": ev.get("attrs") or {}})
        elif kind == "I":
            out.append({"name": ev.get("name"), "ts": ev.get("ts", 0.0),
                        "dur_s": None, "span": ev.get("span"),
                        "parent": ev.get("parent"),
                        "pid": ev.get("pid"), "file": ev.get("file"),
                        "kind": "instant", "open": False, "ok": True,
                        "attrs": ev.get("attrs") or {}})
    out.sort(key=lambda e: e.get("ts", 0.0))
    return out


def merge_timeline(path: str, trace_id: str,
                   files: Optional[Sequence[str]] = None) -> List[dict]:
    """The one-call join: all processes' trace files under ``path`` →
    the single request timeline for ``trace_id``."""
    return build_timeline(
        group_traces(load_events(path, files=files)).get(trace_id, []))


def to_chrome_trace(timeline: List[dict],
                    trace_id: Optional[str] = None) -> dict:
    """A timeline as Chrome trace-event JSON (``chrome://tracing`` /
    Perfetto). Processes map to trace rows, so a failover mid-request
    reads as the request hopping from one row to another; OPEN spans
    (killed mid-work) render with an ``[open]`` suffix and whatever
    duration was observed before the process died (0 if unknown)."""
    events = []
    pids = {}
    for e in timeline:
        key = e.get("file") or e.get("pid") or 0
        pid = pids.setdefault(key, len(pids) + 1)
        ts_us = float(e.get("ts", 0.0)) * 1e6
        args = dict(e.get("attrs") or {})
        if e.get("span"):
            args["span"] = e["span"]
        if e.get("parent"):
            args["parent"] = e["parent"]
        if e["kind"] == "instant":
            events.append({"name": e["name"], "ph": "i", "s": "p",
                           "ts": ts_us, "pid": pid, "tid": 1,
                           "args": args})
            continue
        name = e["name"] + (" [open]" if e.get("open") else "")
        dur = e.get("dur_s")
        events.append({"name": name, "ph": "X", "ts": ts_us,
                       "dur": float(dur) * 1e6 if dur else 0.0,
                       "pid": pid, "tid": 1, "args": args})
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "args":
             {"name": str(key)}} for key, pid in pids.items()]
    out = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    if trace_id:
        out["otherData"] = {"trace_id": trace_id}
    return out


def render_text(timeline: List[dict]) -> str:
    """A flat, time-ordered terminal rendering (one line per entry,
    offset from the first event, duration, source process)."""
    if not timeline:
        return "(no events)"
    t0 = timeline[0].get("ts", 0.0)
    lines = []
    for e in timeline:
        off = (e.get("ts", 0.0) - t0) * 1e3
        if e["kind"] == "instant":
            dur = "      --  "
        elif e.get("open"):
            dur = "    OPEN  "
        else:
            dur = f"{(e.get('dur_s') or 0.0) * 1e3:8.2f}ms"
        src = str(e.get("file") or e.get("pid") or "?")
        attrs = ""
        if e.get("attrs"):
            attrs = "  " + json.dumps(e["attrs"], sort_keys=True,
                                      default=str)
        flag = "" if e.get("ok", True) else "  !err"
        lines.append(f"+{off:10.2f}ms  {dur}  {e['name']:<28s} "
                     f"[{src}]{flag}{attrs}")
    return "\n".join(lines)
