"""Multihost metric aggregation over the JAX coordination service.

Workers publish registry snapshots into the coordination-service KV
store (the control plane ``rebalance_shards`` already rides — it works
on every backend and its blocking gets carry timeouts, so a dead peer
becomes a raised error, not an eternal barrier). The merge semantics:

* counters   — **summed** across processes (total retries, total bytes);
* gauges     — **max and min** across processes (the cluster's worst and
  best queue depth / heartbeat age — a cluster-wide *sum* of a gauge is
  rarely meaningful);
* histograms — **bucket-merged** count-by-count (every registry uses the
  same fixed bucket bounds per family, so per-worker distributions add
  exactly; a bounds mismatch falls back to merging ``sum``/``count``).

:func:`aggregate_cluster` is collective — every process calls it, every
process gets the merged cluster view back (the coordinator's view is the
same dict; symmetric gather keeps the API barrier-shaped like
``_kv_allgather``). Single-process: merges just the local snapshot, so
the call sites need no topology branch.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from zoo_tpu.obs.coordination import coordination_client
from zoo_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = ["merge_snapshots", "aggregate_cluster", "last_cluster_view"]

logger = logging.getLogger(__name__)

_agg_generation = 0
_agg_gen_lock = threading.Lock()
_last_view: Optional[Dict] = None


def _series_key(entry: Dict) -> Tuple:
    return (entry["name"], tuple(sorted(entry.get("labels", {}).items())))


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """Merge per-process registry snapshots into one cluster view."""
    counters: Dict[Tuple, Dict] = {}
    gauges: Dict[Tuple, Dict] = {}
    hists: Dict[Tuple, Dict] = {}
    for snap in snaps:
        for e in snap.get("counters", []):
            k = _series_key(e)
            cur = counters.get(k)
            if cur is None:
                counters[k] = {"name": e["name"],
                               "labels": dict(e.get("labels", {})),
                               "value": float(e["value"])}
            else:
                cur["value"] += float(e["value"])
        for e in snap.get("gauges", []):
            k = _series_key(e)
            v = float(e["value"])
            cur = gauges.get(k)
            if cur is None:
                gauges[k] = {"name": e["name"],
                             "labels": dict(e.get("labels", {})),
                             "max": v, "min": v}
            else:
                cur["max"] = max(cur["max"], v)
                cur["min"] = min(cur["min"], v)
        for e in snap.get("histograms", []):
            k = _series_key(e)
            cur = hists.get(k)
            if cur is None:
                hists[k] = {"name": e["name"],
                            "labels": dict(e.get("labels", {})),
                            "bounds": list(e["bounds"]),
                            "counts": list(e["counts"]),
                            "sum": float(e["sum"]),
                            "count": int(e["count"])}
            elif cur["bounds"] == list(e["bounds"]):
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], e["counts"])]
                cur["sum"] += float(e["sum"])
                cur["count"] += int(e["count"])
            else:  # drifted bounds (version skew): totals still add
                logger.warning(
                    "histogram %s: bucket bounds differ across hosts; "
                    "merging sum/count only", e["name"])
                cur["sum"] += float(e["sum"])
                cur["count"] += int(e["count"])
    return {"processes": len(snaps),
            "counters": list(counters.values()),
            "gauges": list(gauges.values()),
            "histograms": list(hists.values())}


def aggregate_cluster(registry: Optional[MetricsRegistry] = None,
                      timeout_s: float = 30.0) -> Dict:
    """Collective: publish this process's snapshot, gather every peer's,
    return the merged cluster view (identical on all processes). A peer
    that never publishes times out within ``timeout_s`` on every waiter.

    The result is cached for :meth:`MetricsExporter.set_cluster_view` /
    :func:`last_cluster_view`, so a scrape of the coordinator's
    ``/cluster`` endpoint shows the latest aggregation."""
    import jax

    global _last_view
    registry = registry or get_registry()
    own = registry.snapshot()
    if jax.process_count() == 1:
        merged = merge_snapshots([own])
        _last_view = merged
        return merged
    client = coordination_client()
    if client is None:
        raise RuntimeError(
            "aggregate_cluster needs the JAX coordination service "
            "(jax.distributed.initialize) in multi-process mode")
    global _agg_generation
    with _agg_gen_lock:
        _agg_generation += 1
        gen = _agg_generation
    pid, nprocs = jax.process_index(), jax.process_count()
    prefix = f"zoo:obs:agg:{gen}:"
    client.key_value_set(prefix + str(pid),
                         json.dumps(own, separators=(",", ":")))
    deadline = time.monotonic() + timeout_s
    snaps = []
    for p in range(nprocs):
        ms = max(1000, int((deadline - time.monotonic()) * 1000))
        try:
            raw = client.blocking_key_value_get(prefix + str(p), ms)
        except Exception as e:
            raise TimeoutError(
                f"host {p} never published its metrics snapshot within "
                f"{timeout_s:.0f}s (crashed or hung peer): {e}") from e
        if isinstance(raw, bytes):
            raw = raw.decode()
        snaps.append(json.loads(raw))
    merged = merge_snapshots(snaps)
    _last_view = merged
    return merged


def last_cluster_view() -> Optional[Dict]:
    """The most recent :func:`aggregate_cluster` result in this process
    (None before the first aggregation)."""
    return _last_view
