"""SLO watchdog: rolling-window burn-rate evaluation over the metrics
registry.

The registry knows everything — ttft and inter-token histograms, the
per-outcome request counters, shed tallies, KV-block gauges, the
speculative accept counters — but nothing watches it; an operator
discovers a latency SLO burn from angry users. This watchdog closes
that loop in-process: every ``interval`` seconds it snapshots the
registry, keeps a rolling window of snapshots, evaluates each armed
rule over the WINDOW DELTA (so a breach reflects the last N seconds,
not the process's whole life), and publishes:

* ``zoo_slo_burn_rate{slo=...}`` — measured / objective for ceilings,
  objective / measured for floors; > 1 means the budget is burning;
* ``zoo_slo_breach{slo=...}``    — 0/1, with hysteresis-free edge
  events recorded into the flight ring (``slo_breach`` /
  ``slo_clear``) so a postmortem bundle shows when the burn started;
* :func:`last_status` — the machine-readable verdict the exporter's
  ``/healthz`` attaches (so :meth:`ReplicaGroup.healthz` sees it with
  no extra wiring) and the PR 9 ``PromotionGate`` vetoes promotions
  on.

Built-in rules arm from the ``ZOO_SLO_*`` env (unset/0 = rule off —
the watchdog costs nothing it wasn't asked for):

=============================  ===========================================
``ZOO_SLO_TTFT_P99_S``         p99 time-to-first-token ceiling (seconds)
``ZOO_SLO_INTER_TOKEN_P99_S``  p99 inter-token gap ceiling (seconds)
``ZOO_SLO_ERROR_RATE``         served-request error-rate ceiling (0..1)
``ZOO_SLO_SHED_RATE``          admission shed-rate ceiling (0..1)
``ZOO_SLO_TENANT_SHED_RATE``   PER-TENANT shed-rate ceiling (0..1) —
                               publishes ``zoo_tenant_burn_rate``
``ZOO_SLO_KV_UTIL``            KV-block pool utilization ceiling (0..1)
``ZOO_SLO_SPEC_ACCEPT_FLOOR``  speculative accept-rate FLOOR (0..1)
``ZOO_SLO_WINDOW_S``           rolling window (default 60 s)
``ZOO_SLO_INTERVAL_S``         evaluation period (default 5 s)
``ZOO_SLO_FAIL_HEALTHZ``       1 = a breach turns ``/healthz`` 503
=============================  ===========================================

Quantiles are bucket-bound estimates from the histogram's cumulative
counts over the window — the same numbers a Prometheus
``histogram_quantile`` would report, computed locally.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from zoo_tpu.obs.flight import record_event
from zoo_tpu.obs.metrics import MetricsRegistry, gauge, get_registry
from zoo_tpu.util import resilience as _res  # env_float only; no cycle:
#                                resilience imports obs.metrics, not us

__all__ = [
    "SLORule", "SLOWatchdog", "default_rules", "last_status",
    "quantile_from_counts",
]

logger = logging.getLogger(__name__)

_burn = gauge(
    "zoo_slo_burn_rate",
    "Measured / objective for ceiling SLOs (objective / measured for "
    "floors) over the rolling window; > 1 = the error budget is "
    "burning", labels=("slo",))
_breach = gauge(
    "zoo_slo_breach", "1 while the SLO is in breach over the rolling "
    "window, else 0", labels=("slo",))
_evals = gauge(
    "zoo_slo_rules_armed", "SLO rules the watchdog is evaluating")
# multi-tenant QoS (docs/multitenancy.md): tenant-scoped burn rates,
# one series per tenant seen in the window — a greedy tenant burning
# its own shed budget shows up HERE without moving the fleet gauge
_tenant_burn = gauge(
    "zoo_tenant_burn_rate",
    "Per-tenant burn rate (measured / objective) for tenant-scoped "
    "SLOs over the rolling window; > 1 = that tenant's error budget "
    "is burning", labels=("tenant", "slo"))


def quantile_from_counts(bounds: List[float], counts: List[int],
                         q: float) -> Optional[float]:
    """Bucket-bound quantile estimate from a cumulative-able histogram
    delta: the upper edge of the bucket the q-th observation falls in
    (+Inf tail reports the last finite bound — a conservative floor).
    None when the window saw no observations."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, n in enumerate(counts):
        cum += n
        if cum >= rank:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class SLORule:
    """One objective: ``fn(window_delta, latest_snapshot) -> measured``
    (None = no data this window) against ``objective``. ``floor=True``
    breaches when measured < objective instead of >."""

    def __init__(self, name: str, fn: Callable, objective: float,
                 floor: bool = False):
        self.name = name
        self.fn = fn
        self.objective = float(objective)
        self.floor = floor

    def evaluate(self, delta: Dict, latest: Dict
                 ) -> Tuple[Optional[float], Optional[float]]:
        """(measured, burn_rate); (None, None) with no data."""
        measured = self.fn(delta, latest)
        if measured is None:
            return None, None
        if self.floor:
            burn = (self.objective / measured) if measured > 0 \
                else float("inf")
        else:
            burn = measured / self.objective if self.objective > 0 \
                else float("inf")
        return measured, burn


# ------------------------------------------------- snapshot arithmetic

def _series(snapshot: Dict, kind: str, name: str) -> List[Dict]:
    return [e for e in snapshot.get(kind, ()) if e.get("name") == name]


def _counter_sum(snapshot: Dict, name: str, **labels) -> float:
    return sum(e.get("value", 0.0) for e in
               _series(snapshot, "counters", name)
               if all(e.get("labels", {}).get(k) == v
                      for k, v in labels.items()))


def _gauge_sum(snapshot: Dict, name: str) -> Optional[float]:
    vals = [e.get("value", 0.0) for e in _series(snapshot, "gauges",
                                                 name)]
    return sum(vals) if vals else None


def _hist_counts(snapshot: Dict, name: str
                 ) -> Optional[Tuple[List[float], List[int]]]:
    entries = _series(snapshot, "histograms", name)
    if not entries:
        return None
    bounds = entries[0]["bounds"]
    counts = [0] * (len(bounds) + 1)
    for e in entries:  # label children merge (same fixed bounds)
        if e.get("bounds") == bounds:
            for i, n in enumerate(e.get("counts", ())):
                counts[i] += n
    return bounds, counts


def _window_delta(old: Dict, new: Dict) -> Dict:
    """new - old for counters and histogram counts (gauges ride the
    latest snapshot, not the delta)."""
    out = {"counters": [], "histograms": []}
    old_c = {(e["name"], tuple(sorted(e.get("labels", {}).items()))):
             e.get("value", 0.0) for e in old.get("counters", ())}
    for e in new.get("counters", ()):
        key = (e["name"], tuple(sorted(e.get("labels", {}).items())))
        out["counters"].append(
            {"name": e["name"], "labels": e.get("labels", {}),
             "value": max(0.0, e.get("value", 0.0) - old_c.get(key,
                                                               0.0))})
    old_h = {(e["name"], tuple(sorted(e.get("labels", {}).items()))):
             e.get("counts", []) for e in old.get("histograms", ())}
    for e in new.get("histograms", ()):
        key = (e["name"], tuple(sorted(e.get("labels", {}).items())))
        prev = old_h.get(key, [0] * len(e.get("counts", [])))
        counts = [max(0, a - b) for a, b in
                  zip(e.get("counts", []), prev)] \
            if len(prev) == len(e.get("counts", [])) \
            else list(e.get("counts", []))
        out["histograms"].append(
            {"name": e["name"], "labels": e.get("labels", {}),
             "bounds": e.get("bounds", []), "counts": counts})
    return out


# --------------------------------------------------------- built-ins

def _p99_rule(hist_name: str):
    def fn(delta: Dict, latest: Dict) -> Optional[float]:
        hc = _hist_counts(delta, hist_name)
        if hc is None:
            return None
        return quantile_from_counts(hc[0], hc[1], 0.99)
    return fn


def _error_rate(delta: Dict, latest: Dict) -> Optional[float]:
    errors = _counter_sum(delta, "zoo_serving_requests_total",
                          outcome="error") + \
        _counter_sum(delta, "zoo_llm_streams_total", outcome="error")
    total = _counter_sum(delta, "zoo_serving_requests_total") + \
        _counter_sum(delta, "zoo_llm_streams_total")
    return errors / total if total > 0 else None


def _shed_rate(delta: Dict, latest: Dict) -> Optional[float]:
    sheds = _counter_sum(delta, "zoo_serve_shed_total")
    total = _counter_sum(delta, "zoo_serving_requests_total")
    return sheds / total if total > 0 else None


def _kv_util(delta: Dict, latest: Dict) -> Optional[float]:
    used = _gauge_sum(latest, "zoo_llm_kv_blocks_used")
    free = _gauge_sum(latest, "zoo_llm_kv_blocks_free")
    if used is None or free is None or used + free <= 0:
        return None
    return used / (used + free)


def _spec_accept(delta: Dict, latest: Dict) -> Optional[float]:
    proposed = _counter_sum(delta, "zoo_llm_spec_proposed_tokens_total")
    if proposed <= 0:
        return None  # nothing drafted this window: no verdict
    return _counter_sum(
        delta, "zoo_llm_spec_accepted_tokens_total") / proposed


def default_rules() -> List[SLORule]:
    """Rules armed by the ``ZOO_SLO_*`` env (unset/<=0 = off)."""
    rules: List[SLORule] = []
    specs = (
        ("ttft_p99", "ZOO_SLO_TTFT_P99_S",
         _p99_rule("zoo_llm_ttft_seconds"), False),
        ("inter_token_p99", "ZOO_SLO_INTER_TOKEN_P99_S",
         _p99_rule("zoo_llm_inter_token_seconds"), False),
        ("error_rate", "ZOO_SLO_ERROR_RATE", _error_rate, False),
        ("shed_rate", "ZOO_SLO_SHED_RATE", _shed_rate, False),
        ("kv_util", "ZOO_SLO_KV_UTIL", _kv_util, False),
        ("spec_accept", "ZOO_SLO_SPEC_ACCEPT_FLOOR", _spec_accept,
         True),
    )
    for name, env, fn, floor in specs:
        objective = _res.env_float(env, 0.0)
        if objective > 0:
            rules.append(SLORule(name, fn, objective, floor=floor))
    return rules


# ----------------------------------------------------------- watchdog

_last_status: Optional[Dict] = None
_status_lock = threading.Lock()


def last_status() -> Optional[Dict]:
    """The most recent watchdog verdict in this process (None before
    any evaluation) — what ``/healthz`` attaches and the promotion
    gate consults."""
    with _status_lock:
        return _last_status


def _set_status(status: Optional[Dict]):
    global _last_status
    with _status_lock:
        _last_status = status


class SLOWatchdog:
    """``SLOWatchdog().start()`` evaluates until ``stop()`` (a daemon
    thread; also drivable synchronously via :meth:`evaluate` for
    tests). With no armed rules :meth:`start` is a no-op returning
    self, so callers can arm it unconditionally."""

    def __init__(self, rules: Optional[List[SLORule]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 window_s: Optional[float] = None,
                 interval_s: Optional[float] = None):
        self.rules = default_rules() if rules is None else list(rules)
        self.registry = registry or get_registry()
        self.window_s = window_s if window_s is not None else \
            _res.env_float("ZOO_SLO_WINDOW_S", 60.0)
        self.interval_s = interval_s if interval_s is not None else \
            _res.env_float("ZOO_SLO_INTERVAL_S", 5.0)
        self._snaps: "collections.deque" = collections.deque()
        self._breached: Dict[str, bool] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # tenant-scoped shed-rate ceiling (docs/multitenancy.md):
        # evaluated per tenant over the window, published as
        # zoo_tenant_burn_rate{tenant, slo="shed_rate"}
        self.tenant_shed_objective = _res.env_float(
            "ZOO_SLO_TENANT_SHED_RATE", 0.0)
        _evals.set(len(self.rules) +
                   (1 if self.tenant_shed_objective > 0 else 0))

    def evaluate(self) -> Dict:
        """One evaluation pass: snapshot, window-delta, every rule.
        Returns (and publishes) the status dict."""
        now = time.monotonic()
        snap = self.registry.snapshot()
        self._snaps.append((now, snap))
        while len(self._snaps) > 2 and \
                now - self._snaps[0][0] > self.window_s:
            self._snaps.popleft()
        oldest = self._snaps[0][1]
        delta = _window_delta(oldest, snap)
        status: Dict = {"ok": True, "breaches": [], "rules": {},
                        "window_s": round(now - self._snaps[0][0], 3),
                        "ts": time.time()}
        for rule in self.rules:
            measured, burn = rule.evaluate(delta, snap)
            entry: Dict = {"objective": rule.objective,
                           "floor": rule.floor}
            breached = False
            if measured is not None:
                entry["measured"] = measured
                entry["burn_rate"] = burn
                breached = burn is not None and burn > 1.0
                _burn.labels(slo=rule.name).set(
                    burn if burn != float("inf") else 1e9)
            entry["breached"] = breached
            status["rules"][rule.name] = entry
            _breach.labels(slo=rule.name).set(1.0 if breached else 0.0)
            if breached:
                status["breaches"].append(rule.name)
                status["ok"] = False
            was = self._breached.get(rule.name, False)
            if breached != was:
                self._breached[rule.name] = breached
                record_event("slo_breach" if breached else "slo_clear",
                             slo=rule.name, measured=measured,
                             objective=rule.objective)
                (logger.warning if breached else logger.info)(
                    "SLO %s %s: measured=%r objective=%r",
                    rule.name, "BREACHED" if breached else "cleared",
                    measured, rule.objective)
        if self.tenant_shed_objective > 0:
            self._evaluate_tenants(delta, status)
        _set_status(status)
        return status

    def _evaluate_tenants(self, delta: Dict, status: Dict):
        """Per-tenant shed-rate burn over the window delta: one
        verdict per tenant that admitted or shed anything, with the
        same breach edge events (``slo_breach`` with the tenant-keyed
        rule name) the fleet rules record."""
        status["tenants"] = {}
        tenants = sorted({
            e.get("labels", {}).get("tenant")
            for e in delta.get("counters", ())
            if e.get("name") in ("zoo_tenant_shed_total",
                                 "zoo_tenant_admitted_total")
            and e.get("labels", {}).get("tenant")})
        for t in tenants:
            sheds = _counter_sum(delta, "zoo_tenant_shed_total",
                                 tenant=t)
            admitted = _counter_sum(delta, "zoo_tenant_admitted_total",
                                    tenant=t)
            total = sheds + admitted
            if total <= 0:
                continue
            measured = sheds / total
            burn = measured / self.tenant_shed_objective
            _tenant_burn.labels(tenant=t, slo="shed_rate").set(burn)
            breached = burn > 1.0
            status["tenants"][t] = {
                "shed_rate": measured, "burn_rate": burn,
                "objective": self.tenant_shed_objective,
                "breached": breached}
            name = f"tenant_shed_rate[{t}]"
            if breached:
                status["breaches"].append(name)
                status["ok"] = False
            was = self._breached.get(name, False)
            if breached != was:
                self._breached[name] = breached
                record_event("slo_breach" if breached else "slo_clear",
                             slo=name, measured=measured,
                             objective=self.tenant_shed_objective)
                (logger.warning if breached else logger.info)(
                    "SLO %s %s: measured=%r objective=%r",
                    name, "BREACHED" if breached else "cleared",
                    measured, self.tenant_shed_objective)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as e:  # noqa: BLE001 — the watchdog must
                # outlive a malformed snapshot; log and keep watching
                logger.warning("slo evaluation failed: %s", e)

    def start(self) -> "SLOWatchdog":
        if (not self.rules and self.tenant_shed_objective <= 0) \
                or self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="zoo-slo-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
