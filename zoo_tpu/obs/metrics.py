# zoo-lint: jax-free
"""Process-global metrics registry: Counter / Gauge / Histogram.

The reference platform scattered its instruments — a per-stage ``Timer``
in Cluster Serving (``serving/engine/Timer.scala:22-60``), per-iteration
wall-clock logs in DistriOptimizer, TensorBoard summaries — with no
cluster-wide view. This module is the single sink they all feed instead:
a named-metric registry in the Prometheus data model (monotonic counters,
set-anywhere gauges, fixed-exponential-bucket histograms, label support),
rendered by :mod:`zoo_tpu.obs.exporters` and merged across hosts by
:mod:`zoo_tpu.obs.aggregate`.

Hot-path contract: recording into a metric of a *disabled* registry is a
single attribute check and an early return (micro-benchmarked under 1 µs
in ``tests/test_obs.py``); an enabled record is one short critical
section. Instrumented modules create their metric objects at import time
and cache label children, so the steady state never touches the registry
dict. This module depends on the stdlib only — every layer of the stack
(resilience, serving, checkpointing, the data plane) imports it, so it
must sit below all of them.
"""

from __future__ import annotations

import bisect
import contextlib
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "StatTimer",
    "MetricsRegistry", "DEFAULT_BUCKETS",
    "get_registry", "counter", "gauge", "histogram",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# exponential latency buckets: 100 µs .. ~105 s, ratio 2 (the fixed-bucket
# shape lets per-worker histograms bucket-merge exactly in the aggregator)
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (2 ** i) for i in range(21))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    # Prometheus text format: integers without a trailing .0 keep the
    # output stable for counters; everything else uses repr (full
    # precision, parses back exactly)
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """One (family, label-values) time series."""

    __slots__ = ("_registry", "_lock", "labels_kv")

    def __init__(self, registry: "MetricsRegistry",
                 labels_kv: Tuple[Tuple[str, str], ...]):
        self._registry = registry
        self._lock = threading.Lock()
        self.labels_kv = labels_kv


class Counter(_Metric):
    """Monotonic counter. ``inc()`` is the hot path: one enabled-check,
    one lock, one add."""

    __slots__ = ("_value",)

    def __init__(self, registry, labels_kv):
        super().__init__(registry, labels_kv)
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if not self._registry._enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, open breakers, bench axes)."""

    __slots__ = ("_value",)

    def __init__(self, registry, labels_kv):
        super().__init__(registry, labels_kv)
        self._value = 0.0

    def set(self, value: float):
        if not self._registry._enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        if not self._registry._enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket distribution (Prometheus cumulative-``le`` layout).

    ``bounds`` are the inclusive upper edges; one implicit ``+Inf``
    bucket catches the tail. Buckets are fixed at family creation so the
    multihost aggregator can merge per-worker histograms count-by-count.
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count")

    def __init__(self, registry, labels_kv,
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, labels_kv)
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        if not self._registry._enabled:
            return
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def snapshot_value(self) -> Dict:
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}


class StatTimer:
    """Running avg/max/min stats for one named stage or phase.

    The single class behind both of the former copies — serving's
    ``StageTimer`` and profiling's ``PhaseTimer`` (reference
    ``Timer.scala:22-60``); both old import paths re-export it. Pass
    ``histogram=`` to mirror every ``record`` into a registry
    :class:`Histogram` child, which is how the serving stage timers and
    the step profiler publish into the shared registry without changing
    their local-stats API.
    """

    __slots__ = ("n", "total", "max", "min", "_hist")

    def __init__(self, histogram: Optional[Histogram] = None):
        self.n = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self._hist = histogram

    def record(self, dt: float):
        self.n += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)
        if self._hist is not None:
            self._hist.observe(dt)

    def stats(self) -> Dict[str, float]:
        return {"count": self.n,
                "avg_ms": 1000 * self.total / max(self.n, 1),
                "max_ms": 1000 * self.max,
                "min_ms": 0.0 if self.n == 0 else 1000 * self.min}


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named metric family: type + help + one child per label-value
    combination (the no-label family has exactly one child, keyed ())."""

    def __init__(self, registry, name: str, kind: str, help: str,
                 label_names: Tuple[str, ...],
                 buckets: Optional[Sequence[float]]):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = label_names
        self.buckets = tuple(buckets) if buckets is not None else None
        self._children: Dict[Tuple[str, ...], _Metric] = {}
        self._lock = threading.Lock()
        if not label_names:
            self._default = self._make(())
        else:
            self._default = None

    def _make(self, values: Tuple[str, ...]) -> _Metric:
        kv = tuple(zip(self.label_names, values))
        if self.kind == "histogram":
            child = Histogram(self.registry, kv,
                              self.buckets or DEFAULT_BUCKETS)
        else:
            child = _TYPES[self.kind](self.registry, kv)
        self._children[values] = child
        return child

    def labels(self, **kv: str) -> _Metric:
        """The child for these label values (created on first use).
        Cache the returned child on hot paths — this does a dict lookup
        under a lock."""
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(kv)}")
        values = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make(values)
            return child

    def children(self) -> List[_Metric]:
        with self._lock:
            return list(self._children.values())

    # convenience: a label-less family proxies the single child so the
    # common case reads `requests.inc()` not `requests.labels().inc()`
    def __getattr__(self, item):
        default = self.__dict__.get("_default")
        if default is not None:
            return getattr(default, item)
        raise AttributeError(
            f"{self.name} has labels {self.label_names}; "
            f"use .labels(...).{item}")


class MetricsRegistry:
    """Ordered, thread-safe collection of metric families.

    ``counter``/``gauge``/``histogram`` are get-or-create: a second call
    with the same name returns the existing family (so independent
    modules can share one series) and raises on type/label mismatch.
    ``disable()`` turns every record into a near-free no-op — the
    knob the < 1 µs hot-path bound is measured against.
    """

    def __init__(self, enabled: bool = True):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()
        self._enabled = bool(enabled)

    # -- lifecycle ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self):
        self._enabled = True

    def disable(self):
        """Stop recording (existing values freeze; rendering still works)."""
        self._enabled = False

    # -- family creation ---------------------------------------------------
    def _get_or_create(self, name: str, kind: str, help: str,
                       labels: Sequence[str],
                       buckets: Optional[Sequence[float]] = None) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, not "
                        f"{kind}{label_names}")
                return fam
            fam = _Family(self, name, kind, help, label_names, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._get_or_create(name, "histogram", help, labels, buckets)

    # -- output ------------------------------------------------------------
    def snapshot(self) -> Dict:
        """JSON-able dump of every series — the wire format the multihost
        aggregator merges and the JSONL snapshot writer persists."""
        out = {"counters": [], "gauges": [], "histograms": []}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            for child in fam.children():
                entry = {"name": fam.name, "labels": dict(child.labels_kv)}
                if fam.kind == "histogram":
                    entry.update(child.snapshot_value())
                    out["histograms"].append(entry)
                else:
                    entry["value"] = child.value
                    out["counters" if fam.kind == "counter"
                        else "gauges"].append(entry)
        return out

    def render_prometheus(self) -> str:
        """The registry in Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for child in fam.children():
                base = "".join(
                    f'{k}="{_escape_label(v)}",'
                    for k, v in child.labels_kv)
                if fam.kind != "histogram":
                    sel = f"{{{base[:-1]}}}" if base else ""
                    lines.append(f"{fam.name}{sel} {_fmt(child.value)}")
                    continue
                snap = child.snapshot_value()
                cum = 0
                for bound, n in zip(snap["bounds"], snap["counts"]):
                    cum += n
                    lines.append(
                        f'{fam.name}_bucket{{{base}le="{_fmt(bound)}"}} '
                        f"{cum}")
                cum += snap["counts"][-1]
                lines.append(
                    f'{fam.name}_bucket{{{base}le="+Inf"}} {cum}')
                sel = f"{{{base[:-1]}}}" if base else ""
                lines.append(f"{fam.name}_sum{sel} {_fmt(snap['sum'])}")
                lines.append(f"{fam.name}_count{sel} {snap['count']}")
        return "\n".join(lines) + "\n"


# --------------------------------------------------------------- default
# One process-global registry: instrumented modules register at import
# time and every exporter/aggregator reads the same view (the reference's
# per-component Timers had no such shared sink).

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> _Family:
    return _default_registry.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> _Family:
    return _default_registry.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
    return _default_registry.histogram(name, help, labels, buckets)
