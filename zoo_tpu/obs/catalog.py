# zoo-lint: jax-free
"""The telemetry catalog: every ``zoo_*`` metric family and every
flight-ring event kind, declared in one place.

The PR 2 obs e2e scrape asserts a *sample* of families end to end; this
catalog is the complete contract the ``zoo-lint`` telemetry pass
(:mod:`zoo_tpu.analysis.telemetry`) checks statically: a
``counter/gauge/histogram`` creation site anywhere in ``zoo_tpu/``
whose name is not declared here is a typo waiting to split a time
series (``TEL-UNDECLARED``); a creation site whose labels disagree
with the declaration is a label-cardinality bomb or a silent join
break (``TEL-LABELS``); a declared family no creation site still
builds is docs drift (``TEL-DEAD``). Flight-ring event kinds
(:func:`zoo_tpu.obs.flight.record_event`) follow the same rules.

Label VALUES are deliberately not declared — they are bounded at the
call sites; the label *names* here are what the aggregator joins on
and what docs/observability.md documents.

stdlib-only and jax-free: the lint runner imports this module.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

__all__ = ["METRICS", "EVENT_KINDS"]

#: name -> (kind, label names). Kind is ``counter`` / ``gauge`` /
#: ``histogram`` exactly as created against the
#: :class:`zoo_tpu.obs.metrics.MetricsRegistry`.
METRICS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    # -- resilience (retry / breaker / fault injection) ---------------------
    "zoo_retry_attempts_total": ("counter", ()),
    "zoo_retry_giveups_total": ("counter", ()),
    "zoo_breaker_transitions_total": ("counter", ("state",)),
    "zoo_breaker_open": ("gauge", ()),
    "zoo_fault_injections_total": ("counter", ("site",)),
    # -- checkpointing ------------------------------------------------------
    "zoo_ckpt_save_seconds": ("histogram", ()),
    "zoo_ckpt_restore_seconds": ("histogram", ()),
    "zoo_ckpt_verify_seconds": ("histogram", ()),
    "zoo_ckpt_quarantined_total": ("counter", ()),
    # -- training guard -----------------------------------------------------
    "zoo_guard_nonfinite_steps_total": ("counter", ()),
    "zoo_guard_rollbacks_total": ("counter", ()),
    "zoo_guard_preempt_checkpoints_total": ("counter", ()),
    "zoo_guard_diverged_total": ("counter", ()),
    "zoo_guard_rolling_loss": ("gauge", ()),
    # -- worker supervision -------------------------------------------------
    "zoo_worker_restarts_total": ("counter", ()),
    "zoo_worker_hung_total": ("counter", ()),
    "zoo_worker_quarantine_total": ("counter", ("event",)),
    # -- data plane ---------------------------------------------------------
    "zoo_shard_fetch_seconds": ("histogram", ()),
    "zoo_shard_fetch_bytes_total": ("counter", ()),
    "zoo_shard_fetch_requests_total": ("counter", ("mode",)),
    "zoo_shard_pool_connections_total": ("counter", ("event",)),
    "zoo_shard_lane_total": ("counter", ("lane",)),
    "zoo_shard_lane_bytes_total": ("counter", ("lane",)),
    "zoo_shard_wire_saved_bytes_total": ("counter", ()),
    "zoo_shard_pipeline_stage_seconds": ("histogram", ("stage",)),
    "zoo_shard_readahead": ("gauge", ("knob",)),
    "zoo_rebalance_barrier_wait_seconds": ("histogram", ("phase",)),
    # -- wire integrity -----------------------------------------------------
    "zoo_wire_corrupt_frames_total": ("counter", ("plane",)),
    # -- step profiling / mesh ---------------------------------------------
    "zoo_step_phase_seconds": ("histogram", ("phase",)),
    "zoo_mesh_axis_size": ("gauge", ("axis",)),
    "zoo_mesh_collective_bytes_total": ("counter", ("op",)),
    # -- serving (single server) -------------------------------------------
    "zoo_serving_queue_depth": ("gauge", ()),
    "zoo_serving_batch_occupancy": ("histogram", ()),
    "zoo_serving_stage_seconds": ("histogram", ("stage",)),
    "zoo_serving_requests_total": ("counter", ("outcome",)),
    "zoo_serve_shed_total": ("counter", ("reason",)),
    "zoo_serve_deadline_expired_total": ("counter", ("stage",)),
    "zoo_serve_dedup_total": ("counter", ("kind",)),
    "zoo_serve_reload_total": ("counter", ("outcome",)),
    "zoo_serve_drain_seconds": ("histogram", ()),
    "zoo_registry_version_info": ("gauge", ("version",)),
    "zoo_quant_path_info": ("gauge", ("path", "speedup")),
    # -- serving HA (replica group / client) -------------------------------
    "zoo_serve_replicas_healthy": ("gauge", ()),
    "zoo_serve_replica_restarts": ("gauge", ()),
    "zoo_serve_replicas_quarantined": ("gauge", ()),
    "zoo_serve_rolling_update_total": ("counter", ("outcome",)),
    "zoo_serve_rolling_update_seconds": ("histogram", ()),
    "zoo_serve_hedge_total": ("counter", ("event",)),
    "zoo_serve_failover_total": ("counter", ()),
    "zoo_serve_client_attempt_seconds": ("histogram", ()),
    "zoo_serve_ab_requests_total": ("counter", ("version", "outcome")),
    "zoo_serve_ab_latency_seconds": ("histogram", ("version",)),
    # -- gray-failure ejection ---------------------------------------------
    "zoo_serve_ejections_total": ("counter", ("event",)),
    "zoo_serve_replicas_ejected": ("gauge", ()),
    "zoo_serve_replicas_probation": ("gauge", ()),
    # -- model registry / promotion ----------------------------------------
    "zoo_registry_publish_total": ("counter", ("outcome",)),
    "zoo_registry_quarantined_total": ("counter", ()),
    "zoo_registry_gc_removed_total": ("counter", ()),
    "zoo_registry_versions": ("gauge", ()),
    "zoo_promotion_total": ("counter", ("outcome",)),
    "zoo_promotion_canary_error_rate": ("gauge", ()),
    "zoo_promotion_canary_latency_ratio": ("gauge", ()),
    "zoo_promotion_canary_loss_ratio": ("gauge", ()),
    # -- LLM engine ---------------------------------------------------------
    "zoo_llm_tokens_total": ("counter", ("kind",)),
    "zoo_llm_decode_steps_total": ("counter", ()),
    "zoo_llm_ttft_seconds": ("histogram", ()),
    "zoo_llm_inter_token_seconds": ("histogram", ()),
    "zoo_llm_stream_ttft_seconds": ("histogram", ("outcome",)),
    "zoo_llm_slot_occupancy": ("gauge", ()),
    "zoo_llm_waiting_streams": ("gauge", ()),
    "zoo_llm_preempt_total": ("counter", ()),
    "zoo_llm_streams_total": ("counter", ("outcome",)),
    "zoo_llm_stream_dedup_total": ("counter", ()),
    "zoo_llm_tick_seconds": ("histogram", ("phase",)),
    "zoo_llm_tick_overlap_ratio": ("gauge", ()),
    "zoo_llm_kv_blocks_used": ("gauge", ()),
    "zoo_llm_kv_blocks_free": ("gauge", ()),
    "zoo_llm_kv_blocks_shared": ("gauge", ()),
    "zoo_llm_kv_blocks_cached": ("gauge", ()),
    "zoo_llm_kv_bytes_per_token": ("gauge", ()),
    "zoo_llm_prefix_cache_hit_tokens_total": ("counter", ()),
    "zoo_llm_prefix_cache_miss_tokens_total": ("counter", ()),
    "zoo_llm_host_transfer_bytes_total": ("counter", ("kind",)),
    "zoo_llm_spec_proposed_tokens_total": ("counter", ()),
    "zoo_llm_spec_accepted_tokens_total": ("counter", ()),
    "zoo_llm_spec_accept_len": ("histogram", ()),
    "zoo_llm_spec_draft_hit_rate": ("gauge", ()),
    # -- disaggregated serving (prefill/decode pools + kv_migrate) ----------
    "zoo_llm_kv_migrated_blocks_total": ("counter", ()),
    "zoo_llm_kv_migrated_bytes_total": ("counter", ()),
    "zoo_llm_handoff_seconds": ("histogram", ()),
    "zoo_serve_route_affinity_total": ("counter", ("reason",)),
    # -- multi-tenant QoS (docs/multitenancy.md) ---------------------------
    "zoo_tenant_admitted_total": ("counter", ("tenant",)),
    "zoo_tenant_shed_total": ("counter", ("tenant", "reason")),
    "zoo_tenant_preempted_total": ("counter", ("tenant", "reason")),
    "zoo_tenant_kv_blocks": ("gauge", ("tenant",)),
    "zoo_tenant_decode_slots": ("gauge", ("tenant",)),
    "zoo_tenant_kv_cross_evictions_total": ("counter", ("tenant",)),
    "zoo_tenant_burn_rate": ("gauge", ("tenant", "slo")),
    # -- flight recorder / SLO watchdog ------------------------------------
    "zoo_flight_events_total": ("counter", ("kind",)),
    "zoo_flight_dumps_total": ("counter", ("reason",)),
    "zoo_slo_burn_rate": ("gauge", ("slo",)),
    "zoo_slo_breach": ("gauge", ("slo",)),
    "zoo_slo_rules_armed": ("gauge", ()),
}

#: every structured event kind fed to the crash flight recorder
#: (:func:`zoo_tpu.obs.flight.record_event` / ``FlightRecorder.record``)
EVENT_KINDS: FrozenSet[str] = frozenset({
    "replica_boot",
    "shed",
    "tenant_shed",
    "drain",
    "engine_tick",
    "llm_preempt",
    "llm_stream_end",
    "frame_corrupt",
    "corrupt_request_dropped",
    "chaos_arm",
    "chaos_clear",
    "kv_migrate_out",
    "kv_migrate_in",
    "kv_handoff_abort",
    "slo_breach",
    "slo_clear",
    "preempt_exit",
    "fatal_signal",
    "unhandled_exception",
})
