"""Trace spans: per-process JSONL event log + cross-host trace ids.

:func:`span` is a context manager marking one timed region::

    with span("ckpt.save", step=120):
        ...

Each span emits two JSONL records into the process's trace file — a
``B`` (begin) event and an ``E`` (end) event carrying the monotonic
duration and error flag — with a ``span`` id, its ``parent`` span id
(spans nest per thread), and the process-wide ``trace`` id. One training
step or serving request can therefore be followed across hosts: the
coordinator mints a trace id and :func:`share_trace_id` propagates it to
every process over the same JAX coordination-service KV store that
``rebalance_shards`` uses, so all hosts' trace files stitch on the
shared id.

Tracing is off until a sink exists: call :func:`trace_to` or set
``$ZOO_TRACE_DIR``. A disabled :func:`span` costs one global check and a
no-op context manager — safe to leave in hot paths.

Request-scoped tracing (docs/observability.md): a serving client mints
one trace id per logical request and it rides the wire; the server
adopts it with :func:`trace_context`, so every span recorded while
handling that request — on any process of the fleet — carries the
REQUEST's trace id instead of the process-wide one, and
``zoo_tpu.obs.timeline`` joins the per-process JSONL files back into
one per-request timeline. :func:`emit_span` / :func:`emit_event` write
complete ("X") and instant ("I") events with an EXPLICIT trace id for
code that works on behalf of many requests at once (the LLM engine's
scheduler thread, the batcher) where thread-local nesting cannot apply.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import socket
import threading
import time
import uuid
from typing import Iterator, List, Optional

from zoo_tpu.obs.coordination import coordination_client

__all__ = [
    "span", "trace_to", "stop_tracing", "tracing_enabled",
    "current_trace_id", "set_trace_id", "share_trace_id",
    "read_trace", "TRACE_DIR_ENV",
    "trace_context", "ambient_trace_id", "current_span_id",
    "new_trace_id", "emit_span", "emit_event", "active_spans",
    "iter_jsonl", "trace_file_path",
]

logger = logging.getLogger(__name__)

TRACE_DIR_ENV = "ZOO_TRACE_DIR"

_lock = threading.Lock()
_sink = None            # type: Optional[_TraceLog]
_env_checked = False
_trace_id: Optional[str] = None
_tls = threading.local()  # .stack: span-id stack per thread
#                           .trace: request trace-id override per thread
# spans begun but not yet ended, across every thread — what a crash
# flight-recorder bundle captures as "where was this process when it
# died". Only mutated while a sink exists (span() returns early when
# tracing is off), so the disabled hot path never touches it.
_live_spans: dict = {}
_live_lock = threading.Lock()


class _TraceLog:
    """Append-only JSONL writer for one process's trace events."""

    def __init__(self, dir_path: str):
        os.makedirs(dir_path, exist_ok=True)
        self.path = os.path.join(
            dir_path,
            f"trace-{socket.gethostname()}-{os.getpid()}.jsonl")
        self._f = open(self.path, "a", encoding="utf-8")
        self._wlock = threading.Lock()

    def write(self, event: dict):
        line = json.dumps(event, separators=(",", ":"), default=str)
        try:
            with self._wlock:
                self._f.write(line + "\n")
                self._f.flush()
        except (OSError, ValueError) as e:
            # telemetry must never fail the instrumented operation (a
            # full disk, or stop_tracing() racing a span in another
            # thread) — and an error raised from span()'s finally would
            # even MASK the operation's own exception
            logger.debug("trace write dropped: %s", e)

    def close(self):
        with self._wlock:
            try:
                self._f.close()
            except OSError:
                pass


def trace_to(dir_path: str) -> str:
    """Start writing span events under ``dir_path``; returns the trace
    file path for this process."""
    global _sink
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = _TraceLog(dir_path)
        return _sink.path


def stop_tracing():
    global _sink, _env_checked
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _env_checked = True  # an explicit stop beats the env default


def _active_sink() -> "Optional[_TraceLog]":  # zoo-lint: config-parse
    global _sink, _env_checked
    if _sink is not None:
        return _sink
    if _env_checked:
        return None
    with _lock:
        if _sink is None and not _env_checked:
            _env_checked = True
            d = os.environ.get(TRACE_DIR_ENV)
            if d:
                try:
                    _sink = _TraceLog(d)
                except OSError as e:  # bad dir must not kill the caller
                    logger.warning("cannot open trace dir %s: %s", d, e)
        return _sink


def tracing_enabled() -> bool:
    return _active_sink() is not None


def trace_file_path() -> Optional[str]:
    """This process's trace file path (None while tracing is off)."""
    sink = _active_sink()
    return sink.path if sink is not None else None


# ------------------------------------------------------------- trace ids

def new_trace_id() -> str:
    """A fresh request-scoped trace id (what a serving client mints per
    logical request before putting it on the wire)."""
    return uuid.uuid4().hex


def current_trace_id() -> str:
    """The ACTIVE trace id: the thread's adopted request trace inside a
    :func:`trace_context`, else this process's own id (minted on first
    use)."""
    tid = getattr(_tls, "trace", None)
    if tid is not None:
        return tid
    global _trace_id
    with _lock:
        if _trace_id is None:
            _trace_id = uuid.uuid4().hex
        return _trace_id


def ambient_trace_id() -> Optional[str]:
    """The thread's adopted REQUEST trace id, or None outside any
    :func:`trace_context` (never mints; the wire stamps only explicit
    request traces, not the ambient process id)."""
    return getattr(_tls, "trace", None)


def current_span_id() -> Optional[str]:
    """The innermost open span id on this thread (for parenting a
    remote child over the wire), or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


@contextlib.contextmanager
def trace_context(trace_id: Optional[str],
                  parent_span: Optional[str] = None) -> Iterator[None]:
    """Adopt ``trace_id`` for this thread: every :func:`span` inside
    carries the request's trace id (and parents under ``parent_span``,
    the caller's span id from the wire) instead of the process-wide
    trace. ``trace_id=None`` is a no-op passthrough, so wire handlers
    can wrap unconditionally."""
    if trace_id is None:
        yield
        return
    prev = getattr(_tls, "trace", None)
    _tls.trace = str(trace_id)
    st = _stack()
    pushed = parent_span is not None
    if pushed:
        st.append(str(parent_span))
    try:
        yield
    finally:
        if pushed:
            st.pop()
        _tls.trace = prev


def set_trace_id(trace_id: str):
    global _trace_id
    with _lock:
        _trace_id = str(trace_id)


_share_generation = 0
_share_gen_lock = threading.Lock()


def share_trace_id(timeout_s: float = 30.0) -> str:
    """Adopt one cluster-wide trace id (collective: call on every
    process). Process 0 publishes its trace id through the coordination
    service; everyone else blocks for it and adopts it, so all hosts'
    span events stitch into one distributed trace. Single-process: just
    returns the local id."""
    import jax

    if jax.process_count() == 1:
        return current_trace_id()
    client = coordination_client()
    if client is None:
        raise RuntimeError(
            "share_trace_id needs the JAX coordination service "
            "(jax.distributed.initialize) in multi-process mode")
    global _share_generation
    with _share_gen_lock:
        _share_generation += 1
        gen = _share_generation
    key = f"zoo:obs:trace:{gen}"
    if jax.process_index() == 0:
        client.key_value_set(key, current_trace_id())
    tid = client.blocking_key_value_get(key, int(timeout_s * 1000))
    if isinstance(tid, bytes):
        tid = tid.decode()
    set_trace_id(tid)
    return tid


# ----------------------------------------------------------------- spans

def _stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Optional[str]]:
    """Timed, nested trace region; yields the span id (None when tracing
    is off). Exceptions propagate; the end event records ``ok: false``."""
    sink = _active_sink()
    if sink is None:
        yield None
        return
    sid = uuid.uuid4().hex[:16]
    st = _stack()
    parent = st[-1] if st else None
    ev = {"ev": "B", "name": name, "trace": current_trace_id(),
          "span": sid, "parent": parent, "pid": os.getpid(),
          "ts": time.time()}
    if attrs:
        ev["attrs"] = attrs
    sink.write(ev)
    with _live_lock:
        _live_spans[sid] = ev
    st.append(sid)
    t0 = time.perf_counter()
    ok = True
    try:
        yield sid
    except BaseException:
        ok = False
        raise
    finally:
        st.pop()
        with _live_lock:
            _live_spans.pop(sid, None)
        sink.write({"ev": "E", "name": name,
                    "trace": ev["trace"], "span": sid,
                    "ts": time.time(),
                    "dur_s": time.perf_counter() - t0, "ok": ok})


def active_spans() -> List[dict]:
    """Begin events of every span currently OPEN in this process (any
    thread) — the "where was it" a flight-recorder bundle captures."""
    with _live_lock:
        return list(_live_spans.values())


def emit_span(name: str, ts: float, dur_s: float,
              trace: Optional[str] = None,
              parent: Optional[str] = None, ok: bool = True,
              span_id: Optional[str] = None,
              **attrs) -> Optional[str]:
    """Write one COMPLETE ("X") span event: started at wall ``ts``,
    lasted ``dur_s``. For recorders that time a region themselves on
    behalf of a specific request (the engine's scheduler working a
    stream, a client attempt thread) where a nested :func:`span` cannot
    carry the right identity. ``trace=None`` falls back to the active
    trace id. Returns the span id (None while tracing is off)."""
    sink = _active_sink()
    if sink is None:
        return None
    sid = span_id if span_id is not None else uuid.uuid4().hex[:16]
    ev = {"ev": "X", "name": name,
          "trace": trace if trace is not None else current_trace_id(),
          "span": sid, "parent": parent, "pid": os.getpid(),
          "ts": ts, "dur_s": float(dur_s), "ok": bool(ok)}
    if attrs:
        ev["attrs"] = attrs
    sink.write(ev)
    return sid


def emit_event(name: str, trace: Optional[str] = None,
               parent: Optional[str] = None, **attrs) -> Optional[str]:
    """Write one INSTANT ("I") event (admission, preemption, a shed —
    things with a moment but no duration). Same identity rules as
    :func:`emit_span`."""
    sink = _active_sink()
    if sink is None:
        return None
    sid = uuid.uuid4().hex[:16]
    ev = {"ev": "I", "name": name,
          "trace": trace if trace is not None else current_trace_id(),
          "span": sid, "parent": parent, "pid": os.getpid(),
          "ts": time.time()}
    if attrs:
        ev["attrs"] = attrs
    sink.write(ev)
    return sid


def iter_jsonl(path: str) -> Iterator[dict]:
    """Yield every parseable JSON object from a JSONL file, skipping
    torn or truncated lines. A crash mid-write is an EXPECTED event for
    trace files and flight-recorder spills (a SIGKILL can land between
    any two bytes), so a half-written tail, an interleaved torn line,
    or invalid UTF-8 from a partial flush must never take the readable
    prefix down with it. A missing/unreadable file yields nothing."""
    try:
        f = open(path, encoding="utf-8", errors="replace")
    except OSError:
        return
    with f:
        while True:
            try:
                line = f.readline()
            except (OSError, ValueError):
                return  # unreadable remainder: keep what we have
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue  # torn write: skip, keep the rest
            if isinstance(obj, dict):
                yield obj


def read_trace(dir_path: str) -> List[dict]:
    """Load every span event under ``dir_path`` (all hosts' files),
    sorted by wall timestamp — the offline-analysis read-back. Torn or
    truncated lines (a replica killed mid-write) are skipped, never
    raised."""
    events: List[dict] = []
    if not os.path.isdir(dir_path):
        return events
    for fname in sorted(os.listdir(dir_path)):
        if not (fname.startswith("trace-") and fname.endswith(".jsonl")):
            continue
        events.extend(iter_jsonl(os.path.join(dir_path, fname)))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return events
