"""PPML — privacy-preserving ML building blocks.

Rebuild of the reference's ``ppml/`` (SGX-trusted Spark/BigDL and trusted
Cluster Serving via Graphene/Occlum enclaves). TPU has no SGX; the
equivalent trust story is documented in ``zoo_tpu/ppml/README.md``
(Confidential-VM hosts + encrypted-at-rest artifacts + TLS in transit).
What is code here is the part that carries over 1:1: AES model/file
encryption (:class:`EncryptSupportive`, wire-compatible with the
reference's ``EncryptSupportive.scala``) used by
``InferenceModel.load_encrypted`` and ``save_encrypted``.
"""

from zoo_tpu.ppml.crypto import EncryptSupportive  # noqa: F401

__all__ = ["EncryptSupportive"]
