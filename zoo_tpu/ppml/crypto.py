"""Encrypt-at-rest support (PPML building block).

Rebuild of the reference's ``EncryptSupportive``
(``zoo/src/main/scala/com/intel/analytics/zoo/pipeline/inference/EncryptSupportive.scala:27``):
AES-CBC/PKCS5 and AES-GCM with a PBKDF2-HMAC-SHA256 key (65536
iterations), IV prepended to the ciphertext, Base64 for the string APIs —
wire-compatible with artifacts produced by the reference (same KDF, same
framing). Key derivation uses stdlib ``hashlib.pbkdf2_hmac``; the AES
primitives are the platform's native OpenSSL ``libcrypto`` driven through
``ctypes`` EVP calls (this environment has no Python AES package, and the
reference's crypto is likewise the JVM's native provider).
"""

from __future__ import annotations

import base64
import ctypes
import ctypes.util
import hashlib
import os
from typing import Optional

_ITERATIONS = 65536
_CBC_IV_LEN = 16
_GCM_IV_LEN = 12
_GCM_TAG_LEN = 16
# EVP_CIPHER_CTX_ctrl codes (openssl/evp.h)
_EVP_CTRL_GCM_SET_TAG = 0x11
_EVP_CTRL_GCM_GET_TAG = 0x10

_lib: Optional[ctypes.CDLL] = None


def _crypto() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        name = ctypes.util.find_library("crypto")
        for candidate in ([name] if name else []) + [
                "libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]:
            try:
                lib = ctypes.CDLL(candidate)
                lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
                for fn in ("EVP_aes_128_cbc", "EVP_aes_256_cbc",
                           "EVP_aes_128_gcm", "EVP_aes_256_gcm"):
                    getattr(lib, fn).restype = ctypes.c_void_p
                _lib = lib
                break
            except OSError:
                continue
        if _lib is None:
            raise RuntimeError(
                "OpenSSL libcrypto not found; encrypted-model support "
                "requires the system OpenSSL library")
    return _lib


def _derive_key(secret: str, salt: str, key_len_bits: int) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", secret.encode(), salt.encode(),
                               _ITERATIONS, dklen=key_len_bits // 8)


def _evp(mode: str, encrypt: bool, key: bytes, iv: bytes, data: bytes,
         tag: Optional[bytes] = None) -> bytes:
    lib = _crypto()
    cipher_name = f"EVP_aes_{len(key) * 8}_{mode}"
    cipher = getattr(lib, cipher_name)()
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise RuntimeError("EVP_CIPHER_CTX_new failed")
    try:
        init = lib.EVP_EncryptInit_ex if encrypt else lib.EVP_DecryptInit_ex
        update = (lib.EVP_EncryptUpdate if encrypt
                  else lib.EVP_DecryptUpdate)
        final = (lib.EVP_EncryptFinal_ex if encrypt
                 else lib.EVP_DecryptFinal_ex)
        if init(ctypes.c_void_p(ctx), ctypes.c_void_p(cipher), None,
                key, iv) != 1:
            raise RuntimeError(f"{cipher_name} init failed")
        out = ctypes.create_string_buffer(len(data) + 32)
        outl = ctypes.c_int(0)
        if update(ctypes.c_void_p(ctx), out, ctypes.byref(outl), data,
                  len(data)) != 1:
            raise RuntimeError(f"{cipher_name} update failed")
        total = outl.value
        if mode == "gcm" and not encrypt:
            if tag is None:
                raise ValueError("GCM decrypt requires the auth tag")
            if lib.EVP_CIPHER_CTX_ctrl(
                    ctypes.c_void_p(ctx), _EVP_CTRL_GCM_SET_TAG,
                    len(tag), tag) != 1:
                raise RuntimeError("setting GCM tag failed")
        fin = ctypes.create_string_buffer(32)
        finl = ctypes.c_int(0)
        if final(ctypes.c_void_p(ctx), fin, ctypes.byref(finl)) != 1:
            raise ValueError(
                "decryption failed (wrong secret/salt or corrupted "
                "ciphertext)" if not encrypt else "encryption failed")
        result = out.raw[:total] + fin.raw[:finl.value]
        if mode == "gcm" and encrypt:
            gtag = ctypes.create_string_buffer(_GCM_TAG_LEN)
            if lib.EVP_CIPHER_CTX_ctrl(
                    ctypes.c_void_p(ctx), _EVP_CTRL_GCM_GET_TAG,
                    _GCM_TAG_LEN, gtag) != 1:
                raise RuntimeError("getting GCM tag failed")
            result += gtag.raw
        return result
    finally:
        lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(ctx))


class EncryptSupportive:
    """AES-CBC / AES-GCM helpers, reference-compatible framing."""

    # -- CBC (reference encryptWithAESCBC:37 / decryptWithAESCBC:62) ------
    @staticmethod
    def encrypt_bytes_with_aes_cbc(content: bytes, secret: str, salt: str,
                                   key_len: int = 128) -> bytes:
        key = _derive_key(secret, salt, key_len)
        iv = os.urandom(_CBC_IV_LEN)
        return iv + _evp("cbc", True, key, iv, content)

    @staticmethod
    def decrypt_bytes_with_aes_cbc(content: bytes, secret: str, salt: str,
                                   key_len: int = 128) -> bytes:
        key = _derive_key(secret, salt, key_len)
        iv, body = content[:_CBC_IV_LEN], content[_CBC_IV_LEN:]
        return _evp("cbc", False, key, iv, body)

    @classmethod
    def encrypt_with_aes_cbc(cls, content: str, secret: str, salt: str,
                             key_len: int = 128) -> str:
        return base64.b64encode(cls.encrypt_bytes_with_aes_cbc(
            content.encode(), secret, salt, key_len)).decode()

    @classmethod
    def decrypt_with_aes_cbc(cls, content: str, secret: str, salt: str,
                             key_len: int = 128) -> str:
        return cls.decrypt_bytes_with_aes_cbc(
            base64.b64decode(content), secret, salt, key_len).decode()

    # -- GCM (reference encryptBytesWithAESGCM:100; IV=12, tag=16) --------
    @staticmethod
    def encrypt_bytes_with_aes_gcm(content: bytes, secret: str, salt: str,
                                   key_len: int = 128) -> bytes:
        key = _derive_key(secret, salt, key_len)
        iv = os.urandom(_GCM_IV_LEN)
        return iv + _evp("gcm", True, key, iv, content)

    @staticmethod
    def decrypt_bytes_with_aes_gcm(content: bytes, secret: str, salt: str,
                                   key_len: int = 128) -> bytes:
        key = _derive_key(secret, salt, key_len)
        iv = content[:_GCM_IV_LEN]
        body = content[_GCM_IV_LEN:-_GCM_TAG_LEN]
        tag = content[-_GCM_TAG_LEN:]
        return _evp("gcm", False, key, iv, body, tag=tag)

    @classmethod
    def encrypt_with_aes_gcm(cls, content: str, secret: str, salt: str,
                             key_len: int = 128) -> str:
        return base64.b64encode(cls.encrypt_bytes_with_aes_gcm(
            content.encode(), secret, salt, key_len)).decode()

    @classmethod
    def decrypt_with_aes_gcm(cls, content: str, secret: str, salt: str,
                             key_len: int = 128) -> str:
        return cls.decrypt_bytes_with_aes_gcm(
            base64.b64decode(content), secret, salt, key_len).decode()

    # -- files (reference encryptFileWithAESCBC area) ---------------------
    @classmethod
    def encrypt_file(cls, in_path: str, out_path: str, secret: str,
                     salt: str, key_len: int = 128, mode: str = "cbc"):
        with open(in_path, "rb") as f:
            data = f.read()
        enc = (cls.encrypt_bytes_with_aes_cbc if mode == "cbc"
               else cls.encrypt_bytes_with_aes_gcm)
        with open(out_path, "wb") as f:
            f.write(enc(data, secret, salt, key_len))

    @classmethod
    def decrypt_file(cls, in_path: str, secret: str, salt: str,
                     key_len: int = 128, mode: str = "cbc") -> bytes:
        with open(in_path, "rb") as f:
            data = f.read()
        dec = (cls.decrypt_bytes_with_aes_cbc if mode == "cbc"
               else cls.decrypt_bytes_with_aes_gcm)
        return dec(data, secret, salt, key_len)
