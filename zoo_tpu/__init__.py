"""zoo_tpu — a TPU-native "Big Data AI" framework.

A from-scratch rebuild of the capabilities of Analytics Zoo
(reference: TheaperDeng/analytics-zoo, ``pyzoo/zoo/__init__.py``) designed
TPU-first on JAX/XLA/pjit/Pallas:

- **Orca**: one-line context bootstrap (``init_orca_context``) + sklearn-style
  distributed Estimators over XShards / pandas / tf.data-like pipelines
  (reference: ``pyzoo/zoo/orca``).
- **Keras-style layer API** on Flax instead of BigDL Scala layers
  (reference: ``pyzoo/zoo/pipeline/api/keras``).
- **Parallelism**: a ``jax.sharding.Mesh`` over ICI with DP / FSDP (ZeRO) /
  TP / sequence(ring-attention) sharding plans instead of the reference's
  Spark-shuffle parameter-server AllReduce (``Topology.scala:1204``).
- **Chronos**: time-series datasets, forecasters and AutoTS
  (reference: ``pyzoo/zoo/chronos``).
- **Friesian**: recsys feature engineering (reference: ``pyzoo/zoo/friesian``).
- **Serving / Inference**: AOT-compiled XLA inference with a model-copy pool
  (reference: ``pipeline/inference/InferenceModel.scala``).

Unlike the reference there is no JVM, Py4J, or Spark in the training loop:
the whole step (forward, backward, gradient allreduce, optimizer update) is a
single jitted XLA computation.
"""

__version__ = "0.1.0"

from zoo_tpu.common.context import ZooContext  # noqa: F401
