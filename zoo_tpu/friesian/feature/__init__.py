from zoo_tpu.friesian.feature.table import FeatureTable, StringIndex

__all__ = ["FeatureTable", "StringIndex"]
