"""Friesian FeatureTable — recsys feature engineering.

Rebuild of ``pyzoo/zoo/friesian/feature/table.py:37,554`` (FeatureTable over
Spark DataFrames with a Scala UDF kernel ``PythonFriesian.scala:48-321``).
Here the table is pandas-backed (shardable via XShards when it outgrows one
host); every op returns a NEW FeatureTable like the reference.

Ops (reference names): fillna, dropna, fill_median, log, clip, cross_columns,
category_encode (StringIndex), gen_string_idx, encode_string, add_neg_samples,
add_hist_seq, pad, mask, normalize, min_max_scale, one_hot_encode, rename,
size, select, filter, cast, union, join, group_by, to_shards.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd


class StringIndex:
    """Category→index mapping (reference: ``StringIndex`` table with
    ``col_name``; index 0 is reserved like the reference's 1-based ids)."""

    def __init__(self, mapping: Dict, col_name: str):
        self.mapping = dict(mapping)
        self.col_name = col_name

    @property
    def size(self) -> int:
        return len(self.mapping)

    def to_dict(self) -> Dict:
        return dict(self.mapping)

    def df(self) -> pd.DataFrame:
        return pd.DataFrame({self.col_name: list(self.mapping.keys()),
                             "id": list(self.mapping.values())})


def _as_list(cols) -> List[str]:
    if cols is None:
        return []
    return [cols] if isinstance(cols, str) else list(cols)


class FeatureTable:
    def __init__(self, df: pd.DataFrame):
        self.df = df.reset_index(drop=True)

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_pandas(df: pd.DataFrame) -> "FeatureTable":
        return FeatureTable(df.copy())

    @staticmethod
    def _read_parts(path: str, reader, **kwargs) -> "FeatureTable":
        from zoo_tpu.orca.data.file import list_files
        files = list_files(path)
        if not files:
            raise FileNotFoundError(f"no files under {path!r}")
        return FeatureTable(pd.concat(
            [reader(f, **kwargs) for f in files], ignore_index=True))

    @staticmethod
    def read_csv(path: str, **kwargs) -> "FeatureTable":
        return FeatureTable._read_parts(path, pd.read_csv, **kwargs)

    @staticmethod
    def read_parquet(path: str) -> "FeatureTable":
        return FeatureTable(pd.read_parquet(path))

    @staticmethod
    def read_json(path: str, **kwargs) -> "FeatureTable":
        """reference: ``read_json``."""
        return FeatureTable._read_parts(path, pd.read_json, **kwargs)

    @staticmethod
    def from_dict(data: Dict) -> "FeatureTable":
        """reference: ``from_dict`` — column name → values."""
        return FeatureTable(pd.DataFrame(data))

    def write_parquet(self, path: str) -> "FeatureTable":
        """reference: ``write_parquet``."""
        self.df.to_parquet(path)
        return self

    # -- basic -------------------------------------------------------------
    def select(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df[list(cols)].copy())

    def drop(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df.drop(columns=list(cols)))

    def rename(self, columns: Dict[str, str]) -> "FeatureTable":
        return FeatureTable(self.df.rename(columns=columns))

    def filter(self, condition) -> "FeatureTable":
        """``condition``: boolean Series or callable(df)->mask."""
        mask = condition(self.df) if callable(condition) else condition
        return FeatureTable(self.df[mask])

    def cast(self, columns, dtype) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            df[c] = df[c].astype(dtype)
        return FeatureTable(df)

    def size(self) -> int:
        return len(self.df)

    @property
    def columns(self) -> List[str]:
        """reference: ``columns`` property."""
        return list(self.df.columns)

    def col(self, name: str) -> pd.Series:
        """reference: ``col``."""
        return self.df[name]

    def distinct(self) -> "FeatureTable":
        """reference: ``distinct``."""
        return FeatureTable(self.df.drop_duplicates())

    def sample(self, fraction: float, seed: int = 0) -> "FeatureTable":
        """reference: ``sample`` (without replacement)."""
        return FeatureTable(self.df.sample(frac=fraction,
                                           random_state=seed))

    def split(self, weights: Sequence[float], seed: int = 0
              ) -> List["FeatureTable"]:
        """Random row split by normalized weights (reference:
        ``split``/``random_split``)."""
        w = np.asarray(weights, np.float64)
        w = w / w.sum()
        idx = np.random.RandomState(seed).permutation(len(self.df))
        bounds = (np.concatenate([[0], np.cumsum(w)]) * len(idx)
                  ).round().astype(int)
        bounds[-1] = len(idx)  # float cumsum must not drop the tail row
        return [FeatureTable(self.df.iloc[idx[bounds[i]:bounds[i + 1]]]
                             .reset_index(drop=True))
                for i in range(len(w))]

    def append_column(self, name: str, value) -> "FeatureTable":
        """reference: ``append_column`` — constant or array/Series."""
        df = self.df.copy()
        df[name] = value
        return FeatureTable(df)

    def merge_cols(self, columns: Sequence[str], target: str
                   ) -> "FeatureTable":
        """Merge columns into one list column (reference:
        ``merge_cols``)."""
        cols = _as_list(columns)
        df = self.df.copy()
        # row-wise so mixed dtypes keep their own type (int ids must not
        # float-upcast on the way into a list column)
        df[target] = [list(row) for row in
                      zip(*(df[c].tolist() for c in cols))]
        return FeatureTable(df.drop(columns=cols))

    def add(self, columns, value: float = 1.0) -> "FeatureTable":
        """Add a scalar to numeric columns (reference: ``add``)."""
        df = self.df.copy()
        for c in _as_list(columns):
            df[c] = df[c] + value
        return FeatureTable(df)

    def median(self, columns=None) -> "FeatureTable":
        """Per-column medians as a (column, median) table (reference:
        ``median``)."""
        cols = _as_list(columns) or list(
            self.df.select_dtypes("number").columns)
        return FeatureTable(pd.DataFrame(
            {"column": cols,
             "median": [float(self.df[c].median()) for c in cols]}))

    def get_stats(self, columns, aggr: Union[str, Dict]) -> Dict:
        """Column statistics dict (reference: ``get_stats``; ``aggr`` is
        one of min/max/avg/sum or a per-column dict)."""
        cols = _as_list(columns) or list(
            self.df.select_dtypes("number").columns)
        out = {}
        for c in cols:
            how = aggr[c] if isinstance(aggr, dict) else aggr
            how = {"avg": "mean"}.get(how, how)
            out[c] = float(getattr(self.df[c], how)())
        return out

    def filter_by_frequency(self, columns, min_freq: int = 2
                            ) -> "FeatureTable":
        """Keep rows whose value combination appears >= min_freq times
        (reference: ``filter_by_frequency``)."""
        cols = _as_list(columns)
        counts = self.df.groupby(cols)[cols[0]].transform("size")
        return FeatureTable(self.df[counts >= min_freq])

    def hash_encode(self, columns, bins: int, method: str = "md5"
                    ) -> "FeatureTable":
        """Hash string columns into ``bins`` buckets (reference:
        ``hash_encode``)."""
        import hashlib
        df = self.df.copy()
        for c in _as_list(columns):
            h = getattr(hashlib, method)
            df[c] = [int(h(str(v).encode()).hexdigest(), 16) % bins
                     for v in df[c]]
        return FeatureTable(df)

    def cross_hash_encode(self, columns, bin_size: int,
                          cross_col_name: Optional[str] = None
                          ) -> "FeatureTable":
        """Hash-cross of several columns (reference:
        ``cross_hash_encode``)."""
        cols = _as_list(columns)
        out = self.cross_columns([cols], [bin_size])
        if cross_col_name:
            out = out.rename({"_".join(cols): cross_col_name})
        return out

    def one_hot(self, columns) -> "FeatureTable":
        """alias kept for the reference's ``one_hot``."""
        return self.one_hot_encode(columns)

    def ordinal_shuffle_partition(self, seed: int = 0) -> "FeatureTable":
        """Global row shuffle (the reference shuffles within partitions;
        single-table equivalent is a full permutation)."""
        return FeatureTable(self.df.sample(frac=1.0, random_state=seed)
                            .reset_index(drop=True))

    def show(self, n: int = 20):
        print(self.df.head(n).to_string())

    def to_pandas(self) -> pd.DataFrame:
        return self.df.copy()

    def to_shards(self, num_shards: Optional[int] = None):
        """→ XShards of DataFrame partitions (feeds the Orca estimators)."""
        from zoo_tpu.orca.data.shard import LocalXShards, _pool_size
        n = num_shards or _pool_size()
        n = max(1, min(n, max(len(self.df), 1)))
        bounds = np.linspace(0, len(self.df), n + 1).astype(int)
        return LocalXShards([
            self.df.iloc[bounds[i]:bounds[i + 1]].reset_index(drop=True)
            for i in range(n)])

    # -- cleaning ----------------------------------------------------------
    def fillna(self, value, columns=None) -> "FeatureTable":
        """reference: ``fillna`` (int columns stay int)."""
        df = self.df.copy()
        cols = _as_list(columns) or df.columns
        for c in cols:
            df[c] = df[c].fillna(value)
        return FeatureTable(df)

    def dropna(self, columns=None) -> "FeatureTable":
        return FeatureTable(self.df.dropna(
            subset=_as_list(columns) or None))

    def fill_median(self, columns=None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns) or df.select_dtypes("number").columns:
            df[c] = df[c].fillna(df[c].median())
        return FeatureTable(df)

    # -- math --------------------------------------------------------------
    def log(self, columns=None, clipping: bool = True) -> "FeatureTable":
        """reference: ``log`` — log(x+1), clipping negatives to 0 first."""
        df = self.df.copy()
        for c in _as_list(columns) or df.select_dtypes("number").columns:
            v = df[c].to_numpy(dtype=np.float64)
            if clipping:
                v = np.clip(v, 0, None)
            df[c] = np.log1p(v)
        return FeatureTable(df)

    def clip(self, columns=None, min: Optional[float] = None,
             max: Optional[float] = None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            df[c] = df[c].clip(lower=min, upper=max)
        return FeatureTable(df)

    def normalize(self, columns=None) -> "FeatureTable":
        """z-score columns (reference: ``normalize``)."""
        df = self.df.copy()
        for c in _as_list(columns):
            v = df[c].to_numpy(dtype=np.float64)
            df[c] = (v - v.mean()) / (v.std() + 1e-12)
        return FeatureTable(df)

    def min_max_scale(self, columns=None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            v = df[c].to_numpy(dtype=np.float64)
            rng = v.max() - v.min()
            df[c] = (v - v.min()) / (rng if rng else 1.0)
        return FeatureTable(df)

    # -- categorical -------------------------------------------------------
    def gen_string_idx(self, columns, freq_limit: int = 0
                       ) -> List[StringIndex]:
        """Build 1-based StringIndexes by descending frequency (reference:
        ``gen_string_idx`` with ``freq_limit``)."""
        out = []
        for c in _as_list(columns):
            counts = self.df[c].value_counts()
            if freq_limit:
                counts = counts[counts >= freq_limit]
            mapping = {k: i + 1 for i, k in enumerate(counts.index)}
            out.append(StringIndex(mapping, c))
        return out

    def encode_string(self, columns, indices: Sequence[StringIndex]
                      ) -> "FeatureTable":
        """Map categorical values to ids; unseen → 0 (reference:
        ``encode_string``)."""
        df = self.df.copy()
        for c, idx in zip(_as_list(columns), indices):
            df[c] = df[c].map(idx.mapping).fillna(0).astype(np.int64)
        return FeatureTable(df)

    def category_encode(self, columns, freq_limit: int = 0):
        """gen + encode in one call (reference: ``category_encode``)."""
        indices = self.gen_string_idx(columns, freq_limit)
        return self.encode_string(columns, indices), indices

    def one_hot_encode(self, columns) -> "FeatureTable":
        df = self.df
        for c in _as_list(columns):
            dummies = pd.get_dummies(df[c], prefix=c, dtype=np.int64)
            df = pd.concat([df.drop(columns=[c]), dummies], axis=1)
        return FeatureTable(df)

    def cross_columns(self, crossed_columns: Sequence[Sequence[str]],
                      bucket_sizes: Sequence[int]) -> "FeatureTable":
        """Hash-cross column tuples into buckets (reference:
        ``cross_columns`` — the Wide&Deep wide-part features)."""
        df = self.df.copy()
        for cols, size in zip(crossed_columns, bucket_sizes):
            name = "_".join(cols)
            joined = df[list(cols)].astype(str).agg("_".join, axis=1)
            df[name] = pd.util.hash_pandas_object(
                joined, index=False).to_numpy() % size
        return FeatureTable(df)

    # -- recsys specials ---------------------------------------------------
    def add_neg_samples(self, item_size: int, item_col: str = "item",
                        label_col: str = "label", neg_num: int = 1,
                        seed: int = 0) -> "FeatureTable":
        """For each positive row add ``neg_num`` rows with random items and
        label 0 (reference: ``add_neg_samples``; items are 1-based)."""
        rs = np.random.RandomState(seed)
        pos = self.df.copy()
        pos[label_col] = 1
        negs = pos.loc[pos.index.repeat(neg_num)].copy()
        pos_items = negs[item_col].to_numpy()
        rnd = rs.randint(1, item_size + 1, len(negs))
        # re-draw collisions with the positive item once (cheap approx)
        collide = rnd == pos_items
        rnd[collide] = (rnd[collide] % item_size) + 1
        negs[item_col] = rnd
        negs[label_col] = 0
        return FeatureTable(pd.concat([pos, negs], ignore_index=True))

    def add_hist_seq(self, cols: Sequence[str], user_col: str,
                     sort_col: str, min_len: int = 1, max_len: int = 10
                     ) -> "FeatureTable":
        """Per-user trailing history sequences (reference:
        ``add_hist_seq`` — builds ``<col>_hist_seq`` arrays)."""
        df = self.df.sort_values([user_col, sort_col])
        out_rows = []
        for _, g in df.groupby(user_col, sort=False):
            vals = {c: g[c].tolist() for c in cols}
            for i in range(len(g)):
                if i < min_len:
                    continue
                row = g.iloc[i].to_dict()
                for c in cols:
                    row[f"{c}_hist_seq"] = vals[c][max(0, i - max_len):i]
                out_rows.append(row)
        return FeatureTable(pd.DataFrame(out_rows))

    def add_length(self, col_name: str) -> "FeatureTable":
        """Length of a list column as ``<col>_length`` (reference:
        ``add_length``)."""
        df = self.df.copy()
        df[f"{col_name}_length"] = df[col_name].apply(len)
        return FeatureTable(df)

    def add_neg_hist_seq(self, item_size: int, item_history_col: str,
                         neg_num: int, seed: int = 0) -> "FeatureTable":
        """For each history sequence add ``neg_num`` random negative items
        per step as ``neg_<col>`` (reference: ``add_neg_hist_seq``)."""
        rs = np.random.RandomState(seed)
        df = self.df.copy()

        def _negs(seq):
            out = []
            for v in seq:
                draws = rs.randint(1, item_size + 1, neg_num)
                draws[draws == v] = (draws[draws == v] % item_size) + 1
                out.append(draws.tolist())
            return out

        df[f"neg_{item_history_col}"] = df[item_history_col].apply(_negs)
        return FeatureTable(df)

    def mask(self, cols: Sequence[str], seq_len: int) -> "FeatureTable":
        """1/0 mask columns ``<col>_mask`` for list columns (reference:
        ``mask``)."""
        df = self.df.copy()
        for c in _as_list(cols):
            df[f"{c}_mask"] = df[c].apply(
                lambda v: [1] * min(len(v), seq_len)
                + [0] * max(0, seq_len - len(v)))
        return FeatureTable(df)

    def mask_pad(self, padding_cols: Sequence[str],
                 mask_cols: Sequence[str], seq_len: int) -> "FeatureTable":
        """mask then pad in one call (reference: ``mask_pad``)."""
        return self.mask(mask_cols, seq_len).pad(padding_cols, seq_len)

    def pad(self, cols: Sequence[str], seq_len: int,
            mask_cols: Optional[Sequence[str]] = None) -> "FeatureTable":
        """Pad/truncate list columns to ``seq_len`` (reference: ``pad``
        with optional mask columns)."""
        df = self.df.copy()
        for c in _as_list(cols):
            def _pad(v):
                v = list(v)[:seq_len]
                return v + [0] * (seq_len - len(v))
            df[c] = df[c].apply(_pad)
        for c in _as_list(mask_cols):
            base = c.replace("_mask", "")
            src = base if base in df.columns else _as_list(cols)[0]
            df[c] = df[src].apply(
                lambda v: [1 if x != 0 else 0 for x in v])
        return FeatureTable(df)

    # -- relational --------------------------------------------------------
    def join(self, other: "FeatureTable", on, how: str = "inner"
             ) -> "FeatureTable":
        return FeatureTable(self.df.merge(other.df, on=on, how=how))

    def union(self, other: "FeatureTable") -> "FeatureTable":
        return FeatureTable(pd.concat([self.df, other.df],
                                      ignore_index=True))

    def group_by(self, columns, agg: Dict[str, str]) -> "FeatureTable":
        out = self.df.groupby(_as_list(columns)).agg(agg).reset_index()
        out.columns = ["_".join(c) if isinstance(c, tuple) and c[1]
                       else (c[0] if isinstance(c, tuple) else c)
                       for c in out.columns]
        return FeatureTable(out)

    def max(self, column: str):
        return self.df[column].max()

    def min(self, column: str):
        return self.df[column].min()
