"""Friesian FeatureTable — recsys feature engineering.

Rebuild of ``pyzoo/zoo/friesian/feature/table.py:37,554`` (FeatureTable over
Spark DataFrames with a Scala UDF kernel ``PythonFriesian.scala:48-321``).
Here the table is pandas-backed (shardable via XShards when it outgrows one
host); every op returns a NEW FeatureTable like the reference.

Ops (reference names): fillna, dropna, fill_median, log, clip, cross_columns,
category_encode (StringIndex), gen_string_idx, encode_string, add_neg_samples,
add_hist_seq, pad, mask, normalize, min_max_scale, one_hot_encode, rename,
size, select, filter, cast, union, join, group_by, to_shards.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd


class StringIndex:
    """Category→index mapping (reference: ``StringIndex`` table with
    ``col_name``; index 0 is reserved like the reference's 1-based ids)."""

    def __init__(self, mapping: Dict, col_name: str):
        self.mapping = dict(mapping)
        self.col_name = col_name

    @property
    def size(self) -> int:
        return len(self.mapping)

    def to_dict(self) -> Dict:
        return dict(self.mapping)

    def df(self) -> pd.DataFrame:
        return pd.DataFrame({self.col_name: list(self.mapping.keys()),
                             "id": list(self.mapping.values())})


def _as_list(cols) -> List[str]:
    if cols is None:
        return []
    return [cols] if isinstance(cols, str) else list(cols)


class FeatureTable:
    def __init__(self, df: pd.DataFrame):
        self.df = df.reset_index(drop=True)

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_pandas(df: pd.DataFrame) -> "FeatureTable":
        return FeatureTable(df.copy())

    @staticmethod
    def read_csv(path: str, **kwargs) -> "FeatureTable":
        from zoo_tpu.orca.data.file import list_files
        parts = [pd.read_csv(f, **kwargs) for f in list_files(path)]
        return FeatureTable(pd.concat(parts, ignore_index=True))

    @staticmethod
    def read_parquet(path: str) -> "FeatureTable":
        return FeatureTable(pd.read_parquet(path))

    # -- basic -------------------------------------------------------------
    def select(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df[list(cols)].copy())

    def drop(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df.drop(columns=list(cols)))

    def rename(self, columns: Dict[str, str]) -> "FeatureTable":
        return FeatureTable(self.df.rename(columns=columns))

    def filter(self, condition) -> "FeatureTable":
        """``condition``: boolean Series or callable(df)->mask."""
        mask = condition(self.df) if callable(condition) else condition
        return FeatureTable(self.df[mask])

    def cast(self, columns, dtype) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            df[c] = df[c].astype(dtype)
        return FeatureTable(df)

    def size(self) -> int:
        return len(self.df)

    def show(self, n: int = 20):
        print(self.df.head(n).to_string())

    def to_pandas(self) -> pd.DataFrame:
        return self.df.copy()

    def to_shards(self, num_shards: Optional[int] = None):
        """→ XShards of DataFrame partitions (feeds the Orca estimators)."""
        from zoo_tpu.orca.data.shard import LocalXShards, _pool_size
        n = num_shards or _pool_size()
        n = max(1, min(n, max(len(self.df), 1)))
        bounds = np.linspace(0, len(self.df), n + 1).astype(int)
        return LocalXShards([
            self.df.iloc[bounds[i]:bounds[i + 1]].reset_index(drop=True)
            for i in range(n)])

    # -- cleaning ----------------------------------------------------------
    def fillna(self, value, columns=None) -> "FeatureTable":
        """reference: ``fillna`` (int columns stay int)."""
        df = self.df.copy()
        cols = _as_list(columns) or df.columns
        for c in cols:
            df[c] = df[c].fillna(value)
        return FeatureTable(df)

    def dropna(self, columns=None) -> "FeatureTable":
        return FeatureTable(self.df.dropna(
            subset=_as_list(columns) or None))

    def fill_median(self, columns=None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns) or df.select_dtypes("number").columns:
            df[c] = df[c].fillna(df[c].median())
        return FeatureTable(df)

    # -- math --------------------------------------------------------------
    def log(self, columns=None, clipping: bool = True) -> "FeatureTable":
        """reference: ``log`` — log(x+1), clipping negatives to 0 first."""
        df = self.df.copy()
        for c in _as_list(columns) or df.select_dtypes("number").columns:
            v = df[c].to_numpy(dtype=np.float64)
            if clipping:
                v = np.clip(v, 0, None)
            df[c] = np.log1p(v)
        return FeatureTable(df)

    def clip(self, columns=None, min: Optional[float] = None,
             max: Optional[float] = None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            df[c] = df[c].clip(lower=min, upper=max)
        return FeatureTable(df)

    def normalize(self, columns=None) -> "FeatureTable":
        """z-score columns (reference: ``normalize``)."""
        df = self.df.copy()
        for c in _as_list(columns):
            v = df[c].to_numpy(dtype=np.float64)
            df[c] = (v - v.mean()) / (v.std() + 1e-12)
        return FeatureTable(df)

    def min_max_scale(self, columns=None) -> "FeatureTable":
        df = self.df.copy()
        for c in _as_list(columns):
            v = df[c].to_numpy(dtype=np.float64)
            rng = v.max() - v.min()
            df[c] = (v - v.min()) / (rng if rng else 1.0)
        return FeatureTable(df)

    # -- categorical -------------------------------------------------------
    def gen_string_idx(self, columns, freq_limit: int = 0
                       ) -> List[StringIndex]:
        """Build 1-based StringIndexes by descending frequency (reference:
        ``gen_string_idx`` with ``freq_limit``)."""
        out = []
        for c in _as_list(columns):
            counts = self.df[c].value_counts()
            if freq_limit:
                counts = counts[counts >= freq_limit]
            mapping = {k: i + 1 for i, k in enumerate(counts.index)}
            out.append(StringIndex(mapping, c))
        return out

    def encode_string(self, columns, indices: Sequence[StringIndex]
                      ) -> "FeatureTable":
        """Map categorical values to ids; unseen → 0 (reference:
        ``encode_string``)."""
        df = self.df.copy()
        for c, idx in zip(_as_list(columns), indices):
            df[c] = df[c].map(idx.mapping).fillna(0).astype(np.int64)
        return FeatureTable(df)

    def category_encode(self, columns, freq_limit: int = 0):
        """gen + encode in one call (reference: ``category_encode``)."""
        indices = self.gen_string_idx(columns, freq_limit)
        return self.encode_string(columns, indices), indices

    def one_hot_encode(self, columns) -> "FeatureTable":
        df = self.df
        for c in _as_list(columns):
            dummies = pd.get_dummies(df[c], prefix=c, dtype=np.int64)
            df = pd.concat([df.drop(columns=[c]), dummies], axis=1)
        return FeatureTable(df)

    def cross_columns(self, crossed_columns: Sequence[Sequence[str]],
                      bucket_sizes: Sequence[int]) -> "FeatureTable":
        """Hash-cross column tuples into buckets (reference:
        ``cross_columns`` — the Wide&Deep wide-part features)."""
        df = self.df.copy()
        for cols, size in zip(crossed_columns, bucket_sizes):
            name = "_".join(cols)
            joined = df[list(cols)].astype(str).agg("_".join, axis=1)
            df[name] = pd.util.hash_pandas_object(
                joined, index=False).to_numpy() % size
        return FeatureTable(df)

    # -- recsys specials ---------------------------------------------------
    def add_neg_samples(self, item_size: int, item_col: str = "item",
                        label_col: str = "label", neg_num: int = 1,
                        seed: int = 0) -> "FeatureTable":
        """For each positive row add ``neg_num`` rows with random items and
        label 0 (reference: ``add_neg_samples``; items are 1-based)."""
        rs = np.random.RandomState(seed)
        pos = self.df.copy()
        pos[label_col] = 1
        negs = pos.loc[pos.index.repeat(neg_num)].copy()
        pos_items = negs[item_col].to_numpy()
        rnd = rs.randint(1, item_size + 1, len(negs))
        # re-draw collisions with the positive item once (cheap approx)
        collide = rnd == pos_items
        rnd[collide] = (rnd[collide] % item_size) + 1
        negs[item_col] = rnd
        negs[label_col] = 0
        return FeatureTable(pd.concat([pos, negs], ignore_index=True))

    def add_hist_seq(self, cols: Sequence[str], user_col: str,
                     sort_col: str, min_len: int = 1, max_len: int = 10
                     ) -> "FeatureTable":
        """Per-user trailing history sequences (reference:
        ``add_hist_seq`` — builds ``<col>_hist_seq`` arrays)."""
        df = self.df.sort_values([user_col, sort_col])
        out_rows = []
        for _, g in df.groupby(user_col, sort=False):
            vals = {c: g[c].tolist() for c in cols}
            for i in range(len(g)):
                if i < min_len:
                    continue
                row = g.iloc[i].to_dict()
                for c in cols:
                    row[f"{c}_hist_seq"] = vals[c][max(0, i - max_len):i]
                out_rows.append(row)
        return FeatureTable(pd.DataFrame(out_rows))

    def pad(self, cols: Sequence[str], seq_len: int,
            mask_cols: Optional[Sequence[str]] = None) -> "FeatureTable":
        """Pad/truncate list columns to ``seq_len`` (reference: ``pad``
        with optional mask columns)."""
        df = self.df.copy()
        for c in _as_list(cols):
            def _pad(v):
                v = list(v)[:seq_len]
                return v + [0] * (seq_len - len(v))
            df[c] = df[c].apply(_pad)
        for c in _as_list(mask_cols):
            base = c.replace("_mask", "")
            src = base if base in df.columns else _as_list(cols)[0]
            df[c] = df[src].apply(
                lambda v: [1 if x != 0 else 0 for x in v])
        return FeatureTable(df)

    # -- relational --------------------------------------------------------
    def join(self, other: "FeatureTable", on, how: str = "inner"
             ) -> "FeatureTable":
        return FeatureTable(self.df.merge(other.df, on=on, how=how))

    def union(self, other: "FeatureTable") -> "FeatureTable":
        return FeatureTable(pd.concat([self.df, other.df],
                                      ignore_index=True))

    def group_by(self, columns, agg: Dict[str, str]) -> "FeatureTable":
        out = self.df.groupby(_as_list(columns)).agg(agg).reset_index()
        out.columns = ["_".join(c) if isinstance(c, tuple) and c[1]
                       else (c[0] if isinstance(c, tuple) else c)
                       for c in out.columns]
        return FeatureTable(out)

    def max(self, column: str):
        return self.df[column].max()

    def min(self, column: str):
        return self.df[column].min()
