"""One-line cluster bootstrap for TPU.

Rebuild of ``init_orca_context`` / ``stop_orca_context``
(reference: ``pyzoo/zoo/orca/common.py:161,271``). The reference's job was to
assemble a SparkContext (local / yarn / k8s / standalone), boot BigDL's JVM
engine, and optionally start a Ray cluster inside the Spark executors
(RayOnSpark, ``pyzoo/zoo/ray/raycontext.py:323``). On TPU there is no JVM and
no Spark: bootstrap means initializing the JAX distributed runtime (for
multi-host pods), picking the device set, and building the global
``jax.sharding.Mesh`` every Estimator will ``pjit`` over.

Supported cluster modes:

- ``"local"``      — whatever ``jax.devices()`` says on this process
                     (a CPU mesh in tests, a single TPU chip on a dev VM).
- ``"tpu"``        — multi-host TPU pod: calls ``jax.distributed.initialize``
                     (TPU env vars are auto-detected by JAX) then meshes all
                     global devices.
- ``"spark-submit"``/``"yarn"``/``"k8s"`` — accepted for API compatibility;
                     they behave like ``"tpu"`` (the scheduler that launched
                     the processes is irrelevant once JAX is initialized).
"""

from __future__ import annotations

import atexit
import logging
import os
from typing import Dict, Optional, Sequence

from zoo_tpu.common.context import (
    RuntimeContext,
    ZooContext,
    _set_runtime_context,
    default_cores,
    get_runtime_context,
)

logger = logging.getLogger("zoo_tpu.orca")


class OrcaContext(ZooContext):
    """Process-global Orca config flags (reference: ``OrcaContextMeta``,
    ``orca/common.py:21-134``). Inherits the knobs from :class:`ZooContext`;
    aliased here so user code reads ``from zoo_tpu.orca import OrcaContext``
    exactly like the reference."""

    # reference ``barrier_mode`` gated Spark barrier-scheduling for the
    # RayOnSpark bootstrap (``raycontext.py:565``); the supervised
    # bootstrap here always gang-launches, so the flag is accepted and
    # inert (kept for reference user code that sets it)
    barrier_mode = True

    @staticmethod
    def get_ray_context():
        """reference ``OrcaContext.get_ray_context`` — the active
        RayContext (a lifecycle shim here; see ``zoo_tpu.ray``)."""
        from zoo_tpu.ray import RayContext
        return RayContext.get(initialize=False)

    @staticmethod
    def get_spark_context():
        raise RuntimeError(
            "no SparkContext exists in the TPU rebuild (no JVM); Spark "
            "DataFrames enter through the gated ingestion "
            "(zoo_tpu.orca.data.spark) and everything else is "
            "XShards/numpy — see docs/migration.md")

    @staticmethod
    def get_spark_session():
        OrcaContext.get_spark_context()


_DIST_INITIALIZED = False


def _dist_already_initialized() -> bool:
    try:
        import jax
        if hasattr(jax.distributed, "is_initialized"):
            return bool(jax.distributed.is_initialized())
        from jax._src import distributed as _d
        return _d.global_state.client is not None
    except Exception:
        return False


def _maybe_init_distributed(cluster_mode: str, num_nodes: int = 1):  # zoo-lint: config-parse
    """Initialize jax.distributed for multi-host pods. If the launcher (or
    user code) initialized it already, that wins. A failed initialize is
    only tolerable on a single-host dev box — when the caller declared
    ``num_nodes > 1`` it is a hard error, not a debug log (round-1 weak
    point: silently-degraded multi-host)."""
    global _DIST_INITIALIZED
    if _DIST_INITIALIZED or cluster_mode == "local":
        return
    import jax

    if _dist_already_initialized():
        _DIST_INITIALIZED = True
        return
    try:
        coord = os.environ.get("ZOO_COORDINATOR_ADDRESS")
        if coord:  # rendezvous injected by zoo_tpu.orca.bootstrap
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(os.environ["ZOO_NUM_PROCESSES"]),
                process_id=int(os.environ["ZOO_PROCESS_ID"]))
        else:  # real pod: topology discovered from the TPU metadata
            jax.distributed.initialize()
        _DIST_INITIALIZED = True
    except Exception as e:
        if num_nodes > 1:
            raise RuntimeError(
                f"cluster_mode={cluster_mode!r} with num_nodes={num_nodes} "
                "needs the JAX distributed runtime, but "
                f"jax.distributed.initialize() failed: {e}") from e
        logger.debug("jax.distributed.initialize skipped: %s", e)


def init_orca_context(cluster_mode: str = "local",
                      cores: Optional[int] = None,
                      memory: Optional[str] = None,
                      num_nodes: int = 1,
                      mesh_axes: Optional[Dict[str, int]] = None,
                      axis_names: Optional[Sequence[str]] = None,
                      devices=None,
                      **kwargs) -> RuntimeContext:
    """Create (or return) the global :class:`RuntimeContext`.

    Parameters mirror the reference (``orca/common.py:161``): ``cores`` and
    ``memory`` sized the Spark executors there; here ``cores`` sizes the
    host-side input-pipeline worker pool and ``memory`` is accepted and
    recorded but not enforced (the OS does that). ``num_nodes`` is validated
    against the actual JAX process count on multi-host jobs.

    TPU-specific additions: ``mesh_axes`` (e.g. ``{"data": -1}`` or
    ``{"data": 2, "model": 4}``) chooses the parallelism layout — the
    reference was data-parallel only (SURVEY §2.10), this rebuild makes the
    layout a bootstrap-time choice.
    """
    cluster_mode = cluster_mode.lower()
    if cluster_mode not in ("local", "tpu", "yarn", "k8s", "standalone",
                            "spark-submit", "yarn-client", "yarn-cluster"):
        raise ValueError(f"unsupported cluster_mode: {cluster_mode}")
    if cluster_mode == "local" and num_nodes > 1:
        raise ValueError(
            f"num_nodes={num_nodes} requires a multi-host cluster_mode "
            "(e.g. 'tpu'); cluster_mode='local' is single-host by "
            "definition")

    existing = get_runtime_context(required=False)
    if existing is not None:
        prev = existing.extra.get("_init_args")
        same = prev == (cluster_mode, mesh_axes,
                        tuple(axis_names) if axis_names else None,
                        tuple(devices) if devices is not None else None)
        default_call = (cluster_mode == "local" and not mesh_axes
                        and not axis_names and devices is None)
        if not (same or default_call):
            raise RuntimeError(
                "init_orca_context called twice with different arguments; "
                "call stop_orca_context() first to rebuild")
        logger.warning("init_orca_context called twice; returning existing "
                       "context")
        return existing

    _maybe_init_distributed(cluster_mode, num_nodes)

    # supervised workers (zoo_tpu.orca.bootstrap with hung-worker
    # detection) hand us a heartbeat file through the env; start beating
    # so the supervisor can tell hung from healthy. No-op otherwise.
    from zoo_tpu.util.resilience import start_heartbeat_thread
    start_heartbeat_thread()

    import jax
    from zoo_tpu.parallel.mesh import (
        build_mesh,
        mesh_axes_from_env,
        publish_mesh_metrics,
    )

    devs = list(devices if devices is not None else jax.devices())
    if mesh_axes is None:
        # deployment-wide layout knobs (docs/multichip.md): ZOO_MESH_DATA /
        # ZOO_MESH_FSDP / ZOO_MESH_MODEL / ... choose the parallelism
        # layout without touching launcher code; an explicit mesh_axes=
        # argument always wins, and env axes that do not fit this
        # context's device list (a single-device reference fit, a bench
        # pinning one chip) fall back to pure DP with a warning instead
        # of crashing the caller
        env_axes = mesh_axes_from_env()
        if env_axes:
            from zoo_tpu.parallel.mesh import DEFAULT_AXES, _factor_shape
            try:
                _factor_shape(len(devs), dict(env_axes),
                              tuple(axis_names or DEFAULT_AXES))
                mesh_axes = dict(env_axes)
            except ValueError as e:
                logger.warning(
                    "ZOO_MESH_* axes %s do not fit the %d device(s) of "
                    "this context (%s); using the data-parallel default",
                    env_axes, len(devs), e)
    mesh = build_mesh(devs, axis_sizes=mesh_axes, axis_names=axis_names)
    publish_mesh_metrics(mesh)

    nproc = jax.process_count()
    if cluster_mode != "local" and num_nodes > 1 and nproc not in (1, num_nodes):
        logger.warning("num_nodes=%d but jax.process_count()=%d",
                       num_nodes, nproc)

    ctx = RuntimeContext(
        cluster_mode=cluster_mode,
        platform=devs[0].platform if devs else "cpu",
        devices=tuple(devs),
        mesh=mesh,
        num_processes=nproc,
        process_index=jax.process_index(),
        cores=cores or default_cores(),
        extra={"memory": memory, "num_nodes": num_nodes,
               "_init_args": (cluster_mode, mesh_axes,
                              tuple(axis_names) if axis_names else None,
                              tuple(devices) if devices is not None else None),
               **kwargs},
    )
    _set_runtime_context(ctx)
    atexit.register(stop_orca_context)
    logger.info("Orca context: mode=%s platform=%s devices=%d mesh=%s",
                cluster_mode, ctx.platform, ctx.num_devices,
                dict(mesh.shape))
    return ctx


def stop_orca_context():
    """Tear down the global context (reference: ``orca/common.py:271``;
    registered atexit there too). Device buffers owned by Estimators are
    dropped with their Python references; nothing else to kill — there are
    no Ray raylets or JVMs here."""
    if get_runtime_context(required=False) is not None:
        _set_runtime_context(None)
        logger.info("Orca context stopped")
