"""Worker-process bootstrap and supervision.

Rebuild of the reference's RayOnSpark machinery
(``pyzoo/zoo/ray/raycontext.py:323`` ``RayContext._start_cluster``,
``gen_ray_start``:271 with its barrier-mode start; ``ProcessMonitor``
``pyzoo/zoo/ray/process.py:90``; ``JVMGuard``:33 which registers the
raylet pids so the JVM kills orphans). There the cluster fabric to boot
was Ray-on-Spark-executors; on TPU the fabric is the JAX distributed
runtime — one Python worker process per host — so what carries over is
the *supervision* capability:

* :class:`ProcessMonitor` — spawn N workers, watch them, restart on crash
  (bounded), tear the whole group down when any worker fails fatally or
  the parent exits. The JVMGuard orphan-kill maps to ``PR_SET_PDEATHSIG``
  (children get SIGKILLed by the kernel if the supervisor dies) plus
  process-group kills.
* :func:`launch_local_cluster` — the reference's ``local`` RayContext:
  boot an N-process JAX CPU cluster on one machine (coordinator on a free
  localhost port, ranks via ``ZOO_*`` env) for dev/test of multi-host
  code paths.
* CLI: ``python -m zoo_tpu.orca.bootstrap --nproc 4 train.py ...`` —
  supervised multi-process launch, the torchrun/spark-submit analogue
  (on a real pod, ``scripts/run_tpu_pod.sh`` runs one of these per host).

``init_orca_context(cluster_mode="tpu")`` picks the rank/coordinator up
from the ``ZOO_COORDINATOR_ADDRESS`` / ``ZOO_NUM_PROCESSES`` /
``ZOO_PROCESS_ID`` environment this module sets.
"""

from __future__ import annotations

import atexit
import ctypes
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from zoo_tpu.obs.metrics import counter
from zoo_tpu.orca.learn.guard import PREEMPT_EXIT_CODE
from zoo_tpu.util.resilience import (
    HEARTBEAT_FILE_ENV,
    HEARTBEAT_INTERVAL_ENV,
    heartbeat_age,
)

logger = logging.getLogger(__name__)


class WorkersPreempted(RuntimeError):
    """Every worker exited with :data:`PREEMPT_EXIT_CODE` — a
    preemption-triggered coordinated checkpoint, not a crash. The
    supervisor should relaunch at the SAME world size and let the job
    resume from the checkpoint (``run_elastic`` does exactly that;
    resume-don't-retry)."""

_worker_restarts = counter(
    "zoo_worker_restarts_total",
    "Supervised workers respawned after a crash or hang")
_workers_hung = counter(
    "zoo_worker_hung_total",
    "Supervised workers killed for a stale heartbeat")
_worker_quarantines = counter(
    "zoo_worker_quarantine_total",
    "Quarantine-mode transitions performed by supervisors in this "
    "process (quarantined = a worker exhausted its restart budget and "
    "was parked instead of killing the group; probe = a backoff-timed "
    "respawn attempt; readmitted = a probe survived the heal window "
    "and the seat returned to normal supervision)",
    labels=("event",))


def _flight(kind: str, **fields):
    """Flight-recorder event (lazy import — supervision must never fail
    to load because the obs ring could not)."""
    try:
        from zoo_tpu.obs.flight import record_event
        record_event(kind, **fields)
    except Exception:  # noqa: BLE001 — telemetry never fails the op
        pass

_PR_SET_PDEATHSIG = 1


def _child_preexec():
    """Run in the child between fork and exec: new session (own process
    group for clean group-kill) and kernel-level orphan protection."""
    os.setsid()
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(_PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:
        pass  # non-Linux: atexit kill still covers the common case


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pick_coordinator_port(retries: int = 16) -> int:
    """A free port for the JAX coordinator, re-probed immediately before
    use. ``free_port`` releases the port when it returns, so another
    process can grab it before worker 0 binds (the classic TOCTOU race);
    re-probing right here and retrying with a fresh candidate shrinks
    that window from "whole launch setup" to microseconds instead of
    failing the entire launch on a stale candidate."""
    last: Optional[OSError] = None
    for _ in range(max(1, retries)):
        port = free_port()
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", port))
            return port
        except OSError as e:  # taken since the probe: try a fresh one
            last = e
            logger.warning("coordinator port %d taken between probe and "
                           "use; retrying with a fresh port", port)
    raise RuntimeError(
        f"could not reserve a coordinator port after {retries} "
        "attempts") from last


class WorkerProcess:
    """One supervised worker (reference: a ray start subprocess tracked by
    ``ProcessInfo``)."""

    def __init__(self, cmd: Sequence[str], env: Dict[str, str],
                 name: str, log_dir: Optional[str] = None,
                 heartbeat_file: Optional[str] = None):
        self.cmd = list(cmd)
        self.env = dict(env)
        self.name = name
        self.log_dir = log_dir
        self.heartbeat_file = heartbeat_file
        if heartbeat_file:
            self.env[HEARTBEAT_FILE_ENV] = heartbeat_file
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self._log_fh = None
        self.heartbeat_spawn_mtime: Optional[float] = None
        # quarantine-mode state (docs/fault_tolerance.md): set by a
        # ProcessMonitor(quarantine=True) when this worker exhausts its
        # restart budget — parked, probed on a backoff timer, readmitted
        # after a probe survives the heal window
        self.quarantined = False
        self.quarantine_until = 0.0
        self.quarantine_backoff = 0.0
        self.quarantines = 0
        self.last_spawn_monotonic: Optional[float] = None

    def spawn(self):
        if self._log_fh:  # restart: release the previous run's handle
            self._log_fh.close()
            self._log_fh = None
        if self.heartbeat_file:
            # stamp at spawn so staleness is measured from launch even if
            # the worker never gets far enough to beat on its own; record
            # the stamp so the monitor can tell "never beat yet (still
            # booting — import jax alone can take many seconds)" from
            # "beat, then went silent (hung)"
            from zoo_tpu.util.resilience import touch_heartbeat
            touch_heartbeat(self.heartbeat_file)
            try:
                self.heartbeat_spawn_mtime = \
                    os.stat(self.heartbeat_file).st_mtime
            except OSError:
                self.heartbeat_spawn_mtime = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            self._log_fh = open(
                os.path.join(self.log_dir, f"{self.name}.log"), "ab")
            out = err = self._log_fh
        else:
            out = err = None
        self.proc = subprocess.Popen(
            self.cmd, env=self.env, stdout=out, stderr=err,
            preexec_fn=_child_preexec)
        self.last_spawn_monotonic = time.monotonic()
        return self.proc

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.poll() if self.proc else None

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            try:  # group-kill: the worker may have forked its own helpers
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(self.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                self.proc.wait()
        if self._log_fh:  # close even for self-exited workers
            self._log_fh.close()
            self._log_fh = None


class ProcessMonitor:
    """Spawn + supervise a set of workers (reference ``ProcessMonitor``
    ``ray/process.py:90``: tracks pids, raises when a member dies, cleans
    the rest up).

    ``max_restarts``: per-worker crash budget. Within budget a crashed
    worker is respawned; past it the whole group is torn down and
    :meth:`wait` raises. Exit code 0 counts as completion, not a crash.

    ``heartbeat_timeout``: optional hung-worker detection. Workers whose
    :class:`WorkerProcess` carries a ``heartbeat_file`` (stamped by
    ``touch_heartbeat`` / the ``init_orca_context`` heartbeat thread) are
    SIGKILLed and charged against the restart budget when the file goes
    stale for longer than this many seconds — a worker stuck in a dead
    collective is a crash the same as one that exited nonzero.

    ``quarantine``: what happens when ONE worker exhausts its restart
    budget. ``False`` (default — training semantics): the whole group
    is torn down and :meth:`wait` raises, because a gang-scheduled job
    cannot run short a rank. ``True`` (serving semantics, what
    :class:`~zoo_tpu.serving.ha.ReplicaGroup` passes): the crash-looping
    worker is QUARANTINED — parked with a flight-ring event instead of
    silently burning the group — while its siblings keep serving; a
    probe respawn is attempted on an exponential-backoff timer
    (``ZOO_QUARANTINE_PROBE_S`` base, ``ZOO_QUARANTINE_PROBE_MAX_S``
    cap), and a probe that stays alive for ``ZOO_QUARANTINE_HEAL_S``
    re-admits the seat with a fresh restart budget.
    """

    def __init__(self, workers: List[WorkerProcess], max_restarts: int = 0,
                 poll_interval: float = 0.2,
                 heartbeat_timeout: Optional[float] = None,
                 heartbeat_boot_grace: float = 120.0,
                 quarantine: bool = False):
        from zoo_tpu.util.resilience import env_float
        self.workers = workers
        self.max_restarts = int(max_restarts)
        self.poll_interval = poll_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.quarantine = bool(quarantine)
        self.quarantine_probe_s = env_float("ZOO_QUARANTINE_PROBE_S",
                                            5.0)
        self.quarantine_probe_max_s = env_float(
            "ZOO_QUARANTINE_PROBE_MAX_S", 60.0)
        self.quarantine_heal_s = env_float("ZOO_QUARANTINE_HEAL_S", 30.0)
        # until a worker has beaten ON ITS OWN at least once it is
        # booting, not hung — a cold `import jax` alone can outlast a
        # tight heartbeat_timeout; the boot window gets the larger bound
        self.heartbeat_boot_grace = max(heartbeat_boot_grace,
                                        heartbeat_timeout or 0.0)
        self._failed: Optional[str] = None
        self._preempted = False
        self._stop = threading.Event()
        self._lock = threading.Lock()  # serializes respawn vs teardown
        self._thread: Optional[threading.Thread] = None
        atexit.register(self.stop)

    def start(self) -> "ProcessMonitor":
        for w in self.workers:
            w.spawn()
            logger.info("spawned %s (pid %d)", w.name, w.proc.pid)
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="zoo-process-monitor")
        self._thread.start()
        return self

    def _crash_reason(self, w: WorkerProcess) -> Optional[str]:
        """A crash description for worker ``w``, or None while healthy.
        Hung workers (stale heartbeat) are killed here so the respawn /
        teardown path treats them exactly like a nonzero exit."""
        rc = w.returncode
        if rc is not None:
            # PREEMPT_EXIT_CODE is a deliberate checkpoint-and-exit
            # (training guardian, docs/fault_tolerance.md): completion,
            # never a crash — no respawn, no restart-budget charge
            return None if rc in (0, PREEMPT_EXIT_CODE) \
                else f"exited rc={rc}"
        if self.heartbeat_timeout and w.heartbeat_file:
            age = heartbeat_age(w.heartbeat_file)
            try:
                mtime = os.stat(w.heartbeat_file).st_mtime
            except OSError:
                mtime = None
            booted = (mtime is not None
                      and w.heartbeat_spawn_mtime is not None
                      and mtime > w.heartbeat_spawn_mtime)
            limit = self.heartbeat_timeout if booted \
                else self.heartbeat_boot_grace
            if age is not None and age > limit:
                logger.warning(
                    "%s heartbeat stale (%.1fs > %.1fs%s); killing the "
                    "hung worker", w.name, age, limit,
                    "" if booted else ", boot grace")
                _workers_hung.inc()
                w.kill()
                return (f"hung (heartbeat stale {age:.1f}s > "
                        f"{limit}s limit)")
        return None

    def _probe_beating(self, w: WorkerProcess) -> bool:
        """Whether a live quarantine probe has proven PROGRESS, not
        just liveness: with heartbeat monitoring armed, the probe must
        have beaten on its own since the spawn and be fresh — a probe
        wedged at boot must never read as healed (it would be
        re-admitted with a fresh budget, hung-killed, re-quarantined,
        and churn forever)."""
        if not (self.heartbeat_timeout and w.heartbeat_file):
            return True  # no heartbeat contract: alive is the bar
        age = heartbeat_age(w.heartbeat_file)
        try:
            mtime = os.stat(w.heartbeat_file).st_mtime
        except OSError:
            return False
        booted = (w.heartbeat_spawn_mtime is not None
                  and mtime > w.heartbeat_spawn_mtime)
        return booted and age is not None and \
            age <= self.heartbeat_timeout

    def _watch_quarantined(self, w: WorkerProcess):
        """One poll of a quarantined seat: probe respawns on the
        backoff timer, re-admission after a probe survives the heal
        window. Never touches the group."""
        now = time.monotonic()
        if w.returncode is None and w.last_spawn_monotonic is not None:
            if now - w.last_spawn_monotonic >= self.quarantine_heal_s:
                if not self._probe_beating(w):
                    # alive past the heal window but HUNG: the probe
                    # failed — kill it; the dead-seat branch below
                    # schedules the next (longer) backoff
                    logger.warning(
                        "%s quarantine probe is alive but not beating "
                        "— hung probe killed, staying quarantined",
                        w.name)
                    w.kill()
                    return
                # the probe held AND made progress: the seat is a real
                # replica again, with a fresh restart budget
                w.quarantined = False
                w.restarts = 0
                w.quarantine_backoff = 0.0
                _worker_quarantines.labels(event="readmitted").inc()
                _flight("replica_unquarantined", worker=w.name,
                        quarantines=w.quarantines)
                logger.warning(
                    "%s survived its quarantine probe for %.0fs; "
                    "re-admitted with a fresh restart budget",
                    w.name, self.quarantine_heal_s)
            return
        if w.returncode is None:
            return  # probe still running inside the heal window
        if now < w.quarantine_until:
            return  # dead, waiting out the backoff
        with self._lock:
            if self._stop.is_set():
                return
            # each failed probe doubles the next wait (capped): a seat
            # with a genuinely broken substrate converges to one cheap
            # respawn a minute instead of a crash loop
            w.quarantine_backoff = min(
                max(self.quarantine_probe_s, 2 * w.quarantine_backoff),
                self.quarantine_probe_max_s)
            w.quarantine_until = now + w.quarantine_backoff
            _worker_quarantines.labels(event="probe").inc()
            _flight("replica_quarantine_probe", worker=w.name,
                    next_backoff_s=w.quarantine_backoff)
            logger.info("%s quarantine probe respawn (next backoff "
                        "%.1fs)", w.name, w.quarantine_backoff)
            w.spawn()

    def _watch(self):
        while not self._stop.is_set():
            for w in self.workers:
                if w.quarantined:
                    self._watch_quarantined(w)
                    continue
                reason = self._crash_reason(w)
                if reason is None:
                    continue
                if w.restarts < self.max_restarts:
                    with self._lock:
                        if self._stop.is_set():
                            return  # teardown won the race: no respawn
                        w.restarts += 1
                        _worker_restarts.inc()
                        logger.warning(
                            "%s %s; restart %d/%d", w.name, reason,
                            w.restarts, self.max_restarts)
                        w.spawn()
                elif self.quarantine:
                    # serving semantics: the seat exhausted its budget
                    # — park it LOUDLY (flight event + counter; the
                    # gauge rides ReplicaGroup.healthz) instead of the
                    # old silent permanent death, and keep probing it
                    # back on a backoff timer while the rest of the
                    # group serves on
                    with self._lock:
                        if self._stop.is_set():
                            return
                        w.quarantined = True
                        w.quarantines += 1
                        # a RE-quarantine (a seat whose earlier probe
                        # "healed" then failed again) continues the
                        # backoff ladder instead of resetting to the
                        # base — only a genuine readmission clears it
                        w.quarantine_backoff = min(
                            max(self.quarantine_probe_s,
                                2 * w.quarantine_backoff),
                            self.quarantine_probe_max_s)
                        w.quarantine_until = (time.monotonic()
                                              + w.quarantine_backoff)
                        _worker_quarantines.labels(
                            event="quarantined").inc()
                        _flight("replica_quarantined", worker=w.name,
                                reason=reason, restarts=w.restarts,
                                probe_backoff_s=w.quarantine_backoff)
                        logger.error(
                            "%s %s with no restart budget left "
                            "(%d/%d) — QUARANTINED; probing back every "
                            "%.1fs (doubling, cap %.0fs)",
                            w.name, reason, w.restarts,
                            self.max_restarts, w.quarantine_backoff,
                            self.quarantine_probe_max_s)
                else:
                    with self._lock:
                        if self._stop.is_set():
                            return  # deliberate stop(), not a crash
                        self._failed = (
                            f"{w.name} {reason} with no restart "
                            f"budget left "
                            f"({w.restarts}/{self.max_restarts})")
                        logger.error("%s — tearing the group down",
                                     self._failed)
                        self._stop.set()
                        for other in self.workers:
                            other.kill()
                    return
            rcs = [w.returncode for w in self.workers]
            if all(rc is not None and rc in (0, PREEMPT_EXIT_CODE)
                   for rc in rcs):
                if PREEMPT_EXIT_CODE in rcs:
                    self._preempted = True
                self._stop.set()
                return
            time.sleep(self.poll_interval)

    def wait(self, timeout: Optional[float] = None):
        """Block until every worker exits 0; raise on fatal failure.
        Raises :class:`WorkersPreempted` when the group completed via a
        coordinated preemption checkpoint (exit :data:`PREEMPT_EXIT_CODE`)
        so the caller relaunches-and-resumes instead of scaling down."""
        deadline = time.time() + timeout if timeout is not None else None
        while True:
            if self._failed:
                raise RuntimeError(self._failed)
            rcs = [w.returncode for w in self.workers]
            if all(rc is not None and rc in (0, PREEMPT_EXIT_CODE)
                   for rc in rcs):
                if PREEMPT_EXIT_CODE in rcs:
                    raise WorkersPreempted(
                        f"{rcs.count(PREEMPT_EXIT_CODE)}/{len(rcs)} "
                        "worker(s) exited via the preemption checkpoint "
                        "protocol; relaunch and resume")
                return
            if self._stop.is_set():
                # the watch thread assigns _failed BEFORE setting _stop;
                # re-check so a failure set between our two reads is not
                # mistaken for a deliberate stop()
                if self._failed:
                    raise RuntimeError(self._failed)
                if self._preempted:
                    raise WorkersPreempted(
                        "workers exited via the preemption checkpoint "
                        "protocol; relaunch and resume")
                return  # deliberate stop(): termination, not failure
            if deadline is not None and time.time() > deadline:
                self.stop()
                raise TimeoutError(
                    f"workers still running after {timeout}s")
            time.sleep(self.poll_interval)

    def alive(self) -> List[str]:
        return [w.name for w in self.workers if w.returncode is None]

    def quarantined(self) -> List[str]:
        """Names of workers currently parked in quarantine — every
        seat accounted for, none silently missing."""
        return [w.name for w in self.workers if w.quarantined]

    def stop(self):
        with self._lock:  # no respawn may interleave with the kills
            self._stop.set()
            for w in self.workers:
                w.kill()


def launch_local_cluster(nproc: int, script: str,
                         args: Sequence[str] = (),
                         local_devices_per_proc: int = 1,
                         max_restarts: int = 0,
                         log_dir: Optional[str] = None,
                         env: Optional[Dict[str, str]] = None,
                         heartbeat_timeout: Optional[float] = None
                         ) -> ProcessMonitor:
    """Boot an ``nproc``-process JAX CPU cluster running ``script`` on
    this machine (the reference's local RayContext). Each worker gets
    ``ZOO_COORDINATOR_ADDRESS`` / ``ZOO_NUM_PROCESSES`` /
    ``ZOO_PROCESS_ID`` plus a forced-CPU JAX platform with
    ``local_devices_per_proc`` virtual devices, so
    ``init_orca_context(cluster_mode="tpu")`` forms the same process mesh
    it would on a pod.

    ``heartbeat_timeout``: enable hung-worker detection — each worker is
    handed a heartbeat file (``ZOO_HEARTBEAT_FILE``; stamped by the
    ``init_orca_context`` heartbeat thread) and is killed + charged to
    the restart budget when the stamp goes stale for longer than this
    many seconds."""
    import tempfile

    coord = f"127.0.0.1:{_pick_coordinator_port()}"
    hb_dir = None
    if heartbeat_timeout:
        hb_dir = log_dir or tempfile.mkdtemp(prefix="zoo-heartbeat-")
        os.makedirs(hb_dir, exist_ok=True)
    workers = []
    for pid in range(nproc):
        wenv = dict(os.environ)
        wenv.update(env or {})
        wenv.update({
            "ZOO_COORDINATOR_ADDRESS": coord,
            "ZOO_NUM_PROCESSES": str(nproc),
            "ZOO_PROCESS_ID": str(pid),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (wenv.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count="
                          f"{local_devices_per_proc}").strip(),
        })
        # never let a worker inherit the SUPERVISOR's heartbeat file
        # (nested launches: every child stamping the parent's file would
        # mask a hung sibling); each worker gets its own below, or none
        wenv.pop(HEARTBEAT_FILE_ENV, None)
        hb_file = None
        if hb_dir:
            hb_file = os.path.join(hb_dir, f"worker-{pid}.heartbeat")
            # a stale stamp carried over from a previous elastic attempt
            # in the same log_dir must not count as this attempt's beat
            try:
                os.unlink(hb_file)
            except OSError:
                pass
            # beat at a quarter of the timeout: three missed beats of
            # slack before a healthy-but-busy worker reads as hung
            wenv[HEARTBEAT_INTERVAL_ENV] = str(
                max(0.05, heartbeat_timeout / 4.0))
        workers.append(WorkerProcess(
            [sys.executable, script, *args], wenv, f"worker-{pid}",
            log_dir=log_dir, heartbeat_file=hb_file))
    return ProcessMonitor(workers, max_restarts=max_restarts,
                          heartbeat_timeout=heartbeat_timeout).start()


def run_elastic(nproc: int, script: str, args: Sequence[str] = (),
                min_workers: int = 1, max_restarts: int = 0,
                local_devices_per_proc: int = 1,
                log_dir: Optional[str] = None,
                env: Optional[Dict[str, str]] = None,
                wait_timeout: Optional[float] = None,
                heartbeat_timeout: Optional[float] = None,
                max_preempts: int = 100) -> int:
    """Scale-down elastic supervision (SURVEY §5.3; reference:
    ``Topology.scala:1255-1337`` retries within the job from the latest
    snapshot — this is that mechanism lifted to the supervisor, plus the
    re-mesh the reference cannot do).

    Runs ``script`` as an ``nproc``-process cluster. Same-size crashes
    are handled inside :class:`ProcessMonitor` (per-worker
    ``max_restarts``). When a worker exhausts its budget — a PERMANENT
    loss — the whole group is torn down and relaunched as an
    ``nproc-1``-process cluster (fresh coordinator, smaller mesh); the
    training script is expected to resume from its latest checkpoint
    (``est.load_orca_checkpoint()``), which the env var
    ``ZOO_ELASTIC_ATTEMPT`` (> "0") signals. Stops scaling at
    ``min_workers``; returns the world size that completed.

    A group that exits through the training guardian's preemption
    protocol (every worker exited :data:`PREEMPT_EXIT_CODE` after ONE
    coordinated checkpoint) is **resumed at the same world size** —
    preemption is the platform reclaiming a machine, not the job
    failing — bounded by ``max_preempts`` relaunches.
    """
    n, attempt, preempts = int(nproc), 0, 0
    while True:
        wenv = dict(env or {})
        wenv["ZOO_ELASTIC_ATTEMPT"] = str(attempt)
        mon = launch_local_cluster(
            n, script, args, max_restarts=max_restarts,
            local_devices_per_proc=local_devices_per_proc,
            log_dir=log_dir, env=wenv,
            heartbeat_timeout=heartbeat_timeout)
        try:
            mon.wait(timeout=wait_timeout)
            return n
        except WorkersPreempted as e:
            mon.stop()
            preempts += 1
            if preempts > max_preempts:
                raise RuntimeError(
                    f"preempted {preempts} times (> max_preempts="
                    f"{max_preempts}); giving up") from e
            logger.warning(
                "world size %d preempted (%s); relaunching at the same "
                "size, resuming from the preemption checkpoint "
                "(attempt %d)", n, e, attempt + 1)
            attempt += 1
        except RuntimeError as e:
            mon.stop()
            if n - 1 < min_workers:
                raise RuntimeError(
                    f"cannot scale below min_workers={min_workers} "
                    f"(world {n} failed: {e})") from e
            logger.warning(
                "permanent worker loss at world size %d (%s); resuming "
                "from the latest checkpoint on %d workers", n, e, n - 1)
            n -= 1
            attempt += 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m zoo_tpu.orca.bootstrap",
        description="Supervised multi-process launcher (reference: "
                    "RayContext/spark-submit role)")
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--max-restarts", type=int, default=0)
    ap.add_argument("--devices-per-proc", type=int, default=1)
    ap.add_argument("--log-dir", default=None)
    ap.add_argument("--elastic-min-workers", type=int, default=0,
                    help="enable scale-down elastic mode: on permanent "
                         "worker loss, relaunch the job on a smaller "
                         "mesh (resuming from the latest checkpoint) "
                         "down to this world size")
    ap.add_argument("--heartbeat-timeout", type=float, default=None,
                    help="kill a worker whose heartbeat file goes stale "
                         "for this many seconds (hung-worker detection; "
                         "charged to the restart budget)")
    ap.add_argument("script")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(argv)
    try:
        if ns.elastic_min_workers > 0:
            run_elastic(ns.nproc, ns.script, ns.args,
                        min_workers=ns.elastic_min_workers,
                        max_restarts=ns.max_restarts,
                        local_devices_per_proc=ns.devices_per_proc,
                        log_dir=ns.log_dir,
                        heartbeat_timeout=ns.heartbeat_timeout)
            return 0
        mon = launch_local_cluster(
            ns.nproc, ns.script, ns.args,
            local_devices_per_proc=ns.devices_per_proc,
            max_restarts=ns.max_restarts, log_dir=ns.log_dir,
            heartbeat_timeout=ns.heartbeat_timeout)
        mon.wait()
        return 0
    except (RuntimeError, KeyboardInterrupt) as e:
        logger.error("%s", e)
        if "mon" in locals():
            mon.stop()
        return 1


if __name__ == "__main__":
    sys.exit(main())
