"""Same-host shared-memory payload lane for the shard exchange.

BENCH_r05 measured NCF at 5.07M samples/s on-device but 1.91M with
transport — and on a multi-process single-host cluster (the dominant
TPU-VM topology: one JAX process per chip, all on one VM) every one of
those payload bytes crossed loopback TCP: two kernel copies and a
syscall per send/recv for data that already lives in the same DRAM.
This module is the fix: when :mod:`~zoo_tpu.orca.data.plane` detects
(empirically, see below) that a peer shares its host, payload bytes move
through a file in ``/dev/shm`` (tmpfs) instead of the socket. The TCP
connection stays — it carries the ZSX2 control frames (headers, shapes,
offsets) whose bytes are tiny — but the payload path becomes: server
writes the array's buffer into the mapped segment, client decodes with
``np.frombuffer`` **directly over its own mapping** of the same pages.
Zero copies, no kernel socket path.

Lifecycle — built so a SIGKILL'd peer cannot leak segments:

* one segment per multi-get response chunk, created by the server,
  named ``zoo_shm_p<pid>_<seq>_<token>`` (the pid is load-bearing: it
  is how the stale sweep decides ownership);
* the client **unlinks the file immediately after mapping it** — on
  Linux the mapping survives the unlink, and numpy's base-chain
  refcount (array → memoryview → mmap) frees the pages when the last
  decoded array dies.  From that instant nothing can leak, whichever
  side is killed;
* the server holds (fd, name) only until the client's ack frame (or
  the connection drops), then closes and best-effort unlinks (ENOENT
  expected — the client usually got there first);
* :func:`gc_stale_segments` sweeps segments whose creating pid is dead
  — the only leak window left is a server SIGKILL'd *between* creating
  a segment and the client mapping it, and every
  :class:`~zoo_tpu.orca.data.plane.ShardExchange` start runs the sweep.

Retention caveat: the segment is mapped ONCE per response chunk, so
every array decoded from that chunk shares the one mapping — retaining
any single array (even a small label column) keeps the whole chunk's
pages resident until it dies. Consumers that keep a small slice of a
chunk long-term should ``np.array(...)`` it out; the staged ingest path
(``device_put`` copies to HBM, host arrays dropped) never hits this.

Same-host detection is a direct experiment, not an IP heuristic: at
negotiation the server drops a random token into a probe file under the
shm dir and the client tries to read it back. Readable-and-matching
*is* "same host" (two hosts cannot share tmpfs); anything else — ENOENT
on the real other-host case, a permission error, a mismatch — falls
back to the TCP payload path.
"""

from __future__ import annotations

import logging
import mmap
import os
import re
import tempfile
import threading
from typing import Optional

__all__ = ["shm_dir", "SegmentWriter", "SegmentReader", "write_probe",
           "check_probe", "gc_stale_segments", "SEGMENT_PREFIX"]

logger = logging.getLogger(__name__)

SEGMENT_PREFIX = "zoo_shm_"
_NAME_RE = re.compile(r"^zoo_shm_p(\d+)_")

_seq_lock = threading.Lock()
_seq = 0


def shm_dir() -> str:  # zoo-lint: config-parse
    """Directory backing the lane: ``ZOO_SHARD_SHM_DIR`` > ``/dev/shm``
    (tmpfs — the real shared-memory path) > the tempdir (still mmap'd
    and kernel-socket-free, just disk-backed if dirty pages flush)."""
    d = os.environ.get("ZOO_SHARD_SHM_DIR")
    if d:
        return d
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def _token() -> str:
    return os.urandom(8).hex()


def _next_name() -> str:
    global _seq
    with _seq_lock:
        _seq += 1
        n = _seq
    return f"{SEGMENT_PREFIX}p{os.getpid()}_{n}_{_token()}"


class SegmentWriter:
    """Server side of one response chunk: a preallocated tmpfs file the
    payloads are appended into. Pages are reserved UP FRONT
    (``posix_fallocate``) rather than lazily on write: a full tmpfs
    must fail HERE, at construction — where the caller can still fall
    back to inline TCP payloads for the whole chunk — not as a
    mid-frame ``ENOSPC`` that tears the connection after the segment
    announce is already on the wire. The reservation is transient (the
    client unlinks at map time) and bounded by the chunk's raw bytes —
    the same pages an uncompressed chunk writes anyway."""

    def __init__(self, directory: str, nbytes: int):
        self.name = _next_name()
        self.path = os.path.join(directory, self.name)
        self.size = int(nbytes)
        self._fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR,
                           0o600)
        try:
            if hasattr(os, "posix_fallocate"):
                os.posix_fallocate(self._fd, 0, self.size)
            else:  # pragma: no cover - non-POSIX fallback
                os.ftruncate(self._fd, self.size)
        except OSError:
            self.discard()
            raise
        self._off = 0

    def write(self, payload) -> int:
        """Append one payload; returns its offset within the segment."""
        view = memoryview(payload)
        off = self._off
        if off + view.nbytes > self.size:
            raise ValueError(
                f"segment {self.name} overflow: {off}+{view.nbytes} > "
                f"{self.size} (encoder produced more than the raw upper "
                "bound — codec bug)")
        written = 0
        while written < view.nbytes:
            written += os.pwrite(self._fd, view[written:], off + written)
        self._off = off + view.nbytes
        return off

    def discard(self):
        """Close and best-effort unlink (the client normally unlinked
        already — ENOENT is the expected case)."""
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


class SegmentReader:
    """Client side: map the announced segment, then immediately unlink
    it — the mapping (and therefore every array decoded from it) stays
    valid, and from this point no crash on either side can leak the
    file. Decoded arrays keep the mapping alive through their numpy
    base chain; nothing here is ever explicitly closed."""

    def __init__(self, directory: str, name: str, size: int):
        if "/" in name or not name.startswith(SEGMENT_PREFIX):
            # the name rode the wire: never let it traverse out of the
            # negotiated shm dir
            raise ValueError(f"illegal shm segment name {name!r}")
        path = os.path.join(directory, name)
        fd = os.open(path, os.O_RDWR)
        try:
            self._map = mmap.mmap(fd, size) if size else None
            try:
                os.unlink(path)
            except OSError:
                pass
        finally:
            os.close(fd)
        self._view = memoryview(self._map) if self._map is not None else \
            memoryview(b"")
        self.size = size

    def view(self, off: int, nbytes: int) -> memoryview:
        if off + nbytes > self.size:
            raise ValueError(
                f"shm payload [{off}:{off + nbytes}] exceeds segment "
                f"size {self.size} — desynchronized stream")
        return self._view[off:off + nbytes]


def write_probe(directory: str) -> tuple:
    """Server: drop a token into a probe file; returns (basename,
    token, path). The client proving it can read the token back IS the
    same-host test."""
    token = _token()
    name = f"{SEGMENT_PREFIX}p{os.getpid()}_probe_{token}"
    path = os.path.join(directory, name)
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
    try:
        os.write(fd, token.encode("ascii"))
    finally:
        os.close(fd)
    return name, token, path


def check_probe(directory: str, name: str, token: str) -> bool:
    """Client: same host iff the server's probe file is readable here
    and carries the server's token."""
    if "/" in name or not name.startswith(SEGMENT_PREFIX):
        return False
    try:
        with open(os.path.join(directory, name), "rb") as f:
            return f.read(64).decode("ascii", "replace") == token
    except OSError:
        return False


def gc_stale_segments(directory: Optional[str] = None) -> int:
    """Unlink segments (and probes) whose creating pid no longer runs —
    the cleanup of record for a server SIGKILL'd between creating a
    segment and its client mapping it. Run by every ShardExchange
    start and by the chaos suite. Returns the number removed."""
    directory = directory or shm_dir()
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        m = _NAME_RE.match(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)  # signal 0: existence test only
            continue  # owner still alive — not ours to reap
        except ProcessLookupError:
            pass
        except PermissionError:
            continue  # alive, different uid
        try:
            os.unlink(os.path.join(directory, name))
            removed += 1
        except OSError:
            pass
    if removed:
        logger.info("shm lane: reaped %d stale segment(s) from dead "
                    "peers in %s", removed, directory)
    return removed
