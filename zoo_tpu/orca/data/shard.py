"""XShards — a partitioned collection of Python objects.

Rebuild of ``SparkXShards`` (reference: ``pyzoo/zoo/orca/data/shard.py:25,129``)
without Spark. Each shard is an arbitrary Python object — most commonly a
pandas DataFrame or a dict of numpy arrays — and transforms run per-shard.

On the reference, shards live in Spark partitions and move to Ray plasma for
training (``RayXShards``, ``orca/data/ray_xshards.py:106``). On a TPU pod the
topology is simpler and faster: shards live in host RAM of each TPU-VM
process, transforms run in a thread pool (numpy/pandas release the GIL for
the heavy parts), and the training path assembles per-host shards directly
into a globally-sharded ``jax.Array`` via
``jax.make_array_from_process_local_data`` — no object store hop at all
(SURVEY §7.4 hard part #1).

Eager semantics: the reference's SparkXShards caches eagerly by default
(``OrcaContext.eager_mode``); ``LocalXShards`` is always materialized, which
matches that contract.
"""

from __future__ import annotations

import math
import os
import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from zoo_tpu.common.context import ZooContext, get_runtime_context


def _pool_size() -> int:
    ctx = get_runtime_context(required=False)
    return max(1, ctx.cores if ctx else (os.cpu_count() or 1))


class XShards:
    """Abstract distributed collection (reference: ``shard.py:25``)."""

    def transform_shard(self, func: Callable, *args) -> "XShards":
        raise NotImplementedError

    def collect(self) -> List[Any]:
        raise NotImplementedError

    def num_partitions(self) -> int:
        raise NotImplementedError

    @staticmethod
    def partition(data, num_shards: Optional[int] = None) -> "LocalXShards":
        """Split an ndarray / dict / (nested) list-or-tuple of ndarrays into
        shards along axis 0 (reference: ``XShards.partition``,
        ``shard.py:42``). All leaves must share the same length."""
        leaves = []

        def _is_frame(d):
            try:
                import pandas as pd
            except ImportError:
                return False
            return isinstance(d, (pd.DataFrame, pd.Series))

        def _len(d):
            if isinstance(d, np.ndarray):
                leaves.append(d)
                return d.shape[0]
            if _is_frame(d):
                return len(d)
            if isinstance(d, dict):
                sizes = {k: _len(v) for k, v in d.items()}
                return next(iter(sizes.values()))
            if isinstance(d, (list, tuple)):
                return _len(d[0])
            raise ValueError(f"cannot partition data of type {type(d)}")

        n = _len(data)
        if num_shards is None:
            num_shards = _pool_size()
        num_shards = max(1, min(num_shards, n))
        bounds = np.linspace(0, n, num_shards + 1).astype(int)

        def _slice(d, lo, hi):
            if isinstance(d, np.ndarray):
                return d[lo:hi]
            if _is_frame(d):
                return d.iloc[lo:hi].reset_index(drop=True)
            if isinstance(d, dict):
                return {k: _slice(v, lo, hi) for k, v in d.items()}
            if isinstance(d, tuple):
                return tuple(_slice(v, lo, hi) for v in d)
            return [_slice(v, lo, hi) for v in d]

        shards = [_slice(data, bounds[i], bounds[i + 1])
                  for i in range(num_shards)]
        return LocalXShards(shards)


class LocalXShards(XShards):
    """Materialized in-process XShards (one list entry per shard)."""

    def __init__(self, shards: Sequence[Any]):
        self._shards = list(shards)

    # -- core API (SparkXShards parity) ----------------------------------
    def transform_shard(self, func: Callable, *args) -> "LocalXShards":
        """Apply ``func(shard, *args)`` to every shard (reference:
        ``shard.py:139``). Runs in a thread pool sized by the context's
        ``cores``."""
        with ThreadPoolExecutor(max_workers=_pool_size()) as pool:
            out = list(pool.map(lambda s: func(s, *args), self._shards))
        return LocalXShards(out)

    def collect(self) -> List[Any]:
        return list(self._shards)

    def num_partitions(self) -> int:
        return len(self._shards)

    def repartition(self, num_partitions: int) -> "LocalXShards":
        """Re-split shards into ``num_partitions`` parts. For DataFrame /
        ndarray / dict-of-ndarray shards this rebalances rows evenly
        (unlike Spark coalesce, we can do it exactly)."""
        first = self._shards[0] if self._shards else None
        if isinstance(first, np.ndarray):
            whole = np.concatenate(self._shards, axis=0)
            return XShards.partition(whole, num_partitions)
        if isinstance(first, dict) and all(
                isinstance(v, np.ndarray) for v in first.values()):
            whole = {k: np.concatenate([s[k] for s in self._shards], axis=0)
                     for k in first}
            return XShards.partition(whole, num_partitions)
        try:
            import pandas as pd
            if isinstance(first, pd.DataFrame):
                whole = pd.concat(self._shards, ignore_index=True)
                n = len(whole)
                num_partitions = max(1, min(num_partitions, max(n, 1)))
                bounds = np.linspace(0, n, num_partitions + 1).astype(int)
                return LocalXShards(
                    [whole.iloc[bounds[i]:bounds[i + 1]].reset_index(drop=True)
                     for i in range(num_partitions)])
        except ImportError:
            pass
        # generic fallback: regroup shard objects without splitting them
        groups = [[] for _ in range(num_partitions)]
        for i, s in enumerate(self._shards):
            groups[i % num_partitions].append(s)
        flat = [g if len(g) != 1 else g[0] for g in groups if g]
        return LocalXShards(flat)

    def partition_by(self, cols, num_partitions: Optional[int] = None
                     ) -> "LocalXShards":
        """Hash-partition DataFrame shards by column(s) so that equal keys
        land in the same shard (reference: ``shard.py:189``)."""
        import pandas as pd
        if isinstance(cols, str):
            cols = [cols]
        whole = pd.concat(self.collect(), ignore_index=True)
        n = num_partitions or self.num_partitions()
        codes = pd.util.hash_pandas_object(
            whole[cols], index=False).to_numpy() % n
        return LocalXShards(
            [whole[codes == i].reset_index(drop=True) for i in range(n)])

    def unique(self) -> np.ndarray:
        """Distinct values across shards of 1-D data (reference:
        ``shard.py:214``)."""
        vals = []
        for s in self._shards:
            vals.append(np.unique(np.asarray(s)))
        return np.unique(np.concatenate(vals)) if vals else np.array([])

    def split(self) -> List["LocalXShards"]:
        """Shards of tuples/lists → one XShards per element (reference:
        ``shard.py:230``)."""
        first = self._shards[0]
        if not isinstance(first, (list, tuple)):
            return [self]
        width = len(first)
        return [LocalXShards([s[i] for s in self._shards])
                for i in range(width)]

    def zip(self, other: "LocalXShards") -> "LocalXShards":
        """Pairwise-zip equal-length shard lists (reference: ``shard.py:260``;
        same constraint: identical partition count and per-partition size)."""
        if not isinstance(other, LocalXShards):
            raise ValueError("zip requires another LocalXShards")
        if other.num_partitions() != self.num_partitions():
            raise ValueError("zip requires equal numbers of partitions")
        return LocalXShards(list(zip(self._shards, other.collect())))

    def __len__(self) -> int:
        total = 0
        for s in self._shards:
            try:
                total += len(s)
            except TypeError:
                total += 1
        return total

    # -- persistence ------------------------------------------------------
    def save_pickle(self, path: str) -> "LocalXShards":
        """One pickle file per shard under ``path`` (reference:
        ``shard.py:164``)."""
        os.makedirs(path, exist_ok=True)
        width = max(5, len(str(len(self._shards))))
        for i, s in enumerate(self._shards):
            with open(os.path.join(path, f"part-{i:0{width}d}.pkl"), "wb") as f:
                pickle.dump(s, f, protocol=pickle.HIGHEST_PROTOCOL)
        return self

    @classmethod
    def load_pickle(cls, path: str) -> "LocalXShards":
        files = sorted(os.path.join(path, f) for f in os.listdir(path)
                       if f.endswith(".pkl"))
        shards = []
        for fp in files:
            with open(fp, "rb") as f:
                shards.append(pickle.load(f))
        return cls(shards)

    # -- training-path glue ----------------------------------------------
    def stack_numpy(self, cols: Optional[Sequence[str]] = None):
        """Concatenate all shards into one host-local dict of numpy arrays.

        The handoff point to :func:`zoo_tpu.parallel.mesh.host_local_to_global`
        — the rebuild of RayXShards' partition→actor streaming
        (``ray_xshards.py:250``) collapsed to a single in-process step.
        """
        shards = self.collect()
        first = shards[0]
        try:
            import pandas as pd
        except ImportError:
            pd = None
        if pd is not None and isinstance(first, pd.DataFrame):
            whole = pd.concat(shards, ignore_index=True)
            cols = cols or list(whole.columns)
            missing = [c for c in cols if c not in whole.columns]
            if missing:
                raise ValueError(f"feature/label column(s) not found: "
                                 f"{missing}; available: {list(whole.columns)}")
            return {c: whole[c].to_numpy() for c in cols}
        if isinstance(first, dict):
            keys = cols or list(first.keys())
            return {k: _concat_leaf([s[k] for s in shards]) for k in keys}
        if isinstance(first, np.ndarray):
            return np.concatenate(shards, axis=0)
        raise ValueError(f"cannot stack shards of type {type(first)}")


def _concat_leaf(parts):
    if isinstance(parts[0], (list, tuple)):
        return type(parts[0])(
            _concat_leaf([p[i] for p in parts]) for i in range(len(parts[0])))
    return np.concatenate([np.asarray(p) for p in parts], axis=0)


def shards_for_process(shards: "LocalXShards",
                       process_index: "Optional[int]" = None,
                       process_count: "Optional[int]" = None
                       ) -> "LocalXShards":
    """Select this JAX process's partitions (round-robin) — the multi-host
    data plane: each host keeps only the shards it will feed into
    ``make_array_from_process_local_data``, no driver-side collect
    (reference: ``ray_xshards.py:250`` locality-aware partition→actor
    assignment)."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pcnt = jax.process_count() if process_count is None else process_count
    parts = shards.collect()
    # every process MUST end up with the same partition count, or SPMD
    # step counts desync and the collectives hang: trim the remainder
    per = len(parts) // pcnt
    if per == 0:
        raise ValueError(f"{len(parts)} partitions cannot feed "
                         f"{pcnt} processes; repartition() first")
    if len(parts) % pcnt:
        import warnings
        warnings.warn(
            f"dropping {len(parts) % pcnt} of {len(parts)} partitions so "
            f"all {pcnt} processes hold {per}; repartition() to a "
            "multiple to keep every row")
    return LocalXShards(parts[pi::pcnt][:per])
