from zoo_tpu.orca.data.shard import XShards, LocalXShards

__all__ = ["XShards", "LocalXShards"]
