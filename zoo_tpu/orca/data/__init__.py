from zoo_tpu.orca.data.shard import XShards, LocalXShards
from zoo_tpu.orca.data.plane import (
    ExchangeConfig,
    fetch_many,
    iter_fetch,
    rebalance_shards,
)
from zoo_tpu.orca.data.ingest import (
    ReadaheadController,
    async_device_ingest,
    staged_pipeline,
)


class SharedValue:
    """reference ``orca/data/utils.py`` ``SharedValue`` — a broadcast
    handle (Spark Broadcast there). One process-space here: it simply
    carries ``.value``."""

    def __init__(self, value=None):
        self.value = value


__all__ = ["XShards", "LocalXShards", "rebalance_shards", "fetch_many",
           "iter_fetch", "ExchangeConfig", "ReadaheadController",
           "staged_pipeline", "async_device_ingest", "SharedValue"]
