from zoo_tpu.orca.data.shard import XShards, LocalXShards
from zoo_tpu.orca.data.plane import rebalance_shards

__all__ = ["XShards", "LocalXShards", "rebalance_shards"]
