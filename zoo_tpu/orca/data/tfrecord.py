"""TFRecord reader/writer (component #43 / SURVEY §2.9(7)).

The reference reads TFRecord through the ``tensorflow-hadoop`` Hadoop
InputFormat on Spark executors (``tf_dataset.py:484`` from_tfrecord_file,
``zoo/pom.xml:458``). Here the hot path is the C++ reader in
``native/zoo_native.cc`` (CRC32C-checked streaming, loaded via ctypes),
with a pure-Python fallback (struct + table CRC32C) when the toolchain is
unavailable. Sharded file sets map to XShards partitions.
"""

from __future__ import annotations

import ctypes
import glob as _glob
import struct
from typing import Callable, Iterable, Iterator, List, Optional

from zoo_tpu import native as _native

# ------------------------------------------------------- python crc32c

_PY_TABLE = None


def _py_crc32c_table():
    global _PY_TABLE
    if _PY_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            tbl.append(c)
        _PY_TABLE = tbl
    return _PY_TABLE


def crc32c(data: bytes) -> int:
    lib = _native.load()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return lib.zoo_crc32c(buf, len(data))
    tbl = _py_crc32c_table()
    c = 0xFFFFFFFF
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


class TFRecordCorruptError(IOError):
    pass


# --------------------------------------------------------------- reader

def _iter_native(path: str, check_crc: bool) -> Iterator[bytes]:
    lib = _native.load()
    h = lib.zoo_tfr_reader_open(path.encode(), 1 if check_crc else 0)
    if not h:
        raise FileNotFoundError(path)
    try:
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        while True:
            n = lib.zoo_tfr_reader_next(h, ctypes.byref(ptr))
            if n == -1:
                return
            if n == -2:
                raise TFRecordCorruptError(path)
            yield ctypes.string_at(ptr, n)
    finally:
        lib.zoo_tfr_reader_close(h)


def _iter_python(path: str, check_crc: bool) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            if len(hdr) != 12:
                raise TFRecordCorruptError(path)
            (length,) = struct.unpack("<Q", hdr[:8])
            (len_crc,) = struct.unpack("<I", hdr[8:])
            if check_crc and _masked_crc(hdr[:8]) != len_crc:
                raise TFRecordCorruptError(path)
            payload = f.read(length + 4)
            if len(payload) != length + 4:
                raise TFRecordCorruptError(path)
            data, (data_crc,) = payload[:-4], struct.unpack(
                "<I", payload[-4:])
            if check_crc and _masked_crc(data) != data_crc:
                raise TFRecordCorruptError(path)
            yield data


def tfrecord_iterator(path: str, check_crc: bool = True) -> Iterator[bytes]:
    """Stream raw records from one TFRecord file."""
    if _native.available():
        return _iter_native(path, check_crc)
    return _iter_python(path, check_crc)


def read_tfrecord(paths, parse_fn: Optional[Callable[[bytes], object]] = None,
                  check_crc: bool = True) -> List[object]:
    """Read records from a file, glob, or list of files."""
    if isinstance(paths, str):
        matched = sorted(_glob.glob(paths)) or [paths]
    else:
        matched = list(paths)
    out: List[object] = []
    for p in matched:
        for rec in tfrecord_iterator(p, check_crc):
            out.append(parse_fn(rec) if parse_fn else rec)
    return out


def read_tfrecord_shards(paths, parse_fn=None, check_crc: bool = True):
    """One XShards partition per file — the TPU analog of the reference's
    one-Hadoop-split-per-task TFRecord read."""
    from zoo_tpu.orca.data.shard import LocalXShards

    if isinstance(paths, str):
        matched = sorted(_glob.glob(paths)) or [paths]
    else:
        matched = list(paths)
    parts = [read_tfrecord(p, parse_fn, check_crc) for p in matched]
    return LocalXShards(parts)


# --------------------------------------------------------------- writer

class TFRecordWriter:
    """Append records to a TFRecord file (context manager)."""

    def __init__(self, path: str):
        self._path = path
        self._lib = _native.load()
        if self._lib is not None:
            self._h = self._lib.zoo_tfr_writer_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open {path}")
            self._f = None
        else:
            self._h = None
            self._f = open(path, "wb")

    def write(self, record: bytes):
        if self._h is not None:
            buf = (ctypes.c_uint8 * len(record)).from_buffer_copy(record)
            if self._lib.zoo_tfr_writer_write(self._h, buf, len(record)):
                raise IOError(f"write failed: {self._path}")
        else:
            hdr = struct.pack("<Q", len(record))
            self._f.write(hdr)
            self._f.write(struct.pack("<I", _masked_crc(hdr)))
            self._f.write(record)
            self._f.write(struct.pack("<I", _masked_crc(record)))

    def close(self):
        if self._h is not None:
            self._lib.zoo_tfr_writer_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_tfrecord(path: str, records: Iterable[bytes]):
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
