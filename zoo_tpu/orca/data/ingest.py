"""Staged async ingest: overlap fetch / decode / device placement.

The transport gap measured in BENCH_r05 — NCF at 5.07M samples/s
device-side but 1.91M end-to-end — is serial plumbing, not bandwidth:
each leg of the ingest path (network fetch, host decode/slice,
``jax.device_put``) waited for the previous one. This module chains the
legs into a pipeline of :class:`~zoo_tpu.orca.data.cache.
DoubleBufferedIterator` stages, one daemon thread per stage, so stage
``i`` of item ``k`` runs while stage ``i-1`` prepares item ``k+1`` —
device transfer of shard *k* overlaps the network fetch of shard *k+1*,
the same overlap the reference gets from Spark's prefetching iterators
feeding BigDL's per-executor miniBatch queues.

Every stage records its busy time into the
``zoo_shard_pipeline_stage_seconds{stage=...}`` histogram, and a
:class:`PipelineStats` passed to :func:`staged_pipeline` accumulates
per-stage busy seconds so callers (``bench.py``,
``scripts/check_data_plane.py``) can report the **overlap ratio** —
total stage-busy seconds divided by pipeline wall time; 1.0 means the
stages ran back-to-back serially, above 1.0 means real overlap.

Used by:

* :func:`zoo_tpu.orca.data.plane.rebalance_shards` (``stage_fn=`` —
  device placement streams behind the shard exchange);
* the estimator feed (``pipeline/api/keras/engine/topology.py``): the
  host-fed superbatch path splits its old slice+put staging thread into
  a slice stage and a device-put stage, so ``fit`` steps on batch ``k``
  while batch ``k+1`` transfers and batch ``k+2`` is sliced.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from zoo_tpu.obs.metrics import gauge, histogram
from zoo_tpu.orca.data.cache import DoubleBufferedIterator

__all__ = ["PipelineStats", "StagedPipeline", "staged_pipeline",
           "async_device_ingest", "ReadaheadController",
           "StagingBufferPool"]

logger = logging.getLogger(__name__)

_stage_seconds = histogram(
    "zoo_shard_pipeline_stage_seconds",
    "Busy time per ingest pipeline stage (fetch / decode / slice / "
    "device put)", labels=("stage",))


class PipelineStats:
    """Per-stage busy-seconds accumulator + wall clock for one pipeline.

    ``overlap_ratio()`` = sum of stage busy time / wall time since the
    pipeline started. A perfectly serial pipeline scores ~1.0; each
    fully-hidden stage adds ~its share above that. Thread-safe — stages
    record from their own daemon threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self.busy: Dict[str, float] = {}
        self.items: Dict[str, int] = {}
        self._t0 = time.perf_counter()
        self._t_end: Optional[float] = None

    def record(self, stage: str, dt: float):
        with self._lock:
            self.busy[stage] = self.busy.get(stage, 0.0) + dt
            self.items[stage] = self.items.get(stage, 0) + 1

    def finish(self):
        """Pin the wall clock (called when the pipeline is exhausted or
        closed; idempotent — first call wins)."""
        if self._t_end is None:
            self._t_end = time.perf_counter()

    def wall(self) -> float:
        return (self._t_end or time.perf_counter()) - self._t0

    def busy_total(self) -> float:
        with self._lock:
            return sum(self.busy.values())

    def overlap_ratio(self) -> float:
        wall = self.wall()
        if wall <= 0:
            return float("nan")
        return self.busy_total() / wall


def _timed_source(source: Iterable[Any],
                  stats: Optional[PipelineStats]):
    """Record the time spent blocked on the raw source's ``next()`` as
    the ``source`` stage (the network-fetch leg when the source is a
    streaming fetch generator) — without it the overlap ratio would
    miss the very leg the pipeline exists to hide."""
    it = iter(source)
    child = _stage_seconds.labels(stage="source")
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        dt = time.perf_counter() - t0
        child.observe(dt)
        if stats is not None:
            stats.record("source", dt)
        yield item


class StagedPipeline:
    """A chain of double-buffered stages over ``source``.

    Iterating yields fully-staged items; ``close()`` (or exiting the
    context manager) stops every stage thread, outermost first, so an
    early-exiting consumer cannot strand a producer pinning staged
    device buffers."""

    def __init__(self, source: Iterable[Any],
                 stages: List[Tuple[str, Optional[Callable[[Any], Any]]]],
                 depth: int = 2, stats: Optional[PipelineStats] = None):
        self.stats = stats
        self._iters: List[DoubleBufferedIterator] = []
        it: Iterable[Any] = _timed_source(source, self.stats)
        for name, fn in stages:
            it = DoubleBufferedIterator(it,
                                        stage_fn=self._timed(name, fn),
                                        depth=depth)
            self._iters.append(it)
        self._tail = it

    def _timed(self, name: str, fn: Optional[Callable[[Any], Any]]):
        stats = self.stats
        child = _stage_seconds.labels(stage=name)

        def run(item):
            t0 = time.perf_counter()
            out = fn(item) if fn is not None else item
            dt = time.perf_counter() - t0
            child.observe(dt)
            if stats is not None:
                stats.record(name, dt)
            return out

        return run

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._tail)
        except StopIteration:
            if self.stats is not None:
                self.stats.finish()
            raise

    def close(self):
        # outermost first: stop consumers before their producers so the
        # inner close never races a stage thread mid-put
        for it in reversed(self._iters):
            it.close()
        if self.stats is not None:
            self.stats.finish()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def staged_pipeline(source: Iterable[Any],
                    stages: List[Tuple[str,
                                       Optional[Callable[[Any], Any]]]],
                    depth: int = 2,
                    stats: Optional[PipelineStats] = None
                    ) -> StagedPipeline:
    """Chain ``stages`` = [(name, fn-or-None), ...] over ``source``.

    Each stage gets its own staging thread and a bounded queue of
    ``depth`` in-flight items. A stage with ``fn=None`` is a pure
    prefetch stage — useful to give a slow *source* (a network fetch
    generator) its own thread so downstream stages overlap it."""
    return StagedPipeline(source, stages, depth=depth, stats=stats)


_readahead_gauge = gauge(
    "zoo_shard_readahead",
    "Live readahead knob values chosen by the adaptive controller",
    labels=("knob",))


class ReadaheadController:
    """Close the loop between :class:`PipelineStats` and the fetch
    knobs: grow/shrink ``config.concurrency`` and ``config.multiget``
    toward the point where the fetch leg fully hides under
    decode + device placement.

    The signal is the *window share* of the ``source`` stage (the
    network-fetch leg) in pipeline wall time since the last decision —
    deltas, not cumulative totals, so late-exchange behavior is not
    damped by early-exchange history:

    * share > ``high`` — the pipeline is starving on fetch. Double the
      fetch concurrency first (parallelism is the cheap lever), then
      the multi-get chunk (fewer round trips per byte, at the cost of
      coarser retry granularity).
    * share < ``low`` — fetch is already fully hidden with room to
      spare: step concurrency back down one worker. Narrower readahead
      means fewer staged shards pinned in host memory, and the
      asymmetric walk (×2 up, −1 down) keeps the controller from
      oscillating.

    ``config`` is the single mutation point (`ExchangeConfig`; env
    parsed once at its construction): :func:`~zoo_tpu.orca.data.plane.
    iter_fetch` re-reads it when carving each chunk, so decisions take
    effect mid-exchange without tearing anything down. Thread-safe —
    ``on_chunk`` is called from fetch worker threads. The decision
    trail is kept on ``decisions`` (and exported through the
    ``zoo_shard_readahead`` gauge) so benches report what the
    controller actually did rather than asserting it."""

    def __init__(self, config, stats: Optional[PipelineStats] = None,
                 min_chunk: int = 4, max_chunk: int = 256,
                 min_concurrency: int = 1, max_concurrency: int = 32,
                 window: int = 4, high: float = 0.55, low: float = 0.25):
        self.config = config
        self.stats = stats
        self.min_chunk, self.max_chunk = min_chunk, max_chunk
        self.min_concurrency = min_concurrency
        self.max_concurrency = max_concurrency
        self.window = max(1, window)
        self.high, self.low = high, low
        self.decisions: List[Tuple[int, int]] = []
        self._lock = threading.Lock()
        self._chunks = 0
        self._last_wall = 0.0
        self._last_src = 0.0

    def on_chunk(self, ngids: int, nbytes: int, seconds: float):
        with self._lock:
            self._chunks += 1
            if self._chunks % self.window:
                return
            self._decide()

    def _decide(self):
        st = self.stats
        if st is None:
            return
        wall = st.wall()
        src = st.busy.get("source", 0.0)
        dw = wall - self._last_wall
        ds = src - self._last_src
        if dw <= 0:
            return
        self._last_wall, self._last_src = wall, src
        share = ds / dw
        cfg = self.config
        if share > self.high:
            if cfg.concurrency < self.max_concurrency:
                cfg.concurrency = min(self.max_concurrency,
                                      cfg.concurrency * 2)
            elif cfg.multiget < self.max_chunk:
                cfg.multiget = min(self.max_chunk, cfg.multiget * 2)
            else:
                return
        elif share < self.low:
            # unwind in reverse order of growth: width first, then the
            # chunk back toward its floor (fine retry granularity costs
            # nothing once fetch is fully hidden)
            if cfg.concurrency > self.min_concurrency:
                cfg.concurrency -= 1
            elif cfg.multiget > self.min_chunk:
                cfg.multiget = max(self.min_chunk, cfg.multiget // 2)
            else:
                return
        else:
            return
        self.decisions.append((cfg.concurrency, cfg.multiget))
        _readahead_gauge.labels(knob="concurrency").set(cfg.concurrency)
        _readahead_gauge.labels(knob="multiget").set(cfg.multiget)
        logger.debug("readahead: source share %.2f -> concurrency=%d "
                     "multiget=%d", share, cfg.concurrency, cfg.multiget)


# ------------------------------------------------- staged host buffers


def _misaligned_empty(shape, dtype) -> np.ndarray:
    """Host buffer whose data pointer is deliberately NOT 16-byte
    aligned (addr % 16 == 8). XLA:CPU's zero-copy ``device_put`` fast
    path only engages for suitably aligned host buffers (16- or 64-byte
    depending on version), and whether a given numpy allocation lands
    aligned is allocator luck — "does device_put copy?" is a property
    of the ALLOCATION, not the backend. Staging buffers must always be
    copied (an aliased buffer's reuse would mutate the device value),
    so make the property deterministic: an 8-mod-16 address never
    qualifies for zero-copy yet satisfies every real dtype's (<=8-byte)
    alignment."""
    dt = np.dtype(dtype)
    count = 1
    for s in shape:
        count *= int(s)
    nbytes = count * dt.itemsize
    if dt.itemsize > 8 or not nbytes:
        return np.empty(shape, dt)  # exotic/empty: the probe decides
    raw = np.empty(nbytes + 16, np.uint8)
    off = (8 - raw.ctypes.data % 16) % 16
    return raw[off:off + nbytes].view(dt).reshape(shape)


def _buffer_aliased_on_device(buf: np.ndarray) -> bool:
    """Directly test whether ``jax.device_put`` aliases THIS buffer's
    memory: put a head view, mutate the host bytes, read the device
    value back. The zero-copy decision keys on the data pointer, so
    the head answers for the whole buffer — a per-buffer test, because
    a process-global probe of one throwaway array provably flips with
    that array's own (random) alignment."""
    if not buf.size:
        return False
    import jax
    head = buf.reshape(-1).view(np.uint8)[:16]
    head[0] = 0
    dev = jax.device_put(head)
    jax.block_until_ready(dev)
    head[0] = 255
    return int(np.asarray(dev)[0]) == 255


class StagingBufferPool:
    """Rotating preallocated host staging buffers for the host-fed
    superbatch feed — the double-buffered ``device_put`` leg of the
    ingest path.

    Without it, every superbatch slice allocates fresh host arrays
    (allocator churn + cold pages on the DMA path). With it, the slice
    stage writes each superbatch into one of ``nbufs`` preallocated
    buffers via ``np.take(..., out=...)``, and the put stage returns
    the buffer to the pool only after ``block_until_ready`` confirms
    the host→device transfer read it — so the DMA of batch *k* safely
    overlaps the slicing of batch *k+1* into a different buffer.

    FIFO discipline: the pipeline's stages hand items over in order
    (one slice thread, one put thread), so ``recycle()`` frees the
    oldest outstanding buffer with no per-item bookkeeping. ``nbufs``
    must exceed the pipeline's maximum in-flight items (slice holds 1,
    each stage queue holds ``depth``, put holds 1 → 3 at depth 1;
    default 4 leaves margin).

    Safety: a reused buffer must never be aliased by ``device_put``
    (XLA:CPU zero-copies suitably aligned host arrays — recycling an
    aliased buffer would mutate the live device value). Buffers are
    therefore allocated OFF the zero-copy alignment
    (:func:`_misaligned_empty`) and ``maybe_create`` additionally
    probes each one (:func:`_buffer_aliased_on_device`), returning
    ``None`` — plain slicing — if any still aliases. The
    ``ZOO_FEED_STAGING`` env kill switch forces ``None`` outright.
    """

    def __init__(self, arrs, rows: int, nbufs: int = 4):
        self._slots = [[_misaligned_empty((rows,) + a.shape[1:], a.dtype)
                        for a in arrs] for _ in range(nbufs)]
        self._free: "queue.Queue" = queue.Queue()
        for i in range(nbufs):
            self._free.put(i)
        self._inflight: List[int] = []
        self._lock = threading.Lock()
        self._gen = 0
        self.rows = rows

    @staticmethod
    def maybe_create(arrs, rows: int, nbufs: int = 4,  # zoo-lint: config-parse
                     max_bytes: int = 2 << 30) -> Optional[
                         "StagingBufferPool"]:
        mode = os.environ.get("ZOO_FEED_STAGING", "auto").lower()
        if mode in ("0", "off"):
            return None
        if rows <= 0 or not arrs:
            return None
        if any(not isinstance(a, np.ndarray) or a.dtype.hasobject
               for a in arrs):
            return None
        row_bytes = sum(a[:1].nbytes for a in arrs)
        if row_bytes * rows * nbufs > max_bytes:
            return None  # the pool would dwarf the dataset's own copies
        pool = StagingBufferPool(arrs, rows, nbufs=nbufs)
        # every _misaligned_empty buffer shares the same deterministic
        # 8-mod-16 alignment, so ONE probe answers for all of them —
        # per-buffer probes are only needed for the np.empty fallback
        # (itemsize > 8), whose alignment genuinely is allocator luck.
        # Each probe is a blocking device round trip; probing all
        # nbufs x n_arrays buffers would tax every fit() start.
        to_probe, probed_misaligned = [], False
        for slot in pool._slots:
            for b in slot:
                if b.dtype.itemsize > 8:
                    to_probe.append(b)
                elif not probed_misaligned and b.size:
                    probed_misaligned = True
                    to_probe.append(b)
        try:
            aliased = any(_buffer_aliased_on_device(b) for b in to_probe)
        except Exception:  # no devices / weird backend: stay off
            return None
        if aliased:
            logger.info("staging buffers disabled: jax.device_put "
                        "aliases a staging buffer on this backend")
            return None
        return pool

    def take(self, arrs, idx, gen: Optional[int] = None,
             timeout: float = 30.0) -> List[np.ndarray]:
        """Slice ``arrs[i][idx]`` into the next free buffer; returns
        views sized to ``len(idx)`` (the ragged-tail superbatch just
        uses a prefix of the buffer).

        ``gen`` is the generation token :meth:`reset` returned. A call
        carrying a superseded token gets plain freshly-allocated slices
        and never touches the pool — the caller is a zombie stage
        thread from a torn-down pipeline (``DoubleBufferedIterator.
        close()`` does not join), and letting it occupy a slot would
        hand the NEW pipeline's buffers to output nobody consumes."""
        with self._lock:
            superseded = gen is not None and gen != self._gen
        idx = np.asarray(idx)
        if superseded:
            return [a[idx] for a in arrs]
        try:
            slot = self._free.get(timeout=timeout)
        except queue.Empty:
            raise RuntimeError(
                f"staging buffer pool starved for {timeout:g}s — the "
                "device_put stage stopped recycling (stuck transfer?)"
            ) from None
        n = len(idx)
        out = []
        for a, buf in zip(arrs, self._slots[slot]):
            view = buf[:n]
            np.take(a, idx, axis=0, out=view)
            out.append(view)
        with self._lock:
            if gen is not None and gen != self._gen:
                # reset() ran while we held the slot: hand it straight
                # back so the new generation keeps full capacity, and
                # give the zombie caller throwaway copies instead of
                # views into a slot the new pipeline may now be filling
                self._free.put(slot)
                return [a[idx] for a in arrs]
            self._inflight.append(slot)
        return out

    def recycle(self, gen: Optional[int] = None):
        """The oldest outstanding buffer's transfer is complete: make
        it available to the slice stage again. A superseded ``gen``
        token is a no-op — a zombie device_put thread finishing after
        :meth:`reset` must not free the new generation's oldest
        in-flight slot mid-DMA."""
        with self._lock:
            if gen is not None and gen != self._gen:
                return
            slot = self._inflight.pop(0) if self._inflight else None
        if slot is not None:
            self._free.put(slot)

    def reset(self) -> int:
        """Free every outstanding buffer and start a new generation
        (epoch boundary / after a pipeline teardown mid-epoch).
        Returns the new generation token; stage closures pass it back
        to :meth:`take`/:meth:`recycle` so threads surviving a
        non-joining teardown are fenced off from the new epoch's
        slots."""
        with self._lock:
            stale, self._inflight = self._inflight, []
            self._gen += 1
            gen = self._gen
        for slot in stale:
            self._free.put(slot)
        return gen


def async_device_ingest(shards: Iterable[Any], put_fn=None,
                        depth: int = 2,
                        stats: Optional[PipelineStats] = None
                        ) -> StagedPipeline:
    """Iterate ``shards`` with device placement running one item ahead.

    ``put_fn`` defaults to ``jax.device_put`` (applied to the whole
    shard pytree). The source iterable is drained on a prefetch thread
    and placement happens on a second stage thread, so the consumer's
    compute, the device transfer, and the source's own work (e.g. a
    streaming shard fetch) all overlap."""
    if put_fn is None:
        import jax
        put_fn = jax.device_put
    return staged_pipeline(iter(shards),
                           [("fetch", None), ("device_put", put_fn)],
                           depth=depth, stats=stats)
