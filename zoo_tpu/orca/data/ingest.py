"""Staged async ingest: overlap fetch / decode / device placement.

The transport gap measured in BENCH_r05 — NCF at 5.07M samples/s
device-side but 1.91M end-to-end — is serial plumbing, not bandwidth:
each leg of the ingest path (network fetch, host decode/slice,
``jax.device_put``) waited for the previous one. This module chains the
legs into a pipeline of :class:`~zoo_tpu.orca.data.cache.
DoubleBufferedIterator` stages, one daemon thread per stage, so stage
``i`` of item ``k`` runs while stage ``i-1`` prepares item ``k+1`` —
device transfer of shard *k* overlaps the network fetch of shard *k+1*,
the same overlap the reference gets from Spark's prefetching iterators
feeding BigDL's per-executor miniBatch queues.

Every stage records its busy time into the
``zoo_shard_pipeline_stage_seconds{stage=...}`` histogram, and a
:class:`PipelineStats` passed to :func:`staged_pipeline` accumulates
per-stage busy seconds so callers (``bench.py``,
``scripts/check_data_plane.py``) can report the **overlap ratio** —
total stage-busy seconds divided by pipeline wall time; 1.0 means the
stages ran back-to-back serially, above 1.0 means real overlap.

Used by:

* :func:`zoo_tpu.orca.data.plane.rebalance_shards` (``stage_fn=`` —
  device placement streams behind the shard exchange);
* the estimator feed (``pipeline/api/keras/engine/topology.py``): the
  host-fed superbatch path splits its old slice+put staging thread into
  a slice stage and a device-put stage, so ``fit`` steps on batch ``k``
  while batch ``k+1`` transfers and batch ``k+2`` is sliced.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from zoo_tpu.obs.metrics import histogram
from zoo_tpu.orca.data.cache import DoubleBufferedIterator

__all__ = ["PipelineStats", "StagedPipeline", "staged_pipeline",
           "async_device_ingest"]

_stage_seconds = histogram(
    "zoo_shard_pipeline_stage_seconds",
    "Busy time per ingest pipeline stage (fetch / decode / slice / "
    "device put)", labels=("stage",))


class PipelineStats:
    """Per-stage busy-seconds accumulator + wall clock for one pipeline.

    ``overlap_ratio()`` = sum of stage busy time / wall time since the
    pipeline started. A perfectly serial pipeline scores ~1.0; each
    fully-hidden stage adds ~its share above that. Thread-safe — stages
    record from their own daemon threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self.busy: Dict[str, float] = {}
        self.items: Dict[str, int] = {}
        self._t0 = time.perf_counter()
        self._t_end: Optional[float] = None

    def record(self, stage: str, dt: float):
        with self._lock:
            self.busy[stage] = self.busy.get(stage, 0.0) + dt
            self.items[stage] = self.items.get(stage, 0) + 1

    def finish(self):
        """Pin the wall clock (called when the pipeline is exhausted or
        closed; idempotent — first call wins)."""
        if self._t_end is None:
            self._t_end = time.perf_counter()

    def wall(self) -> float:
        return (self._t_end or time.perf_counter()) - self._t0

    def busy_total(self) -> float:
        with self._lock:
            return sum(self.busy.values())

    def overlap_ratio(self) -> float:
        wall = self.wall()
        if wall <= 0:
            return float("nan")
        return self.busy_total() / wall


def _timed_source(source: Iterable[Any],
                  stats: Optional[PipelineStats]):
    """Record the time spent blocked on the raw source's ``next()`` as
    the ``source`` stage (the network-fetch leg when the source is a
    streaming fetch generator) — without it the overlap ratio would
    miss the very leg the pipeline exists to hide."""
    it = iter(source)
    child = _stage_seconds.labels(stage="source")
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        dt = time.perf_counter() - t0
        child.observe(dt)
        if stats is not None:
            stats.record("source", dt)
        yield item


class StagedPipeline:
    """A chain of double-buffered stages over ``source``.

    Iterating yields fully-staged items; ``close()`` (or exiting the
    context manager) stops every stage thread, outermost first, so an
    early-exiting consumer cannot strand a producer pinning staged
    device buffers."""

    def __init__(self, source: Iterable[Any],
                 stages: List[Tuple[str, Optional[Callable[[Any], Any]]]],
                 depth: int = 2, stats: Optional[PipelineStats] = None):
        self.stats = stats
        self._iters: List[DoubleBufferedIterator] = []
        it: Iterable[Any] = _timed_source(source, self.stats)
        for name, fn in stages:
            it = DoubleBufferedIterator(it,
                                        stage_fn=self._timed(name, fn),
                                        depth=depth)
            self._iters.append(it)
        self._tail = it

    def _timed(self, name: str, fn: Optional[Callable[[Any], Any]]):
        stats = self.stats
        child = _stage_seconds.labels(stage=name)

        def run(item):
            t0 = time.perf_counter()
            out = fn(item) if fn is not None else item
            dt = time.perf_counter() - t0
            child.observe(dt)
            if stats is not None:
                stats.record(name, dt)
            return out

        return run

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._tail)
        except StopIteration:
            if self.stats is not None:
                self.stats.finish()
            raise

    def close(self):
        # outermost first: stop consumers before their producers so the
        # inner close never races a stage thread mid-put
        for it in reversed(self._iters):
            it.close()
        if self.stats is not None:
            self.stats.finish()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def staged_pipeline(source: Iterable[Any],
                    stages: List[Tuple[str,
                                       Optional[Callable[[Any], Any]]]],
                    depth: int = 2,
                    stats: Optional[PipelineStats] = None
                    ) -> StagedPipeline:
    """Chain ``stages`` = [(name, fn-or-None), ...] over ``source``.

    Each stage gets its own staging thread and a bounded queue of
    ``depth`` in-flight items. A stage with ``fn=None`` is a pure
    prefetch stage — useful to give a slow *source* (a network fetch
    generator) its own thread so downstream stages overlap it."""
    return StagedPipeline(source, stages, depth=depth, stats=stats)


def async_device_ingest(shards: Iterable[Any], put_fn=None,
                        depth: int = 2,
                        stats: Optional[PipelineStats] = None
                        ) -> StagedPipeline:
    """Iterate ``shards`` with device placement running one item ahead.

    ``put_fn`` defaults to ``jax.device_put`` (applied to the whole
    shard pytree). The source iterable is drained on a prefetch thread
    and placement happens on a second stage thread, so the consumer's
    compute, the device transfer, and the source's own work (e.g. a
    streaming shard fetch) all overlap."""
    if put_fn is None:
        import jax
        put_fn = jax.device_put
    return staged_pipeline(iter(shards),
                           [("fetch", None), ("device_put", put_fn)],
                           depth=depth, stats=stats)
