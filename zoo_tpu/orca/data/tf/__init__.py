from zoo_tpu.orca.data.tf.data import Dataset  # noqa: F401

__all__ = ["Dataset"]
