"""Orca TF-data bridge (reference: ``pyzoo/zoo/orca/data/tf/data.py:124``
— ``Dataset.from_tensor_slices(xshards)`` + ``map`` building a deferred
tf.data pipeline per worker).

The rebuild's estimators consume tf.data datasets and XShards directly
(``data_utils.to_xy_arrays``), so this module is the thin deferred
builder that keeps the reference's composition style working: build from
XShards (or arrays), chain ``map``s, and either hand the result to an
estimator (it materializes lazily) or export a real ``tf.data.Dataset``.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np


class Dataset:
    """Deferred per-element dataset over XShards / arrays (reference
    ``Dataset`` + ``TensorSliceDataset`` + ``MapDataset`` collapsed)."""

    def __init__(self, elements: List, fns: Optional[List[Callable]] = None):
        self._elements = elements
        self._fns = list(fns or [])

    # -- construction (reference Dataset.from_tensor_slices:190) ----------
    @staticmethod
    def from_tensor_slices(data) -> "Dataset":
        """``data``: XShards of dicts/arrays, a dict of arrays, an array,
        or a tuple of arrays — sliced along axis 0 like
        ``tf.data.Dataset.from_tensor_slices``."""
        from zoo_tpu.orca.data.shard import LocalXShards

        if isinstance(data, LocalXShards):
            elements = []
            for shard in data.collect():
                elements.extend(_slice_rows(shard))
            return Dataset(elements)
        return Dataset(_slice_rows(data))

    def map(self, fn: Callable) -> "Dataset":
        """Deferred per-element transform (reference ``MapDataset``)."""
        return Dataset(self._elements, self._fns + [fn])

    # -- materialization ---------------------------------------------------
    def _realized(self):
        out = self._elements
        for fn in self._fns:
            out = [fn(e) for e in out]
        return out

    def to_numpy(self):
        """(x, y) arrays. Element shapes map back like tf.data:
        2-tuples split into (features, labels); longer tuples become a
        list of feature arrays (no labels); dict rows become a dict of
        stacked column arrays; plain rows stack as features."""
        rows = self._realized()
        if not rows:
            raise ValueError("empty dataset")
        first = rows[0]
        if isinstance(first, tuple) and len(first) == 2:
            xs = np.stack([np.asarray(r[0]) for r in rows])
            ys = np.stack([np.asarray(r[1]) for r in rows])
            return xs, ys
        if isinstance(first, tuple):
            return [np.stack([np.asarray(r[i]) for r in rows])
                    for i in range(len(first))], None
        if isinstance(first, dict):
            return {k: np.stack([np.asarray(r[k]) for r in rows])
                    for k in first}, None
        return np.stack([np.asarray(r) for r in rows]), None

    def to_tf_dataset(self, batch_size: Optional[int] = None):
        """Export a real ``tf.data.Dataset`` (needs tensorflow)."""
        import tensorflow as tf

        x, y = self.to_numpy()
        if isinstance(x, list):
            x = tuple(x)
        ds = tf.data.Dataset.from_tensor_slices((x, y) if y is not None
                                                else x)
        return ds.batch(batch_size) if batch_size else ds

    def __len__(self):
        return len(self._elements)


def _check_equal_lengths(arrays):
    lengths = {len(a) for a in arrays}
    if len(lengths) > 1:
        raise ValueError(
            f"from_tensor_slices components disagree on length: "
            f"{sorted(lengths)} (tf.data raises on this too)")


def _slice_rows(data) -> List:
    if isinstance(data, dict):
        if "x" in data:
            xs = np.asarray(data["x"])
            ys = data.get("y")
            if ys is not None:
                ys = np.asarray(ys)
                _check_equal_lengths([xs, ys])
                return list(zip(xs, ys))
            return list(xs)
        keys = sorted(data)
        cols = [np.asarray(data[k]) for k in keys]
        _check_equal_lengths(cols)
        return [dict(zip(keys, row)) for row in zip(*cols)]
    if isinstance(data, tuple):
        cols = [np.asarray(c) for c in data]
        _check_equal_lengths(cols)
        return list(zip(*cols))
    return list(np.asarray(data))
