"""Tiered training-data cache + double-buffered device feeder.

Rebuild of the reference's FeatureSet memory tiers (SURVEY §2 #20): the
JVM FeatureSet caches training samples in DRAM, Optane PMEM, off-heap
DIRECT buffers, or disk (``feature/FeatureSet.scala:52-233``, tier picked
by ``OrcaContext.train_data_store``, ``orca/common.py:86-103``). TPU VMs
have no PMEM, so the beyond-DRAM tier is a local-SSD spill file managed by
the C++ buffer manager in ``native/zoo_native.cc`` (pure-Python dict/file
fallback when the toolchain is absent).

``DoubleBufferedIterator`` is the host→device leg: a background thread
stages batch i+1 (cache read + unpickle + ``jax.device_put``) while the
step function runs batch i — the reference gets the same overlap from
Spark's prefetching iterators feeding BigDL's per-executor miniBatch
queues.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import queue
import tempfile
import threading
import weakref
from typing import Any, Iterable, Iterator, Optional

from zoo_tpu import native as _native
from zoo_tpu.common.context import ZooContext


def _dram_budget_for(store: str, total_hint: Optional[int]) -> int:
    """Map the reference's tier string to a DRAM byte budget: DRAM → no
    limit; DISK_n → dataset is ~n× DRAM capacity, i.e. keep 1/n of the
    bytes resident (the reference uses n the same way for PMEM sizing)."""
    store = store.upper()
    if store == "DRAM":
        return -1
    if store.startswith("DISK"):
        try:
            n = int(store.split("_", 1)[1])
        except (IndexError, ValueError):
            n = 2
        if total_hint:
            return max(1, total_hint // max(n, 1))
        return 512 * 1024 * 1024 // max(n, 1)
    raise ValueError(f"unknown train_data_store {store!r}")


class TieredSampleCache:
    """Append-only blob cache with DRAM budget + disk spill.

    ``put`` pickles an arbitrary sample/batch; ``get`` returns it.
    Backed by the native C++ cache when available.
    """

    def __init__(self, store: Optional[str] = None,
                 dram_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 total_bytes_hint: Optional[int] = None):
        store = store or ZooContext.train_data_store
        self._budget = (dram_budget if dram_budget is not None
                        else _dram_budget_for(store, total_bytes_hint))
        self._spill_dir = spill_dir or tempfile.gettempdir()
        self._spill_path = os.path.join(
            self._spill_dir, f"zoo_cache_{os.getpid()}_{id(self):x}.bin")
        self._lib = _native.load()
        self._lock = threading.Lock()
        if self._lib is not None:
            self._h = self._lib.zoo_cache_create(self._budget,
                                                 self._spill_path.encode())
        else:  # pure-Python tiers
            self._h = None
            self._ram: dict = {}
            self._disk_index: dict = {}
            self._dram_used = 0
            self._spill_f = None

    # -- core --------------------------------------------------------------
    def put(self, obj: Any) -> int:
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if self._h is not None:
            buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
            idx = self._lib.zoo_cache_put(self._h, buf, len(blob))
            if idx < 0:
                raise IOError("cache put failed (spill tier unavailable?)")
            return int(idx)
        with self._lock:
            idx = len(self._ram) + len(self._disk_index)
            fits = self._budget < 0 or \
                self._dram_used + len(blob) <= self._budget
            if fits:
                self._ram[idx] = blob
                self._dram_used += len(blob)
            else:
                if self._spill_f is None:
                    self._spill_f = open(self._spill_path, "w+b")
                self._spill_f.seek(0, os.SEEK_END)
                off = self._spill_f.tell()
                self._spill_f.write(blob)
                self._disk_index[idx] = (off, len(blob))
            return idx

    def get(self, idx: int) -> Any:
        if self._h is not None:
            n = self._lib.zoo_cache_len(self._h, idx)
            if n < 0:
                raise IndexError(idx)
            buf = (ctypes.c_uint8 * n)()
            got = self._lib.zoo_cache_get(self._h, idx, buf, n)
            if got != n:
                raise IOError(f"cache get failed for {idx}")
            return pickle.loads(bytes(buf))
        with self._lock:
            if idx in self._ram:
                return pickle.loads(self._ram[idx])
            if idx in self._disk_index:
                off, n = self._disk_index[idx]
                self._spill_f.seek(off)
                return pickle.loads(self._spill_f.read(n))
        raise IndexError(idx)

    def __len__(self) -> int:
        if self._h is not None:
            return int(self._lib.zoo_cache_count(self._h))
        with self._lock:
            return len(self._ram) + len(self._disk_index)

    def dram_used(self) -> int:
        if self._h is not None:
            return int(self._lib.zoo_cache_dram_used(self._h))
        with self._lock:
            return self._dram_used

    def __iter__(self) -> Iterator[Any]:
        for i in range(len(self)):
            yield self.get(i)

    def close(self):
        if self._h is not None:
            self._lib.zoo_cache_destroy(self._h)
            self._h = None
            self._lib = None
        elif getattr(self, "_spill_f", None) is not None:
            self._spill_f.close()
            try:
                os.unlink(self._spill_path)
            except OSError:
                pass
            self._spill_f = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class CachedDataset:
    """Cache an iterable of batches once, then replay epochs from the
    tiered store (the FeatureSet.cache() usage pattern)."""

    def __init__(self, batches: Iterable[Any], **cache_kwargs):
        self._cache = TieredSampleCache(**cache_kwargs)
        for b in batches:
            self._cache.put(b)

    def __len__(self):
        return len(self._cache)

    def __iter__(self):
        return iter(self._cache)

    def close(self):
        self._cache.close()


class DoubleBufferedIterator:
    """Wrap an iterator; a daemon thread keeps ``depth`` items staged
    ahead (optionally through ``stage_fn``, e.g. ``jax.device_put``)."""

    _END = object()

    def __init__(self, it: Iterable[Any], stage_fn=None, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._err_box: list = []  # producer's exception, if any
        self._stop = threading.Event()
        # The producer closure must NOT capture self: the live thread would
        # then keep the iterator reachable and the GC finalizer below could
        # never fire for an abandoned consumer.
        q, stop, err_box, end = self._q, self._stop, self._err_box, self._END

        def run():
            try:
                for item in it:
                    staged = stage_fn(item) if stage_fn else item
                    # bounded put that aborts when the consumer closed us,
                    # so an early-exiting consumer cannot strand the
                    # producer (and its device-resident batch) forever
                    while not stop.is_set():
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate into consumer
                err_box.append(e)
            finally:
                # END must arrive or the consumer blocks forever; bounded
                # retry so close() can still release us.
                while not stop.is_set():
                    try:
                        q.put(end, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()
        # a consumer that abandons iteration without close() must not strand
        # the producer retrying puts (pinning staged device batches): stop it
        # when the iterator is collected (the Event outlives self safely)
        weakref.finalize(self, self._stop.set)

    def close(self):
        """Stop the producer and drop staged items."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return self

    def __next__(self):
        # after close() the queue may already be drained (close() eats the
        # END sentinel) — never park forever on a stopped producer
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                continue
        if item is self._END:
            self._stop.set()  # latch: later __next__ calls must not spin
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        return item
