from zoo_tpu.orca.data.pandas.preprocessing import (  # noqa: F401
    read_csv,
    read_json,
    read_parquet,
)

__all__ = ["read_csv", "read_json", "read_parquet"]
