from zoo_tpu.orca.data.pandas.preprocessing import read_csv, read_json

__all__ = ["read_csv", "read_json"]
