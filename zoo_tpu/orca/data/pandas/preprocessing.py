"""pandas-backed readers producing XShards.

Rebuild of ``pyzoo/zoo/orca/data/pandas/preprocessing.py`` (``read_csv`` /
``read_json`` over local/hdfs/s3 into SparkXShards of DataFrames). Here the
file list is read in a thread pool sized by the context ``cores``; the
``OrcaContext.pandas_read_backend`` flag selects pandas or pyarrow parsing,
mirroring the reference's "pandas" vs "spark" backends.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from zoo_tpu.common.context import ZooContext
from zoo_tpu.orca.data.file import list_files
from zoo_tpu.orca.data.shard import LocalXShards, _pool_size


def _read_one_csv(path: str, **kwargs):
    if ZooContext.pandas_read_backend == "arrow":
        from pyarrow import csv as pacsv
        # map the common pandas kwargs onto pyarrow
        opts = {}
        if "names" in kwargs:
            opts["column_names"] = kwargs["names"]
        if kwargs.get("header", "infer") is None and "names" not in kwargs:
            opts["autogenerate_column_names"] = True
        ropt = pacsv.ReadOptions(**opts)
        popt = pacsv.ParseOptions(delimiter=kwargs.get("sep", ","))
        table = pacsv.read_csv(path, read_options=ropt, parse_options=popt)
        df = table.to_pandas()
        if "usecols" in kwargs:
            df = df[list(kwargs["usecols"])]
        if "dtype" in kwargs:
            df = df.astype(kwargs["dtype"])
        return df
    import pandas as pd
    return pd.read_csv(path, **kwargs)


def _read_one_json(path: str, **kwargs):
    import pandas as pd
    return pd.read_json(path, **kwargs)


def _read_files(paths: List[str], reader, num_shards: Optional[int], **kwargs
                ) -> LocalXShards:
    if not paths:
        raise FileNotFoundError("no input files found")
    with ThreadPoolExecutor(max_workers=_pool_size()) as pool:
        dfs = list(pool.map(lambda p: reader(p, **kwargs), paths))
    shards = LocalXShards(dfs)
    if ZooContext.shard_size:  # rows-per-shard flag wins over num_shards
        total = sum(len(d) for d in dfs)
        nparts = max(1, -(-total // ZooContext.shard_size))
        return shards.repartition(nparts)
    if num_shards and num_shards != shards.num_partitions():
        return shards.repartition(num_shards)
    return shards


def read_csv(file_path: str, num_shards: Optional[int] = None, **kwargs
             ) -> LocalXShards:
    """Read csv file(s)/folder/glob into an XShards of pandas DataFrames
    (reference: ``preprocessing.py`` ``read_csv``). Extra kwargs pass through
    to the underlying reader."""
    return _read_files(list_files(file_path), _read_one_csv, num_shards,
                       **kwargs)


def read_json(file_path: str, num_shards: Optional[int] = None, **kwargs
              ) -> LocalXShards:
    """Read json file(s) into an XShards of pandas DataFrames (reference:
    ``preprocessing.py`` ``read_json``)."""
    return _read_files(list_files(file_path), _read_one_json, num_shards,
                       **kwargs)


def _read_one_parquet(path, **kwargs):
    import pandas as pd
    return pd.read_parquet(path, **kwargs)


def read_parquet(file_path: str, num_shards: Optional[int] = None,
                 **kwargs) -> LocalXShards:
    """Read parquet file(s)/folder into an XShards of pandas DataFrames
    (reference: ``TextSet.read_parquet`` / spark ``read.parquet``)."""
    files = [f for f in list_files(file_path) if f.endswith(".parquet")]
    if not files:  # a single file given directly, whatever its suffix
        files = [file_path]
    return _read_files(files, _read_one_parquet, num_shards, **kwargs)
