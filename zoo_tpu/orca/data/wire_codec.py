"""Optional per-array wire narrowing + compression for the shard
exchange.

The ZSX2 codec ships raw tensor bytes; this module is the negotiated
layer on top that can make those bytes *fewer*:

* **dtype narrowing** — f32 payloads travel as bf16 (half the bytes,
  ~2^-8 relative error) or as int8 with a per-array absmax scale
  (quarter the bytes, absmax/254 absolute error), widened back to f32
  on the receiving side. Narrowing is LOSSY and therefore **opt-in
  only**: the default policy ships bit-identical bytes, and the
  cross-lane smoke (`scripts/check_data_plane.py`) asserts exactly
  that.
* **compression** — zlib (always available) or lz4 (when importable)
  framing for low-entropy arrays, applied per array *after* narrowing
  and kept only when it actually shrinks the payload (the flag byte
  says which, so an incompressible array costs nothing but the
  attempt).

Both features are negotiated per connection (``ZSXN`` hello — see
``plane.py``): the *fetching* side proposes what it wants on its wire
(``ZOO_SHARD_WIRE_DTYPE`` / ``ZOO_SHARD_WIRE_COMPRESS``), the serving
side answers with what it will actually do, and a legacy ZSX2-only
peer that understands neither simply gets the plain protocol.

Nothing here is executable from the wire: decode is ``zlib.decompress``
/ ``lz4.frame.decompress`` plus ``np.frombuffer`` with a parsed dtype,
and every length is validated against the header before allocation.
"""

from __future__ import annotations

import zlib
from typing import List, Optional, Tuple

import numpy as np

# ONE absmax int8 implementation serves the wire and the paged KV
# cache (zoo_tpu/serving/llm/model.py quantizes cache rows with the
# same helpers under jnp); re-exported here for existing importers
from zoo_tpu.util.quantize import absmax_scale, narrow_int8, widen_int8

try:  # optional — never a hard dependency (container may lack it)
    import lz4.frame as _lz4
except ImportError:  # pragma: no cover - environment-dependent
    _lz4 = None

__all__ = ["WirePolicy", "encode_array", "decode_payload",
           "supported_codecs", "supported_wire_dtypes",
           "FLAG_NARROWED", "FLAG_COMPRESSED", "FLAG_SHM", "FLAG_CRC",
           "WIRE_DTYPES", "absmax_scale", "narrow_int8", "widen_int8"]

FLAG_NARROWED = 0x01
FLAG_COMPRESSED = 0x02
FLAG_SHM = 0x04  # payload field is a segment offset, not inline bytes
FLAG_CRC = 0x08  # a u32 CRC of the wire payload follows the headers
#                  (negotiated via the ZSXN hello "crc" capability;
#                  covers the bytes as transported — narrowed/compressed
#                  for the TCP lane, the mapped segment bytes for shm —
#                  so bit rot ANYWHERE between encode and decode is
#                  caught before np.frombuffer ever runs)

WIRE_DTYPES = ("off", "bf16", "int8")

# compress only when it pays: tiny arrays cost more in per-call
# overhead than their bytes, and the attempt itself is not free
_MIN_COMPRESS_BYTES = 1 << 10


def supported_codecs() -> List[str]:
    return (["lz4"] if _lz4 is not None else []) + ["zlib"]


def supported_wire_dtypes() -> List[str]:
    """Narrowings this process can actually encode/decode — bf16 needs
    ml_dtypes (jax ships it, but a jax-free serving process may not).
    Granting a narrowing the codec would ImportError on mid-response
    kills the stream after frames are on the wire; filtering here makes
    it negotiate down instead, exactly like compression."""
    out = ["off", "int8"]
    try:
        import ml_dtypes  # noqa: F401
        out.insert(1, "bf16")
    except ImportError:  # pragma: no cover - environment-dependent
        pass
    return out


class WirePolicy:
    """One connection's negotiated wire treatment."""

    __slots__ = ("dtype", "compress")

    def __init__(self, dtype: str = "off", compress: str = "off"):
        if dtype not in WIRE_DTYPES:
            raise ValueError(
                f"ZOO_SHARD_WIRE_DTYPE={dtype!r}: pick one of "
                f"{WIRE_DTYPES} (narrowing is lossy and therefore "
                "never a default)")
        if compress not in ("off", "zlib", "lz4"):
            raise ValueError(
                f"ZOO_SHARD_WIRE_COMPRESS={compress!r}: off, zlib or lz4")
        self.dtype = dtype
        self.compress = compress

    @property
    def active(self) -> bool:
        return self.dtype != "off" or self.compress != "off"

    def __repr__(self):
        return f"WirePolicy(dtype={self.dtype!r}, compress={self.compress!r})"


def _bf16():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


def payload_view(arr: np.ndarray) -> memoryview:
    """The array's raw bytes WITHOUT a serialize copy (contiguous
    arrays; a non-contiguous shard pays one compaction copy)."""
    a = np.ascontiguousarray(arr)
    if a.nbytes == 0:
        return memoryview(b"")
    try:
        return memoryview(a).cast("B")
    except (ValueError, TypeError):
        # extension dtypes (bfloat16) refuse the buffer protocol; a
        # uint8 view of the same memory does not copy
        return memoryview(a.reshape(-1).view(np.uint8))


def encode_array(arr: np.ndarray, policy: Optional[WirePolicy]
                 ) -> Tuple[int, Optional[bytes], float, object]:
    """Apply the policy to one array.

    Returns ``(flags, wire_dtype_descr, scale, payload)`` where
    ``payload`` is a buffer (memoryview for the untouched zero-copy
    case, bytes when narrowed/compressed). Narrowing applies to f32
    arrays only — everything else passes through un-narrowed, so a
    mixed shard (int labels + float features) narrows exactly the part
    that tolerates it.
    """
    flags = 0
    wire_descr: Optional[bytes] = None
    scale = 0.0
    payload: object = None
    if policy is not None and policy.dtype != "off" \
            and arr.dtype == np.float32 and arr.size:
        if policy.dtype == "bf16":
            narrowed = np.ascontiguousarray(arr).astype(_bf16())
            wire_descr = b"bfloat16"
        else:  # int8 with per-array absmax scale (shared helpers)
            scale = float(absmax_scale(arr))
            narrowed = narrow_int8(arr, scale)
            wire_descr = b"|i1"
        flags |= FLAG_NARROWED
        # reshape(-1).view covers 0-d and extension dtypes alike (a
        # memoryview cast would refuse both)
        payload = memoryview(narrowed.reshape(-1).view(np.uint8))
    else:
        payload = payload_view(arr)
    if policy is not None and policy.compress != "off":
        view = memoryview(payload)
        if view.nbytes >= _MIN_COMPRESS_BYTES:
            # compressors take the buffer directly — a bytes() copy
            # here would double transient memory on the hot send path
            if policy.compress == "lz4" and _lz4 is not None:
                packed = _lz4.compress(view)
            else:
                packed = zlib.compress(view, 1)
            if len(packed) < view.nbytes:  # keep only a real win
                flags |= FLAG_COMPRESSED
                payload = packed
    return flags, wire_descr, scale, payload


def _inflated_nbytes(flags: int, dtype, shape,
                     wire_descr: Optional[str]) -> int:
    """Exact decompressed size the header promises — the allocation
    bound for the inflate step."""
    count = 1
    for s in shape:
        count *= int(s)
    if flags & FLAG_NARROWED:
        return count * (2 if wire_descr == "bfloat16" else 1)
    return count * np.dtype(dtype).itemsize


def decode_payload(buf, flags: int, dtype: np.dtype, shape,
                   wire_descr: Optional[str], scale: float,
                   compress: str) -> np.ndarray:
    """Invert :func:`encode_array`: bytes off the wire (or out of the
    mapped segment) back to the logical array. The untouched path is
    ``np.frombuffer`` over ``buf`` — zero copy; narrowing/compression
    inherently allocate (they must widen/inflate). Inflation is BOUNDED
    by the size the header promises — a corrupt or hostile stream must
    not turn a tiny compressed payload into an arbitrary allocation."""
    if flags & FLAG_COMPRESSED:
        bound = _inflated_nbytes(flags, dtype, shape, wire_descr)
        data = bytes(buf)
        if compress == "lz4":
            if _lz4 is None:
                raise RuntimeError(
                    "peer sent lz4-compressed payload but lz4 is not "
                    "importable here — negotiation bug")
            d = _lz4.LZ4FrameDecompressor()
            out = d.decompress(data, max_length=bound + 1)
        else:
            # bound+1, not bound: at exactly max_length the stream
            # trailer can sit unconsumed, which is indistinguishable
            # from a real overrun — one spare byte disambiguates
            out = zlib.decompressobj().decompress(data, bound + 1)
        if len(out) != bound:
            raise ValueError(
                f"compressed payload inflates to "
                f"{'>' if len(out) > bound else ''}{len(out)} bytes "
                f"but the header promises {bound} — corrupt or "
                "desynchronized stream")
        buf = out
    if flags & FLAG_NARROWED:
        # astype/multiply allocate the widened array, so frombuffer can
        # read straight off the (possibly read-only) wire buffer
        if wire_descr == "bfloat16":
            narrow = np.frombuffer(buf, dtype=_bf16())
            out = narrow.astype(np.float32)
        elif wire_descr in ("|i1", "int8"):
            narrow = np.frombuffer(buf, dtype=np.int8)
            out = widen_int8(narrow, np.float32(scale))
        else:
            raise ValueError(f"unknown wire dtype {wire_descr!r}")
        return out.reshape(shape)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)
