"""Gated Spark DataFrame ingestion: partitions → per-host shards, no
driver collect.

Rebuild of the reference's primary estimator feed — every Orca estimator
accepts a Spark DataFrame plus feature/label columns
(``pyzoo/zoo/orca/learn/tf/estimator.py:486`` ``fit(df, feature_cols,
label_cols)``; ``pyzoo/zoo/pipeline/nnframes/nn_classifier.py:139``;
``pyzoo/zoo/orca/data/shard.py:129`` builds SparkXShards on the RDD).
There, partitions stream executor→JVM tensors; here they become
numpy shard FILES written *by the executors* (``mapPartitionsWithIndex``)
into a staging directory every TPU host can read (GCS/NFS — the
plasma-store role of ``ray_xshards.py:67``), and each JAX process loads
only its round-robin slice (``shards_for_process``). The only thing that
ever reaches the Spark driver is the list of file paths — never row data
(SURVEY §7.4 hard part #1).

pyspark is NOT a dependency: the adapter talks to a four-method surface
(``df.columns``, ``df.rdd``, ``rdd.mapPartitionsWithIndex(f)``,
``.collect()``) so it is testable against a pandas-backed stub, and the
estimator detects DataFrames by module name (``is_spark_dataframe``)
without importing pyspark.
"""

from __future__ import annotations

import os
import uuid
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["is_spark_dataframe", "spark_dataframe_to_shards"]


def is_spark_dataframe(obj) -> bool:
    """True for ``pyspark.sql.DataFrame`` (connect or classic) without
    importing pyspark."""
    mod = type(obj).__module__ or ""
    return mod.startswith("pyspark.") and type(obj).__name__ == "DataFrame"


def _column_array(name: str, vals: List) -> np.ndarray:
    """Convert one column's python values (as Spark rows deliver them) to
    a numeric ndarray, covering the SQL-type edge cases: nulls become NaN
    in float columns (and are an error in non-float ones), ``Decimal``
    becomes float64, array columns (``ArrayType``) stack to 2-D. String/
    object columns fail with a clear message — the staged ``.npz`` files
    are loaded with ``allow_pickle=False`` on the TPU hosts."""
    import decimal

    has_null = any(v is None for v in vals)
    if vals and any(isinstance(v, decimal.Decimal) for v in vals):
        return np.asarray([np.nan if v is None else float(v)
                           for v in vals], np.float64)
    if has_null:
        if all(v is None or isinstance(v, float) for v in vals):
            return np.asarray([np.nan if v is None else v for v in vals],
                              np.float64)
        raise ValueError(
            f"column {name!r} contains nulls in a non-float type; "
            "fill or drop them in Spark (df.na.fill / df.na.drop) "
            "before handing the DataFrame to the estimator")
    if vals and isinstance(vals[0], (list, tuple, np.ndarray)):
        try:
            return np.stack([np.asarray(v, np.float32) for v in vals])
        except ValueError as e:
            raise ValueError(
                f"array column {name!r} has ragged lengths; pad it to a "
                "fixed size in Spark before ingestion") from e
    arr = np.asarray(vals)
    if arr.dtype.kind not in "biufc":  # unicode/object/bytes/datetime
        raise TypeError(
            f"column {name!r} has non-numeric type "
            f"{type(vals[0]).__name__}; select/cast numeric columns "
            "(StringIndexer etc. happen Spark-side)")
    return arr


class _PartitionWriter:
    """The callable shipped to Spark executors via
    ``rdd.mapPartitionsWithIndex``. A module-level class instance — NOT a
    closure — so it serializes under plain pickle as well as Spark's
    cloudpickle; executors only need this module importable (the zoo_tpu
    wheel on the executor python path, the reference's ``--py-files``
    story). Converts a partition's rows to one ``.npz`` of column arrays
    and yields only the (partition_id, path, row_count) triple."""

    def __init__(self, columns: Sequence[str], staging_dir: str, run: str):
        self.columns = list(columns)
        self.staging_dir = staging_dir
        self.run = run

    def __call__(self, pid, rows):
        cols = {c: [] for c in self.columns}
        n = 0
        for row in rows:
            for c in self.columns:
                cols[c].append(row[c])
            n += 1
        if n == 0:
            return iter(())
        path = os.path.join(self.staging_dir,
                            f"zoo-{self.run}-p{pid:05d}.npz")
        np.savez(path, **{c: _column_array(c, v)
                          for c, v in cols.items()})
        return iter([(pid, path, n)])


def _partition_writer(columns: Sequence[str], staging_dir: str, run: str):
    return _PartitionWriter(columns, staging_dir, run)


def spark_dataframe_to_shards(df, feature_cols: Sequence[str],  # zoo-lint: config-parse
                              label_cols: Optional[Sequence[str]] = None,
                              staging_dir: Optional[str] = None,
                              process_index: Optional[int] = None,
                              process_count: Optional[int] = None):
    """Materialize a Spark DataFrame as THIS process's ``LocalXShards``.

    ``staging_dir`` must be visible to both Spark executors and the TPU
    hosts (defaults to ``$ZOO_SPARK_STAGING`` or a tmp dir — the latter
    only works in ``local[*]`` mode where executors share the
    filesystem). Returns shards shaped for the estimator feed:
    ``{"x": (n, F) | (n,), "y": (n, L) | (n,)}``.

    Retention: every call stages a fresh uuid-tagged copy of the
    DataFrame. In single-process runs the run's files are deleted after
    loading; multi-host runs cannot know when peers finish reading, so
    the files persist — point ``ZOO_SPARK_STAGING`` at job-scoped
    storage that is reclaimed with the job.
    """
    if not feature_cols:
        raise ValueError("feature_cols required for DataFrame input")
    label_cols = list(label_cols or [])
    missing = [c for c in list(feature_cols) + label_cols
               if c not in df.columns]
    if missing:
        raise ValueError(f"column(s) not found: {missing}; "
                         f"available: {list(df.columns)}")
    import jax

    live_multihost = (process_index is None and process_count is None
                      and jax.process_count() > 1)
    staging_dir = staging_dir or os.environ.get("ZOO_SPARK_STAGING")
    if staging_dir is None:
        if live_multihost:
            # each process would mkdtemp() a DIFFERENT directory; peers
            # would then fail on the manifest read after the sync barrier
            # with a confusing FileNotFoundError — fail fast, before
            # creating anything, with the real cause
            raise RuntimeError(
                "spark_dataframe_to_shards in multi-host mode needs a "
                "staging directory visible to every host: set "
                "ZOO_SPARK_STAGING (or pass staging_dir=) to shared "
                "storage (NFS/GCS-fuse); the default per-process tmp dir "
                "is host-local")
        import tempfile
        staging_dir = tempfile.mkdtemp(prefix="zoo_spark_")
    if live_multihost:
        # stage ONCE for the whole cluster: process 0 runs the Spark job
        # and publishes a manifest; peers agree on the run tag through
        # the coordination service and read the manifest from the shared
        # staging dir (one materialization, one dataset copy)
        import json

        from jax.experimental import multihost_utils

        tag = np.frombuffer(uuid.uuid4().bytes[:8], np.uint8)
        tag = multihost_utils.broadcast_one_to_all(tag)
        run = bytes(tag.tolist()).hex()
        manifest = os.path.join(staging_dir, f"zoo-{run}-manifest.json")
        if jax.process_index() == 0:
            writer = _partition_writer(list(feature_cols) + label_cols,
                                       staging_dir, run)
            meta = sorted(df.rdd.mapPartitionsWithIndex(writer).collect())
            with open(manifest, "w") as f:
                json.dump(meta, f)
        multihost_utils.sync_global_devices(f"zoo_spark_stage_{run}")
        with open(manifest) as f:
            meta = [tuple(m) for m in json.load(f)]
    else:
        run = uuid.uuid4().hex[:8]
        writer = _partition_writer(list(feature_cols) + label_cols,
                                   staging_dir, run)
        # executors write the shard files; ONLY the path metadata collects
        meta = sorted(df.rdd.mapPartitionsWithIndex(writer).collect())

    from zoo_tpu.orca.data.shard import LocalXShards, shards_for_process

    paths = LocalXShards([p for _, p, _ in meta])
    mine = shards_for_process(paths, process_index=process_index,
                              process_count=process_count)

    def load(path: str):
        with np.load(path, allow_pickle=False) as z:
            feats = [z[c] for c in feature_cols]
            labs = [z[c] for c in label_cols]
        x = feats[0] if len(feats) == 1 else np.stack(feats, axis=1)
        shard = {"x": x}
        if labs:
            shard["y"] = labs[0] if len(labs) == 1 \
                else np.stack(labs, axis=1)
        return shard

    out = LocalXShards([load(p) for p in mine.collect()])
    import jax

    pcnt = process_count if process_count is not None \
        else jax.process_count()
    if pcnt == 1:
        for _, p, _ in meta:  # single reader: reclaim this run's staging
            try:
                os.remove(p)
            except OSError:
                pass
    return out
