"""ParquetDataset: write generator/ndarray/image-folder datasets as
parquet, read back as arrays / XShards / a streaming iterator.

Rebuild of the reference's ParquetDataset
(``pyzoo/zoo/orca/data/image/parquet_dataset.py:37`` ``write``, ``:121``
``read_as_tf``, ``:132`` ``read_as_torch``, ``:175``
``write_from_directory``, ``:207`` ``_write_ndarrays``). The reference
materializes a generator through a schema into parquet blocks and reads
them back as tf.data / torch datasets; here the read side produces numpy
arrays, LocalXShards, or a batched iterator feeding the TPU input pipeline
(the ``read_as_tf``/``read_as_torch`` roles collapse into array-native
forms). A ``_metadata.json`` sidecar records the schema like the
reference's schema pickle.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

_META = "_orca_metadata.json"


class ParquetDataset:
    @staticmethod
    def write(path: str, generator: Iterator[Dict], schema: Dict[str, str],
              block_size: int = 1000, write_mode: str = "overwrite"):
        """``schema``: {column: kind} with kind in
        ``scalar | ndarray | image`` (image = raw bytes). Records from
        ``generator`` are dicts keyed by the schema."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        if write_mode not in ("overwrite", "error", "append"):
            raise ValueError(f"write_mode must be overwrite|error|append, "
                             f"got {write_mode!r}")
        start_idx = 0
        if os.path.isdir(path):
            if write_mode == "error":
                raise FileExistsError(path)
            if write_mode == "overwrite":
                import shutil
                shutil.rmtree(path)
            else:  # append continues the part numbering
                parts = [f for f in os.listdir(path)
                         if f.endswith(".parquet")]
                start_idx = len(parts)
        os.makedirs(path, exist_ok=True)
        dtypes: Dict[str, str] = {}

        def flush(rows: List[Dict], idx: int):
            if not rows:
                return
            cols = {}
            for name, kind in schema.items():
                vals = [r[name] for r in rows]
                if kind == "ndarray":
                    dt = np.asarray(vals[0]).dtype
                    dtypes.setdefault(name, dt.name)
                    cols[name] = pa.array(
                        [np.asarray(v, dt).flatten().tolist()
                         for v in vals],
                        pa.list_(pa.from_numpy_dtype(dt)))
                    cols[name + "_shape"] = pa.array(
                        [list(np.asarray(v).shape) for v in vals],
                        pa.list_(pa.int32()))
                elif kind == "image":
                    cols[name] = pa.array(
                        [v if isinstance(v, bytes) else bytes(v)
                         for v in vals], pa.binary())
                else:
                    cols[name] = pa.array(vals)
            table = pa.table(cols)
            pq.write_table(table,
                           os.path.join(path, f"part-{idx:05d}.parquet"))

        rows: List[Dict] = []
        idx = start_idx
        for rec in generator:
            rows.append(rec)
            if len(rows) >= block_size:
                flush(rows, idx)
                rows, idx = [], idx + 1
        flush(rows, idx)
        with open(os.path.join(path, _META), "w") as f:
            json.dump({"schema": schema, "dtypes": dtypes}, f)

    # -- read -------------------------------------------------------------
    @staticmethod
    def _meta(path: str) -> Dict:
        with open(os.path.join(path, _META)) as f:
            return json.load(f)

    @staticmethod
    def _schema(path: str) -> Dict[str, str]:
        return ParquetDataset._meta(path)["schema"]

    @staticmethod
    def read_as_arrays(path: str) -> Dict[str, np.ndarray]:
        """Whole dataset as {column: array} (ndarray columns reshaped,
        dtypes restored from the metadata sidecar)."""
        import pyarrow.parquet as pq

        meta = ParquetDataset._meta(path)
        schema = meta["schema"]
        dtypes = meta.get("dtypes", {})
        parts = sorted(f for f in os.listdir(path)
                       if f.endswith(".parquet"))
        out: Dict[str, List] = {k: [] for k in schema}
        for part in parts:
            table = pq.read_table(os.path.join(path, part))
            cols = {c: table[c].to_pylist() for c in table.column_names}
            for name, kind in schema.items():
                if kind == "ndarray":
                    dt = np.dtype(dtypes.get(name, "float32"))
                    for flat, shape in zip(cols[name],
                                           cols[name + "_shape"]):
                        out[name].append(
                            np.asarray(flat, dt).reshape(shape))
                else:
                    out[name].extend(cols[name])
        return {k: (np.stack(v) if schema[k] == "ndarray"
                    and len({a.shape for a in v}) == 1
                    else np.asarray(v) if schema[k] == "scalar"
                    else v)
                for k, v in out.items()}

    @staticmethod
    def read_as_xshards(path: str, num_shards: Optional[int] = None):
        """reference ``read_as_tf``/``read_as_torch`` role: a partitioned
        dataset feeding workers."""
        from zoo_tpu.orca.data.shard import LocalXShards

        arrays = ParquetDataset.read_as_arrays(path)
        return LocalXShards.partition(arrays, num_shards=num_shards)

    @staticmethod
    def read_batched(path: str, batch_size: int = 32,
                     columns: Optional[List[str]] = None
                     ) -> Iterator[Dict[str, np.ndarray]]:
        """Streaming batches straight off the parquet blocks (the input-
        pipeline form; wrap with DoubleBufferedIterator to stage ahead)."""
        import pyarrow.parquet as pq

        meta = ParquetDataset._meta(path)
        schema = meta["schema"]
        dtypes = meta.get("dtypes", {})
        want = columns or list(schema)
        parts = sorted(f for f in os.listdir(path)
                       if f.endswith(".parquet"))
        buf: Dict[str, List] = {k: [] for k in want}
        for part in parts:
            table = pq.read_table(os.path.join(path, part))
            cols = {c: table[c].to_pylist() for c in table.column_names}
            n = table.num_rows
            for i in range(n):
                for name in want:
                    if schema[name] == "ndarray":
                        buf[name].append(np.asarray(
                            cols[name][i],
                            np.dtype(dtypes.get(name, "float32"))).reshape(
                            cols[name + "_shape"][i]))
                    else:
                        buf[name].append(cols[name][i])
                if len(buf[want[0]]) == batch_size:
                    yield {k: np.stack(v) if schema[k] == "ndarray"
                           else np.asarray(v) for k, v in buf.items()}
                    buf = {k: [] for k in want}
        if buf[want[0]]:
            yield {k: np.stack(v) if schema[k] == "ndarray"
                   else np.asarray(v) for k, v in buf.items()}


def write_from_directory(directory: str, label_map: Dict[str, int],
                         output_path: str, shuffle: bool = True,
                         seed: int = 0, **kwargs):
    """Image folder (``dir/<class>/*.jpg``) → parquet of (image bytes,
    label, origin) — reference ``write_from_directory``."""
    records = []
    for cls, label in sorted(label_map.items()):
        cdir = os.path.join(directory, cls)
        for fname in sorted(os.listdir(cdir)):
            records.append((os.path.join(cdir, fname), label))
    if shuffle:
        np.random.RandomState(seed).shuffle(records)

    def gen():
        for fpath, label in records:
            with open(fpath, "rb") as f:
                yield {"image": f.read(), "label": label, "origin": fpath}

    ParquetDataset.write(output_path, gen(),
                         {"image": "image", "label": "scalar",
                          "origin": "scalar"}, **kwargs)


def write_ndarrays(images: np.ndarray, labels: np.ndarray,
                   output_path: str, **kwargs):
    """reference ``_write_ndarrays`` (the mnist path)."""
    def gen():
        for img, lab in zip(images, labels):
            yield {"image": np.asarray(img, np.float32),
                   "label": int(lab)}

    ParquetDataset.write(output_path, gen(),
                         {"image": "ndarray", "label": "scalar"}, **kwargs)


def write_parquet(format: str, output_path: str, *args, **kwargs):
    """reference ``orca/data/image/parquet_dataset.py`` ``write_parquet``
    — format-dispatching writer ("ndarray" arrays, "image_folder" a
    class-subdir tree)."""
    if format in ("ndarray", "ndarrays"):
        return write_ndarrays(*args, output_path=output_path, **kwargs)
    if format in ("image_folder", "voc", "directory"):
        return write_from_directory(*args, output_path=output_path,
                                    **kwargs)
    raise ValueError(f"unknown format {format!r}; use 'ndarray' or "
                     "'image_folder'")


def read_parquet(format: str, path: str, **kwargs):
    """reference ``read_parquet`` — "tf"/"torch" loaders collapse onto
    the framework-neutral array/batched readers here."""
    if format in ("arrays", "numpy"):
        return ParquetDataset.read_as_arrays(path)
    if format == "batched":
        return ParquetDataset.read_batched(path, **kwargs)
    if format in ("xshards", "shards"):
        return ParquetDataset.read_as_xshards(path, **kwargs)
    if format in ("tf", "torch"):
        # the reference returns tf.data / torch datasets; the rebuild's
        # estimators consume arrays or XShards directly
        return ParquetDataset.read_as_arrays(path)
    raise ValueError(f"unknown format {format!r}")
