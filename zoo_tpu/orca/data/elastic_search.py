"""ElasticSearch I/O for XShards (reference:
``pyzoo/zoo/orca/data/elastic_search.py:27`` — ``elastic_search.read_df``
/ ``write_df`` / ``read_rdd`` over the es-hadoop Spark connector).

The rebuild talks to ES over its plain REST API via the official
``elasticsearch`` Python client (8.x calling conventions) when it is
installed (this hermetic image does not ship it, so every entry point
degrades to a clear ImportError-derived message rather than an attribute
crash); results land in pandas DataFrames / :class:`LocalXShards`, the
rebuild's data plane. Reads paginate with ``search_after`` so whole
indices come back (the es-hadoop connector read everything too); writes
use the bulk API.
"""

from __future__ import annotations

from typing import Optional

import pandas as pd

_PAGE = 1000


def _client(es_config: dict):
    try:
        from elasticsearch import Elasticsearch
    except ImportError as e:
        raise ImportError(
            "elastic_search I/O needs the `elasticsearch` package "
            "(pip install elasticsearch); it is not bundled with zoo_tpu"
        ) from e
    default_port = int(es_config.get("es.port", 9200))
    hosts = es_config.get("es.nodes", "localhost")
    if isinstance(hosts, str):
        hosts = [h.strip() for h in hosts.split(",")]
    def _split_host_port(h: str, default: int):
        """host / host:port / [v6]:port / bare v6 — only strip a suffix
        that is actually a numeric port."""
        if h.startswith("["):  # [v6addr]:port or [v6addr]
            addr, _, rest = h[1:].partition("]")
            port = rest[1:] if rest.startswith(":") else ""
            return addr, int(port) if port.isdigit() else default
        head, _, tail = h.rpartition(":")
        if head and tail.isdigit() and ":" not in head:
            return head, int(tail)
        return h, default  # bare host or bare IPv6 literal

    nodes = []
    for h in hosts:  # es-hadoop allows bare hosts or host:port entries
        scheme = "http"
        if "://" in h:
            scheme, h = h.split("://", 1)
        host, port = _split_host_port(h, default_port)
        nodes.append({"host": host, "port": port, "scheme": scheme})
    kwargs = {}
    user = es_config.get("es.net.http.auth.user")
    if user:
        kwargs["basic_auth"] = (user,
                                es_config.get("es.net.http.auth.pass", ""))
    return Elasticsearch(nodes, **kwargs)


class elastic_search:  # noqa: N801 — reference spells the class this way
    """Primitives for ES interaction (reference class of the same name)."""

    @staticmethod
    def read_df(es_config: dict, es_resource: str,
                schema: Optional[list] = None,
                query: Optional[dict] = None,
                size: Optional[int] = None) -> pd.DataFrame:
        """Read an index into a DataFrame (reference ``read_df:31``;
        ``schema`` selects columns). Paginates with ``search_after`` so
        indices larger than the ES result window come back whole;
        ``size`` optionally caps the row count."""
        es = _client(es_config)
        rows, after = [], None
        q = query or {"match_all": {}}
        while True:
            page = (min(_PAGE, size - len(rows)) if size is not None
                    else _PAGE)
            if page <= 0:
                break
            resp = es.search(index=es_resource, query=q, size=page,
                             sort=[{"_doc": "asc"}],
                             search_after=after)
            hits = resp["hits"]["hits"]
            if not hits:
                break
            rows.extend(h["_source"] for h in hits)
            after = hits[-1]["sort"]
        df = pd.json_normalize(rows)  # reference flatten_df: dotted names
        if schema:
            df = df[[c for c in schema if c in df.columns]]
        return df

    @staticmethod
    def write_df(es_config: dict, es_resource: str, df: pd.DataFrame,
                 chunk_size: int = 1000):
        """Write a DataFrame into an index via the bulk API (reference
        ``write_df:76`` used the bulk-oriented es-hadoop connector)."""
        es = _client(es_config)
        records = df.to_dict(orient="records")
        for start in range(0, len(records), chunk_size):
            ops = []
            for doc in records[start:start + chunk_size]:
                ops.append({"index": {"_index": es_resource}})
                ops.append(doc)
            if ops:
                resp = es.bulk(operations=ops)
                if resp.get("errors"):
                    bad = [i["index"] for i in resp["items"]
                           if i.get("index", {}).get("error")][:3]
                    raise RuntimeError(f"bulk index failures: {bad}")
        es.indices.refresh(index=es_resource)

    @staticmethod
    def read_shards(es_config: dict, es_resource: str,
                    query: Optional[dict] = None,
                    num_shards: Optional[int] = None):
        """Read an index into XShards of DataFrames (reference
        ``read_rdd:94`` landed in an RDD; here LocalXShards)."""
        from zoo_tpu.orca.data.shard import LocalXShards
        df = elastic_search.read_df(es_config, es_resource, query=query)
        return LocalXShards.partition(df, num_shards or 4)
