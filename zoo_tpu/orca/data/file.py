"""File utilities over local / http(s) / gcs paths.

Rebuild of ``pyzoo/zoo/orca/data/file.py`` (open_text, exists, makedirs,
write_text over local/hdfs/s3). The TPU-native deployment story replaces
HDFS/S3 with GCS and plain HTTP: ``http(s)://`` downloads through urllib
with a local cache, ``gs://`` goes through gcsfs/tensorstore when
installed (gated with a clear error otherwise), everything else is POSIX.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import os
import shutil
import tempfile
import urllib.request
from typing import List, Optional


def _strip_scheme(path: str) -> str:
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


def is_local_path(path: str) -> bool:
    return "://" not in path or path.startswith("file://")


def _gcs_fs():
    try:
        import gcsfs
        return gcsfs.GCSFileSystem()
    except ImportError as e:
        raise ImportError(
            "gs:// paths need the gcsfs package (not installed in this "
            "image); download the data locally or install gcsfs") from e


def download(url: str, cache_dir: Optional[str] = None) -> str:
    """Fetch an http(s) resource into a content-addressed local cache and
    return the local path (the reference's remote reads funnel through
    hadoop; here HTTP is the lingua franca)."""
    cache_dir = cache_dir or os.path.join(tempfile.gettempdir(),
                                          "zoo_tpu_downloads")
    os.makedirs(cache_dir, exist_ok=True)
    name = hashlib.sha1(url.encode()).hexdigest()[:16] + "_" + \
        os.path.basename(url.split("?")[0])
    local = os.path.join(cache_dir, name)
    if not os.path.exists(local):
        # per-writer temp file + atomic publish: concurrent processes
        # (pod hosts on a shared fs) must not interleave into one .part
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".part")
        try:
            with urllib.request.urlopen(url, timeout=60) as resp, \
                    os.fdopen(fd, "wb") as f:
                shutil.copyfileobj(resp, f)
            os.replace(tmp, local)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
    return local


def _localize(path: str) -> str:
    """Any supported path → a local filesystem path."""
    if is_local_path(path):
        return _strip_scheme(path)
    if path.startswith(("http://", "https://")):
        return download(path)
    if path.startswith("gs://"):
        fs = _gcs_fs()
        cache = os.path.join(tempfile.gettempdir(), "zoo_tpu_gcs")
        os.makedirs(cache, exist_ok=True)
        # keep the basename so extension-based filters (read_parquet etc.)
        # still match the localized file
        local = os.path.join(
            cache, hashlib.sha1(path.encode()).hexdigest()[:16] + "_" +
            os.path.basename(path))
        if not os.path.exists(local):
            fd, tmp = tempfile.mkstemp(dir=cache, suffix=".part")
            os.close(fd)
            try:
                fs.get(path, tmp)  # staged: no truncated cache hits
                os.replace(tmp, local)
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        return local
    raise NotImplementedError(f"unsupported path scheme: {path}")


def exists(path: str) -> bool:
    if is_local_path(path):
        return os.path.exists(_strip_scheme(path))
    if path.startswith(("http://", "https://")):
        req = urllib.request.Request(path, method="HEAD")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status < 400
        except Exception:
            return False
    if path.startswith("gs://"):
        return _gcs_fs().exists(path)
    raise NotImplementedError(f"unsupported path scheme: {path}")


def makedirs(path: str):
    if is_local_path(path):
        os.makedirs(_strip_scheme(path), exist_ok=True)
        return
    if path.startswith("gs://"):
        return  # object stores have no directories
    raise NotImplementedError(f"cannot create directories under {path}")


def open_text(path: str) -> List[str]:
    """Read a text file (local, http(s) or gs) and return its lines
    (reference: ``orca/data/file.py`` ``open_text``)."""
    with open(_localize(path)) as f:
        return [line.rstrip("\n") for line in f]


def write_text(path: str, text: str):
    if path.startswith("gs://"):
        with _gcs_fs().open(path, "w") as f:
            f.write(text)
        return
    if not is_local_path(path):
        raise NotImplementedError(f"cannot write to {path}")
    path = _strip_scheme(path)
    with open(path, "w") as f:
        f.write(text)


def list_files(path_glob: str) -> List[str]:
    """Expand a path or glob to a sorted file list; a directory expands to
    its (non-hidden) files — matches the reference's extract_one behavior
    for `read_csv` on a folder. Remote http(s)/gs paths localize to one
    file."""
    if not is_local_path(path_glob):
        return [_localize(path_glob)]
    path_glob = _strip_scheme(path_glob)
    if os.path.isdir(path_glob):
        return sorted(
            os.path.join(path_glob, f) for f in os.listdir(path_glob)
            if not f.startswith((".", "_")))
    matches = sorted(_glob.glob(path_glob))
    if not matches and os.path.exists(path_glob):
        return [path_glob]
    return matches


def rmtree(path: str):
    path = _strip_scheme(path)
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)
