"""File utilities over local / (optionally) gcs paths.

Rebuild of ``pyzoo/zoo/orca/data/file.py`` (open_text, exists, makedirs,
write_text over local/hdfs/s3). The TPU-native deployment story replaces
HDFS/S3 with GCS; ``gs://`` support is gated on an optional gcsfs/tensorstore
install, everything else is plain POSIX.
"""

from __future__ import annotations

import glob as _glob
import os
import shutil
from typing import List


def _strip_scheme(path: str) -> str:
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


def is_local_path(path: str) -> bool:
    return "://" not in path or path.startswith("file://")


def exists(path: str) -> bool:
    path = _strip_scheme(path)
    if is_local_path(path):
        return os.path.exists(path)
    raise NotImplementedError(f"remote path not supported here: {path}")


def makedirs(path: str):
    path = _strip_scheme(path)
    if is_local_path(path):
        os.makedirs(path, exist_ok=True)
        return
    raise NotImplementedError(f"remote path not supported here: {path}")


def open_text(path: str) -> List[str]:
    """Read a text file and return its lines (reference:
    ``orca/data/file.py`` ``open_text``)."""
    path = _strip_scheme(path)
    with open(path) as f:
        return [line.rstrip("\n") for line in f]


def write_text(path: str, text: str):
    path = _strip_scheme(path)
    with open(path, "w") as f:
        f.write(text)


def list_files(path_glob: str) -> List[str]:
    """Expand a path or glob to a sorted file list; a directory expands to
    its (non-hidden) files — matches the reference's extract_one behavior
    for `read_csv` on a folder."""
    path_glob = _strip_scheme(path_glob)
    if os.path.isdir(path_glob):
        return sorted(
            os.path.join(path_glob, f) for f in os.listdir(path_glob)
            if not f.startswith((".", "_")))
    matches = sorted(_glob.glob(path_glob))
    if not matches and os.path.exists(path_glob):
        return [path_glob]
    return matches


def rmtree(path: str):
    path = _strip_scheme(path)
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)
