"""Multi-host shard data plane: locality-aware shard exchange.

Rebuild of the reference's RayXShards movement layer
(``pyzoo/zoo/orca/data/ray_xshards.py:67`` — each Spark partition is put
into the plasma store on its node; ``:250`` ``assign_partitions_to_actors``
assigns actors to co-located partitions so only the imbalance actually
moves). The TPU-native shape of the same capability:

* every JAX process serves its local shards over an ephemeral TCP port
  (:class:`ShardExchange`) using a **non-executable** codec (length-framed
  ``.npz`` — ``numpy.load(allow_pickle=False)``, never pickle);
* peer discovery rides the JAX distributed runtime itself —
  ``multihost_utils.process_allgather`` of each host's (ip, port, count)
  triple, so there is no extra coordinator and no driver-side collect;
* :func:`assign_shards` computes the same deterministic, locality-first
  plan on every host: each host keeps as many of its own shards as the
  balanced target allows, and only surplus shards are fetched by deficit
  hosts;
* :func:`rebalance_shards` runs the whole exchange and returns this
  process's balanced, disjoint shard set — ready for the estimator's
  per-process feed into ``host_local_to_global``
  (``parallel/mesh.py:152``).

Shards must be dicts of numpy arrays (the estimator feed format); use
``XShards.partition({"x": ..., "y": ...})``.
"""

from __future__ import annotations

import io
import logging
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from zoo_tpu.obs.coordination import (
    # the rebalance control plane rides the coordination-service KV
    # store rather than XLA device collectives — see that module (the
    # helper is shared with trace-id propagation and metric aggregation)
    coordination_client as _coordination_client,
)
from zoo_tpu.obs.metrics import counter, histogram
from zoo_tpu.obs.tracing import span
from zoo_tpu.util.resilience import RetryPolicy, fault_point

__all__ = ["ShardExchange", "assign_shards", "rebalance_shards"]

logger = logging.getLogger(__name__)

_fetch_seconds = histogram(
    "zoo_shard_fetch_seconds",
    "Cross-host shard fetch latency (one successful attempt)")
_fetch_bytes = counter(
    "zoo_shard_fetch_bytes_total", "Shard payload bytes fetched from peers")
_barrier_wait = histogram(
    "zoo_rebalance_barrier_wait_seconds",
    "Wall time spent in each rebalance KV-store barrier phase",
    labels=("phase",))

_MAGIC = b"ZSX1"


def _encode_shard(shard: Dict[str, np.ndarray]) -> bytes:
    if not isinstance(shard, dict) or not all(
            isinstance(v, np.ndarray) for v in shard.values()):
        raise TypeError(
            "the shard exchange ships dict-of-ndarray shards only; got "
            f"{type(shard).__name__} (convert DataFrame shards with "
            "to_dict('series') -> numpy first)")
    buf = io.BytesIO()
    np.savez(buf, **shard)
    blob = buf.getvalue()
    if len(blob) > 0xFFFFFFFF:
        raise ValueError(
            f"shard encodes to {len(blob)} bytes, over the exchange's "
            "u32 frame limit (4 GiB) — split it before shipping")
    return blob


def _decode_shard(blob: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # preallocate + recv_into: shards are tens of MB, so quadratic
    # bytes-concat accumulation would dominate the exchange; return the
    # bytearray itself — bytes(out) would re-copy the whole blob, and
    # every caller (magic compare, struct.unpack, BytesIO) takes it
    out = bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed mid-message")
        got += r
    return out


class ShardExchange:
    """Serve this process's shards (by global id) to peer hosts.

    Protocol: request = ``ZSX1`` + u32 global id; response = u32 length +
    npz bytes (length 0 = not held here). The codec cannot execute code
    on either end. The port is ephemeral, announced only through the JAX
    coordination service, and the server thread dies with the process.
    """

    def __init__(self, shards_by_gid: Dict[int, Dict[str, np.ndarray]],
                 bind: str = "0.0.0.0"):
        self._blobs = {gid: _encode_shard(s)
                       for gid, s in shards_by_gid.items()}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                while True:
                    try:
                        head = _recv_exact(conn, 8)
                    except ConnectionError:
                        return
                    if head[:4] != _MAGIC:
                        return  # not our protocol: drop the connection
                    (gid,) = struct.unpack("!I", head[4:])
                    blob = self._blobs.get(gid)
                    if blob is None:
                        conn.sendall(struct.pack("!I", 0))
                    else:
                        conn.sendall(struct.pack("!I", len(blob)) + blob)
        except OSError:
            pass

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    @staticmethod
    def fetch(addr: Tuple[str, int], gid: int, timeout: float = 60.0,
              retry: Optional[RetryPolicy] = None
              ) -> Dict[str, np.ndarray]:
        """Fetch shard ``gid`` from ``addr`` with bounded retries.

        Connect/read failures (flaky network, peer restarting) are
        transient: retried under ``retry`` (default: 3 attempts,
        exponential backoff). A ``KeyError`` — the peer answers but does
        not hold the shard — is a plan bug, never retried."""
        retry = retry or RetryPolicy(max_attempts=3, base_delay=0.1,
                                     max_delay=2.0, deadline=timeout)

        def _once():
            fault_point("shard.fetch", addr=addr, gid=gid)
            t0 = time.perf_counter()
            with socket.create_connection(addr, timeout=timeout) as sock:
                sock.sendall(_MAGIC + struct.pack("!I", gid))
                (n,) = struct.unpack("!I", _recv_exact(sock, 4))
                if n == 0:
                    raise KeyError(
                        f"peer {addr} does not hold shard {gid}")
                out = _decode_shard(_recv_exact(sock, n))
            _fetch_seconds.observe(time.perf_counter() - t0)
            _fetch_bytes.inc(n)
            return out

        return retry.call(_once)


def assign_shards(counts: Sequence[int]) -> List[List[int]]:
    """Deterministic locality-first balanced assignment.

    ``counts[h]`` = shards host ``h`` currently holds; global ids number
    hosts' shards consecutively (host 0 owns 0..counts[0]-1, ...).
    Returns per-host lists of global ids such that (a) totals differ by
    at most 1 (remainder goes to the lowest-indexed hosts, so every host
    derives the same plan), and (b) each host keeps its OWN shards up to
    its target before any shard moves — only the imbalance crosses the
    network (the ``assign_partitions_to_actors`` objective,
    ``ray_xshards.py:250``).
    """
    hosts = len(counts)
    total = sum(counts)
    targets = [total // hosts + (1 if h < total % hosts else 0)
               for h in range(hosts)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    own = [list(range(offsets[h], offsets[h + 1])) for h in range(hosts)]
    keep = [own[h][:targets[h]] for h in range(hosts)]
    surplus = [gid for h in range(hosts) for gid in own[h][targets[h]:]]
    out = []
    for h in range(hosts):
        need = targets[h] - len(keep[h])
        take, surplus = surplus[:need], surplus[need:]
        out.append(keep[h] + take)
    return out


_rebal_generation = 0
_rebal_gen_lock = threading.Lock()




def _kv_allgather(client, gen: int, tag: str, pid: int, nprocs: int,
                  value: str, timeout_s: float) -> List[str]:
    """Publish ``value`` under this process's key, then collect every
    peer's. Doubles as a barrier: nobody returns until all processes
    have published. A peer that never publishes (crashed, hung) makes
    the blocking get raise within ``timeout_s`` on every waiter."""
    prefix = f"zoo:rebalance:{gen}:{tag}:"
    t0 = time.perf_counter()
    client.key_value_set(prefix + str(pid), value)
    # one deadline for the WHOLE phase, re-derived per get — giving every
    # key the full budget would let N slow peers stack to N x timeout_s
    phase_deadline = time.monotonic() + timeout_s
    out = []
    for p in range(nprocs):
        ms = max(1000, int((phase_deadline - time.monotonic()) * 1000))
        try:
            out.append(client.blocking_key_value_get(prefix + str(p), ms))
        except Exception as e:
            raise TimeoutError(
                f"host {p} never reached rebalance phase {tag!r} within "
                f"{timeout_s:.0f}s (crashed or hung peer): {e}") from e
    # the time a host sits here is the stragglers' lead over it — the
    # cluster-wide max of this histogram is the rebalance skew
    _barrier_wait.labels(phase=tag).observe(time.perf_counter() - t0)
    return out


def rebalance_shards(shards, bind_ip: Optional[str] = None,
                     deadline: float = 120.0):
    """Exchange shards so every process holds a balanced, disjoint set.

    ``shards``: this process's :class:`LocalXShards` of dict-of-ndarray
    shards (each host contributes what it has — counts may differ).
    Returns this process's rebalanced ``LocalXShards``. Single-process:
    returns the input unchanged.

    Failure semantics: every phase is bounded by ``deadline`` seconds,
    and every host *always* reaches the post-fetch status exchange — a
    raised fetch error on one host surfaces as ``RuntimeError`` on ALL
    hosts (naming the failed ones), and a peer that dies outright makes
    everyone else time out within the deadline. The pre-fix behavior —
    one host skipping the teardown barrier and deadlocking every healthy
    peer — cannot recur: the status exchange *is* the barrier and is
    reached from both the success and the failure path.
    """
    import jax

    from zoo_tpu.orca.data.shard import LocalXShards

    parts = shards.collect() if hasattr(shards, "collect") else list(shards)
    if jax.process_count() == 1:
        return LocalXShards(parts)

    global _rebal_generation
    with _rebal_gen_lock:
        _rebal_generation += 1
        gen = _rebal_generation

    pid, nprocs = jax.process_index(), jax.process_count()
    client = _coordination_client()
    if client is None:  # pragma: no cover - jax internals moved
        raise RuntimeError(
            "rebalance_shards needs the JAX coordination service "
            "(jax.distributed.initialize) in multi-process mode")
    ip = bind_ip or _default_ip()
    t0 = time.monotonic()

    def remaining() -> float:
        left = deadline - (time.monotonic() - t0)
        if left <= 0:
            raise TimeoutError(
                f"shard rebalance deadline ({deadline}s) exhausted")
        return left

    counts = [int(c) for c in _kv_allgather(
        client, gen, "counts", pid, nprocs, str(len(parts)), remaining())]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    # serve our shards (keyed by global id), then announce (ip, port)
    # through the coordination service — the address allgather is also
    # the start barrier, so no peer fetches before every server is up;
    # the exchange must outlive the fetch phase on every host
    exchange = ShardExchange(
        {int(offsets[pid] + i): s for i, s in enumerate(parts)}, bind=ip)
    try:
        with span("rebalance_shards", gen=gen, pid=pid, nprocs=nprocs):
            table = _kv_allgather(client, gen, "addr", pid, nprocs,
                                  f"{ip}:{exchange.port}", remaining())
            addrs = []
            for row in table:
                host, port = row.rsplit(":", 1)
                addrs.append((host, int(port)))
            plan = assign_shards(counts)
            mine, error = [], None
            try:
                for gid in plan[pid]:
                    src = int(np.searchsorted(offsets, gid,
                                              side="right") - 1)
                    if src == pid:
                        mine.append(parts[gid - offsets[pid]])
                        continue
                    mine.append(ShardExchange.fetch(
                        addrs[src], gid, timeout=min(remaining(), 60.0)))
            except Exception as e:  # noqa: BLE001 — reported to every host
                error = e
                logger.error("shard fetch phase failed on host %d: %r",
                             pid, e)
            # status exchange doubles as the teardown barrier: every host
            # reaches it whether its fetches succeeded or not, and nobody
            # closes its shard server until all hosts have finished
            # fetching. Computed WITHOUT remaining() — which raises once
            # the deadline is spent — because the status publish must
            # happen even (above all) on the host that blew the deadline,
            # or its peers stall waiting for a verdict that never comes
            status_wait = max(5.0, deadline - (time.monotonic() - t0))
            status = _kv_allgather(
                client, gen, "status", pid, nprocs,
                "ok" if error is None else f"err:{error!r:.500}",
                status_wait)
            bad = {i: s for i, s in enumerate(status) if s != "ok"}
            if bad:
                raise RuntimeError(
                    f"shard rebalance failed on host(s) {sorted(bad)}: "
                    f"{bad}") from error
    finally:
        exchange.close()
    return LocalXShards(mine)


def _default_ip() -> str:
    """The address peers can reach us on: the interface that routes out
    (UDP connect trick — nothing is sent); loopback in single-host runs."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
