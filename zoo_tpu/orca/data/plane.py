"""Multi-host shard data plane: locality-aware shard exchange.

Rebuild of the reference's RayXShards movement layer
(``pyzoo/zoo/orca/data/ray_xshards.py:67`` — each Spark partition is put
into the plasma store on its node; ``:250`` ``assign_partitions_to_actors``
assigns actors to co-located partitions so only the imbalance actually
moves). The TPU-native shape of the same capability:

* every JAX process serves its local shards over an ephemeral TCP port
  (:class:`ShardExchange`) using a **non-executable** wire codec
  (protocol v2: per-array binary headers + raw tensor buffers decoded
  with ``np.frombuffer`` — never pickle, nothing on the wire can
  execute);
* shards are served **lazily from the original arrays** — nothing is
  pre-encoded, so serving N shards costs no extra resident memory and
  the payload bytes go from the array's own buffer to the socket via
  ``memoryview`` (no intermediate serialize copy);
* clients keep **persistent pooled connections** per peer and batch
  many global ids into one **multi-get** request whose responses stream
  back on the same connection, so per-fetch latency amortizes across
  the exchange (``ZOO_SHARD_POOL_SIZE`` idle connections per peer);
* peer discovery rides the JAX distributed runtime itself —
  the coordination-service KV store carries each host's (ip, port,
  count) triple, so there is no extra coordinator and no driver-side
  collect;
* :func:`assign_shards` computes the same deterministic, locality-first
  plan on every host: each host keeps as many of its own shards as the
  balanced target allows, and only surplus shards are fetched by deficit
  hosts;
* :func:`rebalance_shards` runs the whole exchange — fetches run
  concurrently across peers (``ZOO_SHARD_FETCH_CONCURRENCY`` threads,
  default 4) and can stream through a staged ingest pipeline
  (``stage_fn=jax.device_put``: device transfer of shard *k* overlaps
  the network fetch of shard *k+1* — see
  :mod:`zoo_tpu.orca.data.ingest`) — and returns this process's
  balanced, disjoint shard set, ready for the estimator's per-process
  feed into ``host_local_to_global`` (``parallel/mesh.py:152``).

Shards must be dicts of numpy arrays (the estimator feed format); use
``XShards.partition({"x": ..., "y": ...})``.

See ``docs/data_plane.md`` for the wire format and tuning knobs.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from zoo_tpu.obs.coordination import (
    # the rebalance control plane rides the coordination-service KV
    # store rather than XLA device collectives — see that module (the
    # helper is shared with trace-id propagation and metric aggregation)
    coordination_client as _coordination_client,
)
from zoo_tpu.obs.metrics import counter, histogram
from zoo_tpu.obs.tracing import span
from zoo_tpu.util.resilience import RetryPolicy, fault_point

__all__ = ["ShardExchange", "assign_shards", "rebalance_shards",
           "fetch_many", "ProtocolError"]

logger = logging.getLogger(__name__)

_fetch_seconds = histogram(
    "zoo_shard_fetch_seconds",
    "Cross-host shard fetch latency (one successful attempt; multi-get "
    "batches count once)")
_fetch_bytes = counter(
    "zoo_shard_fetch_bytes_total", "Shard payload bytes fetched from peers")
_fetch_requests = counter(
    "zoo_shard_fetch_requests_total",
    "Fetch requests by wire mode (single get vs pipelined multi-get)",
    labels=("mode",))
_pool_conns = counter(
    "zoo_shard_pool_connections_total",
    "Peer connections by pool event (opened = fresh TCP dial, reused = "
    "checked out of the per-peer pool)", labels=("event",))
_barrier_wait = histogram(
    "zoo_rebalance_barrier_wait_seconds",
    "Wall time spent in each rebalance KV-store barrier phase",
    labels=("phase",))

_MAGIC_V1 = b"ZSX1"
_MAGIC = b"ZSX2"
def _multiget_chunk() -> int:
    """Gids per multi-get: bounds the cost of a retried attempt (a
    mid-stream peer death refetches one chunk, not the whole plan) and
    keeps responses flowing while later chunks are queued. Read per
    call like the sibling knobs, so runtime env changes take effect."""
    return max(1, min(int(os.environ.get("ZOO_SHARD_MULTIGET", "32")),
                      0xFFFF))


class ProtocolError(RuntimeError):
    """Peer spoke a different exchange protocol (e.g. a v1 ``ZSX1``
    process in a mixed-version cluster). Deliberately loud AND
    deliberately not a ``ConnectionError``: a version mismatch is
    deterministic, so the retry policy must not burn its budget on
    it — upgrade peers in lockstep rather than silently corrupting
    shards."""


# --------------------------------------------------------------------- codec
# Wire codec v2: raw tensor framing. Per shard: i32 array count; per
# array: u16-length name, u16-length dtype descriptor, u8 rank, rank x
# u64 dims, u64 payload bytes, then the raw (C-order) buffer. Decoding
# is np.frombuffer over the received buffer — zero-copy, non-executable.

def _dtype_descr(dt: np.dtype) -> bytes:
    # '<f4'-style descriptors round-trip exactly (endianness included);
    # extension dtypes (bfloat16 via ml_dtypes) don't — their .str is a
    # raw-void alias — so ship the registered name instead. Anything
    # that round-trips NEITHER way (structured/record dtypes: .str is a
    # bare void alias and .name like 'void64' does not parse) must be
    # rejected HERE, at encode time, not as a confusing decode error on
    # the peer after bytes are already on the wire.
    s = dt.str
    try:
        if np.dtype(s) == dt:
            return s.encode("ascii")
    except TypeError:
        pass
    try:
        if np.dtype(dt.name) == dt:
            return dt.name.encode("ascii")
    except TypeError:
        pass
    raise TypeError(
        f"dtype {dt} has no round-trippable wire descriptor — the "
        "exchange codec ships plain numeric/bool/extension dtypes only "
        "(split structured arrays into one plain array per field)")


def _dtype_from_descr(descr: str) -> np.dtype:
    try:
        dt = np.dtype(descr)
    except TypeError:
        # extension dtypes register by name on import (jax always ships
        # ml_dtypes; bench/test processes may not have touched it yet)
        import ml_dtypes  # noqa: F401
        dt = np.dtype(descr)
    if dt.hasobject:
        raise ProtocolError(
            f"refusing object dtype {descr!r} from the wire (pickle "
            "vector; the exchange codec is non-executable)")
    return dt


def _payload_view(arr: np.ndarray) -> memoryview:
    """The array's raw bytes WITHOUT a serialize copy (contiguous
    arrays; a non-contiguous shard pays one compaction copy)."""
    a = np.ascontiguousarray(arr)
    if a.nbytes == 0:
        return memoryview(b"")
    try:
        return memoryview(a).cast("B")
    except (ValueError, TypeError):
        # extension dtypes (bfloat16) refuse the buffer protocol; a
        # uint8 view of the same memory does not copy
        return memoryview(a.reshape(-1).view(np.uint8))


def _check_shard(shard) -> None:
    if not isinstance(shard, dict) or not all(
            isinstance(v, np.ndarray) for v in shard.values()):
        raise TypeError(
            "the shard exchange ships dict-of-ndarray shards only; got "
            f"{type(shard).__name__} (convert DataFrame shards with "
            "to_dict('series') -> numpy first)")
    for k, v in shard.items():
        if v.dtype.hasobject:
            raise TypeError(
                f"array {k!r} has object dtype — the exchange codec is "
                "non-executable and refuses pickle-bearing arrays")
        _dtype_descr(v.dtype)  # unshippable dtypes fail fast, pre-wire


def _array_header(name: str, arr: np.ndarray) -> bytes:
    nb = name.encode("utf-8")
    db = _dtype_descr(arr.dtype)
    return (struct.pack("!H", len(nb)) + nb +
            struct.pack("!H", len(db)) + db +
            struct.pack("!B", arr.ndim) +
            struct.pack(f"!{arr.ndim}Q", *arr.shape) +
            struct.pack("!Q", arr.nbytes))


def _encode_shard(shard: Dict[str, np.ndarray]) -> bytes:
    """Whole-shard v2 blob (header+payload frames). The server never
    calls this — it streams headers and payload views separately — but
    the framing is identical, so tests and file staging share it."""
    _check_shard(shard)
    parts: List[bytes] = [struct.pack("!i", len(shard))]
    for name, arr in shard.items():
        parts.append(_array_header(name, arr))
        parts.append(bytes(_payload_view(arr)))
    return b"".join(parts)


def _decode_shard(blob) -> Dict[str, np.ndarray]:
    view = memoryview(blob)
    (count,) = struct.unpack("!i", view[:4])
    off = 4
    out: Dict[str, np.ndarray] = {}
    for _ in range(count):
        name, arr, off = _decode_array(view, off)
        out[name] = arr
    return out


def _parse_array_header(read) -> Tuple[str, np.dtype, Tuple[int, ...],
                                       int, int]:
    """Parse one array header via ``read(n) -> buffer`` (a socket's
    recv-exact or a memoryview cursor — ONE parser for both, so the
    wire layout cannot drift between them). Returns (name, dtype,
    shape, payload bytes, header bytes consumed).

    The payload length is validated against prod(shape) * itemsize
    BEFORE anyone allocates for it: a corrupt or desynchronized peer
    must surface as a loud :class:`ProtocolError`, not a ~2^60-byte
    ``bytearray`` feeding the OOM killer."""
    (nlen,) = struct.unpack("!H", read(2))
    name = bytes(read(nlen)).decode("utf-8")
    (dlen,) = struct.unpack("!H", read(2))
    dt = _dtype_from_descr(bytes(read(dlen)).decode("ascii"))
    (ndim,) = struct.unpack("!B", read(1))
    shape = struct.unpack(f"!{ndim}Q", read(8 * ndim))
    (nbytes,) = struct.unpack("!Q", read(8))
    expected = dt.itemsize
    for d in shape:
        expected *= int(d)  # python ints: dims cannot overflow this
    if nbytes != expected:
        raise ProtocolError(
            f"array {name!r}: payload length {nbytes} does not match "
            f"shape {tuple(int(d) for d in shape)} x dtype {dt} "
            f"({expected} bytes) — corrupt or desynchronized stream")
    return name, dt, shape, nbytes, 13 + nlen + dlen + 8 * ndim


def _decode_array(view: memoryview, off: int
                  ) -> Tuple[str, np.ndarray, int]:
    pos = [off]

    def read(n: int):
        out = view[pos[0]:pos[0] + n]
        if len(out) != n:
            raise ProtocolError("truncated shard blob")
        pos[0] += n
        return out

    name, dt, shape, nbytes, _ = _parse_array_header(read)
    # frombuffer shares the received buffer: the decoded array is the
    # recv buffer, no copy (writable because the buffer is a bytearray)
    arr = np.frombuffer(read(nbytes), dtype=dt).reshape(shape)
    return name, arr, pos[0]


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # preallocate + recv_into: shards are tens of MB, so quadratic
    # bytes-concat accumulation would dominate the exchange; return the
    # bytearray itself — bytes(out) would re-copy the whole blob, and
    # every caller (magic compare, struct.unpack, frombuffer) takes it
    out = bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed mid-message")
        got += r
    return out


# ---------------------------------------------------------------- conn pool

class _ConnPool:
    """Per-peer idle-connection pool. ``acquire`` hands back a pooled
    socket (metric event ``reused``) or dials a fresh one (``opened``);
    ``release`` returns it for the next fetch. A connection that errors
    mid-RPC must be closed and the peer's pool invalidated — the stream
    is poisoned and every idle sibling probably points at the same dead
    peer."""

    def __init__(self, max_idle_per_peer: Optional[int] = None):
        self._idle: Dict[Tuple[str, int], List[socket.socket]] = {}
        self._lock = threading.Lock()
        self._max_idle = max_idle_per_peer

    @property
    def max_idle(self) -> int:
        if self._max_idle is not None:
            return self._max_idle
        return max(1, int(os.environ.get("ZOO_SHARD_POOL_SIZE", "4")))

    def acquire(self, addr: Tuple[str, int],
                timeout: float) -> socket.socket:
        with self._lock:
            lst = self._idle.get(addr)
            sock = lst.pop() if lst else None
        if sock is not None:
            _pool_conns.labels(event="reused").inc()
            sock.settimeout(timeout)
            return sock
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _pool_conns.labels(event="opened").inc()
        return sock

    def release(self, addr: Tuple[str, int], sock: socket.socket):
        with self._lock:
            lst = self._idle.setdefault(addr, [])
            if len(lst) < self.max_idle:
                lst.append(sock)
                return
        sock.close()

    def invalidate(self, addr: Tuple[str, int]):
        with self._lock:
            stale = self._idle.pop(addr, [])
        for s in stale:
            try:
                s.close()
            except OSError:
                pass

    def clear(self):
        with self._lock:
            all_addrs = list(self._idle)
        for a in all_addrs:
            self.invalidate(a)


_pool = _ConnPool()


# ------------------------------------------------------------------- server

class ShardExchange:
    """Serve this process's shards (by global id) to peer hosts.

    Protocol v2: request = ``ZSX2`` + u16 count + count x u32 global
    ids (a multi-get — count=1 is the single fetch); response, per gid
    in request order = ``ZSX2`` + u32 gid + i32 array count (-1 = not
    held here) + the raw-tensor frames of the shard. Payloads leave
    through ``memoryview`` of the original arrays — nothing is
    pre-encoded and nothing on the wire can execute code. A ``ZSX1``
    (protocol v1) request is rejected loudly and the connection
    dropped: mixed-version clusters must fail, not corrupt. The port is
    ephemeral, announced only through the JAX coordination service, and
    the server thread dies with the process.
    """

    def __init__(self, shards_by_gid: Dict[int, Dict[str, np.ndarray]],
                 bind: str = "0.0.0.0"):
        for s in shards_by_gid.values():
            _check_shard(s)
        # served lazily from the caller's arrays: no blob copies, no
        # doubled resident memory while the exchange is open
        self._shards = dict(shards_by_gid)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self.connections_accepted = 0  # pool-reuse observability/tests
        self._closed = False
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self.connections_accepted += 1
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                while True:
                    try:
                        magic = _recv_exact(conn, 4)
                    except ConnectionError:
                        return
                    if magic == _MAGIC_V1:
                        logger.error(
                            "shard exchange: protocol-v1 (ZSX1) peer "
                            "contacted this v2 server — mixed exchange "
                            "versions in one cluster; upgrade every "
                            "host in lockstep. Dropping the connection.")
                        return
                    if magic != _MAGIC:
                        return  # not our protocol: drop the connection
                    (count,) = struct.unpack("!H", _recv_exact(conn, 2))
                    gids = struct.unpack(f"!{count}I",
                                         _recv_exact(conn, 4 * count))
                    for gid in gids:
                        fault_point("shard.serve", gid=gid)
                        self._send_shard(conn, gid)
        except OSError:
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _send_shard(self, conn: socket.socket, gid: int):
        shard = self._shards.get(gid)
        if shard is None:
            conn.sendall(_MAGIC + struct.pack("!Ii", gid, -1))
            return
        conn.sendall(_MAGIC + struct.pack("!Ii", gid, len(shard)))
        for name, arr in shard.items():
            conn.sendall(_array_header(name, arr))
            payload = _payload_view(arr)
            if payload.nbytes:
                conn.sendall(payload)

    def close(self):
        self._closed = True
        try:
            # wake the accept() thread (it holds the kernel socket — and
            # the port — alive through a bare close(); shutdown makes the
            # blocked accept return EINVAL immediately)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        # drop live per-connection sockets too: clients of a closed
        # exchange must fail fast (and free the port for a restart)
        # instead of hanging on a half-dead stream. SO_LINGER 0 sends
        # RST and destroys the socket outright — a graceful FIN would
        # park the 4-tuple in FIN_WAIT_2 against every pooled client
        # connection, keeping the port unusable for ~a minute
        with self._conns_lock:
            stale = list(self._conns)
            self._conns.clear()
        for c in stale:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                c.close()
            except OSError:
                pass

    @staticmethod
    def fetch(addr: Tuple[str, int], gid: int, timeout: float = 60.0,
              retry: Optional[RetryPolicy] = None, pool: bool = True
              ) -> Dict[str, np.ndarray]:
        """Fetch shard ``gid`` from ``addr`` with bounded retries.

        Connect/read failures (flaky network, peer restarting) are
        transient: retried under ``retry`` (default: 3 attempts,
        exponential backoff), each attempt on a FRESH connection (the
        pooled one is invalidated — its stream is poisoned). A
        ``KeyError`` — the peer answers but does not hold the shard —
        is a plan bug, never retried. ``pool=False`` opens and closes
        one connection per call (the pre-v2 behavior; kept as the
        microbench baseline)."""
        return fetch_many(addr, [gid], timeout=timeout, retry=retry,
                          pool=pool)[gid]


# ------------------------------------------------------------------- client

def _read_shard(sock: socket.socket) -> Tuple[int, Optional[Dict], int]:
    """One response frame → (gid, shard-or-None, bytes received)."""
    head = _recv_exact(sock, 12)
    if head[:4] != _MAGIC:
        raise ProtocolError(
            f"peer answered with magic {bytes(head[:4])!r}, expected "
            f"{_MAGIC!r} — protocol version mismatch (v1 peer in a v2 "
            "cluster?)")
    gid, count = struct.unpack("!Ii", bytes(head[4:]))
    if count < 0:
        return gid, None, 12
    shard: Dict[str, np.ndarray] = {}
    total = 12
    for _ in range(count):
        name, dt, shape, nbytes, header_len = _parse_array_header(
            lambda n: _recv_exact(sock, n))
        buf = _recv_exact(sock, nbytes) if nbytes else b""
        # the decoded array WRAPS the recv buffer — no copy
        shard[name] = np.frombuffer(memoryview(buf),
                                    dtype=dt).reshape(shape)
        total += header_len + nbytes
    return gid, shard, total


def _fetch_chunk_once(addr: Tuple[str, int], gids: Sequence[int],
                      timeout: float, pool: bool) -> Dict[int, Dict]:
    """One pipelined multi-get attempt: N gids in one write, responses
    streamed back on the same connection."""
    for gid in gids:
        fault_point("shard.fetch", addr=addr, gid=gid)
    _fetch_requests.labels(
        mode="multi" if len(gids) > 1 else "single").inc()
    t0 = time.perf_counter()
    if pool:
        sock = _pool.acquire(addr, timeout)
    else:
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _pool_conns.labels(event="opened").inc()
    reusable = False
    try:
        sock.settimeout(timeout)
        sock.sendall(_MAGIC + struct.pack(f"!H{len(gids)}I",
                                          len(gids), *gids))
        out: Dict[int, Dict] = {}
        total = 0
        for want in gids:
            gid, shard, nbytes = _read_shard(sock)
            if gid != want:
                raise ProtocolError(
                    f"peer {addr} answered gid {gid} for request {want} "
                    "— desynchronized stream")
            if shard is None:
                raise KeyError(f"peer {addr} does not hold shard {gid}")
            out[gid] = shard
            total += nbytes
        reusable = pool
        _fetch_seconds.observe(time.perf_counter() - t0)
        _fetch_bytes.inc(total)
        return out
    except (ConnectionError, OSError):
        # poisoned stream AND probably a dead peer: every pooled
        # sibling connection is suspect — drop them so the retry dials
        # fresh instead of drawing another corpse from the pool
        _pool.invalidate(addr)
        raise
    finally:
        if reusable:
            _pool.release(addr, sock)
        else:
            # KeyError leaves unread responses in flight; error paths
            # leave a torn stream — never pool either
            try:
                sock.close()
            except OSError:
                pass


def fetch_many(addr: Tuple[str, int], gids: Sequence[int],
               timeout: float = 60.0,
               retry: Optional[RetryPolicy] = None,
               pool: bool = True) -> Dict[int, Dict[str, np.ndarray]]:
    """Fetch many shards from one peer with pipelined multi-gets.

    ``gids`` are split into chunks of ``ZOO_SHARD_MULTIGET`` (default
    32); each chunk is one wire round trip (one request write, streamed
    responses) retried independently under ``retry`` — a peer dying
    mid-stream costs one chunk's refetch on a fresh connection, and
    ``fault_point("shard.fetch")`` fires per gid per attempt exactly as
    it did for single fetches."""
    gids = [int(g) for g in gids]
    retry = retry or RetryPolicy(max_attempts=3, base_delay=0.1,
                                 max_delay=2.0, deadline=timeout)
    out: Dict[int, Dict[str, np.ndarray]] = {}
    chunk = _multiget_chunk()
    for i in range(0, len(gids), chunk):
        part = gids[i:i + chunk]
        out.update(retry.call(_fetch_chunk_once, addr, part, timeout,
                              pool))
    return out


def iter_fetch(sources: Sequence[Tuple[Tuple[str, int], Sequence[int]]],
               timeout=60.0,
               concurrency: Optional[int] = None,
               retry: Optional[RetryPolicy] = None
               ) -> Iterable[Tuple[int, Dict[str, np.ndarray]]]:
    """Stream ``(gid, shard)`` pairs from many peers as they arrive.

    ``sources`` = [(addr, gids), ...]. Chunks fan out over a bounded
    thread pool (``ZOO_SHARD_FETCH_CONCURRENCY``, default 4) and
    completed chunks yield immediately — the generator is the *fetch
    stage* of the ingest pipeline, so a consumer wrapping it in
    :func:`zoo_tpu.orca.data.ingest.staged_pipeline` overlaps device
    transfer of earlier shards with the network fetch of later ones.
    Ordering across peers is completion order, not plan order.

    ``timeout`` may be a callable re-evaluated when each chunk STARTS
    (not when it was queued) — rebalance passes its ``remaining()``
    budget so queued chunks cannot stack fresh 60s retry deadlines past
    the phase deadline; once the budget is spent the callable raises
    and every pending chunk fails fast."""
    if concurrency is None:
        concurrency = max(1, int(os.environ.get(
            "ZOO_SHARD_FETCH_CONCURRENCY", "4")))
    timeout_fn = timeout if callable(timeout) else (lambda: timeout)
    chunk = _multiget_chunk()
    tasks = []
    for addr, gids in sources:
        gids = list(gids)
        for i in range(0, len(gids), chunk):
            tasks.append((addr, gids[i:i + chunk]))
    if not tasks:
        return

    def _run(addr, part):
        return fetch_many(addr, part, timeout=timeout_fn(), retry=retry)

    tp = ThreadPoolExecutor(max_workers=min(concurrency, len(tasks)),
                            thread_name_prefix="zoo-shard-fetch")
    futs = [tp.submit(_run, addr, part) for addr, part in tasks]
    try:
        for fut in as_completed(futs):
            yield from fut.result().items()
        tp.shutdown(wait=True)
    except BaseException:
        # early exit (consumer broke out / pipeline torn down / a chunk
        # raised): nobody will consume the remaining chunks, so do NOT
        # sit out their full retry budgets — drop queued work and leave
        # in-flight chunks to finish on their own threads
        tp.shutdown(wait=False, cancel_futures=True)
        raise


def assign_shards(counts: Sequence[int]) -> List[List[int]]:
    """Deterministic locality-first balanced assignment.

    ``counts[h]`` = shards host ``h`` currently holds; global ids number
    hosts' shards consecutively (host 0 owns 0..counts[0]-1, ...).
    Returns per-host lists of global ids such that (a) totals differ by
    at most 1 (remainder goes to the lowest-indexed hosts, so every host
    derives the same plan), and (b) each host keeps its OWN shards up to
    its target before any shard moves — only the imbalance crosses the
    network (the ``assign_partitions_to_actors`` objective,
    ``ray_xshards.py:250``).
    """
    hosts = len(counts)
    total = sum(counts)
    targets = [total // hosts + (1 if h < total % hosts else 0)
               for h in range(hosts)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    own = [list(range(offsets[h], offsets[h + 1])) for h in range(hosts)]
    keep = [own[h][:targets[h]] for h in range(hosts)]
    surplus = [gid for h in range(hosts) for gid in own[h][targets[h]:]]
    out = []
    for h in range(hosts):
        need = targets[h] - len(keep[h])
        take, surplus = surplus[:need], surplus[need:]
        out.append(keep[h] + take)
    return out


_rebal_generation = 0
_rebal_gen_lock = threading.Lock()




def _kv_allgather(client, gen: int, tag: str, pid: int, nprocs: int,
                  value: str, timeout_s: float) -> List[str]:
    """Publish ``value`` under this process's key, then collect every
    peer's. Doubles as a barrier: nobody returns until all processes
    have published. A peer that never publishes (crashed, hung) makes
    the blocking get raise within ``timeout_s`` on every waiter."""
    prefix = f"zoo:rebalance:{gen}:{tag}:"
    t0 = time.perf_counter()
    client.key_value_set(prefix + str(pid), value)
    # one deadline for the WHOLE phase, re-derived per get — giving every
    # key the full budget would let N slow peers stack to N x timeout_s
    phase_deadline = time.monotonic() + timeout_s
    out = []
    for p in range(nprocs):
        ms = max(1000, int((phase_deadline - time.monotonic()) * 1000))
        try:
            out.append(client.blocking_key_value_get(prefix + str(p), ms))
        except Exception as e:
            raise TimeoutError(
                f"host {p} never reached rebalance phase {tag!r} within "
                f"{timeout_s:.0f}s (crashed or hung peer): {e}") from e
    # the time a host sits here is the stragglers' lead over it — the
    # cluster-wide max of this histogram is the rebalance skew
    _barrier_wait.labels(phase=tag).observe(time.perf_counter() - t0)
    return out


def rebalance_shards(shards, bind_ip: Optional[str] = None,
                     deadline: float = 120.0, stage_fn=None):
    """Exchange shards so every process holds a balanced, disjoint set.

    ``shards``: this process's :class:`LocalXShards` of dict-of-ndarray
    shards (each host contributes what it has — counts may differ).
    Returns this process's rebalanced ``LocalXShards``. Single-process:
    returns the input unchanged (staged through ``stage_fn`` if given).

    ``stage_fn``: optional per-shard ingest hook (e.g.
    ``jax.device_put``). Fetched shards stream through a staged
    pipeline (:mod:`zoo_tpu.orca.data.ingest`) while later fetches are
    still in flight, so device transfer overlaps the network exchange;
    locally-kept shards are staged inline during final assembly. The
    returned shard ORDER is identical with and without ``stage_fn`` —
    the deterministic :func:`assign_shards` plan.

    Failure semantics: every phase is bounded by ``deadline`` seconds,
    and every host *always* reaches the post-fetch status exchange — a
    raised fetch error on one host surfaces as ``RuntimeError`` on ALL
    hosts (naming the failed ones), and a peer that dies outright makes
    everyone else time out within the deadline. The pre-fix behavior —
    one host skipping the teardown barrier and deadlocking every healthy
    peer — cannot recur: the status exchange *is* the barrier and is
    reached from both the success and the failure path.
    """
    import jax

    from zoo_tpu.orca.data.shard import LocalXShards

    parts = shards.collect() if hasattr(shards, "collect") else list(shards)
    if jax.process_count() == 1:
        if stage_fn is not None:
            from zoo_tpu.orca.data.ingest import staged_pipeline
            with staged_pipeline(iter(parts),
                                 [("ingest", stage_fn)]) as pipe:
                parts = list(pipe)
        return LocalXShards(parts)

    global _rebal_generation
    with _rebal_gen_lock:
        _rebal_generation += 1
        gen = _rebal_generation

    pid, nprocs = jax.process_index(), jax.process_count()
    client = _coordination_client()
    if client is None:  # pragma: no cover - jax internals moved
        raise RuntimeError(
            "rebalance_shards needs the JAX coordination service "
            "(jax.distributed.initialize) in multi-process mode")
    ip = bind_ip or _default_ip()
    t0 = time.monotonic()

    def remaining() -> float:
        left = deadline - (time.monotonic() - t0)
        if left <= 0:
            raise TimeoutError(
                f"shard rebalance deadline ({deadline}s) exhausted")
        return left

    counts = [int(c) for c in _kv_allgather(
        client, gen, "counts", pid, nprocs, str(len(parts)), remaining())]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    # serve our shards (keyed by global id), then announce (ip, port)
    # through the coordination service — the address allgather is also
    # the start barrier, so no peer fetches before every server is up;
    # the exchange must outlive the fetch phase on every host
    exchange = ShardExchange(
        {int(offsets[pid] + i): s for i, s in enumerate(parts)}, bind=ip)
    try:
        with span("rebalance_shards", gen=gen, pid=pid, nprocs=nprocs):
            table = _kv_allgather(client, gen, "addr", pid, nprocs,
                                  f"{ip}:{exchange.port}", remaining())
            addrs = []
            for row in table:
                host, port = row.rsplit(":", 1)
                addrs.append((host, int(port)))
            plan = assign_shards(counts)
            mine, error = [], None
            try:
                mine = _fetch_plan(plan[pid], pid, offsets, addrs, parts,
                                   remaining, stage_fn)
            except Exception as e:  # noqa: BLE001 — reported to every host
                error = e
                logger.error("shard fetch phase failed on host %d: %r",
                             pid, e)
            # status exchange doubles as the teardown barrier: every host
            # reaches it whether its fetches succeeded or not, and nobody
            # closes its shard server until all hosts have finished
            # fetching. Computed WITHOUT remaining() — which raises once
            # the deadline is spent — because the status publish must
            # happen even (above all) on the host that blew the deadline,
            # or its peers stall waiting for a verdict that never comes
            status_wait = max(5.0, deadline - (time.monotonic() - t0))
            status = _kv_allgather(
                client, gen, "status", pid, nprocs,
                "ok" if error is None else f"err:{error!r:.500}",
                status_wait)
            bad = {i: s for i, s in enumerate(status) if s != "ok"}
            if bad:
                raise RuntimeError(
                    f"shard rebalance failed on host(s) {sorted(bad)}: "
                    f"{bad}") from error
    finally:
        exchange.close()
        # the exchange is gone with its port: pooled connections to ANY
        # peer's per-rebalance server are dead weight after teardown
        _pool.clear()
    return LocalXShards(mine)


def _fetch_plan(my_plan: Sequence[int], pid: int, offsets, addrs,
                parts, remaining, stage_fn) -> List:
    """Materialize this host's planned shard list: local shards by
    reference, remote ones via concurrent pipelined multi-gets (grouped
    per source peer), optionally streamed through the ingest pipeline
    so device placement overlaps the network fetch."""
    import itertools

    local_gids: List[int] = []
    by_src: Dict[int, List[int]] = {}
    for gid in my_plan:
        src = int(np.searchsorted(offsets, gid, side="right") - 1)
        if src == pid:
            local_gids.append(gid)
        else:
            by_src.setdefault(src, []).append(gid)
    source_list = [(addrs[src], gids) for src, gids in by_src.items()]
    staged: Dict[int, Dict] = {}
    # the phase budget is re-read when each chunk starts: N queued
    # chunks must not stack N fresh 60s retry deadlines past the
    # rebalance deadline (remaining() raises once it is spent, so
    # pending chunks fail fast and every host reaches the status
    # barrier together)
    stream = iter_fetch(source_list,
                        timeout=lambda: min(remaining(), 60.0))
    if stage_fn is None:
        for gid, shard in stream:
            staged[gid] = shard
        local_set = set(local_gids)
        return [parts[gid - offsets[pid]] if gid in local_set
                else staged[gid] for gid in my_plan]
    from zoo_tpu.orca.data.ingest import staged_pipeline
    # ONE stream for local and remote shards: locals lead (available
    # immediately, so their device placement starts before the first
    # fetch completes — on the locality-first plan most shards are
    # local, and staging them after the network phase would waste the
    # whole fetch window), then fetched shards as they arrive. The
    # pipeline's producer thread drains the stream while its stage
    # thread runs stage_fn (device_put): transfer of shard k overlaps
    # the fetch of shard k+1.
    locals_iter = ((gid, parts[gid - offsets[pid]]) for gid in local_gids)
    with staged_pipeline(
            itertools.chain(locals_iter, stream),
            [("ingest", lambda kv: (kv[0], stage_fn(kv[1])))]) as pipe:
        for gid, shard in pipe:
            staged[gid] = shard
    return [staged[gid] for gid in my_plan]


def _default_ip() -> str:
    """The address peers can reach us on: the interface that routes out
    (UDP connect trick — nothing is sent); loopback in single-host runs."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
