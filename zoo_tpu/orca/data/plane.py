"""Multi-host shard data plane: locality-aware shard exchange.

Rebuild of the reference's RayXShards movement layer
(``pyzoo/zoo/orca/data/ray_xshards.py:67`` — each Spark partition is put
into the plasma store on its node; ``:250`` ``assign_partitions_to_actors``
assigns actors to co-located partitions so only the imbalance actually
moves). The TPU-native shape of the same capability:

* every JAX process serves its local shards over an ephemeral TCP port
  (:class:`ShardExchange`) using a **non-executable** codec (length-framed
  ``.npz`` — ``numpy.load(allow_pickle=False)``, never pickle);
* peer discovery rides the JAX distributed runtime itself —
  ``multihost_utils.process_allgather`` of each host's (ip, port, count)
  triple, so there is no extra coordinator and no driver-side collect;
* :func:`assign_shards` computes the same deterministic, locality-first
  plan on every host: each host keeps as many of its own shards as the
  balanced target allows, and only surplus shards are fetched by deficit
  hosts;
* :func:`rebalance_shards` runs the whole exchange and returns this
  process's balanced, disjoint shard set — ready for the estimator's
  per-process feed into ``host_local_to_global``
  (``parallel/mesh.py:152``).

Shards must be dicts of numpy arrays (the estimator feed format); use
``XShards.partition({"x": ..., "y": ...})``.
"""

from __future__ import annotations

import io
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ShardExchange", "assign_shards", "rebalance_shards"]

_MAGIC = b"ZSX1"


def _encode_shard(shard: Dict[str, np.ndarray]) -> bytes:
    if not isinstance(shard, dict) or not all(
            isinstance(v, np.ndarray) for v in shard.values()):
        raise TypeError(
            "the shard exchange ships dict-of-ndarray shards only; got "
            f"{type(shard).__name__} (convert DataFrame shards with "
            "to_dict('series') -> numpy first)")
    buf = io.BytesIO()
    np.savez(buf, **shard)
    blob = buf.getvalue()
    if len(blob) > 0xFFFFFFFF:
        raise ValueError(
            f"shard encodes to {len(blob)} bytes, over the exchange's "
            "u32 frame limit (4 GiB) — split it before shipping")
    return blob


def _decode_shard(blob: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # preallocate + recv_into: shards are tens of MB, so quadratic
    # bytes-concat accumulation would dominate the exchange; return the
    # bytearray itself — bytes(out) would re-copy the whole blob, and
    # every caller (magic compare, struct.unpack, BytesIO) takes it
    out = bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed mid-message")
        got += r
    return out


class ShardExchange:
    """Serve this process's shards (by global id) to peer hosts.

    Protocol: request = ``ZSX1`` + u32 global id; response = u32 length +
    npz bytes (length 0 = not held here). The codec cannot execute code
    on either end. The port is ephemeral, announced only through the JAX
    coordination service, and the server thread dies with the process.
    """

    def __init__(self, shards_by_gid: Dict[int, Dict[str, np.ndarray]],
                 bind: str = "0.0.0.0"):
        self._blobs = {gid: _encode_shard(s)
                       for gid, s in shards_by_gid.items()}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                while True:
                    try:
                        head = _recv_exact(conn, 8)
                    except ConnectionError:
                        return
                    if head[:4] != _MAGIC:
                        return  # not our protocol: drop the connection
                    (gid,) = struct.unpack("!I", head[4:])
                    blob = self._blobs.get(gid)
                    if blob is None:
                        conn.sendall(struct.pack("!I", 0))
                    else:
                        conn.sendall(struct.pack("!I", len(blob)) + blob)
        except OSError:
            pass

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    @staticmethod
    def fetch(addr: Tuple[str, int], gid: int) -> Dict[str, np.ndarray]:
        with socket.create_connection(addr, timeout=60) as sock:
            sock.sendall(_MAGIC + struct.pack("!I", gid))
            (n,) = struct.unpack("!I", _recv_exact(sock, 4))
            if n == 0:
                raise KeyError(f"peer {addr} does not hold shard {gid}")
            return _decode_shard(_recv_exact(sock, n))


def assign_shards(counts: Sequence[int]) -> List[List[int]]:
    """Deterministic locality-first balanced assignment.

    ``counts[h]`` = shards host ``h`` currently holds; global ids number
    hosts' shards consecutively (host 0 owns 0..counts[0]-1, ...).
    Returns per-host lists of global ids such that (a) totals differ by
    at most 1 (remainder goes to the lowest-indexed hosts, so every host
    derives the same plan), and (b) each host keeps its OWN shards up to
    its target before any shard moves — only the imbalance crosses the
    network (the ``assign_partitions_to_actors`` objective,
    ``ray_xshards.py:250``).
    """
    hosts = len(counts)
    total = sum(counts)
    targets = [total // hosts + (1 if h < total % hosts else 0)
               for h in range(hosts)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    own = [list(range(offsets[h], offsets[h + 1])) for h in range(hosts)]
    keep = [own[h][:targets[h]] for h in range(hosts)]
    surplus = [gid for h in range(hosts) for gid in own[h][targets[h]:]]
    out = []
    for h in range(hosts):
        need = targets[h] - len(keep[h])
        take, surplus = surplus[:need], surplus[need:]
        out.append(keep[h] + take)
    return out


def rebalance_shards(shards, bind_ip: Optional[str] = None):
    """Exchange shards so every process holds a balanced, disjoint set.

    ``shards``: this process's :class:`LocalXShards` of dict-of-ndarray
    shards (each host contributes what it has — counts may differ).
    Returns this process's rebalanced ``LocalXShards``. Single-process:
    returns the input unchanged.
    """
    import jax

    from zoo_tpu.orca.data.shard import LocalXShards

    parts = shards.collect() if hasattr(shards, "collect") else list(shards)
    if jax.process_count() == 1:
        return LocalXShards(parts)

    from jax.experimental import multihost_utils

    pid = jax.process_index()
    ip = bind_ip or _default_ip()
    # announce (ip, port, count) through the coordination service; the
    # exchange must outlive the fetch phase on every host
    counts_probe = multihost_utils.process_allgather(
        np.asarray([len(parts)], np.int32)).reshape(-1)
    offsets = np.concatenate([[0], np.cumsum(counts_probe)]).astype(int)
    exchange = ShardExchange(
        {int(offsets[pid] + i): s for i, s in enumerate(parts)},
        bind=ip)
    try:
        me = np.asarray(list(_ip_to_words(ip)) + [exchange.port],
                        np.int64)
        table = multihost_utils.process_allgather(me)
        addrs = [(_words_to_ip(row[:-1]), int(row[-1])) for row in table]
        plan = assign_shards([int(c) for c in counts_probe])
        mine = []
        for gid in plan[pid]:
            src = int(np.searchsorted(offsets, gid, side="right") - 1)
            if src == pid:
                mine.append(parts[gid - offsets[pid]])
            else:
                mine.append(ShardExchange.fetch(addrs[src], gid))
        # barrier: nobody tears their server down while a peer still fetches
        multihost_utils.sync_global_devices("zoo_tpu_shard_rebalance")
    finally:
        exchange.close()
    return LocalXShards(mine)


def _default_ip() -> str:
    """The address peers can reach us on: the interface that routes out
    (UDP connect trick — nothing is sent); loopback in single-host runs."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _ip_to_words(ip: str):
    return [int(b) for b in socket.inet_aton(ip)]


def _words_to_ip(words) -> str:
    return socket.inet_ntoa(bytes(int(w) for w in words))
