"""Multi-host shard data plane: locality-aware shard exchange.

Rebuild of the reference's RayXShards movement layer
(``pyzoo/zoo/orca/data/ray_xshards.py:67`` — each Spark partition is put
into the plasma store on its node; ``:250`` ``assign_partitions_to_actors``
assigns actors to co-located partitions so only the imbalance actually
moves). The TPU-native shape of the same capability:

* every JAX process serves its local shards over an ephemeral TCP port
  (:class:`ShardExchange`) using a **non-executable** wire codec
  (protocol v2: per-array binary headers + raw tensor buffers decoded
  with ``np.frombuffer`` — never pickle, nothing on the wire can
  execute);
* shards are served **lazily from the original arrays** — nothing is
  pre-encoded, so serving N shards costs no extra resident memory and
  the payload bytes go from the array's own buffer to the socket via
  ``memoryview`` (no intermediate serialize copy);
* clients keep **persistent pooled connections** per peer and batch
  many global ids into one **multi-get** request whose responses stream
  back on the same connection, so per-fetch latency amortizes across
  the exchange (``ZOO_SHARD_POOL_SIZE`` idle connections per peer);
* each fresh connection runs a one-round **ZSXN negotiation**: the
  fetcher proposes wire dtype narrowing (``ZOO_SHARD_WIRE_DTYPE``),
  compression (``ZOO_SHARD_WIRE_COMPRESS``) and the same-host
  shared-memory payload lane (``ZOO_SHARD_LANE``, probe-verified —
  see :mod:`zoo_tpu.orca.data.shm`); a legacy ZSX2-only peer drops the
  hello and the client falls back to the plain protocol (loudly when a
  feature was explicitly requested);
* peer discovery rides the JAX distributed runtime itself —
  the coordination-service KV store carries each host's (ip, port,
  count) triple, so there is no extra coordinator and no driver-side
  collect;
* :func:`assign_shards` computes the same deterministic, locality-first
  plan on every host: each host keeps as many of its own shards as the
  balanced target allows, and only surplus shards are fetched by deficit
  hosts;
* :func:`rebalance_shards` runs the whole exchange — fetches run
  concurrently across peers and can stream through a staged ingest
  pipeline (``stage_fn=jax.device_put``: device transfer of shard *k*
  overlaps the network fetch of shard *k+1* — see
  :mod:`zoo_tpu.orca.data.ingest`), with an adaptive readahead
  controller growing/shrinking fetch concurrency and multi-get chunk
  size toward the point where the fetch leg fully hides under
  decode + device placement — and returns this process's balanced,
  disjoint shard set, ready for the estimator's per-process feed into
  ``host_local_to_global`` (``parallel/mesh.py:152``).

All client knobs are parsed from the environment ONCE per
:class:`ExchangeConfig` (not per call) — the config object is the
single mutation point the readahead controller adjusts.

Shards must be dicts of numpy arrays (the estimator feed format); use
``XShards.partition({"x": ..., "y": ...})``.

See ``docs/data_plane.md`` for the wire format and tuning knobs.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from zoo_tpu.obs.coordination import (
    # the rebalance control plane rides the coordination-service KV
    # store rather than XLA device collectives — see that module (the
    # helper is shared with trace-id propagation and metric aggregation)
    coordination_client as _coordination_client,
)
from zoo_tpu.obs.metrics import counter, histogram
from zoo_tpu.obs.tracing import span
from zoo_tpu.orca.data import shm as _shm
from zoo_tpu.orca.data.wire_codec import (
    FLAG_COMPRESSED,
    FLAG_CRC,
    FLAG_NARROWED,
    FLAG_SHM,
    WirePolicy,
    decode_payload,
    encode_array,
    payload_view as _payload_view,
    supported_codecs,
    supported_wire_dtypes,
)
from zoo_tpu.util.integrity import (
    corrupt_seam,
    frame_crc,
    verify_crc,
    wire_crc_enabled,
)
from zoo_tpu.util.resilience import RetryPolicy, fault_point

__all__ = ["ShardExchange", "ExchangeConfig", "assign_shards",
           "rebalance_shards", "fetch_many", "iter_fetch",
           "ProtocolError"]

logger = logging.getLogger(__name__)

_fetch_seconds = histogram(
    "zoo_shard_fetch_seconds",
    "Cross-host shard fetch latency (one successful attempt; multi-get "
    "batches count once)")
_fetch_bytes = counter(
    "zoo_shard_fetch_bytes_total", "Shard payload bytes fetched from peers")
_fetch_requests = counter(
    "zoo_shard_fetch_requests_total",
    "Fetch requests by wire mode (single get vs pipelined multi-get)",
    labels=("mode",))
_pool_conns = counter(
    "zoo_shard_pool_connections_total",
    "Peer connections by pool event (opened = fresh TCP dial, reused = "
    "checked out of the per-peer pool)", labels=("event",))
_lane_shards = counter(
    "zoo_shard_lane_total",
    "Shard responses received by transport lane (shm = same-host "
    "shared-memory payloads, tcp = socket payloads)", labels=("lane",))
_lane_bytes = counter(
    "zoo_shard_lane_bytes_total",
    "On-the-wire payload bytes received by transport lane",
    labels=("lane",))
_wire_saved = counter(
    "zoo_shard_wire_saved_bytes_total",
    "Logical minus on-wire payload bytes (savings from negotiated "
    "dtype narrowing / compression)")
_barrier_wait = histogram(
    "zoo_rebalance_barrier_wait_seconds",
    "Wall time spent in each rebalance KV-store barrier phase",
    labels=("phase",))

_MAGIC_V1 = b"ZSX1"
_MAGIC = b"ZSX2"
_MAGIC_HELLO = b"ZSXN"   # negotiation hello/reply (json capability blob)
_MAGIC_SHM_OK = b"ZSXS"  # client's probe verdict (u8: 1 = same host)
_MAGIC_SEG = b"ZSXM"     # server's per-chunk segment announce
_MAGIC_ACK = b"ZSXA"     # client mapped+unlinked the announced segment


class ProtocolError(RuntimeError):
    """Peer spoke a different exchange protocol (e.g. a v1 ``ZSX1``
    process in a mixed-version cluster). Deliberately loud AND
    deliberately not a ``ConnectionError``: a version mismatch is
    deterministic, so the retry policy must not burn its budget on
    it — upgrade peers in lockstep rather than silently corrupting
    shards."""


# ------------------------------------------------------------------- config

class ExchangeConfig:
    """Every client-side data-plane knob, parsed from the environment
    ONCE at construction (the old per-call ``os.environ`` reads made
    runtime adaptation impossible — there was no single place to
    mutate). One config rides a whole exchange; the adaptive readahead
    controller (:class:`zoo_tpu.orca.data.ingest.ReadaheadController`)
    mutates ``multiget`` and ``concurrency`` on THIS object between
    chunks, and :func:`iter_fetch` re-reads them when carving the next
    chunk.

    Env fallbacks (constructor args win): ``ZOO_SHARD_MULTIGET`` (32),
    ``ZOO_SHARD_FETCH_CONCURRENCY`` (4), ``ZOO_SHARD_LANE``
    (auto|tcp|shm, default auto), ``ZOO_SHARD_WIRE_DTYPE``
    (off|bf16|int8, default off — narrowing is lossy, never implicit),
    ``ZOO_SHARD_WIRE_COMPRESS`` (off|zlib|lz4, default off),
    ``ZOO_SHARD_READAHEAD`` (adaptive|static, default adaptive).
    """

    LANES = ("auto", "tcp", "shm")

    def __init__(self, multiget: Optional[int] = None,  # zoo-lint: config-parse
                 concurrency: Optional[int] = None,
                 lane: Optional[str] = None,
                 wire_dtype: Optional[str] = None,
                 wire_compress: Optional[str] = None,
                 readahead: Optional[str] = None,
                 crc: Optional[bool] = None):
        env = os.environ
        # per-array payload CRC (ZOO_WIRE_CRC, default on): negotiated
        # in the ZSXN hello like every other wire feature — a peer that
        # pre-dates it simply never grants it
        self.crc = bool(crc) if crc is not None else wire_crc_enabled()
        self.multiget = max(1, min(int(
            multiget if multiget is not None
            else env.get("ZOO_SHARD_MULTIGET", "32")), 0xFFFF))
        self.concurrency = max(1, int(
            concurrency if concurrency is not None
            else env.get("ZOO_SHARD_FETCH_CONCURRENCY", "4")))
        self.lane = (lane or env.get("ZOO_SHARD_LANE", "auto")).lower()
        if self.lane not in self.LANES:
            raise ValueError(
                f"ZOO_SHARD_LANE={self.lane!r}: pick one of {self.LANES}")
        self.wire_dtype = (
            wire_dtype or env.get("ZOO_SHARD_WIRE_DTYPE", "off")).lower()
        self.wire_compress = (
            wire_compress or env.get("ZOO_SHARD_WIRE_COMPRESS",
                                     "off")).lower()
        if self.wire_compress == "lz4" and "lz4" not in supported_codecs():
            logger.warning(
                "ZOO_SHARD_WIRE_COMPRESS=lz4 but the lz4 module is not "
                "importable here — falling back to zlib")
            self.wire_compress = "zlib"
        # validate loudly at parse time, not mid-exchange
        WirePolicy(self.wire_dtype, self.wire_compress)
        if self.wire_dtype != "off" \
                and self.wire_dtype not in supported_wire_dtypes():
            # a VALID narrowing this build cannot decode (ml_dtypes
            # missing): fall toward LOSSLESS, never toward a lossier one
            logger.warning(
                "ZOO_SHARD_WIRE_DTYPE=%s but this build cannot decode "
                "it (ml_dtypes missing?) — narrowing disabled",
                self.wire_dtype)
            self.wire_dtype = "off"
        self.readahead = (
            readahead or env.get("ZOO_SHARD_READAHEAD", "adaptive")).lower()
        if self.readahead not in ("adaptive", "static"):
            # a typo here would silently disable the controller
            raise ValueError(
                f"ZOO_SHARD_READAHEAD={self.readahead!r}: adaptive or "
                "static")

    def wants_negotiation(self) -> bool:
        """Whether a fresh connection should attempt the ZSXN hello:
        any non-default wire feature, the (default) auto lane whose
        same-host probe IS the negotiation, or the (default-on) CRC
        integrity trailer."""
        return (self.lane != "tcp" or self.wire_dtype != "off"
                or self.wire_compress != "off" or self.crc)

    def clone(self) -> "ExchangeConfig":
        return ExchangeConfig(
            multiget=self.multiget, concurrency=self.concurrency,
            lane=self.lane, wire_dtype=self.wire_dtype,
            wire_compress=self.wire_compress, readahead=self.readahead,
            crc=self.crc)

    def __repr__(self):
        return (f"ExchangeConfig(multiget={self.multiget}, "
                f"concurrency={self.concurrency}, lane={self.lane!r}, "
                f"wire_dtype={self.wire_dtype!r}, "
                f"wire_compress={self.wire_compress!r}, "
                f"readahead={self.readahead!r})")


# --------------------------------------------------------------------- codec
# Wire codec v2: raw tensor framing. Per shard: i32 array count; per
# array: u16-length name, u16-length dtype descriptor, u8 rank, rank x
# u64 dims, u64 payload bytes, then the raw (C-order) buffer. Decoding
# is np.frombuffer over the received buffer — zero-copy, non-executable.
# Negotiated connections append a flags byte (+ narrowing/compression/
# shm-offset fields) after each header — see _send_arrays/_read_shard.

def _dtype_descr(dt: np.dtype) -> bytes:
    # '<f4'-style descriptors round-trip exactly (endianness included);
    # extension dtypes (bfloat16 via ml_dtypes) don't — their .str is a
    # raw-void alias — so ship the registered name instead. Anything
    # that round-trips NEITHER way (structured/record dtypes: .str is a
    # bare void alias and .name like 'void64' does not parse) must be
    # rejected HERE, at encode time, not as a confusing decode error on
    # the peer after bytes are already on the wire.
    s = dt.str
    try:
        if np.dtype(s) == dt:
            return s.encode("ascii")
    except TypeError:
        pass
    try:
        if np.dtype(dt.name) == dt:
            return dt.name.encode("ascii")
    except TypeError:
        pass
    raise TypeError(
        f"dtype {dt} has no round-trippable wire descriptor — the "
        "exchange codec ships plain numeric/bool/extension dtypes only "
        "(split structured arrays into one plain array per field)")


def _dtype_from_descr(descr: str) -> np.dtype:
    try:
        dt = np.dtype(descr)
    except TypeError:
        # extension dtypes register by name on import (jax always ships
        # ml_dtypes; bench/test processes may not have touched it yet)
        import ml_dtypes  # noqa: F401
        dt = np.dtype(descr)
    if dt.hasobject:
        raise ProtocolError(
            f"refusing object dtype {descr!r} from the wire (pickle "
            "vector; the exchange codec is non-executable)")
    return dt


def _check_shard(shard) -> None:
    if not isinstance(shard, dict) or not all(
            isinstance(v, np.ndarray) for v in shard.values()):
        raise TypeError(
            "the shard exchange ships dict-of-ndarray shards only; got "
            f"{type(shard).__name__} (convert DataFrame shards with "
            "to_dict('series') -> numpy first)")
    for k, v in shard.items():
        if v.dtype.hasobject:
            raise TypeError(
                f"array {k!r} has object dtype — the exchange codec is "
                "non-executable and refuses pickle-bearing arrays")
        _dtype_descr(v.dtype)  # unshippable dtypes fail fast, pre-wire


def _array_header(name: str, arr: np.ndarray) -> bytes:
    nb = name.encode("utf-8")
    db = _dtype_descr(arr.dtype)
    return (struct.pack("!H", len(nb)) + nb +
            struct.pack("!H", len(db)) + db +
            struct.pack("!B", arr.ndim) +
            struct.pack(f"!{arr.ndim}Q", *arr.shape) +
            struct.pack("!Q", arr.nbytes))


def _encode_shard(shard: Dict[str, np.ndarray]) -> bytes:
    """Whole-shard v2 blob (header+payload frames). The server never
    calls this — it streams headers and payload views separately — but
    the framing is identical, so tests and file staging share it."""
    _check_shard(shard)
    parts: List[bytes] = [struct.pack("!i", len(shard))]
    for name, arr in shard.items():
        parts.append(_array_header(name, arr))
        parts.append(bytes(_payload_view(arr)))
    return b"".join(parts)


def _decode_shard(blob) -> Dict[str, np.ndarray]:
    view = memoryview(blob)
    (count,) = struct.unpack("!i", view[:4])
    off = 4
    out: Dict[str, np.ndarray] = {}
    for _ in range(count):
        name, arr, off = _decode_array(view, off)
        out[name] = arr
    return out


def _parse_array_header(read) -> Tuple[str, np.dtype, Tuple[int, ...],
                                       int, int]:
    """Parse one array header via ``read(n) -> buffer`` (a socket's
    recv-exact or a memoryview cursor — ONE parser for both, so the
    wire layout cannot drift between them). Returns (name, dtype,
    shape, payload bytes, header bytes consumed).

    The payload length is validated against prod(shape) * itemsize
    BEFORE anyone allocates for it: a corrupt or desynchronized peer
    must surface as a loud :class:`ProtocolError`, not a ~2^60-byte
    ``bytearray`` feeding the OOM killer."""
    (nlen,) = struct.unpack("!H", read(2))
    name = bytes(read(nlen)).decode("utf-8")
    (dlen,) = struct.unpack("!H", read(2))
    dt = _dtype_from_descr(bytes(read(dlen)).decode("ascii"))
    (ndim,) = struct.unpack("!B", read(1))
    shape = struct.unpack(f"!{ndim}Q", read(8 * ndim))
    (nbytes,) = struct.unpack("!Q", read(8))
    expected = dt.itemsize
    for d in shape:
        expected *= int(d)  # python ints: dims cannot overflow this
    if nbytes != expected:
        raise ProtocolError(
            f"array {name!r}: payload length {nbytes} does not match "
            f"shape {tuple(int(d) for d in shape)} x dtype {dt} "
            f"({expected} bytes) — corrupt or desynchronized stream")
    return name, dt, shape, nbytes, 13 + nlen + dlen + 8 * ndim


def _decode_array(view: memoryview, off: int
                  ) -> Tuple[str, np.ndarray, int]:
    pos = [off]

    def read(n: int):
        out = view[pos[0]:pos[0] + n]
        if len(out) != n:
            raise ProtocolError("truncated shard blob")
        pos[0] += n
        return out

    name, dt, shape, nbytes, _ = _parse_array_header(read)
    # frombuffer shares the received buffer: the decoded array is the
    # recv buffer, no copy (writable because the buffer is a bytearray)
    arr = np.frombuffer(read(nbytes), dtype=dt).reshape(shape)
    return name, arr, pos[0]


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # preallocate + recv_into: shards are tens of MB, so quadratic
    # bytes-concat accumulation would dominate the exchange; return the
    # bytearray itself — bytes(out) would re-copy the whole blob, and
    # every caller (magic compare, struct.unpack, frombuffer) takes it
    out = bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed mid-message")
        got += r
    return out


# ---------------------------------------------------------------- conn pool

class _Conn:
    """One client connection + its per-connection negotiated state
    (framing is stateful: extended headers and the shm lane apply only
    after a successful ZSXN hello on THIS socket, so the state must
    travel with the socket through the pool)."""

    __slots__ = ("sock", "negotiated", "policy", "lane", "shm_dir",
                 "crc")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.negotiated = False
        self.policy: Optional[WirePolicy] = None
        self.lane = "tcp"
        self.shm_dir: Optional[str] = None
        self.crc = False  # peer granted per-array CRC trailers

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def _dial(addr: Tuple[str, int], timeout: float) -> _Conn:
    """Fresh un-negotiated connection: ONE place for the dial ritual
    (NODELAY, opened-counter) so the pool, the pool=False baseline and
    the legacy redial cannot drift apart."""
    sock = socket.create_connection(addr, timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    _pool_conns.labels(event="opened").inc()
    return _Conn(sock)


class _ConnPool:
    """Per-peer idle-connection pool. ``acquire`` hands back a pooled
    connection (metric event ``reused``) or dials a fresh one
    (``opened``); ``release`` returns it for the next fetch. A
    connection that errors mid-RPC must be closed and the peer's pool
    invalidated — the stream is poisoned and every idle sibling
    probably points at the same dead peer."""

    def __init__(self, max_idle_per_peer: Optional[int] = None):
        self._idle: Dict[Tuple[str, int], List[_Conn]] = {}
        self._lock = threading.Lock()
        self._max_idle = max_idle_per_peer
        # peers that dropped the ZSXN hello (ZSX2-only builds): skip
        # the hello on future dials so every reconnect doesn't re-pay
        # a doomed round trip + a duplicate warning
        self._legacy_peers: set = set()
        self._legacy_warned: set = set()
        # (addr, requested (dtype, compress)) -> granted (dtype,
        # compress): what the peer actually agreed to for a request
        self._negotiated: Dict[tuple, tuple] = {}

    @property
    def max_idle(self) -> int:  # zoo-lint: config-parse
        if self._max_idle is not None:
            return self._max_idle
        return max(1, int(os.environ.get("ZOO_SHARD_POOL_SIZE", "4")))

    def acquire(self, addr: Tuple[str, int], timeout: float) -> _Conn:
        with self._lock:
            lst = self._idle.get(addr)
            conn = lst.pop() if lst else None
        if conn is not None:
            _pool_conns.labels(event="reused").inc()
            conn.sock.settimeout(timeout)
            return conn
        return _dial(addr, timeout)

    def release(self, addr: Tuple[str, int], conn: _Conn):
        with self._lock:
            lst = self._idle.setdefault(addr, [])
            if len(lst) < self.max_idle:
                lst.append(conn)
                return
        conn.close()

    def invalidate(self, addr: Tuple[str, int]):
        with self._lock:
            stale = self._idle.pop(addr, [])
            # the peer may be restarting with a different build whose
            # negotiation answers differ — re-learn EVERYTHING on the
            # next dial, including a legacy verdict (one re-paid hello
            # round trip beats a sticky downgrade if the verdict came
            # from a blip or the peer was since upgraded)
            for k in [k for k in self._negotiated if k[0] == addr]:
                del self._negotiated[k]
            self._legacy_peers.discard(addr)
            self._legacy_warned.discard(addr)
        for c in stale:
            c.close()

    def mark_legacy(self, addr) -> bool:
        """Record a ZSX2-only peer; returns True the FIRST time (the
        caller logs once, not per reconnect)."""
        with self._lock:
            if addr in self._legacy_peers:
                return False
            self._legacy_peers.add(addr)
            return True

    def is_legacy(self, addr) -> bool:
        with self._lock:
            return addr in self._legacy_peers

    def warn_features_once(self, addr) -> bool:
        """First featureful config to hit an already-memoized legacy
        peer gets one loud line (the memo's first-contact log may have
        predated the feature request)."""
        with self._lock:
            if addr in self._legacy_warned:
                return False
            self._legacy_warned.add(addr)
            return True

    def remember_outcome(self, addr, requested: tuple, granted: tuple):
        """Memoize what a peer actually granted for a requested wire
        profile. Negotiation is deterministic per (peer, request), so a
        pooled connection carrying the GRANTED profile stays reusable
        for that request even when the peer negotiated a feature DOWN
        (e.g. no lz4 on the serving side) — without the memo a
        downgrade mismatches every checkout and permanently defeats
        the pool, one silent redial + hello per chunk."""
        with self._lock:
            self._negotiated[(addr, requested)] = granted

    def granted_for(self, addr, requested: tuple) -> Optional[tuple]:
        with self._lock:
            return self._negotiated.get((addr, requested))

    def clear(self):
        with self._lock:
            all_addrs = list(self._idle)
            self._legacy_peers.clear()
            self._legacy_warned.clear()
            self._negotiated.clear()
        for a in all_addrs:
            self.invalidate(a)


_pool = _ConnPool()


# ------------------------------------------------------------------- server

class _ServerConnState:
    """Per-connection negotiated state on the serving side."""

    def __init__(self):
        self.policy: Optional[WirePolicy] = None
        self.crc = False  # client proposed + this build grants CRC
        self.shm_dir: Optional[str] = None
        self.probe_path: Optional[str] = None
        self.shm_pending = False
        self.shm_on = False
        self.shm_failed_logged = False
        # announced segments not yet acked by the client, oldest first
        self.outstanding: List[Optional[_shm.SegmentWriter]] = []

    def confirm_shm(self, ok: bool):
        self._drop_probe()
        self.shm_on = bool(ok) and self.shm_pending
        self.shm_pending = False

    def pop_ack(self):
        if self.outstanding:
            w = self.outstanding.pop(0)
            if w is not None:
                w.discard()  # usually ENOENT — the client unlinked first

    def _drop_probe(self):
        if self.probe_path:
            try:
                os.unlink(self.probe_path)
            except OSError:
                pass
            self.probe_path = None

    def cleanup(self):
        """Connection is gone (ack'd or not): nothing may leak."""
        self._drop_probe()
        for w in self.outstanding:
            if w is not None:
                w.discard()
        self.outstanding = []


class ShardExchange:
    """Serve this process's shards (by global id) to peer hosts.

    Protocol v2: request = ``ZSX2`` + u16 count + count x u32 global
    ids (a multi-get — count=1 is the single fetch); response, per gid
    in request order = ``ZSX2`` + u32 gid + i32 array count (-1 = not
    held here) + the raw-tensor frames of the shard. Payloads leave
    through ``memoryview`` of the original arrays — nothing is
    pre-encoded and nothing on the wire can execute code.

    A client may open with a ``ZSXN`` hello negotiating per-connection
    wire features: dtype narrowing / compression (applied per array by
    :mod:`~zoo_tpu.orca.data.wire_codec`) and the same-host
    shared-memory payload lane (probe-verified; payload bytes then move
    through per-chunk ``/dev/shm`` segments and only control frames
    cross the socket — :mod:`~zoo_tpu.orca.data.shm`). Responses on a
    negotiated connection carry one extra flags byte per array; an
    un-negotiated connection speaks bit-identical v2.

    A ``ZSX1`` (protocol v1) request is rejected loudly and the
    connection dropped: mixed-version clusters must fail, not corrupt.
    The port is ephemeral, announced only through the JAX coordination
    service, and the server thread dies with the process.
    """

    # class-level default so test fixtures that build instances via
    # __new__ (port-pinned exchanges) inherit sane behavior
    _negotiate = True

    def __init__(self, shards_by_gid: Dict[int, Dict[str, np.ndarray]],
                 bind: str = "0.0.0.0", negotiate: bool = True):
        for s in shards_by_gid.values():
            _check_shard(s)
        # served lazily from the caller's arrays: no blob copies, no
        # doubled resident memory while the exchange is open
        self._shards = dict(shards_by_gid)
        self._negotiate = negotiate
        if negotiate:
            # reap segments orphaned by SIGKILL'd peers (the one leak
            # window the unlink-after-map protocol cannot cover)
            _shm.gc_stale_segments()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self.connections_accepted = 0  # pool-reuse observability/tests
        self._closed = False
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self.connections_accepted += 1
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        st = _ServerConnState()
        try:
            with conn:
                while True:
                    try:
                        magic = _recv_exact(conn, 4)
                    except ConnectionError:
                        return
                    if magic == _MAGIC_V1:
                        logger.error(
                            "shard exchange: protocol-v1 (ZSX1) peer "
                            "contacted this v2 server — mixed exchange "
                            "versions in one cluster; upgrade every "
                            "host in lockstep. Dropping the connection.")
                        return
                    if magic == _MAGIC_HELLO and self._negotiate:
                        self._handle_hello(conn, st)
                        continue
                    if magic == _MAGIC_SHM_OK:
                        (ok,) = struct.unpack("!B", _recv_exact(conn, 1))
                        st.confirm_shm(bool(ok))
                        continue
                    if magic == _MAGIC_ACK:
                        st.pop_ack()
                        continue
                    if magic != _MAGIC:
                        return  # not our protocol: drop the connection
                    (count,) = struct.unpack("!H", _recv_exact(conn, 2))
                    gids = struct.unpack(f"!{count}I",
                                         _recv_exact(conn, 4 * count))
                    self._respond(conn, gids, st)
        except OSError:
            pass
        finally:
            st.cleanup()
            with self._conns_lock:
                self._conns.discard(conn)

    def _handle_hello(self, conn: socket.socket, st: _ServerConnState):
        (ln,) = struct.unpack("!H", _recv_exact(conn, 2))
        try:
            prop = json.loads(bytes(_recv_exact(conn, ln)).decode("utf-8"))
        except ValueError:
            prop = {}
        dtype = prop.get("dtype", "off")
        if dtype not in supported_wire_dtypes():
            # unknown string OR a narrowing this build cannot encode
            # (bf16 without ml_dtypes): grant no narrowing rather than
            # ImportError mid-response with frames already on the wire
            dtype = "off"
        comp = next((c for c in prop.get("compress", [])
                     if c in supported_codecs()), "off")
        st.policy = WirePolicy(dtype, comp)
        # integrity trailer: granted only when the fetcher proposed it
        # AND this server wants it (ZOO_WIRE_CRC) — old clients never
        # propose, old servers never answer, either way it stays off
        st.crc = bool(prop.get("crc")) and wire_crc_enabled()
        reply = {"v": 2, "dtype": dtype, "compress": comp,
                 "crc": st.crc, "shm": None}
        if prop.get("shm"):
            try:
                d = _shm.shm_dir()
                name, token, path = _shm.write_probe(d)
                st.shm_dir, st.probe_path = d, path
                st.shm_pending = True
                reply["shm"] = {"dir": d, "name": name, "token": token}
            except OSError:
                pass  # no usable shm dir: stay on the TCP payload path
        blob = json.dumps(reply).encode("utf-8")
        conn.sendall(_MAGIC_HELLO + struct.pack("!H", len(blob)) + blob)

    def _respond(self, conn: socket.socket, gids, st: _ServerConnState):
        if st.policy is None:
            # un-negotiated connection: bit-identical plain v2
            for gid in gids:
                fault_point("shard.serve", gid=gid)
                self._send_shard(conn, gid)
            return
        writer = None
        if st.shm_on:
            # upper bound = raw logical bytes (narrowing/compression
            # can only shrink); pages are reserved up front so a full
            # tmpfs fails HERE, where the chunk can still degrade to
            # inline TCP payloads (empty announce + no FLAG_SHM)
            # instead of tearing the stream mid-frame
            ub = sum(arr.nbytes
                     for g in gids
                     for arr in (self._shards.get(g) or {}).values())
            if ub:
                try:
                    writer = _shm.SegmentWriter(st.shm_dir, ub)
                except OSError as e:
                    if not st.shm_failed_logged:
                        st.shm_failed_logged = True
                        logger.warning(
                            "shm lane: segment allocation of %d bytes "
                            "in %s failed (%s) — serving this "
                            "connection's payloads inline over TCP "
                            "(is the tmpfs full?)", ub, st.shm_dir, e)
            # track BEFORE any frame leaves: the chaos path (peer dies
            # mid-response) must find it in outstanding and discard it
            st.outstanding.append(writer)
            nb = (writer.name if writer else "").encode("ascii")
            conn.sendall(_MAGIC_SEG + struct.pack("!H", len(nb)) + nb +
                         struct.pack("!Q", writer.size if writer else 0))
        for gid in gids:
            fault_point("shard.serve", gid=gid)
            shard = self._shards.get(gid)
            if shard is None:
                conn.sendall(_MAGIC + struct.pack("!Ii", gid, -1))
                continue
            conn.sendall(_MAGIC + struct.pack("!Ii", gid, len(shard)))
            for name, arr in shard.items():
                self._send_array(conn, name, arr, st, writer)

    def _send_array(self, conn, name, arr, st: _ServerConnState, writer):
        flags, wdescr, scale, payload = encode_array(arr, st.policy)
        pv = memoryview(payload)
        parts = [_array_header(name, arr)]
        if writer is not None:
            flags |= FLAG_SHM
        if st.crc:
            flags |= FLAG_CRC
        parts.append(struct.pack("!B", flags))
        if flags & FLAG_NARROWED:
            parts.append(struct.pack("!H", len(wdescr)) + wdescr +
                         struct.pack("!d", scale))
        if flags & (FLAG_NARROWED | FLAG_COMPRESSED):
            parts.append(struct.pack("!Q", pv.nbytes))
        if flags & FLAG_CRC:
            # CRC of the bytes as TRANSPORTED (narrowed/compressed for
            # the socket, the segment bytes for shm) — computed before
            # the corruption seam, so injected "in-transit" bit rot is
            # caught on the receiving side exactly like the real thing
            parts.append(struct.pack("!I", frame_crc(pv)))
            pv = memoryview(corrupt_seam("shard.wire.corrupt", pv))
        if writer is not None:
            parts.append(struct.pack("!Q", writer.write(pv)))
            conn.sendall(b"".join(parts))
        else:
            conn.sendall(b"".join(parts))
            if pv.nbytes:
                conn.sendall(pv)

    def _send_shard(self, conn: socket.socket, gid: int):
        shard = self._shards.get(gid)
        if shard is None:
            conn.sendall(_MAGIC + struct.pack("!Ii", gid, -1))
            return
        conn.sendall(_MAGIC + struct.pack("!Ii", gid, len(shard)))
        for name, arr in shard.items():
            conn.sendall(_array_header(name, arr))
            payload = _payload_view(arr)
            if payload.nbytes:
                conn.sendall(payload)

    def close(self):
        self._closed = True
        try:
            # wake the accept() thread (it holds the kernel socket — and
            # the port — alive through a bare close(); shutdown makes the
            # blocked accept return EINVAL immediately)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
        # drop live per-connection sockets too: clients of a closed
        # exchange must fail fast (and free the port for a restart)
        # instead of hanging on a half-dead stream. SO_LINGER 0 sends
        # RST and destroys the socket outright — a graceful FIN would
        # park the 4-tuple in FIN_WAIT_2 against every pooled client
        # connection, keeping the port unusable for ~a minute
        with self._conns_lock:
            stale = list(self._conns)
            self._conns.clear()
        for c in stale:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                c.close()
            except OSError:
                pass

    @staticmethod
    def fetch(addr: Tuple[str, int], gid: int, timeout: float = 60.0,
              retry: Optional[RetryPolicy] = None, pool: bool = True,
              config: Optional[ExchangeConfig] = None
              ) -> Dict[str, np.ndarray]:
        """Fetch shard ``gid`` from ``addr`` with bounded retries.

        Connect/read failures (flaky network, peer restarting) are
        transient: retried under ``retry`` (default: 3 attempts,
        exponential backoff), each attempt on a FRESH connection (the
        pooled one is invalidated — its stream is poisoned). A
        ``KeyError`` — the peer answers but does not hold the shard —
        is a plan bug, never retried. ``pool=False`` opens and closes
        one connection per call (the pre-v2 behavior; kept as the
        microbench baseline)."""
        return fetch_many(addr, [gid], timeout=timeout, retry=retry,
                          pool=pool, config=config)[gid]


# ------------------------------------------------------------------- client

def _negotiate_conn(conn: _Conn, addr, cfg: ExchangeConfig,
                    timeout: float) -> bool:
    """One-round ZSXN hello on a fresh connection. Returns False when
    the peer dropped the hello (a ZSX2-only build): the socket is dead
    and the caller must redial plain. Raises :class:`ProtocolError` on
    a non-exchange peer or when a hard requirement (forced shm lane)
    cannot be met."""
    sock = conn.sock
    prop = {"v": 2, "dtype": cfg.wire_dtype,
            "compress": ([] if cfg.wire_compress == "off"
                         else [cfg.wire_compress]),
            "crc": cfg.crc,
            "shm": cfg.lane in ("auto", "shm")}
    blob = json.dumps(prop).encode("utf-8")
    sock.sendall(_MAGIC_HELLO + struct.pack("!H", len(blob)) + blob)
    try:
        magic = _recv_exact(sock, 4)
    except ConnectionError:
        return False  # legacy peer: hello dropped, connection closed
    if magic != _MAGIC_HELLO:
        raise ProtocolError(
            f"peer {addr} answered the negotiation hello with magic "
            f"{bytes(magic)!r} — protocol version mismatch (v1 peer in "
            "a v2 cluster?)")
    (ln,) = struct.unpack("!H", _recv_exact(sock, 2))
    reply = json.loads(bytes(_recv_exact(sock, ln)).decode("utf-8"))
    conn.policy = WirePolicy(reply.get("dtype", "off"),
                             reply.get("compress", "off"))
    # a pre-CRC server's reply simply lacks the key → stays off; a
    # frame-integrity downgrade is a soft loss (log once via the memo
    # machinery), never a hard error — unlike the forced shm lane
    conn.crc = bool(reply.get("crc"))
    conn.negotiated = True
    shm_info = reply.get("shm")
    ok = bool(shm_info) and _shm.check_probe(
        shm_info["dir"], shm_info["name"], shm_info["token"])
    sock.sendall(_MAGIC_SHM_OK + struct.pack("!B", 1 if ok else 0))
    if ok:
        conn.lane = "shm"
        conn.shm_dir = shm_info["dir"]
    elif cfg.lane == "shm":
        raise ProtocolError(
            f"ZOO_SHARD_LANE=shm forced but peer {addr} "
            + ("did not offer a shared-memory segment"
               if not shm_info else
               "failed the same-host probe (different host?)"))
    return True


def _forced_shm_legacy_error(addr) -> ProtocolError:
    """The one message for ZOO_SHARD_LANE=shm hitting a ZSX2-only peer,
    whether discovered on this dial or memoized from an earlier one."""
    return ProtocolError(
        f"ZOO_SHARD_LANE=shm forced but peer {addr} pre-dates wire "
        "negotiation (ZSX2-only build) — upgrade it or unset the "
        "forced lane")


def _conn_matches(conn: _Conn, addr, cfg: ExchangeConfig) -> bool:
    """Whether a NEGOTIATED pooled connection's profile is the one this
    config would negotiate. The pool is process-global and profiles are
    per-connection state, so a mismatched checkout must be discarded —
    reusing it would silently apply another caller's (possibly lossy)
    wire treatment, or the wrong lane, to this fetch. The comparison is
    against what this request is KNOWN to get from this peer (the
    pool's negotiation memo) when a prior hello recorded it — a peer
    that grants a feature DOWN (no lz4 on its side, say) must not
    mismatch every checkout forever. (An un-negotiated pooled
    connection never reaches here: it either serves a plain config
    as-is or gets the hello on checkout.)"""
    if not cfg.wants_negotiation():
        return False  # cfg wants bit-plain v2 framing; conn is extended
    pol = conn.policy or WirePolicy()
    requested = (cfg.wire_dtype, cfg.wire_compress, cfg.crc)
    granted = _pool.granted_for(addr, requested)
    if (pol.dtype, pol.compress, conn.crc) != (granted or requested):
        return False
    if cfg.lane == "shm" and conn.lane != "shm":
        return False
    if cfg.lane == "tcp" and conn.lane != "tcp":
        return False
    return True


def _acquire_conn(addr, timeout: float, pool: bool,
                  cfg: ExchangeConfig) -> _Conn:
    """Dial or pool-checkout a connection, negotiating wire features on
    fresh sockets. A pooled connection whose negotiated profile does
    not match THIS config is discarded and replaced — profiles are
    per-connection, configs are per-caller, and the two must never mix.
    A peer that drops the hello (pre-negotiation build) is remembered
    and redialed plain — loudly when the config asked for a feature the
    fallback loses, and a hard error when the shm lane is forced (a
    forced lane never silently degrades, memoized peer or not)."""
    if _pool.is_legacy(addr) and cfg.lane == "shm":
        raise _forced_shm_legacy_error(addr)
    if _pool.is_legacy(addr) \
            and (cfg.wire_dtype != "off" or cfg.wire_compress != "off") \
            and _pool.warn_features_once(addr):
        logger.error(
            "peer %s:%d is a memoized ZSX2-only build: requested wire "
            "dtype/compression (%s/%s) DISABLED for this peer",
            addr[0], addr[1], cfg.wire_dtype, cfg.wire_compress)
    conn = _pool.acquire(addr, timeout) if pool else _dial(addr, timeout)
    if conn.negotiated and not _conn_matches(conn, addr, cfg):
        # another caller's profile: close it and start clean
        conn.close()
        conn = _dial(addr, timeout)
    if conn.negotiated or not cfg.wants_negotiation() \
            or _pool.is_legacy(addr):
        return conn
    # up to two hello attempts, the second on a guaranteed-fresh dial:
    # a dropped hello on the first may be a stale pooled socket or a
    # peer mid-restart, and the legacy verdict is sticky — confirm
    # before memoizing. A transiently-dead peer fails the fresh dial
    # itself, which propagates as the transient error it is.
    for attempt in range(2):
        conn.sock.settimeout(timeout)
        try:
            if _negotiate_conn(conn, addr, cfg, timeout):
                pol = conn.policy or WirePolicy()
                _pool.remember_outcome(
                    addr, (cfg.wire_dtype, cfg.wire_compress, cfg.crc),
                    (pol.dtype, pol.compress, conn.crc))
                return conn
        except ProtocolError:
            conn.close()
            raise
        except (ConnectionError, OSError):
            conn.close()
            raise
        conn.close()
        if attempt == 0:
            conn = _dial(addr, timeout)
    # hello dropped twice on fresh sockets: ZSX2-only peer. Fall back
    # to the plain protocol — loud when that loses a requested feature.
    if cfg.lane == "shm":
        raise _forced_shm_legacy_error(addr)
    if _pool.mark_legacy(addr):
        if cfg.wire_dtype != "off" or cfg.wire_compress != "off":
            # this discovery log already names the lost features:
            # consume the memo-path token so the peer is warned once,
            # not once per dedup mechanism
            _pool.warn_features_once(addr)
            logger.error(
                "peer %s:%d pre-dates wire negotiation (ZSX2-only): "
                "requested wire dtype/compression (%s/%s) DISABLED for "
                "this peer — upgrade hosts in lockstep to get it back",
                addr[0], addr[1], cfg.wire_dtype, cfg.wire_compress)
        else:
            logger.warning(
                "peer %s:%d pre-dates wire negotiation (ZSX2-only); "
                "staying on the plain TCP lane", addr[0], addr[1])
    return _dial(addr, timeout)


def _read_segment_announce(conn: _Conn) -> Optional[_shm.SegmentReader]:
    """Read the server's per-chunk segment announce, map + unlink the
    segment, and ack. Returns None for an all-empty chunk."""
    magic = _recv_exact(conn.sock, 4)
    if magic != _MAGIC_SEG:
        raise ProtocolError(
            f"expected shm segment announce, got magic {bytes(magic)!r} "
            "— desynchronized stream")
    (nlen,) = struct.unpack("!H", _recv_exact(conn.sock, 2))
    name = bytes(_recv_exact(conn.sock, nlen)).decode("ascii")
    (size,) = struct.unpack("!Q", _recv_exact(conn.sock, 8))
    seg = None
    if name and size:
        try:
            seg = _shm.SegmentReader(conn.shm_dir, name, size)
        except (OSError, ValueError) as e:
            raise ConnectionError(
                f"shm segment {name!r} vanished before mapping "
                f"(peer died?): {e}") from e
    conn.sock.sendall(_MAGIC_ACK)
    return seg


def _read_shard(conn: _Conn, seg: Optional[_shm.SegmentReader]
                ) -> Tuple[int, Optional[Dict], int, int]:
    """One response frame → (gid, shard-or-None, wire bytes, logical
    bytes). Wire bytes = what actually crossed the transport (narrowed/
    compressed size; shm offsets count their payload — the bytes moved,
    just not through the socket). Logical = decoded array bytes."""
    sock = conn.sock
    head = _recv_exact(sock, 12)
    if head[:4] != _MAGIC:
        raise ProtocolError(
            f"peer answered with magic {bytes(head[:4])!r}, expected "
            f"{_MAGIC!r} — protocol version mismatch (v1 peer in a v2 "
            "cluster?)")
    gid, count = struct.unpack("!Ii", bytes(head[4:]))
    if count < 0:
        return gid, None, 12, 12
    shard: Dict[str, np.ndarray] = {}
    wire = logical = 12
    for _ in range(count):
        name, dt, shape, nbytes, header_len = _parse_array_header(
            lambda n: _recv_exact(sock, n))
        logical += header_len + nbytes
        if not conn.negotiated:
            buf = _recv_exact(sock, nbytes) if nbytes else b""
            # the decoded array WRAPS the recv buffer — no copy
            shard[name] = np.frombuffer(memoryview(buf),
                                        dtype=dt).reshape(shape)
            wire += header_len + nbytes
            continue
        (flags,) = struct.unpack("!B", _recv_exact(sock, 1))
        wdescr, scale, wn, crc = None, 0.0, nbytes, None
        if flags & FLAG_NARROWED:
            (dlen,) = struct.unpack("!H", _recv_exact(sock, 2))
            wdescr = bytes(_recv_exact(sock, dlen)).decode("ascii")
            (scale,) = struct.unpack("!d", _recv_exact(sock, 8))
            header_len += 10 + dlen
        if flags & (FLAG_NARROWED | FLAG_COMPRESSED):
            (wn,) = struct.unpack("!Q", _recv_exact(sock, 8))
            header_len += 8
            if wn > nbytes:
                raise ProtocolError(
                    f"array {name!r}: wire length {wn} exceeds logical "
                    f"{nbytes} — narrowing/compression can only shrink; "
                    "corrupt or desynchronized stream")
        if flags & FLAG_CRC:
            (crc,) = struct.unpack("!I", _recv_exact(sock, 4))
            header_len += 4
        if flags & FLAG_SHM:
            (off,) = struct.unpack("!Q", _recv_exact(sock, 8))
            if seg is None:
                raise ProtocolError(
                    f"array {name!r}: shm payload flagged but no "
                    "segment was announced for this chunk")
            buf = seg.view(off, wn)
        else:
            buf = _recv_exact(sock, wn) if wn else b""
        if crc is not None:
            # integrity gate BEFORE any decode: a flipped bit (socket,
            # NIC, or a torn shm read) raises FrameCorrupt — a
            # ConnectionError, so the chunk is refetched on a fresh
            # connection instead of np.frombuffer-ing garbage
            verify_crc(buf, crc, "shard", context=f"array {name!r}")
        try:
            shard[name] = decode_payload(
                buf, flags, dt, shape, wdescr, scale,
                conn.policy.compress if conn.policy else "off")
        except ProtocolError:
            raise
        except Exception as e:  # zlib.error / frombuffer size mismatch
            raise ProtocolError(
                f"array {name!r}: wire payload failed to decode "
                f"({e!r}) — corrupt or desynchronized stream") from e
        wire += header_len + 1 + wn
    return gid, shard, wire, logical


def _fetch_chunk_once(addr: Tuple[str, int], gids: Sequence[int],
                      timeout: float, pool: bool,
                      cfg: ExchangeConfig) -> Dict[int, Dict]:
    """One pipelined multi-get attempt: N gids in one write, responses
    streamed back on the same connection (payloads through the shm
    segment when that lane is negotiated)."""
    for gid in gids:
        fault_point("shard.fetch", addr=addr, gid=gid)
    _fetch_requests.labels(
        mode="multi" if len(gids) > 1 else "single").inc()
    t0 = time.perf_counter()
    conn = _acquire_conn(addr, timeout, pool, cfg)
    reusable = False
    try:
        conn.sock.settimeout(timeout)
        conn.sock.sendall(_MAGIC + struct.pack(f"!H{len(gids)}I",
                                               len(gids), *gids))
        seg = _read_segment_announce(conn) if conn.lane == "shm" else None
        out: Dict[int, Dict] = {}
        wire_total = logical_total = 0
        for want in gids:
            gid, shard, wire, logical = _read_shard(conn, seg)
            if gid != want:
                raise ProtocolError(
                    f"peer {addr} answered gid {gid} for request {want} "
                    "— desynchronized stream")
            if shard is None:
                raise KeyError(f"peer {addr} does not hold shard {gid}")
            out[gid] = shard
            wire_total += wire
            logical_total += logical
        reusable = pool
        _fetch_seconds.observe(time.perf_counter() - t0)
        _fetch_bytes.inc(wire_total)
        _lane_shards.labels(lane=conn.lane).inc(len(gids))
        _lane_bytes.labels(lane=conn.lane).inc(wire_total)
        if logical_total > wire_total:
            _wire_saved.inc(logical_total - wire_total)
        return out
    except (ConnectionError, OSError):
        # poisoned stream AND probably a dead peer: every pooled
        # sibling connection is suspect — drop them so the retry dials
        # fresh instead of drawing another corpse from the pool
        _pool.invalidate(addr)
        raise
    finally:
        if reusable:
            _pool.release(addr, conn)
        else:
            # KeyError leaves unread responses in flight; error paths
            # leave a torn stream — never pool either
            conn.close()


def fetch_many(addr: Tuple[str, int], gids: Sequence[int],
               timeout: float = 60.0,
               retry: Optional[RetryPolicy] = None,
               pool: bool = True,
               config: Optional[ExchangeConfig] = None
               ) -> Dict[int, Dict[str, np.ndarray]]:
    """Fetch many shards from one peer with pipelined multi-gets.

    ``gids`` are split into chunks of ``config.multiget`` (default
    ``ZOO_SHARD_MULTIGET`` = 32); each chunk is one wire round trip
    (one request write, streamed responses) retried independently under
    ``retry`` — a peer dying mid-stream costs one chunk's refetch on a
    fresh connection, and ``fault_point("shard.fetch")`` fires per gid
    per attempt exactly as it did for single fetches."""
    gids = [int(g) for g in gids]
    cfg = config or ExchangeConfig()
    retry = retry or RetryPolicy(max_attempts=3, base_delay=0.1,
                                 max_delay=2.0, deadline=timeout)
    out: Dict[int, Dict[str, np.ndarray]] = {}
    i = 0
    while i < len(gids):
        # re-read per chunk: the readahead controller may have resized
        chunk = max(1, min(int(cfg.multiget), 0xFFFF))
        part = gids[i:i + chunk]
        i += chunk
        out.update(retry.call(_fetch_chunk_once, addr, part, timeout,
                              pool, cfg))
    return out


def iter_fetch(sources: Sequence[Tuple[Tuple[str, int], Sequence[int]]],
               timeout=60.0,
               concurrency: Optional[int] = None,
               retry: Optional[RetryPolicy] = None,
               config: Optional[ExchangeConfig] = None,
               controller=None
               ) -> Iterable[Tuple[int, Dict[str, np.ndarray]]]:
    """Stream ``(gid, shard)`` pairs from many peers as they arrive.

    ``sources`` = [(addr, gids), ...]. Chunks are carved LAZILY (next
    chunk's size reads ``config.multiget`` at carve time) and fan out
    over a bounded worker set whose live width is re-read from
    ``config.concurrency`` — so a :class:`~zoo_tpu.orca.data.ingest.
    ReadaheadController` passed as ``controller`` can grow/shrink both
    between chunks. ``controller.on_chunk(ngids, nbytes, seconds)`` is
    invoked after each completed chunk. Ordering across peers is
    completion order, not plan order.

    ``timeout`` may be a callable re-evaluated when each chunk STARTS
    (not when it was queued) — rebalance passes its ``remaining()``
    budget so queued chunks cannot stack fresh 60s retry deadlines past
    the phase deadline; once the budget is spent the callable raises
    and every pending chunk fails fast."""
    if controller is not None:
        # the controller's shared config IS the contract: chunks are
        # carved from it and the concurrency kwarg is ignored outright
        # (applying it would clobber the controller's state). Duck
        # controllers (on_chunk only, no .config) use the passed config.
        ctl_cfg = getattr(controller, "config", None)
        if ctl_cfg is not None and config is not None \
                and ctl_cfg is not config:
            raise ValueError(
                "iter_fetch: controller.config and config are different "
                "objects — the controller would adapt one while chunks "
                "are carved from the other; pass the controller's own "
                "config (or neither)")
        cfg = ctl_cfg or config or ExchangeConfig()
    else:
        cfg = config or ExchangeConfig()
        if concurrency is not None:
            if config is not None:
                # never mutate a caller's config object from a kwarg —
                # the override lives on a private copy
                cfg = cfg.clone()
            cfg.concurrency = max(1, int(concurrency))
    timeout_fn = timeout if callable(timeout) else (lambda: timeout)
    pending = [[addr, list(gids)] for addr, gids in sources if len(gids)]
    total = sum(len(g) for _, g in pending)
    if not total:
        return
    lock = threading.Lock()
    rr = [0]  # round-robin cursor across sources

    def take_chunk():
        with lock:
            for _ in range(len(pending)):
                i = rr[0] % len(pending)
                rr[0] += 1
                addr, gids = pending[i]
                if gids:
                    n = max(1, min(int(cfg.multiget), 0xFFFF))
                    pending[i][1] = gids[n:]
                    return addr, gids[:n]
        return None

    def chunks_left() -> bool:
        with lock:
            return any(gids for _, gids in pending)

    out_q: "queue.Queue" = queue.Queue()
    stop = threading.Event()
    # live-width accounting (NOT thread objects): retired workers must
    # free their slot or later controller growth could never re-spawn
    state = {"live": 0, "spawned": 0}

    def _maybe_retire() -> bool:
        # shrink: a worker above the CURRENT width retires atomically
        # (check-and-decrement under the lock so concurrent retirees
        # cannot undershoot the width) — the consumer re-spawns fresh
        # ones if the controller grows again; no parked threads, no
        # polling. The last live worker never retires (width >= 1), so
        # remaining chunks always have an owner.
        with lock:
            if state["live"] > max(1, int(cfg.concurrency)):
                state["live"] -= 1
                return True
            return False

    def run():
        retired = False
        try:
            while not stop.is_set():
                if _maybe_retire():
                    retired = True
                    return
                task = take_chunk()
                if task is None:
                    return
                addr, part = task
                t0 = time.perf_counter()
                res = fetch_many(addr, part, timeout=timeout_fn(),
                                 retry=retry, config=cfg)
                if controller is not None:
                    nb = sum(v.nbytes for s in res.values()
                             for v in s.values())
                    controller.on_chunk(len(part), nb,
                                        time.perf_counter() - t0)
                out_q.put(("ok", res))
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            out_q.put(("err", e))
        finally:
            if not retired:
                with lock:
                    state["live"] -= 1
            out_q.put(("done", None))

    def ensure_workers():
        """Spawn up to the CURRENT width — called at start and after
        every completed chunk, so controller growth materializes as new
        threads exactly when there is evidence (a completion) to react
        to, and never as a parked-thread pool."""
        while chunks_left():
            with lock:
                if state["live"] >= min(max(1, int(cfg.concurrency)),
                                        total):
                    return
                state["live"] += 1
                state["spawned"] += 1
                n = state["spawned"]
            threading.Thread(target=run, daemon=True,
                             name=f"zoo-shard-fetch-{n}").start()

    ensure_workers()
    delivered = finished = 0
    try:
        while delivered < total:
            kind, val = out_q.get()
            if kind == "err":
                raise val
            if kind == "done":
                finished += 1
                if finished == state["spawned"] and delivered < total:
                    # every worker flushed its results before its done
                    # token (FIFO per producer) and the LAST live worker
                    # only exits with no chunks left, so this is a
                    # genuine shortfall, not a race — unless the width
                    # simply needs re-spawning after a retire wave
                    ensure_workers()
                    if finished == state["spawned"]:
                        raise RuntimeError(
                            f"shard fetch workers exited with only "
                            f"{delivered}/{total} shards delivered")
                continue
            for item in val.items():
                delivered += 1
                yield item
            ensure_workers()
    finally:
        # early exit (consumer broke out / pipeline torn down / a chunk
        # raised): nobody will consume the remaining chunks, so do NOT
        # sit out their full retry budgets — unstarted chunks are never
        # carved, and in-flight chunks finish on their own daemon
        # threads without a join
        stop.set()


def assign_shards(counts: Sequence[int]) -> List[List[int]]:
    """Deterministic locality-first balanced assignment.

    ``counts[h]`` = shards host ``h`` currently holds; global ids number
    hosts' shards consecutively (host 0 owns 0..counts[0]-1, ...).
    Returns per-host lists of global ids such that (a) totals differ by
    at most 1 (remainder goes to the lowest-indexed hosts, so every host
    derives the same plan), and (b) each host keeps its OWN shards up to
    its target before any shard moves — only the imbalance crosses the
    network (the ``assign_partitions_to_actors`` objective,
    ``ray_xshards.py:250``).
    """
    hosts = len(counts)
    total = sum(counts)
    targets = [total // hosts + (1 if h < total % hosts else 0)
               for h in range(hosts)]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    own = [list(range(offsets[h], offsets[h + 1])) for h in range(hosts)]
    keep = [own[h][:targets[h]] for h in range(hosts)]
    surplus = [gid for h in range(hosts) for gid in own[h][targets[h]:]]
    out = []
    for h in range(hosts):
        need = targets[h] - len(keep[h])
        take, surplus = surplus[:need], surplus[need:]
        out.append(keep[h] + take)
    return out


_rebal_generation = 0
_rebal_gen_lock = threading.Lock()




def _kv_allgather(client, gen: int, tag: str, pid: int, nprocs: int,
                  value: str, timeout_s: float) -> List[str]:
    """Publish ``value`` under this process's key, then collect every
    peer's. Doubles as a barrier: nobody returns until all processes
    have published. A peer that never publishes (crashed, hung) makes
    the blocking get raise within ``timeout_s`` on every waiter."""
    prefix = f"zoo:rebalance:{gen}:{tag}:"
    t0 = time.perf_counter()
    client.key_value_set(prefix + str(pid), value)
    # one deadline for the WHOLE phase, re-derived per get — giving every
    # key the full budget would let N slow peers stack to N x timeout_s
    phase_deadline = time.monotonic() + timeout_s
    out = []
    for p in range(nprocs):
        ms = max(1000, int((phase_deadline - time.monotonic()) * 1000))
        try:
            out.append(client.blocking_key_value_get(prefix + str(p), ms))
        except Exception as e:
            raise TimeoutError(
                f"host {p} never reached rebalance phase {tag!r} within "
                f"{timeout_s:.0f}s (crashed or hung peer): {e}") from e
    # the time a host sits here is the stragglers' lead over it — the
    # cluster-wide max of this histogram is the rebalance skew
    _barrier_wait.labels(phase=tag).observe(time.perf_counter() - t0)
    return out


def rebalance_shards(shards, bind_ip: Optional[str] = None,
                     deadline: float = 120.0, stage_fn=None,
                     config: Optional[ExchangeConfig] = None):
    """Exchange shards so every process holds a balanced, disjoint set.

    ``shards``: this process's :class:`LocalXShards` of dict-of-ndarray
    shards (each host contributes what it has — counts may differ).
    Returns this process's rebalanced ``LocalXShards``. Single-process:
    returns the input unchanged (staged through ``stage_fn`` if given).

    ``stage_fn``: optional per-shard ingest hook (e.g.
    ``jax.device_put``). Fetched shards stream through a staged
    pipeline (:mod:`zoo_tpu.orca.data.ingest`) while later fetches are
    still in flight, so device transfer overlaps the network exchange;
    locally-kept shards are staged inline during final assembly. The
    returned shard ORDER is identical with and without ``stage_fn`` —
    the deterministic :func:`assign_shards` plan. With ``stage_fn`` the
    fetch leg also runs under the adaptive readahead controller
    (``config.readahead == "adaptive"``): concurrency and multi-get
    chunk size track the measured overlap ratio instead of static env
    values.

    ``config``: one :class:`ExchangeConfig` for the whole exchange
    (env knobs parsed once; defaults otherwise).

    Failure semantics: every phase is bounded by ``deadline`` seconds,
    and every host *always* reaches the post-fetch status exchange — a
    raised fetch error on one host surfaces as ``RuntimeError`` on ALL
    hosts (naming the failed ones), and a peer that dies outright makes
    everyone else time out within the deadline. The pre-fix behavior —
    one host skipping the teardown barrier and deadlocking every healthy
    peer — cannot recur: the status exchange *is* the barrier and is
    reached from both the success and the failure path.
    """
    import jax

    from zoo_tpu.orca.data.shard import LocalXShards

    parts = shards.collect() if hasattr(shards, "collect") else list(shards)
    if jax.process_count() == 1:
        if stage_fn is not None:
            from zoo_tpu.orca.data.ingest import staged_pipeline
            with staged_pipeline(iter(parts),
                                 [("ingest", stage_fn)]) as pipe:
                parts = list(pipe)
        return LocalXShards(parts)

    global _rebal_generation
    with _rebal_gen_lock:
        _rebal_generation += 1
        gen = _rebal_generation

    pid, nprocs = jax.process_index(), jax.process_count()
    client = _coordination_client()
    if client is None:  # pragma: no cover - jax internals moved
        raise RuntimeError(
            "rebalance_shards needs the JAX coordination service "
            "(jax.distributed.initialize) in multi-process mode")
    cfg = config or ExchangeConfig()
    ip = bind_ip or _default_ip()
    t0 = time.monotonic()

    def remaining() -> float:
        left = deadline - (time.monotonic() - t0)
        if left <= 0:
            raise TimeoutError(
                f"shard rebalance deadline ({deadline}s) exhausted")
        return left

    counts = [int(c) for c in _kv_allgather(
        client, gen, "counts", pid, nprocs, str(len(parts)), remaining())]
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    # serve our shards (keyed by global id), then announce (ip, port)
    # through the coordination service — the address allgather is also
    # the start barrier, so no peer fetches before every server is up;
    # the exchange must outlive the fetch phase on every host
    exchange = ShardExchange(
        {int(offsets[pid] + i): s for i, s in enumerate(parts)}, bind=ip)
    try:
        with span("rebalance_shards", gen=gen, pid=pid, nprocs=nprocs):
            table = _kv_allgather(client, gen, "addr", pid, nprocs,
                                  f"{ip}:{exchange.port}", remaining())
            addrs = []
            for row in table:
                host, port = row.rsplit(":", 1)
                addrs.append((host, int(port)))
            plan = assign_shards(counts)
            mine, error = [], None
            try:
                mine = _fetch_plan(plan[pid], pid, offsets, addrs, parts,
                                   remaining, stage_fn, cfg)
            except Exception as e:  # noqa: BLE001 — reported to every host
                error = e
                logger.error("shard fetch phase failed on host %d: %r",
                             pid, e)
            # status exchange doubles as the teardown barrier: every host
            # reaches it whether its fetches succeeded or not, and nobody
            # closes its shard server until all hosts have finished
            # fetching. Computed WITHOUT remaining() — which raises once
            # the deadline is spent — because the status publish must
            # happen even (above all) on the host that blew the deadline,
            # or its peers stall waiting for a verdict that never comes
            status_wait = max(5.0, deadline - (time.monotonic() - t0))
            status = _kv_allgather(
                client, gen, "status", pid, nprocs,
                "ok" if error is None else f"err:{error!r:.500}",
                status_wait)
            bad = {i: s for i, s in enumerate(status) if s != "ok"}
            if bad:
                raise RuntimeError(
                    f"shard rebalance failed on host(s) {sorted(bad)}: "
                    f"{bad}") from error
    finally:
        exchange.close()
        # the exchange is gone with its port: pooled connections to ANY
        # peer's per-rebalance server are dead weight after teardown
        _pool.clear()
    return LocalXShards(mine)


def _fetch_plan(my_plan: Sequence[int], pid: int, offsets, addrs,
                parts, remaining, stage_fn,
                cfg: Optional[ExchangeConfig] = None) -> List:
    """Materialize this host's planned shard list: local shards by
    reference, remote ones via concurrent pipelined multi-gets (grouped
    per source peer), optionally streamed through the ingest pipeline
    so device placement overlaps the network fetch."""
    import itertools

    cfg = cfg or ExchangeConfig()
    local_gids: List[int] = []
    by_src: Dict[int, List[int]] = {}
    for gid in my_plan:
        src = int(np.searchsorted(offsets, gid, side="right") - 1)
        if src == pid:
            local_gids.append(gid)
        else:
            by_src.setdefault(src, []).append(gid)
    source_list = [(addrs[src], gids) for src, gids in by_src.items()]
    staged: Dict[int, Dict] = {}
    # the phase budget is re-read when each chunk starts: N queued
    # chunks must not stack N fresh 60s retry deadlines past the
    # rebalance deadline (remaining() raises once it is spent, so
    # pending chunks fail fast and every host reaches the status
    # barrier together)
    chunk_timeout = lambda: min(remaining(), 60.0)  # noqa: E731
    if stage_fn is None:
        for gid, shard in iter_fetch(source_list, timeout=chunk_timeout,
                                     config=cfg):
            staged[gid] = shard
        local_set = set(local_gids)
        return [parts[gid - offsets[pid]] if gid in local_set
                else staged[gid] for gid in my_plan]
    from zoo_tpu.orca.data.ingest import (
        PipelineStats,
        ReadaheadController,
        staged_pipeline,
    )
    # ONE stream for local and remote shards: locals lead (available
    # immediately, so their device placement starts before the first
    # fetch completes — on the locality-first plan most shards are
    # local, and staging them after the network phase would waste the
    # whole fetch window), then fetched shards as they arrive. The
    # pipeline's producer thread drains the stream while its stage
    # thread runs stage_fn (device_put): transfer of shard k overlaps
    # the fetch of shard k+1. The readahead controller closes the loop:
    # it reads the pipeline's overlap stats after each chunk and walks
    # concurrency/chunk size toward "fetch fully hidden".
    stats = PipelineStats()
    controller = (ReadaheadController(cfg, stats)
                  if cfg.readahead == "adaptive" else None)
    stream = iter_fetch(source_list, timeout=chunk_timeout, config=cfg,
                        controller=controller)
    locals_iter = ((gid, parts[gid - offsets[pid]]) for gid in local_gids)
    with staged_pipeline(
            itertools.chain(locals_iter, stream),
            [("ingest", lambda kv: (kv[0], stage_fn(kv[1])))],
            stats=stats) as pipe:
        for gid, shard in pipe:
            staged[gid] = shard
    return [staged[gid] for gid in my_plan]


def _default_ip() -> str:
    """The address peers can reach us on: the interface that routes out
    (UDP connect trick — nothing is sent); loopback in single-host runs."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
